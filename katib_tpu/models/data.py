"""Datasets for trial workloads.

The reference trial images download MNIST/CIFAR-10 at container start; this
environment has no egress, so each loader first looks for a cached copy on
disk (numpy ``.npz`` with ``x_train/y_train/x_test/y_test``) and otherwise
falls back to a *structured synthetic* dataset: class prototypes + noise +
class-correlated spatial patterns.  Synthetic data is learnable (models
separate classes far above chance) which is what the orchestration, NAS and
benchmark paths need; accuracy-parity runs on real hardware drop an ``.npz``
into ``KATIB_DATA_DIR`` and get the real datasets with no code change.
"""

from __future__ import annotations

import os
import zlib
from typing import Iterator, NamedTuple

import numpy as np

DATA_DIR_ENV = "KATIB_DATA_DIR"


class Dataset(NamedTuple):
    x_train: np.ndarray
    y_train: np.ndarray
    x_test: np.ndarray
    y_test: np.ndarray
    num_classes: int

    @property
    def input_shape(self) -> tuple[int, ...]:
        return tuple(self.x_train.shape[1:])


def _find_npz(name: str) -> str | None:
    for root in (os.environ.get(DATA_DIR_ENV, ""), "data", "/root/data"):
        if not root:
            continue
        path = os.path.join(root, f"{name}.npz")
        if os.path.exists(path):
            return path
    return None


def synthetic_classification(
    n_train: int,
    n_test: int,
    shape: tuple[int, ...],
    num_classes: int,
    seed: int = 0,
    noise: float = 1.0,
) -> Dataset:
    """Learnable synthetic image classification.

    Each class gets a smooth random prototype plus a localized high-frequency
    signature; samples are prototype + Gaussian noise.  Linear models reach
    mediocre accuracy, convnets do much better — enough structure for HP/NAS
    search to have a real signal to optimize."""
    rng = np.random.default_rng(seed)
    protos = rng.normal(0.0, 1.0, size=(num_classes, *shape)).astype(np.float32)
    # smooth prototypes (class identity is low-frequency)
    for _ in range(2):
        if len(shape) >= 2:
            protos = (
                protos
                + np.roll(protos, 1, axis=1)
                + np.roll(protos, -1, axis=1)
                + np.roll(protos, 1, axis=2)
                + np.roll(protos, -1, axis=2)
            ) / 5.0

    def make(n: int, split_seed: int):
        r = np.random.default_rng(seed + split_seed)
        y = r.integers(num_classes, size=n)
        x = protos[y] + r.normal(0.0, noise, size=(n, *shape)).astype(np.float32)
        return x.astype(np.float32), y.astype(np.int32)

    x_train, y_train = make(n_train, 1)
    x_test, y_test = make(n_test, 2)
    return Dataset(x_train, y_train, x_test, y_test, num_classes)


def _load_or_synthesize(
    name: str, shape: tuple[int, ...], num_classes: int, n_train: int, n_test: int
) -> Dataset:
    path = _find_npz(name)
    if path:
        z = np.load(path)
        x_train = z["x_train"].astype(np.float32)
        x_test = z["x_test"].astype(np.float32)
        if x_train.max() > 2.0:  # raw uint8 pixels
            x_train, x_test = x_train / 255.0, x_test / 255.0
        if x_train.ndim == 3:  # add channel dim
            x_train, x_test = x_train[..., None], x_test[..., None]
        return Dataset(
            x_train,
            z["y_train"].astype(np.int32).reshape(-1),
            x_test,
            z["y_test"].astype(np.int32).reshape(-1),
            num_classes,
        )
    # crc32, not hash(): hash() is salted per-process, and black-box trials
    # run in separate processes that must all see the SAME dataset
    seed = zlib.crc32(name.encode()) % 2**31
    return synthetic_classification(n_train, n_test, shape, num_classes, seed=seed)


def load_digits_real(n_train: int = 1400, n_test: int = 397) -> Dataset:
    """REAL handwritten-digit data, no egress needed: scikit-learn's bundled
    UCI digits (1797 samples of 8x8 grayscale).  The one dataset in this
    image that is not synthetic — accuracy numbers on it are real-world
    evidence, unlike the synthetic fallbacks above (big-dataset parity still
    goes through the ``KATIB_DATA_DIR`` npz path).  Needs scikit-learn (the
    ``bayesopt`` extra); raises ImportError on a base install."""
    from sklearn.datasets import load_digits as _sk_load

    d = _sk_load()
    n_total = len(d.images)
    n_train = min(n_train, n_total - 1)
    n_test = min(n_test, n_total - n_train)
    rng = np.random.default_rng(0)
    perm = rng.permutation(n_total)
    x = (d.images[perm].astype(np.float32) / 16.0)[..., None]  # [N, 8, 8, 1]
    y = d.target[perm].astype(np.int32)
    return Dataset(
        x_train=x[:n_train],
        y_train=y[:n_train],
        x_test=x[n_train : n_train + n_test],
        y_test=y[n_train : n_train + n_test],
        num_classes=10,
    )


def using_real_data(name: str) -> bool:
    """True when a cached real ``.npz`` backs ``name`` (vs the synthetic
    fallback) — run logs record this so synthetic separability is never
    mistaken for real-dataset accuracy."""
    return _find_npz(name) is not None


def load_mnist(n_train: int = 8192, n_test: int = 2048) -> Dataset:
    return _load_or_synthesize("mnist", (28, 28, 1), 10, n_train, n_test)


def load_cifar10(n_train: int = 8192, n_test: int = 2048) -> Dataset:
    return _load_or_synthesize("cifar10", (32, 32, 3), 10, n_train, n_test)


def batches(
    x: np.ndarray,
    y: np.ndarray,
    batch_size: int,
    rng: np.random.Generator,
    drop_remainder: bool = True,
) -> Iterator[tuple[np.ndarray, np.ndarray]]:
    """One shuffled epoch of (x, y) batches."""
    idx = rng.permutation(len(x))
    end = (len(x) // batch_size) * batch_size if drop_remainder else len(x)
    for i in range(0, end, batch_size):
        take = idx[i : i + batch_size]
        if drop_remainder and len(take) < batch_size:
            break
        yield x[take], y[take]


# name -> loader dispatch shared by the NAS trials (enas/trial.py,
# darts/search.py) and the artifact scripts: one place for per-dataset
# split defaults and the accepted-names error
NAMED_DATASETS = ("cifar10", "digits", "mnist")

# one flag upgrades every artifact script at once: KATIB_DATASET overrides
# each script's default dataset, so a real-data drop (cifar10.npz in
# KATIB_DATA_DIR) flows through flagship + hyperband + ENAS with zero code
# changes (reference loads real CIFAR-10 at container start,
# ``darts-cnn-cifar10/run_trial.py:100-111``)
DATASET_ENV = "KATIB_DATASET"


def dataset_from_env(default: str) -> str:
    """The dataset an artifact script should use: ``KATIB_DATASET`` when
    set, else the script's own default.  Unknown names fail here — before
    a multi-minute run records a bogus provenance field."""
    name = os.environ.get(DATASET_ENV) or default
    if name not in NAMED_DATASETS:
        raise ValueError(
            f"{DATASET_ENV}={name!r} unknown (expected one of {NAMED_DATASETS})"
        )
    return name


def is_real_data(name: str) -> bool:
    """Whether ``name`` currently resolves to real data: digits is bundled
    (always real); the npz-backed loaders are real iff the file exists."""
    return True if name == "digits" else using_real_data(name)


def load_named_dataset(
    name: str, n_train: int | None = None, n_test: int | None = None
) -> Dataset:
    """``"digits"`` = the bundled REAL dataset (UCI handwritten digits);
    ``"cifar10"``/``"mnist"`` = npz-backed loaders (real via
    ``KATIB_DATA_DIR``, structured synthetic fallback otherwise).  Split
    defaults are per-dataset: digits has only 1797 samples, so CIFAR-scale
    defaults would clamp its test split to nothing."""
    # only pass what the caller specified — the loaders' own signature
    # defaults (digits 1400/397, cifar/mnist 8192/2048) stay the single source
    kwargs = {}
    if n_train is not None:
        kwargs["n_train"] = n_train
    if n_test is not None:
        kwargs["n_test"] = n_test
    if name == "digits":
        return load_digits_real(**kwargs)
    if name == "cifar10":
        return load_cifar10(**kwargs)
    if name == "mnist":
        return load_mnist(**kwargs)
    raise ValueError(
        f"unknown dataset {name!r} (expected one of {NAMED_DATASETS})"
    )
