"""Tunable MNIST models + the standard white-box trial function.

Parity target: the reference's ``pytorch-mnist`` trial image
(``examples/v1beta1/trial-images/pytorch-mnist/mnist.py``) — an MLP/CNN with
tunable lr/momentum that prints accuracy lines for the sidecar.  Here the
trainer is a JAX function on a device mesh reporting metrics through the
trial context; hyperparameters arrive typed.

Tunable parameters understood by ``mnist_trial``: lr, momentum, units,
num_layers, batch_size, epochs, optimizer(sgd|adam|momentum), arch(mlp|cnn).
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

from katib_tpu import costmodel
from katib_tpu.compile import artifacts as compile_artifacts
from katib_tpu.models.data import Dataset, batches, load_mnist
from katib_tpu.parallel.mesh import shard_batch
from katib_tpu.parallel.train import (
    TrainState,
    accuracy,
    cross_entropy_loss,
    make_cohort_eval_step,
    make_cohort_train_step,
    make_eval_step,
    make_train_step,
    stack_pytrees,
)


class MLP(nn.Module):
    units: int = 64
    num_layers: int = 2
    num_classes: int = 10
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        x = x.reshape((x.shape[0], -1)).astype(self.dtype)
        for _ in range(self.num_layers):
            x = nn.Dense(self.units, dtype=self.dtype)(x)
            x = nn.relu(x)
        return nn.Dense(self.num_classes, dtype=jnp.float32)(x)


class SmallCNN(nn.Module):
    channels: int = 32
    num_classes: int = 10
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        x = x.astype(self.dtype)
        x = nn.Conv(self.channels, (3, 3), dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(self.channels * 2, (3, 3), dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(self.channels * 4, dtype=self.dtype)(x)
        x = nn.relu(x)
        return nn.Dense(self.num_classes, dtype=jnp.float32)(x)


def make_optimizer(name: str, lr: float, momentum: float = 0.9):
    if name == "adam":
        return optax.adam(lr)
    if name == "momentum":
        return optax.sgd(lr, momentum=momentum)
    return optax.sgd(lr)


def _family_optimizer(name: str) -> optax.GradientTransformation:
    """Optimizer with lr/momentum as RUNTIME state (inject_hyperparams).

    Baking hyperparameters into the trace as Python floats means every
    trial of an HP sweep compiles its own executable — on a TPU where the
    full compile is minutes, a 100-trial sweep would spend hours in XLA
    for identical programs.  Injected hyperparameters live in
    ``opt_state.hyperparams``, so one compiled step serves every
    (lr, momentum) assignment; the placeholder 0.0 values are overwritten
    per trial by ``_set_hyperparams``.
    """
    if name == "adam":
        return optax.inject_hyperparams(optax.adam)(learning_rate=0.0)
    if name == "momentum":
        return optax.inject_hyperparams(optax.sgd)(learning_rate=0.0, momentum=0.0)
    return optax.inject_hyperparams(optax.sgd)(learning_rate=0.0)


def _set_hyperparams(opt_state, lr: float, momentum: float):
    """Write the trial's actual hyperparameters into an inject_hyperparams
    state (only keys the family declares are set)."""
    hp = dict(opt_state.hyperparams)
    hp["learning_rate"] = jnp.asarray(lr, jnp.float32)
    if "momentum" in hp:
        hp["momentum"] = jnp.asarray(momentum, jnp.float32)
    return opt_state._replace(hyperparams=hp)


# (model, optimizer family, mesh) -> (tx, step, evaluate, scan_epoch):
# concurrent trials of an HP sweep share ONE set of jit objects, so the
# executable compiles once per architecture instead of once per trial.
# flax Modules hash by field values; unhashable configs (e.g. a genotype
# carrying lists) fall back to uncached per-call builds.  LRU-bounded:
# an ENAS search trains hundreds of DISTINCT child architectures through
# this loop, and an unbounded map would pin every compiled executable for
# the life of the process.
import threading  # noqa: E402  (module-scope cache)
from collections import OrderedDict  # noqa: E402

_STEP_CACHE: OrderedDict = OrderedDict()
_STEP_CACHE_MAX = 32
_STEP_CACHE_LOCK = threading.Lock()


def _build_steps(model: nn.Module, optimizer: str, mesh, augment_fn=None):
    def loss_fn(params, batch):
        x, y = batch
        return cross_entropy_loss(model.apply(params, x), y)

    def metric_fn(params, batch):
        x, y = batch
        logits = model.apply(params, x)
        return {
            "accuracy": accuracy(logits, y),
            "loss": cross_entropy_loss(logits, y),
        }

    tx = _family_optimizer(optimizer)
    step = make_train_step(loss_fn, tx, mesh)
    evaluate = make_eval_step(metric_fn, mesh)

    # train-time augmentation runs INSIDE the scan body (device-side, one
    # fold of the step counter per batch) so the host->device path the
    # device_data scan removed never comes back for augmented runs
    def _epoch(state, x, y, ix, akey):
        def body(s, i):
            xb = x[i]
            if augment_fn is not None:
                xb = augment_fn(jax.random.fold_in(akey, s.step), xb)
            s, m = step(s, (xb, y[i]))
            return s, m["loss"]

        return jax.lax.scan(body, state, ix)

    scan_epoch = jax.jit(_epoch, donate_argnums=(0,))
    # jitted per-batch augment for the streamed path, built (and cached)
    # alongside the steps so concurrent trials share one trace
    aug_step = (
        jax.jit(lambda k, xb: augment_fn(k, xb)) if augment_fn is not None else None
    )
    return tx, step, evaluate, scan_epoch, aug_step


def _model_dtype(model) -> str:
    """Compute-dtype key for the MFU denominator (flax modules here cast
    to their ``dtype`` field internally; f32 inputs still run bf16 math)."""
    return "bf16" if getattr(model, "dtype", None) == jnp.bfloat16 else "f32"


def _mesh_key(mesh):
    """Stable identity for a mesh: id() can be recycled after GC, handing a
    new mesh another mesh's cached steps (stale shardings)."""
    if mesh is None:
        return None
    return (
        tuple(getattr(d, "id", repr(d)) for d in mesh.devices.flat),
        tuple(mesh.axis_names),
        mesh.devices.shape,
    )


def _steps_for(model: nn.Module, optimizer: str, mesh, augment_fn=None):
    try:
        # augment_fn keys by identity: pass a module-level function (e.g.
        # augmentation.cifar_train_augment), not a fresh lambda per call,
        # or every trial recompiles
        key = (hash(model), model, optimizer, _mesh_key(mesh), augment_fn)
    except TypeError:
        return _build_steps(model, optimizer, mesh, augment_fn)
    with _STEP_CACHE_LOCK:
        built = _STEP_CACHE.get(key)
    if built is None:
        # build OUTSIDE the lock (tracing is slow); a concurrent duplicate
        # build is harmless — setdefault keeps exactly one
        fresh = _build_steps(model, optimizer, mesh, augment_fn)
        with _STEP_CACHE_LOCK:
            built = _STEP_CACHE.setdefault(key, fresh)
    with _STEP_CACHE_LOCK:
        if key in _STEP_CACHE:
            _STEP_CACHE.move_to_end(key)
        while len(_STEP_CACHE) > _STEP_CACHE_MAX:
            _STEP_CACHE.popitem(last=False)
    return built


def train_classifier(
    model: nn.Module,
    dataset: Dataset,
    *,
    lr: float,
    epochs: int,
    batch_size: int,
    optimizer: str = "momentum",
    momentum: float = 0.9,
    mesh=None,
    seed: int = 0,
    report=None,
    eval_batch: int = 1024,
    init_transform=None,
    on_finish=None,
    device_data: bool | None = None,
    augment_fn=None,
) -> float:
    """Train and return final test accuracy; calls ``report(epoch, acc, loss)``
    per epoch when given (the trial metrics hook).

    ``init_transform(params) -> params`` warm-starts the freshly initialized
    parameters (ENAS weight sharing); ``on_finish(params)`` receives the
    final parameters (publishing back to a shared pool).

    ``device_data`` (default on for single-device runs, ``KATIB_DEVICE_DATA``
    overrides): train split lives in device memory for the whole run and
    each epoch is ONE jitted ``lax.scan`` with on-device batch gather from
    permutation indices — same transport-only optimization, same
    batch-composition guarantee as ``nas/darts/search.py``.

    ``augment_fn(key, x) -> x``: jittable train-time batch augmentation
    (e.g. ``models.augmentation.cifar_train_augment``), applied inside the
    epoch scan (device-side) or per streamed batch; keyed off the run
    seed + global step, so augmented runs stay reproducible.  Pass a
    module-level function — identity keys the jit-step cache."""
    rng = np.random.default_rng(seed)
    params = model.init(
        jax.random.PRNGKey(seed), jnp.zeros((1, *dataset.input_shape), jnp.float32)
    )
    if init_transform is not None:
        # warm starts (e.g. ENAS weight sharing overlays the shared pool)
        params = init_transform(params)
    tx, step, evaluate, cached_scan_epoch, aug_step = _steps_for(
        model, optimizer, mesh, augment_fn
    )
    # streamed-path twin of the scan_epoch resolve below (one report spans
    # an epoch's worth of single-step dispatches)
    step = compile_artifacts.resolve(
        step,
        program="train_classifier.step",
        per_report=max(1, len(dataset.x_train) // batch_size),
    )
    # augmentation randomness: independent of the shuffle stream, folded
    # with the GLOBAL step in both execution paths (scan folds
    # TrainState.step in-body; the streamed loop mirrors it with a running
    # counter), so the same seed draws the same augmentations regardless
    # of device_data mode
    aug_key = jax.random.PRNGKey(seed + 0x5EED)
    state = TrainState.create(params, tx)
    # lr/momentum are runtime values inside opt_state (compile-once sweeps)
    state = state._replace(
        opt_state=_set_hyperparams(state.opt_state, lr, momentum)
    )
    if mesh is not None:
        from katib_tpu.parallel.mesh import replicate

        state = replicate(state, mesh)

    if device_data is None:
        import os

        from katib_tpu.utils.booleans import parse_bool

        env = os.environ.get("KATIB_DEVICE_DATA")
        device_data = mesh is None if env is None else parse_bool(env)
    scan_steps = len(dataset.x_train) // batch_size
    scan_epoch = None
    if device_data and mesh is None and scan_steps >= 1:
        # split lives in HBM across the run; arrays are explicit arguments
        # (closure-captured constants would be re-embedded per trace), and
        # the jitted epoch comes from the shared cache so concurrent sweep
        # trials reuse one executable
        xd = jax.device_put(dataset.x_train)
        yd = jax.device_put(dataset.y_train)
        # artifact dispatch seam: a serialized executable fetched for this
        # program (compile/artifacts.py) takes the first dispatch instead
        # of tracing; no artifact loaded = plain jit, one dict probe
        scan_epoch = compile_artifacts.resolve(
            cached_scan_epoch, program="train_classifier.scan_epoch"
        )

    # eval prefix is constant across epochs — build (and place) it once;
    # under a mesh it truncates to a multiple of the data-axis size
    # (shard_batch's divisibility contract — 397 test rows on an 8-way
    # axis would otherwise crash after the training epochs already ran)
    ne = min(eval_batch, len(dataset.x_test))
    xe = dataset.x_test[:ne]
    ye = dataset.y_test[:ne]
    if mesh is not None:
        from katib_tpu.parallel.mesh import DATA_AXIS, local_mesh_size

        d = local_mesh_size(mesh, DATA_AXIS)
        if ne >= d:
            xe, ye = xe[: (ne // d) * d], ye[: (ne // d) * d]
        elif ne > 0:  # tiny split: tile up to one row per device
            reps = -(-d // ne)
            xe = np.tile(xe, (reps,) + (1,) * (xe.ndim - 1))[:d]
            ye = np.tile(ye, reps)[:d]
        # ne == 0 shards fine (0 % d == 0) and evals to NaN
        ebatch = shard_batch((xe, ye), mesh)
    else:
        ebatch = jax.device_put((xe, ye))

    test_acc = 0.0
    global_step = 0  # mirrors TrainState.step for the streamed aug keying
    for epoch in range(epochs):
        if scan_epoch is not None:
            # same rng draw as batches() below: one permutation per epoch
            # from the same sequential generator
            idx = rng.permutation(len(dataset.x_train))[: scan_steps * batch_size]
            idx_d = jnp.asarray(idx.reshape(scan_steps, batch_size), jnp.int32)
            state, losses = scan_epoch(state, xd, yd, idx_d, aug_key)
            n = scan_steps
            train_loss = float(jnp.sum(losses))
            if epoch == 0:
                # one report covers ONE dispatch of this epoch program
                # (steps = the folded scan length); observed after the
                # first dispatch so warm/cold classification timing stays
                # untouched.  Memoized on the step-cache key: concurrent
                # sweep trials sharing the executable trace it once.
                costmodel.observe_program(
                    ("mnist.scan", model, optimizer, _mesh_key(mesh),
                     augment_fn, batch_size, scan_steps),
                    scan_epoch,
                    (state, xd, yd, idx_d, aug_key),
                    program="train_classifier.scan_epoch",
                    steps=scan_steps,
                    per_report=1,
                    dtype=_model_dtype(model),
                )
        else:
            # device futures, one transfer per epoch — per-step float()
            # would host-sync every step and serialize async dispatch (see
            # nas/darts/search.py)
            step_losses = []
            for xb, yb in batches(dataset.x_train, dataset.y_train, batch_size, rng):
                batch = (xb, yb) if mesh is None else shard_batch((xb, yb), mesh)
                if aug_step is not None:
                    # augment AFTER sharding (elementwise + per-sample
                    # gathers partition cleanly along the batch axis — no
                    # default-device round-trip), keyed off the same
                    # global step the scan path folds
                    batch = (
                        aug_step(
                            jax.random.fold_in(aug_key, global_step), batch[0]
                        ),
                        batch[1],
                    )
                state, metrics = step(state, batch)
                global_step += 1
                step_losses.append(metrics["loss"])
            n = len(step_losses)
            train_loss = float(np.sum(jax.device_get(step_losses))) if n else 0.0
            if epoch == 0 and n:
                # streamed path: one report covers n single-step dispatches
                costmodel.observe_program(
                    ("mnist.step", model, optimizer, _mesh_key(mesh),
                     augment_fn, batch_size),
                    step,
                    (state, batch),
                    program="train_classifier.step",
                    steps=1,
                    per_report=n,
                    dtype=_model_dtype(model),
                )
        em = evaluate(state.params, ebatch)
        test_acc = float(em["accuracy"])
        if report is not None:
            cont = report(
                epoch=epoch,
                accuracy=test_acc,
                loss=train_loss / max(n, 1),
            )
            if cont is False:
                break
    if on_finish is not None:
        on_finish(jax.device_get(state.params))
    return test_acc


# -- the white-box trial function (workload parity with pytorch-mnist) -------

_DATASET_CACHE: dict[tuple, Dataset] = {}


def _cached_mnist(n_train: int, n_test: int) -> Dataset:
    key = (n_train, n_test)
    if key not in _DATASET_CACHE:
        _DATASET_CACHE[key] = load_mnist(n_train, n_test)
    return _DATASET_CACHE[key]


def _build_cohort_steps(model: nn.Module, optimizer: str, mesh=None):
    def loss_fn(params, batch):
        x, y = batch
        return cross_entropy_loss(model.apply(params, x), y)

    def metric_fn(params, batch):
        x, y = batch
        logits = model.apply(params, x)
        return {
            "accuracy": accuracy(logits, y),
            "loss": cross_entropy_loss(logits, y),
        }

    tx = _family_optimizer(optimizer)
    step = make_cohort_train_step(loss_fn, tx, mesh=mesh)
    evaluate = make_cohort_eval_step(metric_fn, mesh=mesh)
    return tx, step, evaluate


def _cohort_steps_for(model: nn.Module, optimizer: str, mesh=None):
    """Cohort twin of ``_steps_for``: same LRU, ``"cohort"``-tagged keys so
    serial and cohort executables for one architecture coexist (the mesh is
    part of the key — a trial-sharded executable must never serve a
    single-device cohort or vice versa)."""
    try:
        key = ("cohort", hash(model), model, optimizer, _mesh_key(mesh))
    except TypeError:
        return _build_cohort_steps(model, optimizer, mesh)
    with _STEP_CACHE_LOCK:
        built = _STEP_CACHE.get(key)
    if built is None:
        fresh = _build_cohort_steps(model, optimizer, mesh)
        with _STEP_CACHE_LOCK:
            built = _STEP_CACHE.setdefault(key, fresh)
    with _STEP_CACHE_LOCK:
        if key in _STEP_CACHE:
            _STEP_CACHE.move_to_end(key)
        while len(_STEP_CACHE) > _STEP_CACHE_MAX:
            _STEP_CACHE.popitem(last=False)
    return built


def mnist_cohort_trial(cctx) -> None:
    """Cohort twin of ``mnist_trial``: K members differing only in lr/momentum
    train as ONE vmapped program with stacked ``[K, ...]`` states.

    Structural knobs (arch/units/batch size/…) go through ``cctx.shared`` —
    they change the compiled program, so disagreeing members belong in
    different cohorts.  lr/momentum ride as ``[K]`` rows inside
    ``opt_state.hyperparams`` (the inject_hyperparams seam ``_set_hyperparams``
    uses serially), so the executable is identical to the serial one modulo
    the leading vmap axis.

    Batch schedule mirrors ``train_classifier(seed=0)`` exactly — one
    ``default_rng(0)`` permutation per epoch, truncated to whole batches —
    so per-member results match a serial run of the same assignment.

    On a mesh with a ``trial`` axis the stacked member dimension is padded
    to ``cctx.padded_size`` (ghost rows ride member 0's hyperparameters)
    and device-put onto the trial-sharded layout; the shared train/eval
    splits are replicated.  ``cctx.report`` drops the ghost rows, so the
    observation path is identical to the single-device cohort."""
    arch = str(cctx.shared("arch", "mlp"))
    if arch == "cnn":
        model = SmallCNN(channels=int(cctx.shared("channels", 32)))
    else:
        model = MLP(
            units=int(cctx.shared("units", 64)),
            num_layers=int(cctx.shared("num_layers", 2)),
        )
    dataset = _cached_mnist(
        int(cctx.shared("n_train", 4096)), int(cctx.shared("n_test", 1024))
    )
    epochs = int(cctx.shared("epochs", 3))
    batch_size = int(cctx.shared("batch_size", 256))
    optimizer = str(cctx.shared("optimizer", "momentum"))
    lrs = cctx.stacked("lr", default=0.05, dtype=jnp.float32)
    moms = cctx.stacked("momentum", default=0.9, dtype=jnp.float32)

    k = cctx.padded_size  # == len(cctx) without a trial axis
    seed = 0  # train_classifier's default — keeps cohort == serial
    rng = np.random.default_rng(seed)
    params = model.init(
        jax.random.PRNGKey(seed), jnp.zeros((1, *dataset.input_shape), jnp.float32)
    )
    tx, step, evaluate = _cohort_steps_for(model, optimizer, cctx.cohort_mesh)
    # artifact dispatch seam (see train_classifier): fetched cohort-step
    # executables dispatch without tracing
    step = compile_artifacts.resolve(
        step,
        program="mnist_cohort_trial.step",
        per_report=max(1, len(dataset.x_train) // batch_size),
    )
    base = TrainState.create(params, tx)
    state = stack_pytrees([base] * k)
    # per-member hyperparameters as [K] runtime operands (stacked() pads
    # ghost rows with member 0's values)
    hp = dict(state.opt_state.hyperparams)
    hp["learning_rate"] = lrs
    if "momentum" in hp:
        hp["momentum"] = moms
    state = state._replace(opt_state=state.opt_state._replace(hyperparams=hp))
    state = cctx.place_members(state)

    xd, yd = cctx.place_shared((dataset.x_train, dataset.y_train))
    scan_steps = len(dataset.x_train) // batch_size
    ne = min(1024, len(dataset.x_test))
    ebatch = cctx.place_shared((dataset.x_test[:ne], dataset.y_test[:ne]))

    for epoch in range(epochs):
        idx = rng.permutation(len(dataset.x_train))[: scan_steps * batch_size]
        losses = []
        for s in range(scan_steps):
            b = jnp.asarray(idx[s * batch_size : (s + 1) * batch_size], jnp.int32)
            # shared batch, mapped states: in_axes=(0, None) inside the step
            state, metrics = step(state, (xd[b], yd[b]))
            losses.append(metrics["loss"])  # [K], device future
        if epoch == 0 and scan_steps >= 1:
            # whole-cohort program cost ([K]-batched step); one report
            # covers scan_steps dispatches of it
            costmodel.observe_program(
                ("mnist.cohort", model, optimizer,
                 _mesh_key(cctx.cohort_mesh), k, batch_size),
                step,
                (state, (xd[b], yd[b])),
                program="mnist_cohort_trial.step",
                steps=1,
                per_report=scan_steps,
                dtype=_model_dtype(model),
            )
        train_loss = (
            jnp.sum(jnp.stack(losses), axis=0) if losses else jnp.zeros((k,))
        )
        em = evaluate(state.params, ebatch)
        cont = cctx.report(
            step=epoch,
            accuracy=em["accuracy"],
            loss=train_loss / max(scan_steps, 1),
        )
        if not cont:
            break


def mnist_trial(ctx) -> None:
    """White-box trial: tunable MNIST classifier reporting accuracy/loss."""
    p = ctx.params
    arch = str(p.get("arch", "mlp"))
    if arch == "cnn":
        model = SmallCNN(channels=int(p.get("channels", 32)))
    else:
        model = MLP(units=int(p.get("units", 64)), num_layers=int(p.get("num_layers", 2)))
    dataset = _cached_mnist(int(p.get("n_train", 4096)), int(p.get("n_test", 1024)))

    def report(epoch, accuracy, loss):
        return ctx.report(step=epoch, accuracy=accuracy, loss=loss)

    train_classifier(
        model,
        dataset,
        lr=float(p.get("lr", 0.05)),
        momentum=float(p.get("momentum", 0.9)),
        epochs=int(p.get("epochs", 3)),
        batch_size=int(p.get("batch_size", 256)),
        optimizer=str(p.get("optimizer", "momentum")),
        mesh=ctx.mesh,
        report=report,
    )


def mnist_prewarm(shared: dict, k: int, mesh=None) -> None:
    """Compile-only twin of ``mnist_trial``/``mnist_cohort_trial`` (see
    ``compile.prewarm.attach_prewarm_fn``): builds the exact jitted step
    objects the real run will pull from ``_STEP_CACHE`` and runs them once
    on dummy operands of the right shapes/dtypes, so the trial's first step
    hits the in-process jit cache (and, with ``init_compile_cache`` wired,
    the persistent XLA cache) instead of tracing + compiling.

    Dataset-free by design — prewarm must not trigger dataset loads; MNIST
    shapes are static (28, 28, 1) and the loaders produce float32/int32,
    so zeros of the right aval compile the identical program.  Mirrors the
    real paths' branching: ``k > 1`` warms the vmapped cohort step (trial
    sharding when the mesh carries a trial axis), ``k == 1`` warms either
    the device-data epoch scan or the streamed per-batch step, matching
    ``train_classifier``'s own mode selection."""
    p = dict(shared)
    arch = str(p.get("arch", "mlp"))
    if arch == "cnn":
        model = SmallCNN(channels=int(p.get("channels", 32)))
    else:
        model = MLP(
            units=int(p.get("units", 64)), num_layers=int(p.get("num_layers", 2))
        )
    n_train = int(p.get("n_train", 4096))
    n_test = int(p.get("n_test", 1024))
    batch_size = int(p.get("batch_size", 256))
    optimizer = str(p.get("optimizer", "momentum"))
    shape = (28, 28, 1)  # load_mnist's static input_shape
    k = int(k)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, *shape), jnp.float32)
    )
    xb = jnp.zeros((batch_size, *shape), jnp.float32)
    yb = jnp.zeros((batch_size,), jnp.int32)
    ne = min(1024, n_test)

    if k > 1:
        from katib_tpu.parallel.mesh import replicate, shard_members, trial_axis_size

        # cohort_mesh semantics: no trial axis -> single-device vmap
        cmesh = mesh if (mesh is not None and trial_axis_size(mesh) > 1) else None
        tx, step, evaluate = _cohort_steps_for(model, optimizer, cmesh)
        base = TrainState.create(params, tx)
        state = stack_pytrees([base] * k)
        # hyperparameter VALUES are runtime rows — any finite placeholder
        # compiles the same program the real assignments will run
        hp = dict(state.opt_state.hyperparams)
        hp["learning_rate"] = jnp.full((k,), 0.05, jnp.float32)
        if "momentum" in hp:
            hp["momentum"] = jnp.full((k,), 0.9, jnp.float32)
        state = state._replace(opt_state=state.opt_state._replace(hyperparams=hp))
        xe = jnp.zeros((ne, *shape), jnp.float32)
        ye = jnp.zeros((ne,), jnp.int32)
        if cmesh is not None:
            state = shard_members(state, cmesh)
            batch = replicate((xb, yb), cmesh)
            ebatch = replicate((xe, ye), cmesh)
        else:
            batch = (xb, yb)
            ebatch = (xe, ye)
        state, _ = step(state, batch)
        # same memo label as mnist_cohort_trial: the prewarm twin and the
        # real cohort share one executable, so they share one cost record
        # (the ambient slot feeds PrewarmWorker's registry cost merge)
        costmodel.observe_program(
            ("mnist.cohort", model, optimizer, _mesh_key(cmesh), k, batch_size),
            step,
            (state, batch),
            program="mnist_cohort_trial.step",
            steps=1,
            per_report=max(1, n_train // batch_size),
            dtype=_model_dtype(model),
        )
        em = evaluate(state.params, ebatch)
    else:
        import os

        from katib_tpu.utils.booleans import parse_bool

        tx, step, evaluate, scan_epoch, _aug = _steps_for(model, optimizer, mesh)
        state = TrainState.create(params, tx)
        state = state._replace(
            opt_state=_set_hyperparams(state.opt_state, 0.05, 0.9)
        )
        if mesh is not None:
            from katib_tpu.parallel.mesh import replicate

            state = replicate(state, mesh)
        env = os.environ.get("KATIB_DEVICE_DATA")
        device_data = mesh is None if env is None else parse_bool(env)
        scan_steps = n_train // batch_size
        if device_data and mesh is None and scan_steps >= 1:
            xz = jnp.zeros((n_train, *shape), jnp.float32)
            yz = jnp.zeros((n_train,), jnp.int32)
            iz = jnp.zeros((scan_steps, batch_size), jnp.int32)
            kz = jax.random.PRNGKey(0)
            state, _ = scan_epoch(state, xz, yz, iz, kz)
            costmodel.observe_program(
                ("mnist.scan", model, optimizer, _mesh_key(mesh),
                 None, batch_size, scan_steps),
                scan_epoch,
                (state, xz, yz, iz, kz),
                program="train_classifier.scan_epoch",
                steps=scan_steps,
                per_report=1,
                dtype=_model_dtype(model),
            )
        else:
            batch = (xb, yb) if mesh is None else shard_batch((xb, yb), mesh)
            state, _ = step(state, batch)
            costmodel.observe_program(
                ("mnist.step", model, optimizer, _mesh_key(mesh),
                 None, batch_size),
                step,
                (state, batch),
                program="train_classifier.step",
                steps=1,
                per_report=max(1, scan_steps),
                dtype=_model_dtype(model),
            )
        # eval prefix: same truncate/tile placement as train_classifier
        xe = np.zeros((ne, *shape), np.float32)
        ye = np.zeros((ne,), np.int32)
        if mesh is not None:
            from katib_tpu.parallel.mesh import DATA_AXIS, local_mesh_size

            d = local_mesh_size(mesh, DATA_AXIS)
            if ne >= d:
                xe, ye = xe[: (ne // d) * d], ye[: (ne // d) * d]
            elif ne > 0:
                reps = -(-d // ne)
                xe = np.tile(xe, (reps,) + (1,) * (xe.ndim - 1))[:d]
                ye = np.tile(ye, reps)[:d]
            ebatch = shard_batch((xe, ye), mesh)
        else:
            ebatch = jax.device_put((xe, ye))
        em = evaluate(state.params, ebatch)
    em["accuracy"].block_until_ready()


# opt-in: the orchestrator batches compatible mnist_trial proposals through
# the vmapped twin when the experiment declares a cohort (runner/cohort.py),
# and the prewarm worker compiles upcoming groups' programs in the
# background through the compile-only twin (compile/prewarm.py)
from katib_tpu.compile.prewarm import attach_prewarm_fn  # noqa: E402
from katib_tpu.runner.cohort import attach_cohort_fn  # noqa: E402

attach_cohort_fn(mnist_trial, mnist_cohort_trial)
attach_prewarm_fn(mnist_trial, mnist_prewarm)
