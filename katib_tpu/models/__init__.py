from katib_tpu.models.data import Dataset, load_cifar10, load_mnist  # noqa: F401
from katib_tpu.models.mnist import MLP, SmallCNN, mnist_trial, train_classifier  # noqa: F401
