from katib_tpu.models.data import Dataset, load_cifar10, load_mnist  # noqa: F401
from katib_tpu.models.mnist import MLP, SmallCNN, mnist_trial, train_classifier  # noqa: F401
from katib_tpu.models.pbt_digits import pbt_digits_cohort, pbt_digits_trial  # noqa: F401
from katib_tpu.models.pbt_toy import optimal_lr, pbt_toy_trial  # noqa: F401
from katib_tpu.models.transformer import (  # noqa: F401
    TransformerLM,
    make_attention_fn,
    markov_dataset,
    train_lm,
    transformer_trial,
)
