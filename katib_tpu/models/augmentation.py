"""Device-side image augmentation for the DARTS augment phase.

The reference trial image trains CIFAR-10 with RandomCrop(32, padding=4) +
RandomHorizontalFlip + Cutout(16) on the host dataloader
(``examples/v1beta1/trial-images/darts-cnn-cifar10/utils.py:15-30``) — the
transforms the paper's ~97% depends on.  Rebuilding them host-side would
reintroduce the per-step host->device transfer the ``device_data`` epoch
scan exists to avoid, so these are **jittable batch transforms** that run
inside the scan body on the accelerator:

- static output shapes (pad -> ``dynamic_slice`` crop, mask-multiply
  cutout) — no data-dependent shapes, so XLA fuses them into the step;
- per-sample randomness from a single folded PRNG key, split per batch by
  the caller (``train_classifier``'s scan body folds the training step
  counter into an epoch key, so batch composition AND augmentation are
  reproducible from the run seed alone).

Everything is pure elementwise/gather work — negligible next to the conv
stack, and it rides the same one-dispatch-per-epoch economics.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp


def random_crop_flip(key: jax.Array, x: jax.Array, padding: int = 4) -> jax.Array:
    """Zero-pad by ``padding`` then crop back to HxW at a per-sample random
    offset, plus a per-sample horizontal flip — the reference's
    RandomCrop(32, padding=4) + RandomHorizontalFlip."""
    b, h, w, c = x.shape
    padded = jnp.pad(
        x, ((0, 0), (padding, padding), (padding, padding), (0, 0))
    )
    k_y, k_x, k_f = jax.random.split(key, 3)
    off_y = jax.random.randint(k_y, (b,), 0, 2 * padding + 1)
    off_x = jax.random.randint(k_x, (b,), 0, 2 * padding + 1)

    def crop_one(img, oy, ox):
        return jax.lax.dynamic_slice(img, (oy, ox, 0), (h, w, c))

    x = jax.vmap(crop_one)(padded, off_y, off_x)
    flip = jax.random.bernoulli(k_f, 0.5, (b,))
    return jnp.where(flip[:, None, None, None], x[:, :, ::-1, :], x)


def cutout(key: jax.Array, x: jax.Array, length: int = 16) -> jax.Array:
    """Zero a length x length square at a per-sample random center — the
    reference's Cutout(length=16) (``utils.py:33-52``), as a static-shape
    mask multiply (the square clips at the borders, like the original)."""
    b, h, w, _ = x.shape
    k_y, k_x = jax.random.split(key)
    cy = jax.random.randint(k_y, (b,), 0, h)
    cx = jax.random.randint(k_x, (b,), 0, w)
    ys = jnp.arange(h)[None, :, None]
    xs = jnp.arange(w)[None, None, :]
    # half-open [c-half, c+half): exactly `length` rows/cols, matching the
    # reference's y1=y-half..y2=y+half slice semantics
    half = length // 2
    dy = ys - cy[:, None, None]
    dx = xs - cx[:, None, None]
    inside = (dy >= -half) & (dy < half) & (dx >= -half) & (dx < half)
    return jnp.where(inside[..., None], jnp.zeros((), x.dtype), x)


def cifar_train_augment(
    key: jax.Array, x: jax.Array, *, padding: int = 4, cutout_length: int = 16
) -> jax.Array:
    """The reference's full CIFAR-10 train-time pipeline: crop + flip +
    cutout.  Use as ``train_classifier(..., augment_fn=cifar_train_augment)``."""
    k_crop, k_cut = jax.random.split(key)
    x = random_crop_flip(k_crop, x, padding=padding)
    return cutout(k_cut, x, length=cutout_length)


@dataclasses.dataclass(frozen=True)
class CifarAugment:
    """Value-hashable augment_fn: two instances with the same parameters
    hash and compare equal, so the trainer's jit-step cache reuses one
    compiled epoch across trials even when each trial constructs its own
    instance (a functools.partial would key by identity and force a
    recompile per trial)."""

    padding: int = 4
    cutout_length: int = 16

    def __call__(self, key: jax.Array, x: jax.Array) -> jax.Array:
        return cifar_train_augment(
            key, x, padding=self.padding, cutout_length=self.cutout_length
        )


def make_cifar_augment(padding: int = 4, cutout_length: int = 16) -> CifarAugment:
    return CifarAugment(padding=padding, cutout_length=cutout_length)
