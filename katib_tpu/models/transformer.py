"""Tunable long-context transformer LM — the sequence-parallel trial workload.

The reference's trial zoo stops at small CNNs (SURVEY.md §2.3); it has no
long-context model family because it has no sequence parallelism.  This
module adds a decoder-only transformer whose attention runs through the
fused flash kernel (``katib_tpu.ops.flash_attention``) on one chip and
through ring / all-to-all sequence parallelism
(``katib_tpu.parallel.ring_attention``) when the trial's mesh has a ``seq``
axis — so HP search (lr, width, depth, heads) can drive long-sequence
training on a sharded mesh with the same trial API as the CNN workloads.

Tunable parameters understood by ``transformer_trial``: lr, d_model,
n_heads, n_layers, seq_len, batch_size, steps, warmup_frac,
attn(ring|ulysses), dropout.

The training task is a synthetic first-order Markov language-modelling
problem: next-token structure is learnable (entropy well below uniform) and
the data is generated on the fly, so trials are hermetic — no dataset
download, the objective (validation loss) still orders hyperparameters
meaningfully.
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

from katib_tpu.parallel.mesh import DATA_AXIS, SEQ_AXIS, shard_batch
from katib_tpu.parallel.ring_attention import make_sequence_parallel_attention
from katib_tpu.parallel.train import TrainState, clip_by_global_norm


class Block(nn.Module):
    d_model: int
    n_heads: int
    attn_fn: Callable  # (q, k, v) [B,H,S,D] -> [B,H,S,D]
    dropout: float = 0.0
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        d_head = self.d_model // self.n_heads
        h = nn.LayerNorm(dtype=self.dtype)(x)
        qkv = nn.Dense(3 * self.d_model, use_bias=False, dtype=self.dtype)(h)
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def heads(t):  # [B, S, D_model] -> [B, H, S, d_head]
            b, s, _ = t.shape
            return t.reshape(b, s, self.n_heads, d_head).transpose(0, 2, 1, 3)

        o = self.attn_fn(heads(q), heads(k), heads(v))
        b, nh, s, dh = o.shape
        o = o.transpose(0, 2, 1, 3).reshape(b, s, nh * dh).astype(self.dtype)
        o = nn.Dense(self.d_model, use_bias=False, dtype=self.dtype)(o)
        x = x + nn.Dropout(self.dropout, deterministic=deterministic)(o)

        h = nn.LayerNorm(dtype=self.dtype)(x)
        h = nn.Dense(4 * self.d_model, dtype=self.dtype)(h)
        h = nn.gelu(h)
        h = nn.Dense(self.d_model, dtype=self.dtype)(h)
        return x + nn.Dropout(self.dropout, deterministic=deterministic)(h)


class TransformerLM(nn.Module):
    vocab_size: int
    d_model: int = 128
    n_heads: int = 4
    n_layers: int = 2
    max_seq_len: int = 2048
    dropout: float = 0.0
    dtype: jnp.dtype = jnp.bfloat16
    attn_fn: Callable | None = None  # default set in setup-free __call__

    @nn.compact
    def __call__(self, tokens, deterministic: bool = True):
        attn = self.attn_fn
        if attn is None:
            attn = _dense_causal_attention
        x = nn.Embed(self.vocab_size, self.d_model, dtype=self.dtype)(tokens)
        pos = nn.Embed(self.max_seq_len, self.d_model, dtype=self.dtype)(
            jnp.arange(tokens.shape[1])[None, :]
        )
        x = x + pos
        for _ in range(self.n_layers):
            x = Block(
                d_model=self.d_model, n_heads=self.n_heads, attn_fn=attn,
                dropout=self.dropout, dtype=self.dtype,
            )(x, deterministic)
        x = nn.LayerNorm(dtype=self.dtype)(x)
        return nn.Dense(self.vocab_size, dtype=jnp.float32)(x)


def _dense_causal_attention(q, k, v):
    from katib_tpu.ops.flash_attention import reference_attention

    return reference_attention(q, k, v, causal=True)


def make_attention_fn(mesh=None, strategy: str = "ring"):
    """Attention for a trial's mesh: sequence-parallel when the mesh has a
    ``seq`` axis > 1, single-device flash/dense otherwise."""
    if mesh is None:
        from katib_tpu.ops.flash_attention import flash_attention

        if jax.default_backend() == "tpu":
            return lambda q, k, v: flash_attention(q, k, v, causal=True)
        return _dense_causal_attention
    return make_sequence_parallel_attention(mesh, strategy=strategy, causal=True)


# ---------------------------------------------------------------------------
# synthetic Markov LM data
# ---------------------------------------------------------------------------


def markov_dataset(
    vocab_size: int, n_seq: int, seq_len: int, *, seed: int = 0, branching: int = 4
) -> np.ndarray:
    """Token sequences from a fixed sparse first-order Markov chain: every
    token has ``branching`` likely successors, so the optimal next-token loss
    is ≈ log(branching) — far below log(vocab) for an untrained model."""
    rng = np.random.default_rng(seed)
    succ = rng.integers(0, vocab_size, size=(vocab_size, branching))
    out = np.empty((n_seq, seq_len), np.int32)
    state = rng.integers(0, vocab_size, size=n_seq)
    for t in range(seq_len):
        out[:, t] = state
        pick = rng.integers(0, branching, size=n_seq)
        state = succ[state, pick]
    return out


# ---------------------------------------------------------------------------
# training loop
# ---------------------------------------------------------------------------


def lm_loss(logits: jnp.ndarray, tokens: jnp.ndarray) -> jnp.ndarray:
    """Next-token cross entropy over [B, S, V] logits / [B, S] tokens."""
    logp = jax.nn.log_softmax(logits[:, :-1], axis=-1)
    tgt = tokens[:, 1:]
    nll = -jnp.take_along_axis(logp, tgt[..., None], axis=-1)[..., 0]
    return jnp.mean(nll)


def train_lm(
    model: TransformerLM,
    data: np.ndarray,
    *,
    lr: float,
    steps: int,
    batch_size: int,
    warmup_frac: float = 0.1,
    grad_clip: float = 1.0,
    mesh=None,
    seed: int = 0,
    report=None,
    report_every: int = 10,
) -> float:
    """Train on ``data`` [N, S]; returns final eval loss on a held-out tail.
    Calls ``report(step, loss, eval_loss)`` every ``report_every`` steps."""
    rng = np.random.default_rng(seed)
    n_eval = max(batch_size, len(data) // 10)
    train, heldout = data[:-n_eval], data[-n_eval:]

    # init batch must divide the mesh's data axis (the attention shard_map
    # shards the batch dimension even while tracing init)
    init_batch = 1
    if mesh is not None and DATA_AXIS in mesh.shape:
        init_batch = mesh.shape[DATA_AXIS]
    params = model.init(
        jax.random.PRNGKey(seed), jnp.zeros((init_batch, data.shape[1]), jnp.int32)
    )
    sched = optax.warmup_cosine_decay_schedule(
        0.0, lr, max(1, int(steps * warmup_frac)), steps
    )
    tx = optax.adamw(sched, weight_decay=0.01)

    use_dropout = model.dropout > 0.0

    def loss_fn(params, tokens, dropout_key):
        if use_dropout:
            logits = model.apply(
                params, tokens, deterministic=False, rngs={"dropout": dropout_key}
            )
        else:
            logits = model.apply(params, tokens)
        return lm_loss(logits, tokens)

    # donate the state: params + optimizer buffers are dead after the step,
    # so XLA updates them in place instead of copying each iteration
    @partial(jax.jit, donate_argnums=(0,))
    def step_fn(state: TrainState, tokens, dropout_key):
        loss, grads = jax.value_and_grad(loss_fn)(state.params, tokens, dropout_key)
        grads, _ = clip_by_global_norm(grads, grad_clip)
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        return TrainState(state.step + 1, params, opt_state), loss

    @jax.jit
    def eval_fn(params, tokens):
        return lm_loss(model.apply(params, tokens), tokens)

    state = TrainState.create(params, tx)
    if mesh is not None:
        from katib_tpu.parallel.mesh import replicate

        state = replicate(state, mesh)

    def place(tokens):
        tokens = jnp.asarray(tokens)
        return tokens if mesh is None else shard_batch(tokens, mesh)

    eval_tokens = place(heldout[:batch_size])
    eval_loss: float | None = None
    dkey = jax.random.PRNGKey(seed + 1)
    for s in range(steps):
        idx = rng.integers(0, len(train), size=batch_size)
        dkey, sub = jax.random.split(dkey)
        state, loss = step_fn(state, place(train[idx]), sub)
        eval_loss = None  # stale after this step's update
        if report is not None and (s % report_every == 0 or s == steps - 1):
            eval_loss = float(eval_fn(state.params, eval_tokens))
            if report(step=s, loss=float(loss), eval_loss=eval_loss) is False:
                break
    if eval_loss is None:
        eval_loss = float(eval_fn(state.params, eval_tokens))
    return eval_loss


# -- the white-box trial function -------------------------------------------


def transformer_trial(ctx) -> None:
    """White-box trial: tunable long-context LM reporting train/eval loss."""
    p = ctx.params
    vocab = int(p.get("vocab_size", 256))
    seq_len = int(p.get("seq_len", 512))
    mesh = ctx.mesh
    strategy = str(p.get("attn", "ring"))

    model = TransformerLM(
        vocab_size=vocab,
        d_model=int(p.get("d_model", 128)),
        n_heads=int(p.get("n_heads", 4)),
        n_layers=int(p.get("n_layers", 2)),
        max_seq_len=seq_len,
        dropout=float(p.get("dropout", 0.0)),
        attn_fn=make_attention_fn(mesh, strategy=strategy),
    )
    data = markov_dataset(
        vocab, int(p.get("n_seq", 512)), seq_len, seed=int(p.get("data_seed", 0))
    )

    def report(step, loss, eval_loss):
        return ctx.report(step=step, loss=loss, eval_loss=eval_loss)

    train_lm(
        model,
        data,
        lr=float(p.get("lr", 3e-3)),
        steps=int(p.get("steps", 60)),
        batch_size=int(p.get("batch_size", 16)),
        warmup_frac=float(p.get("warmup_frac", 0.1)),
        mesh=mesh,
        report=report,
    )
