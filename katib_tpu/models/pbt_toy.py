"""Toy PBT benchmark workload — parity with the reference's ``simple-pbt``
trial image (``examples/v1beta1/trial-images/simple-pbt/pbt_test.py:31-127``).

The optimal learning rate follows a triangle wave over global steps, so no
fixed lr wins: a population must *exploit* (clone a leader's checkpoint) and
*explore* (perturb lr) to track the moving optimum.  The reference persists
a pickle in the PVC-mounted ``--checkpoint`` dir and sleeps ≥7s for sidecar
PID-scan latency; here state is an Orbax pytree in the trial's checkpoint
directory and metrics stream in-process — no sleeps, no sidecar.
"""

from __future__ import annotations

import jax.numpy as jnp


def optimal_lr(step: int, period: int = 20, peak: float = 0.1) -> float:
    """Triangle wave in [0, peak] with the given period."""
    phase = (step % period) / (period / 2.0)
    return peak * (1.0 - abs(phase - 1.0))


def pbt_toy_trial(ctx) -> None:
    """Score accrues per step by how close this member's lr is to the moving
    optimum; lineage continues from the (possibly inherited) checkpoint."""
    lr = float(ctx.params["lr"])
    steps_per_round = int(ctx.params.get("steps_per_round", 4))

    restored = ctx.restore_checkpoint()
    if restored is not None:
        state, _ = restored
        score = float(state["score"])
        start = int(state["step"]) + 1
    else:
        score, start = 0.0, 0

    for step in range(start, start + steps_per_round):
        opt = optimal_lr(step)
        score += max(0.0, 0.02 - abs(lr - opt))
        if not ctx.report(step=step, score=score, lr_gap=abs(lr - opt)):
            break

    ctx.save_checkpoint(
        {"step": jnp.asarray(step), "score": jnp.asarray(score)}, step
    )
