"""PBT workload with REAL model state: a digits classifier whose weights,
momentum buffers, and step counter ride the PBT checkpoint lineage.

The toy workload (``pbt_toy.py``, reference ``simple-pbt`` parity) carries
one scalar through the lineage; this trial carries an actual JAX model —
exploit clones the winner's Orbax checkpoint (parameters + momentum +
step), explore perturbs the learning rate, and training *continues* from
the inherited weights on the bundled REAL UCI digits.  That is the full
PBT contract at model scale: the thing the reference moves between pods
with ``shutil.copytree`` on a RWX PVC (``pbt/service.py:259-268``), here
an Orbax pytree under the experiment workdir.

Trial params: ``lr`` (the evolved hyperparameter), ``steps_per_round``
(SGD minibatch steps per generation, default 60), ``batch`` (64).
Reports ``accuracy`` on the held-out split once per round.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from katib_tpu.models.data import Dataset, load_digits_real

_HIDDEN = 128

# same in-process cache pattern as mnist._cached_mnist: a PBT sweep calls
# this trial dozens of times per process; reload + re-permute each round
# would be pure waste
_DATASET_CACHE: dict[tuple, Dataset] = {}


def _cached_digits(n_train: int, n_test: int) -> Dataset:
    key = (n_train, n_test)
    if key not in _DATASET_CACHE:
        _DATASET_CACHE[key] = load_digits_real(n_train, n_test)
    return _DATASET_CACHE[key]


def _init_params(key: jax.Array, d_in: int, num_classes: int) -> dict:
    k1, k2 = jax.random.split(key)
    s1 = (2.0 / d_in) ** 0.5
    s2 = (2.0 / _HIDDEN) ** 0.5
    return {
        "w1": s1 * jax.random.normal(k1, (d_in, _HIDDEN), jnp.float32),
        "b1": jnp.zeros((_HIDDEN,), jnp.float32),
        "w2": s2 * jax.random.normal(k2, (_HIDDEN, num_classes), jnp.float32),
        "b2": jnp.zeros((num_classes,), jnp.float32),
    }


def _logits(params: dict, x: jnp.ndarray) -> jnp.ndarray:
    h = jax.nn.relu(x @ params["w1"] + params["b1"])
    return h @ params["w2"] + params["b2"]


def _loss(params: dict, x: jnp.ndarray, y: jnp.ndarray) -> jnp.ndarray:
    logp = jax.nn.log_softmax(_logits(params, x))
    return -jnp.take_along_axis(logp, y[:, None], axis=1).mean()


@jax.jit
def _sgd_step(params: dict, velocity: dict, x, y, lr):
    grads = jax.grad(_loss)(params, x, y)
    velocity = jax.tree_util.tree_map(lambda v, g: 0.9 * v + g, velocity, grads)
    params = jax.tree_util.tree_map(lambda p, v: p - lr * v, params, velocity)
    return params, velocity


@jax.jit
def _accuracy(params: dict, x, y):
    return (jnp.argmax(_logits(params, x), axis=-1) == y).mean()


def pbt_digits_trial(ctx) -> None:
    lr = float(ctx.params["lr"])
    steps_per_round = int(ctx.params.get("steps_per_round", 60))
    batch = int(ctx.params.get("batch", 64))

    ds = _cached_digits(1400, 397)
    x_train = ds.x_train.reshape(len(ds.x_train), -1)
    x_test = jnp.asarray(ds.x_test.reshape(len(ds.x_test), -1))
    y_test = jnp.asarray(ds.y_test)

    restored = ctx.restore_checkpoint()
    if restored is not None:
        state, _ = restored
        params, velocity = state["params"], state["velocity"]
        start = int(state["step"]) + 1
    else:
        params = _init_params(jax.random.PRNGKey(0), x_train.shape[1], 10)
        velocity = jax.tree_util.tree_map(jnp.zeros_like, params)
        start = 0

    rng = np.random.default_rng(start)  # advance the data stream per round
    step = start
    for step in range(start, start + steps_per_round):
        idx = rng.integers(0, len(x_train), size=batch)
        params, velocity = _sgd_step(
            params, velocity, jnp.asarray(x_train[idx]), jnp.asarray(ds.y_train[idx]), lr
        )

    acc = float(_accuracy(params, x_test, y_test))
    ctx.report(step=step, accuracy=acc)
    ctx.save_checkpoint(
        {
            "params": jax.device_get(params),
            "velocity": jax.device_get(velocity),
            "step": np.asarray(step),
        },
        step,
    )


# -- on-device PBT twin -------------------------------------------------------


def _member_checkpointers(cctx):
    from katib_tpu.utils.checkpoint import TrialCheckpointer

    return [
        TrialCheckpointer(d) if d else None for d in cctx.checkpoint_dirs
    ]


def pbt_digits_cohort(cctx) -> None:
    """The on-device PBT twin of :func:`pbt_digits_trial`: the whole
    population trains, scores, selects, clones, and perturbs as chunked
    dispatches of ONE compiled program (``parallel/pbt.py``), with host
    round-trips only at generation boundaries (scores/lineage fetch + the
    per-member Orbax checkpoints that make drain/resume lossless).

    Launched by the ``pbt-ondevice`` suggester, which stamps the shared
    ``pbt_*`` assignments (space JSON, generation count/length, truncation,
    resample probability, seed) on every member.  Without them (a plain
    cohort experiment over this trial fn) it raises, and ``run_cohort``
    falls back to serial per-member execution — the host path.

    Checkpoint schema stays a superset of the host trial's
    (``params``/``velocity``/``step`` + ``hypers``/``generation``), so a
    drained on-device member can resume through EITHER path.  Scores are
    test-set accuracy (maximize), matching the host trial's report.
    """
    import time as _time
    from concurrent.futures import ThreadPoolExecutor

    from katib_tpu import costmodel
    from katib_tpu.parallel.pbt import (
        decode_member_hypers,
        encode_hypers,
        make_pbt_generation_step,
        specs_from_json,
    )
    from katib_tpu.parallel.train import stack_pytrees
    from katib_tpu.suggest.pbt import GENERATION_LABEL, PARENT_LABEL
    from katib_tpu.utils import observability as obs
    from katib_tpu.utils import tracing

    space_json = cctx.shared("pbt_space", None)
    if space_json is None:
        raise ValueError(
            "pbt_digits_cohort needs the pbt-ondevice suggester's pbt_space "
            "assignment (plain cohorts fall back to the serial trial path)"
        )
    specs = specs_from_json(space_json)
    k = len(cctx)
    p = cctx.padded_size
    generations = int(cctx.shared("pbt_generations", 8))
    steps = int(cctx.shared("pbt_steps_per_generation", 60))
    truncation = float(cctx.shared("pbt_truncation", 0.25))
    resample_p = cctx.shared("pbt_resample_p", None)
    resample_p = float(resample_p) if resample_p is not None else None
    seed = int(cctx.shared("pbt_seed", 0))
    batch = int(cctx.shared("batch", 64))

    ds = _cached_digits(1400, 397)
    x_train = jnp.asarray(ds.x_train.reshape(len(ds.x_train), -1))
    y_train = jnp.asarray(ds.y_train)
    data = cctx.place_shared((x_train, y_train))
    eval_batch = cctx.place_shared(
        (
            jnp.asarray(ds.x_test.reshape(len(ds.x_test), -1)),
            jnp.asarray(ds.y_test),
        )
    )
    n_train = len(ds.x_train)
    d_in = int(x_train.shape[1])

    # restore per-member state at a COMMON generation (drain saves every
    # member at the same boundary; a member missing that step restores its
    # newest earlier one and replays — the generation stream is a pure
    # function of (seed, g), so the replay is deterministic)
    ckptrs = _member_checkpointers(cctx)
    latest = [c.latest_step() if c is not None else None for c in ckptrs]
    start_gen = 0
    restore_at = None
    if all(s is not None for s in latest) and latest:
        restore_at = min(latest)
        start_gen = restore_at + 1

    member_states = []
    params_list = []
    for i in range(k):
        restored = None
        if restore_at is not None and ckptrs[i] is not None:
            steps_i = ckptrs[i].all_steps()
            at = restore_at if restore_at in steps_i else max(
                (s for s in steps_i if s <= restore_at), default=None
            )
            restored = ckptrs[i].restore(step=at) if at is not None else None
        if restored is not None:
            state_i, _ = restored
            member_states.append(
                {
                    "params": jax.tree_util.tree_map(
                        jnp.asarray, state_i["params"]
                    ),
                    "velocity": jax.tree_util.tree_map(
                        jnp.asarray, state_i["velocity"]
                    ),
                    "step": jnp.asarray(int(state_i["step"]), jnp.int32),
                }
            )
            hyp = state_i.get("hypers")
            if hyp is not None:
                params_list.append(
                    decode_member_hypers(
                        specs, {n: np.asarray([float(v)]) for n, v in hyp.items()}, 0
                    )
                )
            else:
                params_list.append(cctx.params_list[i])
        else:
            # identical init across members (host trial parity: PRNGKey(0))
            prm = _init_params(jax.random.PRNGKey(0), d_in, 10)
            member_states.append(
                {
                    "params": prm,
                    "velocity": jax.tree_util.tree_map(jnp.zeros_like, prm),
                    "step": jnp.asarray(0, jnp.int32),
                }
            )
            params_list.append(cctx.params_list[i])

    # ghost rows repeat member 0 (inert; never win, never cloned)
    member_states += [member_states[0]] * (p - k)
    states = cctx.place_members(stack_pytrees(member_states))
    hypers = cctx.place_members(encode_hypers(specs, params_list, p))

    def member_step(state, hrow, mb):
        x, y = mb
        lr = hrow["lr"]
        grads = jax.grad(_loss)(state["params"], x, y)
        velocity = jax.tree_util.tree_map(
            lambda v, g: 0.9 * v + g, state["velocity"], grads
        )
        params = jax.tree_util.tree_map(
            lambda pp, v: pp - lr * v, state["params"], velocity
        )
        return {"params": params, "velocity": velocity, "step": state["step"] + 1}

    def member_eval(state, ev):
        x, y = ev
        return (jnp.argmax(_logits(state["params"], x), axis=-1) == y).mean()

    gen_step = make_pbt_generation_step(
        member_step,
        member_eval,
        specs=specs,
        k=k,
        truncation=truncation,
        resample_p=resample_p,
        mesh=cctx.cohort_mesh,
    )

    obs.pbt_onchip.set(1.0)
    try:
        for g in range(start_gen, generations):
            # per-generation streams are pure functions of (seed, g): a
            # same-seed rerun is bit-stable and a resumed run replays the
            # exact generation it drained out of
            idx = jnp.asarray(
                np.random.default_rng((seed, g)).integers(
                    0, n_train, size=(steps, batch)
                ),
                jnp.int32,
            )
            key_g = jax.random.fold_in(jax.random.PRNGKey(seed), g)
            if g == start_gen:
                costmodel.observe_program(
                    ("pbt_digits.generation", k, p, steps, batch, _HIDDEN),
                    gen_step,
                    (states, hypers, key_g, idx, data, eval_batch),
                    program="pbt_digits_cohort.generation",
                    steps=steps,
                    dtype="f32",
                )
            started = _time.perf_counter()
            states, hypers, _key, scores, parent, exploited = gen_step(
                states, hypers, key_g, idx, data, eval_batch
            )
            # generation boundary: the ONLY host transfers in the loop
            scores_np = np.asarray(scores)[:k]
            parent_np = np.asarray(parent)[:k].astype(int)
            expl_np = np.asarray(exploited)[:k].astype(bool)
            n_exploits = int(expl_np.sum())
            n_winners = len(set(parent_np[expl_np]))
            obs.pbt_generations.inc()
            if n_exploits:
                obs.pbt_exploits.inc(float(n_exploits))
            tracing.record_span(
                "pbt-generation",
                _time.perf_counter() - started,
                generation=g,
                exploits=n_exploits,
                winners=n_winners,
                perturbs=k - n_exploits,
                population=k,
            )
            # lineage, exactly as the host path labels next-gen trials:
            # exploiters point at their winner, explorers at themselves
            for i, t in enumerate(cctx.members):
                t.spec.labels[GENERATION_LABEL] = str(g + 1)
                t.spec.labels[PARENT_LABEL] = (
                    cctx.members[parent_np[i]].name if expl_np[i] else t.name
                )
            # an exploited member's row now carries its winner's state, so
            # report the score of what the member actually holds (a
            # diverged member heals through the exploit path instead of
            # settling Permanent-failed on a non-finite row)
            report_acc = scores_np[parent_np]
            cont = cctx.report(
                step=g,
                accuracy=report_acc,
                pbt_generation=np.full(k, float(g + 1)),
                pbt_parent=parent_np.astype(float),
                pbt_exploit=expl_np.astype(float),
            )
            # stacked-population checkpoint at the generation boundary:
            # drain/resume re-enters the loop at start_gen = g + 1 with
            # zero lost members.  The member saves overlap in a thread
            # pool — each Orbax commit is fsync/rename-bound, and serial
            # saves would cost more than the generation dispatch itself.
            host_states = jax.device_get(states)
            host_hypers = {n: np.asarray(v) for n, v in hypers.items()}

            def _save_member(i: int) -> None:
                row = jax.tree_util.tree_map(lambda x: x[i], host_states)
                ckptrs[i].save(
                    {
                        "params": row["params"],
                        "velocity": row["velocity"],
                        "step": np.asarray(int(row["step"])),
                        "hypers": {
                            n: np.float32(v[i]) for n, v in host_hypers.items()
                        },
                        "generation": np.asarray(g),
                    },
                    g,
                )

            with ThreadPoolExecutor(max_workers=min(8, k)) as pool:
                # list() re-raises the first member-save failure
                list(
                    pool.map(
                        _save_member,
                        [i for i in range(k) if ckptrs[i] is not None],
                    )
                )
            if not cont or cctx.should_stop():
                return
    finally:
        obs.pbt_onchip.set(0.0)


from katib_tpu.runner.cohort import attach_cohort_fn  # noqa: E402

attach_cohort_fn(pbt_digits_trial, pbt_digits_cohort)
