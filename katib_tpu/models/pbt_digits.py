"""PBT workload with REAL model state: a digits classifier whose weights,
momentum buffers, and step counter ride the PBT checkpoint lineage.

The toy workload (``pbt_toy.py``, reference ``simple-pbt`` parity) carries
one scalar through the lineage; this trial carries an actual JAX model —
exploit clones the winner's Orbax checkpoint (parameters + momentum +
step), explore perturbs the learning rate, and training *continues* from
the inherited weights on the bundled REAL UCI digits.  That is the full
PBT contract at model scale: the thing the reference moves between pods
with ``shutil.copytree`` on a RWX PVC (``pbt/service.py:259-268``), here
an Orbax pytree under the experiment workdir.

Trial params: ``lr`` (the evolved hyperparameter), ``steps_per_round``
(SGD minibatch steps per generation, default 60), ``batch`` (64).
Reports ``accuracy`` on the held-out split once per round.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from katib_tpu.models.data import Dataset, load_digits_real

_HIDDEN = 128

# same in-process cache pattern as mnist._cached_mnist: a PBT sweep calls
# this trial dozens of times per process; reload + re-permute each round
# would be pure waste
_DATASET_CACHE: dict[tuple, Dataset] = {}


def _cached_digits(n_train: int, n_test: int) -> Dataset:
    key = (n_train, n_test)
    if key not in _DATASET_CACHE:
        _DATASET_CACHE[key] = load_digits_real(n_train, n_test)
    return _DATASET_CACHE[key]


def _init_params(key: jax.Array, d_in: int, num_classes: int) -> dict:
    k1, k2 = jax.random.split(key)
    s1 = (2.0 / d_in) ** 0.5
    s2 = (2.0 / _HIDDEN) ** 0.5
    return {
        "w1": s1 * jax.random.normal(k1, (d_in, _HIDDEN), jnp.float32),
        "b1": jnp.zeros((_HIDDEN,), jnp.float32),
        "w2": s2 * jax.random.normal(k2, (_HIDDEN, num_classes), jnp.float32),
        "b2": jnp.zeros((num_classes,), jnp.float32),
    }


def _logits(params: dict, x: jnp.ndarray) -> jnp.ndarray:
    h = jax.nn.relu(x @ params["w1"] + params["b1"])
    return h @ params["w2"] + params["b2"]


def _loss(params: dict, x: jnp.ndarray, y: jnp.ndarray) -> jnp.ndarray:
    logp = jax.nn.log_softmax(_logits(params, x))
    return -jnp.take_along_axis(logp, y[:, None], axis=1).mean()


@jax.jit
def _sgd_step(params: dict, velocity: dict, x, y, lr):
    grads = jax.grad(_loss)(params, x, y)
    velocity = jax.tree_util.tree_map(lambda v, g: 0.9 * v + g, velocity, grads)
    params = jax.tree_util.tree_map(lambda p, v: p - lr * v, params, velocity)
    return params, velocity


@jax.jit
def _accuracy(params: dict, x, y):
    return (jnp.argmax(_logits(params, x), axis=-1) == y).mean()


def pbt_digits_trial(ctx) -> None:
    lr = float(ctx.params["lr"])
    steps_per_round = int(ctx.params.get("steps_per_round", 60))
    batch = int(ctx.params.get("batch", 64))

    ds = _cached_digits(1400, 397)
    x_train = ds.x_train.reshape(len(ds.x_train), -1)
    x_test = jnp.asarray(ds.x_test.reshape(len(ds.x_test), -1))
    y_test = jnp.asarray(ds.y_test)

    restored = ctx.restore_checkpoint()
    if restored is not None:
        state, _ = restored
        params, velocity = state["params"], state["velocity"]
        start = int(state["step"]) + 1
    else:
        params = _init_params(jax.random.PRNGKey(0), x_train.shape[1], 10)
        velocity = jax.tree_util.tree_map(jnp.zeros_like, params)
        start = 0

    rng = np.random.default_rng(start)  # advance the data stream per round
    step = start
    for step in range(start, start + steps_per_round):
        idx = rng.integers(0, len(x_train), size=batch)
        params, velocity = _sgd_step(
            params, velocity, jnp.asarray(x_train[idx]), jnp.asarray(ds.y_train[idx]), lr
        )

    acc = float(_accuracy(params, x_test, y_test))
    ctx.report(step=step, accuracy=acc)
    ctx.save_checkpoint(
        {
            "params": jax.device_get(params),
            "velocity": jax.device_get(velocity),
            "step": np.asarray(step),
        },
        step,
    )
