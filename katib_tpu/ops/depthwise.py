"""Partitioner-safe convolution forms for the NAS cells.

Why not plain ``nn.Conv`` everywhere: XLA's SPMD partitioner miscompiles
grouped-convolution FILTER gradients when the enclosing jit carries a
device mesh with an idle ``model`` axis — measured on the 8-virtual-device
CPU backend (jax 0.9.0): the grouped kernel gradient comes back 100% wrong
(max|diff| == max|grad|) against both the unsharded f32 run and an f64
ground truth, while loss, input gradients, and ungrouped-conv gradients
stay exact.  Two of this framework's constructions hit that path:

- explicit depthwise convs (``feature_group_count=C`` in SepConv/DilConv);
- ANY conv whose parameters are ``nn.vmap``-stacked (the DARTS cell's
  per-edge mixed ops): jax's conv batching rule implements a vmapped
  kernel as a grouped convolution, so even innocent 1x1 convs inherit the
  corrupt gradient once vmapped.

A framework that promises "the same code path from one chip to a v5e-64
mesh" cannot ship ops whose gradients silently corrupt on some mesh
shapes, so both forms are reformulated in partitioner-safe primitives:

- :class:`DepthwiseConv` — K*K shifted multiply-accumulates (elementwise
  ops only).  Depthwise convs are bandwidth-bound on TPU either way (no
  MXU contraction) and XLA fuses the unrolled taps into one pass.
- :class:`PointwiseConv` — the 1x1 conv written as the matmul it is
  (``einsum nhwc,cf->nhwf``).  dot_general has first-class SPMD rules AND
  this is the MXU-native form; under ``nn.vmap`` it batches as a plain
  3-d einsum, never a grouped conv.

``tests/test_depthwise.py`` pins numerical equality with the ``nn.Conv``
forms on one device, and gradient parity across a dp x model mesh — the
exact case the conv forms corrupt — including under ``nn.vmap``.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp


class DepthwiseConv(nn.Module):
    """Per-channel KxK conv (SAME padding), formulation selected by ``safe``.

    Drop-in for ``nn.Conv(C, (K, K), feature_group_count=C, use_bias=False)``
    — same param layout (K, K, 1, C) and lecun-normal fan-in (K*K*1) in both
    modes, so flipping ``safe`` never changes the parameter tree.

    ``safe=False`` (default): the native grouped convolution — the fast
    form, and numerically exact on single devices and data-only meshes
    (verified to 2e-7 on an 8-way dp mesh).  ``safe=True``: the shift-MAC
    form for meshes with a ``model`` axis, where the grouped form's filter
    gradient is miscompiled (module doc).  The MAC unrolling costs real
    compile time (measured 3s -> 141s on the CPU bench at small shapes) and
    ~2x step time on CPU, so it is opt-in for exactly the mesh shapes that
    need it; callers that own a mesh (``run_darts_search``,
    ``dryrun_multichip``) set it from the mesh's axes.
    """

    kernel: int
    stride: int = 1
    dilation: int = 1
    dtype: jnp.dtype = jnp.bfloat16
    safe: bool = False

    @nn.compact
    def __call__(self, x):
        k, s, d = self.kernel, self.stride, self.dilation
        n, h, w, c = x.shape
        # shape matches nn.Conv's grouped kernel (KH, KW, in/groups=1, C)
        # so fan-in (and hence init scale) is identical: K*K*1
        kern = self.param(
            "kernel", nn.initializers.lecun_normal(), (k, k, 1, c), jnp.float32
        )
        if not self.safe:
            return jax.lax.conv_general_dilated(
                x.astype(self.dtype),
                kern.astype(self.dtype),
                window_strides=(s, s),
                padding="SAME",
                rhs_dilation=(d, d),
                dimension_numbers=("NHWC", "HWIO", "NHWC"),
                feature_group_count=c,
            )
        extent = (k - 1) * d + 1
        out_h, out_w = -(-h // s), -(-w // s)
        pad_h = max((out_h - 1) * s + extent - h, 0)
        pad_w = max((out_w - 1) * s + extent - w, 0)
        xp = jnp.pad(
            x.astype(self.dtype),
            (
                (0, 0),
                (pad_h // 2, pad_h - pad_h // 2),
                (pad_w // 2, pad_w - pad_w // 2),
                (0, 0),
            ),
        )
        kern = kern.astype(self.dtype)
        out = None
        for i in range(k):
            for j in range(k):
                tap = xp[
                    :,
                    i * d : i * d + (out_h - 1) * s + 1 : s,
                    j * d : j * d + (out_w - 1) * s + 1 : s,
                    :,
                ]
                term = tap * kern[i, j, 0]
                out = term if out is None else out + term
        return out


class PointwiseConv(nn.Module):
    """1x1 conv as the einsum it is (see module doc for why not nn.Conv).

    Drop-in for ``nn.Conv(F, (1, 1), strides=(s, s), use_bias=...)``: a
    1x1 kernel with SAME padding and stride s is subsampling followed by a
    per-pixel matmul.  Param shape (C, F) gives lecun-normal fan-in C —
    identical to nn.Conv's (1, 1, C, F).
    """

    features: int
    stride: int = 1
    use_bias: bool = False
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        c = x.shape[-1]
        kern = self.param(
            "kernel", nn.initializers.lecun_normal(), (c, self.features), jnp.float32
        )
        if self.stride > 1:
            x = x[:, :: self.stride, :: self.stride, :]
        out = jnp.einsum(
            "nhwc,cf->nhwf", x.astype(self.dtype), kern.astype(self.dtype)
        )
        if self.use_bias:
            bias = self.param(
                "bias", nn.initializers.zeros_init(), (self.features,), jnp.float32
            )
            out = out + bias.astype(self.dtype)
        return out
