"""Fused softmax-weighted mixed-op contraction as a Pallas TPU kernel.

The DARTS supernet's :class:`~katib_tpu.nas.darts.ops.MixedOp` ends in
``einsum("o,onhwc->nhwc", weights, stacked)`` — a weighted sum over the
stacked primitive outputs.  The AOT cost analysis puts that contraction's
bytes-accessed term at the top of the supernet cell (the stacked tensor is
``n_ops`` full activations wide), and at 0.55% MFU the search is bound by
exactly this kind of bytes-over-FLOPs op.  This kernel fuses the weighting
and the accumulation into ONE pass over the stacked tensor: each grid step
streams an ``(n_ops, TILE)`` block through VMEM and contracts it against the
``(1, n_ops)`` weight row on the MXU with f32 accumulation, so the stacked
activations are read exactly once and no intermediate ``n_ops``-wide product
is materialized in HBM.

Exposure:

- :func:`mixed_op_sum` is the public entry point; the backward pass is a
  ``jax.custom_vjp`` in plain lax (two bandwidth-bound contractions XLA
  already fuses well), so ``jax.grad``/``nn.vmap``/``lax.scan`` all compose
  — the vmapped stacked-alpha MixedOp in ``nas/darts/model.py`` batches the
  kernel through pallas_call's vmap rule.
- ``KATIB_PALLAS_MIXED_OP`` selects the implementation:
  ``auto`` (default) — compiled Pallas on TPU backends, lax reference
  elsewhere (CPU numerics stay bit-identical to the pre-kernel einsum);
  ``1``/``pallas`` — force the kernel (interpret mode off-TPU, so forcing
  works everywhere); ``interpret`` — force ``interpret=True`` (the CPU test
  path); ``0``/``lax`` — force the einsum reference.
"""

from __future__ import annotations

import functools
import math
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

# one (n_ops, TILE) block per grid step: at n_ops=8 / f32 that is ~16 KiB of
# VMEM per operand block, far under budget, and 512 lanes keep the trailing
# dim aligned to the (8, 128) f32 tile
_TILE = 512

_VALID_MODES = ("auto", "pallas", "interpret", "lax")


def _mode() -> str:
    raw = os.environ.get("KATIB_PALLAS_MIXED_OP", "auto").strip().lower()
    if raw in ("", "auto"):
        return "auto"
    if raw in ("1", "true", "yes", "on", "pallas"):
        return "pallas"
    if raw == "interpret":
        return "interpret"
    if raw in ("0", "false", "no", "off", "lax"):
        return "lax"
    raise ValueError(
        f"KATIB_PALLAS_MIXED_OP={raw!r} is not one of {_VALID_MODES}"
    )


def _lax_reference(weights: jnp.ndarray, stacked: jnp.ndarray) -> jnp.ndarray:
    """The pre-kernel einsum, verbatim — the parity baseline and the default
    on non-TPU backends (keeps CPU numerics bit-identical to the seed)."""
    return jnp.einsum(
        "o,o...->...", weights.astype(stacked.dtype), stacked
    )


def _kernel(w_ref, x_ref, o_ref):
    # (1, n_ops) @ (n_ops, TILE) on the MXU, f32 accumulation regardless of
    # the activation dtype (bf16 stacked inputs upcast per-block)
    o_ref[...] = jnp.dot(
        w_ref[...],
        x_ref[...].astype(jnp.float32),
        preferred_element_type=jnp.float32,
    ).astype(o_ref.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _pallas_mixed_op(weights, stacked, interpret):
    return _pallas_fwd_impl(weights, stacked, interpret)


def _pallas_fwd_impl(weights, stacked, interpret):
    n_ops = stacked.shape[0]
    out_shape = stacked.shape[1:]
    m = math.prod(out_shape)
    tile = min(_TILE, m)
    # columns of the flattened activation are independent, so the padded
    # tail of the last block is write-masked garbage we simply never read
    out = pl.pallas_call(
        _kernel,
        grid=(pl.cdiv(m, tile),),
        in_specs=[
            pl.BlockSpec((1, n_ops), lambda i: (0, 0)),
            pl.BlockSpec((n_ops, tile), lambda i: (0, i)),
        ],
        out_specs=pl.BlockSpec((1, tile), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct((1, m), stacked.dtype),
        interpret=interpret,
    )(
        weights.astype(jnp.float32).reshape(1, n_ops),
        stacked.reshape(n_ops, m),
    )
    return out.reshape(out_shape)


def _fwd(weights, stacked, interpret):
    return _pallas_fwd_impl(weights, stacked, interpret), (weights, stacked)


def _bwd(interpret, residuals, g):
    weights, stacked = residuals
    # backward in plain lax: dw is a full reduction over the activation
    # (f32-accumulated), dx a rank-1 broadcast — both bandwidth-bound ops
    # XLA fuses into neighbors, so a hand kernel buys nothing here
    dw = jnp.einsum(
        "o...,...->o",
        stacked.astype(jnp.float32),
        g.astype(jnp.float32),
    ).astype(weights.dtype)
    dx = (
        weights.astype(g.dtype).reshape((-1,) + (1,) * g.ndim) * g[None]
    ).astype(stacked.dtype)
    return dw, dx


_pallas_mixed_op.defvjp(_fwd, _bwd)


def mixed_op_sum(weights: jnp.ndarray, stacked: jnp.ndarray) -> jnp.ndarray:
    """``sum_o weights[o] * stacked[o]`` over the leading (op) axis.

    ``weights``: ``(n_ops,)`` softmax over one edge's alphas.
    ``stacked``: ``(n_ops, *activation)`` stacked primitive outputs.
    Implementation selected by ``KATIB_PALLAS_MIXED_OP`` (module doc).
    """
    mode = _mode()
    if mode == "lax":
        return _lax_reference(weights, stacked)
    if mode == "auto":
        if jax.default_backend() == "tpu":
            return _pallas_mixed_op(weights, stacked, False)
        return _lax_reference(weights, stacked)
    if mode == "pallas":
        return _pallas_mixed_op(
            weights, stacked, jax.default_backend() != "tpu"
        )
    return _pallas_mixed_op(weights, stacked, True)
