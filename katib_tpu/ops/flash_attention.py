"""Blockwise (flash) attention as Pallas TPU kernels.

The reference delegates all attention math to PyTorch/TF inside trial
containers (it has none of its own — SURVEY.md §2.4); here attention is a
first-class fused kernel so HP/NAS search over transformer trials runs at
MXU speed without materialising the [S, S] score matrix in HBM.

Design (FlashAttention-2 style, adapted to the TPU memory hierarchy):

- forward: grid over (batch, head, q-block); K/V stream through VMEM while
  an online softmax keeps running (max, sum, output) accumulators in f32.
  Emits the per-row logsumexp so sequence-parallel ring attention
  (``katib_tpu.parallel.ring_attention``) can merge partial results from
  other sequence shards.
- backward: two kernels — dq over q-blocks, dk/dv over k-blocks — that
  recompute probabilities from the saved logsumexp instead of storing the
  score matrix (rematerialisation trades FLOPs for HBM, the TPU-native
  default).
- both are exposed through one ``jax.custom_vjp`` so ``jax.grad`` composes
  with jit/shard_map/scan.

On non-TPU backends (CPU tests, the 8-device virtual mesh) the kernels run
in interpreter mode automatically; numerics match a dense jnp reference to
~1e-5 (f32).
"""

from __future__ import annotations

import functools
import math
from typing import Any

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = float("-inf")
_MASK_VALUE = -1e30  # large-negative instead of -inf inside kernels (no NaNs)


def _interpret_default() -> bool:
    return jax.default_backend() != "tpu"


def _block_sizes(seq_q: int, seq_k: int, block_q: int, block_k: int):
    bq = min(block_q, seq_q)
    bk = min(block_k, seq_k)
    if seq_q % bq or seq_k % bk:
        raise ValueError(
            f"block sizes ({bq}, {bk}) must divide sequence lengths ({seq_q}, {seq_k})"
        )
    return bq, bk


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, sm_scale, causal, block_k, shift):
    """``shift = seq_k - seq_q`` makes the causal mask bottom-right aligned
    (last query row sees every key), matching ``reference_attention_with_lse``
    for seq_q != seq_k; both collapse to the usual mask when shift == 0."""
    bq, d = q_ref.shape[-2], q_ref.shape[-1]
    seq_k = k_ref.shape[-2]
    n_kb = seq_k // block_k
    qi = pl.program_id(2)
    q = q_ref[0, 0, :, :].astype(jnp.float32) * sm_scale

    if causal:
        # only k-blocks starting at or before the last query row's diagonal
        last_col = jnp.maximum((qi + 1) * bq + shift, 0)
        n_kb_live = jnp.clip(pl.cdiv(last_col, block_k), 0, n_kb)
    else:
        n_kb_live = n_kb

    def body(j, carry):
        o_acc, m_acc, l_acc = carry
        k = k_ref[0, 0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, 0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [bq, block_k]
        if causal:
            rows = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)
            cols = j * block_k + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 1)
            mask = cols <= rows + shift
            s = jnp.where(mask, s, _MASK_VALUE)
        m_new = jnp.maximum(m_acc, jnp.max(s, axis=1))
        # mask the exponent, not just the score: a fully-masked row has
        # s == m_new == _MASK_VALUE, where exp(s - m_new) would be exp(0)=1
        e = s - m_new[:, None]
        if causal:
            e = jnp.where(mask, e, _MASK_VALUE)
        p = jnp.exp(e)
        alpha = jnp.exp(m_acc - m_new)
        l_new = l_acc * alpha + jnp.sum(p, axis=1)
        o_new = o_acc * alpha[:, None] + jnp.dot(
            p, v, preferred_element_type=jnp.float32
        )
        return o_new, m_new, l_new

    o0 = jnp.zeros((bq, d), jnp.float32)
    m0 = jnp.full((bq,), _MASK_VALUE, jnp.float32)
    l0 = jnp.zeros((bq,), jnp.float32)
    o, m, l = jax.lax.fori_loop(0, n_kb_live, body, (o0, m0, l0))

    l_safe = jnp.where(l == 0.0, 1.0, l)
    o_ref[0, 0, :, :] = (o / l_safe[:, None]).astype(o_ref.dtype)
    lse = jnp.where(l == 0.0, _MASK_VALUE, m + jnp.log(l_safe))
    # trailing singleton keeps the block 4-D: TPU tiling requires the last
    # two block dims divide (8, 128) or equal the array dims
    lse_ref[0, 0, :, 0] = lse


def _fwd(q, k, v, *, sm_scale, causal, block_q, block_k, interpret):
    b, h, sq, d = q.shape
    sk = k.shape[2]
    bq, bk = _block_sizes(sq, sk, block_q, block_k)
    grid = (b, h, sq // bq)
    o, lse = pl.pallas_call(
        functools.partial(
            _fwd_kernel, sm_scale=sm_scale, causal=causal, block_k=bk,
            shift=sk - sq,
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda i, j, l: (i, j, l, 0)),
            pl.BlockSpec((1, 1, sk, d), lambda i, j, l: (i, j, 0, 0)),
            pl.BlockSpec((1, 1, sk, d), lambda i, j, l: (i, j, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda i, j, l: (i, j, l, 0)),
            pl.BlockSpec((1, 1, bq, 1), lambda i, j, l: (i, j, l, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct((b, h, sq, 1), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)
    return o, lse[..., 0]


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dmd_ref, dq_ref, *, sm_scale, causal, block_k, shift):
    """dq for one q-block; streams K/V blocks.  ``dmd`` = rowsum(dO*O) - d_lse,
    folding the logsumexp cotangent into the usual flash "delta" term."""
    bq, d = q_ref.shape[-2], q_ref.shape[-1]
    seq_k = k_ref.shape[-2]
    n_kb = seq_k // block_k
    qi = pl.program_id(2)
    q = q_ref[0, 0, :, :].astype(jnp.float32)
    do = do_ref[0, 0, :, :].astype(jnp.float32)
    lse = lse_ref[0, 0, :, 0]
    dmd = dmd_ref[0, 0, :, 0]

    n_kb_live = (
        jnp.clip(pl.cdiv(jnp.maximum((qi + 1) * bq + shift, 0), block_k), 0, n_kb)
        if causal
        else n_kb
    )

    def body(j, dq_acc):
        k = k_ref[0, 0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, 0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = sm_scale * jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        e = s - lse[:, None]
        if causal:
            rows = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)
            cols = j * block_k + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 1)
            e = jnp.where(cols <= rows + shift, e, _MASK_VALUE)
        p = jnp.exp(e)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - dmd[:, None])
        return dq_acc + sm_scale * jnp.dot(ds, k, preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(0, n_kb_live, body, jnp.zeros((bq, d), jnp.float32))
    dq_ref[0, 0, :, :] = dq.astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dmd_ref, dk_ref, dv_ref, *, sm_scale, causal, block_q, shift):
    """dk, dv for one k-block; streams q-blocks (with their dO/lse/delta rows)."""
    bk, d = k_ref.shape[-2], k_ref.shape[-1]
    seq_q = q_ref.shape[-2]
    n_qb = seq_q // block_q
    ki = pl.program_id(2)
    k = k_ref[0, 0, :, :].astype(jnp.float32)
    v = v_ref[0, 0, :, :].astype(jnp.float32)

    # with causal masking, q-blocks strictly above this k-block's diagonal
    # (bottom-right aligned: row + shift >= col) contribute 0
    first_qb = jnp.maximum(0, ki * bk - shift) // block_q if causal else 0

    def body(i, carry):
        dk_acc, dv_acc = carry
        q = q_ref[0, 0, pl.ds(i * block_q, block_q), :].astype(jnp.float32)
        do = do_ref[0, 0, pl.ds(i * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[0, 0, pl.ds(i * block_q, block_q), 0]
        dmd = dmd_ref[0, 0, pl.ds(i * block_q, block_q), 0]
        s = sm_scale * jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [block_q, bk]
        e = s - lse[:, None]
        if causal:
            rows = i * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, bk), 0)
            cols = ki * bk + jax.lax.broadcasted_iota(jnp.int32, (block_q, bk), 1)
            e = jnp.where(cols <= rows + shift, e, _MASK_VALUE)
        p = jnp.exp(e)
        dv_new = dv_acc + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - dmd[:, None])
        dk_new = dk_acc + sm_scale * jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return dk_new, dv_new

    z = jnp.zeros((bk, d), jnp.float32)
    dk, dv = jax.lax.fori_loop(first_qb, n_qb, body, (z, z))
    dk_ref[0, 0, :, :] = dk.astype(dk_ref.dtype)
    dv_ref[0, 0, :, :] = dv.astype(dv_ref.dtype)


def _bwd(q, k, v, o, lse, do, dlse, *, sm_scale, causal, block_q, block_k, interpret):
    b, h, sq, d = q.shape
    sk = k.shape[2]
    bq, bk = _block_sizes(sq, sk, block_q, block_k)
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    dmd = delta - dlse.astype(jnp.float32)  # [b, h, sq]
    lse4 = lse[..., None]
    dmd4 = dmd[..., None]

    dq = pl.pallas_call(
        functools.partial(
            _dq_kernel, sm_scale=sm_scale, causal=causal, block_k=bk, shift=sk - sq
        ),
        grid=(b, h, sq // bq),
        in_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda i, j, l: (i, j, l, 0)),
            pl.BlockSpec((1, 1, sk, d), lambda i, j, l: (i, j, 0, 0)),
            pl.BlockSpec((1, 1, sk, d), lambda i, j, l: (i, j, 0, 0)),
            pl.BlockSpec((1, 1, bq, d), lambda i, j, l: (i, j, l, 0)),
            pl.BlockSpec((1, 1, bq, 1), lambda i, j, l: (i, j, l, 0)),
            pl.BlockSpec((1, 1, bq, 1), lambda i, j, l: (i, j, l, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, bq, d), lambda i, j, l: (i, j, l, 0)),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        interpret=interpret,
    )(q, k, v, do, lse4, dmd4)

    dk, dv = pl.pallas_call(
        functools.partial(
            _dkv_kernel, sm_scale=sm_scale, causal=causal, block_q=bq, shift=sk - sq
        ),
        grid=(b, h, sk // bk),
        in_specs=[
            pl.BlockSpec((1, 1, sq, d), lambda i, j, l: (i, j, 0, 0)),
            pl.BlockSpec((1, 1, bk, d), lambda i, j, l: (i, j, l, 0)),
            pl.BlockSpec((1, 1, bk, d), lambda i, j, l: (i, j, l, 0)),
            pl.BlockSpec((1, 1, sq, d), lambda i, j, l: (i, j, 0, 0)),
            pl.BlockSpec((1, 1, sq, 1), lambda i, j, l: (i, j, 0, 0)),
            pl.BlockSpec((1, 1, sq, 1), lambda i, j, l: (i, j, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, bk, d), lambda i, j, l: (i, j, l, 0)),
            pl.BlockSpec((1, 1, bk, d), lambda i, j, l: (i, j, l, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        interpret=interpret,
    )(q, k, v, do, lse4, dmd4)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# public API (custom VJP)
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def flash_attention_with_lse(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    sm_scale: float | None = None,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool | None = None,
) -> tuple[jax.Array, jax.Array]:
    """Fused attention over [batch, heads, seq, head_dim] inputs.

    Returns ``(output, logsumexp)``; the logsumexp output makes this the
    mergeable building block for ring attention.  Rows with every key masked
    produce output 0 and logsumexp ≈ -1e30 (an exact no-op when merged).
    """
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(q.shape[-1])
    itp = _interpret_default() if interpret is None else interpret
    return _fwd(q, k, v, sm_scale=scale, causal=causal, block_q=block_q, block_k=block_k, interpret=itp)


def _vjp_fwd(q, k, v, causal, sm_scale, block_q, block_k, interpret):
    o, lse = flash_attention_with_lse(
        q, k, v, causal, sm_scale, block_q, block_k, interpret
    )
    return (o, lse), (q, k, v, o, lse)


def _vjp_bwd(causal, sm_scale, block_q, block_k, interpret, res, cts):
    q, k, v, o, lse = res
    do, dlse = cts
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(q.shape[-1])
    itp = _interpret_default() if interpret is None else interpret
    return _bwd(
        q, k, v, o, lse, do, dlse,
        sm_scale=scale, causal=causal, block_q=block_q, block_k=block_k,
        interpret=itp,
    )


flash_attention_with_lse.defvjp(_vjp_fwd, _vjp_bwd)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    sm_scale: float | None = None,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool | None = None,
) -> jax.Array:
    """Standard entry point: fused attention output only."""
    o, _ = flash_attention_with_lse(
        q, k, v, causal, sm_scale, block_q, block_k, interpret
    )
    return o


# ---------------------------------------------------------------------------
# dense reference (tests + tiny shapes where kernel overhead dominates)
# ---------------------------------------------------------------------------


def reference_attention_with_lse(
    q: jax.Array, k: jax.Array, v: jax.Array, causal: bool = True,
    sm_scale: float | None = None,
) -> tuple[jax.Array, jax.Array]:
    """O(S^2)-memory jnp attention returning (output, logsumexp)."""
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(q.shape[-1])
    s = jnp.einsum(
        "bhqd,bhkd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32)
    ) * scale
    visible = None
    if causal:
        sq, sk = q.shape[2], k.shape[2]
        mask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        s = jnp.where(mask, s, _MASK_VALUE)
        if sq > sk:
            visible = mask.any(-1)  # rows before the diagonal see no key
    lse_raw = jax.scipy.special.logsumexp(s, axis=-1)
    if visible is None:
        lse = lse_raw
        p = jnp.exp(s - lse[..., None])
    else:
        # fully-masked rows: output 0 and lse=_MASK_VALUE (a no-op when
        # merged), matching the kernel, instead of uniform-attention junk
        lse = jnp.where(visible, lse_raw, _MASK_VALUE)
        p = jnp.exp(s - jnp.where(visible, lse_raw, 0.0)[..., None])
    o = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))
    return o.astype(q.dtype), lse


def reference_attention(q, k, v, *, causal: bool = True, sm_scale=None) -> jax.Array:
    o, _ = reference_attention_with_lse(q, k, v, causal, sm_scale)
    return o
