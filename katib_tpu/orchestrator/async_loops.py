"""Podracer-style asynchronous orchestration: three decoupled loops joined
by bounded queues (the Podracer architectures pattern from PAPERS.md applied
to HPO control flow).

The synchronous run loop interleaves propose -> execute -> harvest on one
thread, so the mesh idles whenever the suggester is thinking, a cohort is
short of members, or harvest is settling.  This engine splits the loop:

- **suggest loop** (thread): keeps ``suggest_lookahead`` proposals journaled
  and ready ahead of the scheduler, so suggester latency hides behind
  training instead of gating dispatch.  Budget-aware: never materializes
  past ``max_trial_count``.
- **schedule loop** (thread): heterogeneous cohort packing — ready trials
  accumulate into per-key shape buckets (``compile/buckets.py`` pads the
  dispatched width to a power of two, so a 5-member flush reuses the
  8-wide executable) and flush on *any* of: full width, the
  ``cohort_fill_deadline_seconds`` timeout, suggester exhaustion, or a
  remaining budget that can never fill the bucket — a partial cohort never
  waits indefinitely.  Dispatch backpressure is driven by slot occupancy
  (``occupancy_target``) rather than a fixed trial count, and each flushed
  bucket's compile signature feeds the prewarm worker before submit.
- **harvest loop** (thread): settles completions through the exactly-once
  journal path (``Orchestrator._harvest``) and owns terminal verdicts,
  stop, drain, and the livelock guard.

The caller's thread runs the :class:`~katib_tpu.orchestrator.supervisor.
LoopSupervisor` tick loop: all three loops are heartbeated via progress
watermarks, classified (OK / STALLED / STARVED / CRASHED / DONE), and
crashed or stalled loops are respawned at ``generation+1`` with frontier
state re-seeded from the journal-backed trial map (``_reseed_lost``) under
a bounded per-loop restart budget; stale-generation threads are fenced out
of shared state by generation checks at every iteration and hand-off.
After the budget is exhausted ``run()`` returns ``None`` and
``Orchestrator.run`` degrades to the synchronous loop instead of dying.

With ``speculativeRedispatch`` on, the harvest loop also re-dispatches a
straggling member (running past ``stragglerFactor`` x the median settle
time) as a singleton rival on a free slot: the rival executes a *clone* of
the Trial, and first-settle-wins is enforced by object identity — the
winner's object is (or becomes) ``exp.trials[name]``, the loser's eventual
result hits ``Orchestrator._harvest``'s stale-owner guard and is
discarded, so the (trial, attempt-epoch) journal keying never sees a
second settle.

The event journal is the coordination substrate: ``proposed`` (suggest),
``queued`` (entered a packing bucket), ``started`` (dispatched) and the
existing ``settled`` records mean a crash at any hand-off point leaves
non-terminal trials that resume re-seeds into the ready queue —
exactly-once settlement keyed on (trial, retry epoch) is unchanged.

Locking discipline (acquire order: state > queue > futures):

- ``_state_lock`` — inserts into ``exp.trials`` (materialize) vs the
  iterations harvest / ``update_optimal`` / terminal checks perform.  The
  suggester call itself runs OUTSIDE the lock (only its own thread
  inserts), so a slow suggester never stalls settlement or dispatch.
- ``_queue_lock`` — the ready deque, packing buckets, and dispatch queue
  move atomically, so the terminal check can never observe a trial
  "in neither queue nor futures" mid-hand-off.
- ``_futures_lock`` — the shared futures dict (scheduler inserts while
  harvest iterates).

Pool threads (``_execute`` / ``_execute_cohort``) take no engine locks, so
the mesh critical path is untouched.
"""

from __future__ import annotations

import collections
import copy
import statistics
import threading
import traceback

from katib_tpu.analysis import guarded_by, make_lock
from katib_tpu.utils.clock import get_clock
from katib_tpu.core.types import (
    COHORT_KEY_LABEL,
    Experiment,
    ExperimentCondition,
    Trial,
    TrialCondition,
)
from katib_tpu.runner.cohort import cohort_fn_of
from katib_tpu.suggest.base import call_suggester
from katib_tpu.utils import observability as obs
from katib_tpu.utils import tracing

#: how long the wind-down waits for the suggest/schedule threads to notice
#: the halt flag (a suggester blocked mid-call is abandoned on its daemon
#: thread — the breaker/watchdog own misbehaving suggesters, not drain)
_JOIN_TIMEOUT = 5.0

#: livelock guard threshold, matching the synchronous loop's 30s stall cap
_STALL_SECONDS = 30.0


class OccupancyMeter:
    """Time-weighted mean busy-slot fraction.

    The clock starts lazily at the FIRST dispatch (running > 0), so the
    unavoidable cold ramp — the first suggester call before any trial can
    exist — does not dilute the sustained number; what is measured is
    "once work started flowing, how full did the mesh stay".
    """

    def __init__(self, slots: int):
        self.slots = max(1, int(slots))
        self._t0: float | None = None
        self._last = 0.0
        self._frac = 0.0
        self._area = 0.0

    def update(self, busy: int) -> float:
        now = get_clock().monotonic()
        frac = min(1.0, busy / self.slots)
        if self._t0 is None:
            if busy <= 0:
                return frac
            self._t0 = self._last = now
            self._frac = frac
            return frac
        self._area += self._frac * (now - self._last)
        self._last = now
        self._frac = frac
        return frac

    def elapsed(self) -> float:
        return 0.0 if self._t0 is None else self._last - self._t0

    def sustained(self) -> float:
        el = self.elapsed()
        return (self._area / el) if el > 0 else 0.0


class AsyncLoops:
    """One experiment's async engine; ``run()`` replaces the synchronous
    while-loop body inside ``Orchestrator.run``'s pool context and returns
    the terminal (or drained) experiment."""

    # the queues move together (see the module docstring's discipline
    # section), and the dispatch/consumption counters move WITH the queues
    # they describe — the suggest loop's bank-deficit estimate must read
    # both under the same lock or the refill races the scheduler's drain.
    # The futures-side set covers everything the scheduler inserts while
    # the harvest thread iterates, including the speculation bookkeeping.
    _GUARDS = guarded_by(
        _queue_lock=(
            "_ready", "_packing", "_pack_ts", "_dispatchq",
            "_dispatched_total", "_consumed_last_call",
        ),
        _futures_lock=(
            "futures", "_fut_meta", "_rivals", "_speculated",
            "_settle_durations",
        ),
    )

    def __init__(
        self,
        orch,
        exp: Experiment,
        suggester,
        early_stopper,
        mesh,
        pool,
        breaker,
        stop_event: threading.Event,
        drain_event: threading.Event,
        futures: dict,
        initial_ready: list[Trial] = (),
    ):
        self.orch = orch
        self.exp = exp
        self.spec = exp.spec
        self.suggester = suggester
        self.early_stopper = early_stopper
        self.mesh = mesh
        self.pool = pool
        self.breaker = breaker
        self.stop_event = stop_event
        self.drain_event = drain_event
        self.futures = futures

        self._state_lock = make_lock("async.state")
        self._queue_lock = make_lock("async.queue")
        self._futures_lock = make_lock("async.futures")

        #: proposed trials awaiting packing (suggest -> schedule hand-off)
        self._ready: collections.deque[Trial] = collections.deque(initial_ready)
        #: per-cohort-key packing buckets + first-arrival timestamps
        self._packing: dict[str, list[Trial]] = {}
        self._pack_ts: dict[str, float] = {}
        #: flushed units awaiting a free slot (schedule -> pool hand-off)
        self._dispatchq: collections.deque[list[Trial]] = collections.deque()

        self._halt = threading.Event()       # internal: stop all three loops
        self._exhausted = threading.Event()  # suggester returned exhausted
        self._suggest_inflight = False       # a get_suggestions call is running
        self._suggester_busy = False         # erroring / cooling down, not idle
        self._last_activity = get_clock().monotonic()
        #: terminal/drained result hand-off from the harvest thread to the
        #: supervising caller thread
        self._result: Experiment | None = None
        self._done = threading.Event()
        #: first-finalizer-wins guard: a restarted-over stale harvest thread
        #: waking up mid-wind-down must not run _terminal/_drain twice
        self._finalize_once = make_lock("async.finalize")
        self._finalized = False
        self._supervisor = None  # LoopSupervisor, built in run()
        self._fallback_reason: str | None = None
        #: last crash traceback per loop, for the journal's supervisor events
        self._loop_errors: dict[str, str] = {}
        # -- speculative straggler re-dispatch bookkeeping --------------------
        #: future -> dispatch time (monotonic), for settle-duration medians
        #: and straggler detection; guarded by _futures_lock
        self._fut_meta: dict = {}
        self._settle_durations: list[float] = []
        #: rival future -> (original future, trial name, clone trial)
        self._rivals: dict = {}
        self._speculated: set[str] = set()  # one rival per trial per run
        self._spec_wins = 0
        #: members dispatched since engine start (consumption-rate estimator
        #: for the suggest loop's anticipatory refill)
        self._dispatched_total = 0
        self._consumed_last_call = 0
        #: set by _submit; the harvest loop owes a status.json publish
        self._publish_dirty = False

        spec = self.spec
        trial_devices = 1
        if mesh is not None:
            from katib_tpu.parallel.mesh import trial_axis_size

            trial_devices = trial_axis_size(mesh)
        self.width = max(spec.cohort_width, trial_devices)
        self._use_cohorts = self.width > 1 and cohort_fn_of(spec.train_fn) is not None
        self._default_key = spec.cohort_key or (
            orch._TRIAL_MESH_KEY if trial_devices > 1 else None
        )
        # proposal lookahead: deep for non-adaptive suggesters (the points
        # never depend on results), clamped to the in-flight width for
        # adaptive ones (ASHA/BO/PBT) — racing them ahead of observations
        # burns the budget on uninformed proposals (see Suggester.adaptive)
        base_width = max(spec.parallel_trial_count, self.width)
        self.lookahead = spec.suggest_lookahead or (
            base_width if getattr(suggester, "adaptive", True) else 4 * base_width
        )
        # occupancy backpressure, counted in MEMBER trials (a cohort future
        # carries width members on one slot): ``parallel_trial_count`` is
        # the concurrency contract the sync loop enforces via _shortfall,
        # scaled down by occupancy_target to deliberately throttle.  A unit
        # wider than the limit dispatches alone (the sync loop can never
        # build one, but an explicit suggestLookahead + wide mesh can).
        self.member_limit = max(
            1, round(spec.parallel_trial_count * spec.occupancy_target)
        )
        self.meter = OccupancyMeter(spec.parallel_trial_count)

    # -- entry point ---------------------------------------------------------

    def run(self) -> Experiment | None:
        """Run to a terminal (or drained) experiment under supervision.
        Returns ``None`` when the supervisor exhausted its restart budget:
        the caller (``Orchestrator.run``) then degrades to the synchronous
        loop — in-flight futures stay live in the shared dict, and queued
        proposals were put back to PENDING for resubmission."""
        from katib_tpu.orchestrator.supervisor import LoopSupervisor
        from katib_tpu.utils.faults import Backoff

        spec = self.spec
        sup = self._supervisor = LoopSupervisor(
            stall_deadline=spec.loop_stall_deadline_seconds,
            restart_budget=spec.loop_restart_budget,
            backoff=Backoff(base=0.2, factor=2.0, cap=5.0, full_jitter=True, seed=0),
            on_restart=self._on_loop_restart,
        )
        done_or_halt = lambda: self._halt.is_set() or self._done.is_set()
        sup.add(
            "suggest",
            self._spawner("suggest", self._suggest_loop),
            has_work=self._suggest_has_work,
            finished=lambda: done_or_halt() or self._exhausted.is_set(),
        )
        sup.add(
            "schedule",
            self._spawner("schedule", self._schedule_loop),
            has_work=self._schedule_has_work,
            finished=done_or_halt,
        )
        sup.add(
            "harvest",
            self._spawner("harvest", self._harvest_loop),
            # the harvest loop is the engine's poll heart: it always has
            # work (terminal checks, occupancy metering), so its silence is
            # always a stall, never starvation
            finished=done_or_halt,
        )
        try:
            while not get_clock().wait(self._done, self.orch.poll_interval):
                sup.tick()
                if sup.fallback:
                    return self._fallback_to_sync(sup.fallback_reason)
            # the harvest THREAD ran _finish/_drain_and_exit, which closed
            # the tracer and cleared only that thread's ambient slot — the
            # ambient tracer is thread-local, so the caller thread (the one
            # Orchestrator.run activated it on) restores its own slot here
            tracing.deactivate(self.orch._prev_tracer)
            return self._result
        finally:
            self._stop_loops()
            self._cancel_rivals()
            # satellite guarantee: a finished/fallen-back run never reports
            # stale occupancy or a latched stall flag on /api/status
            obs.pending_proposals.set(0.0)
            obs.mesh_occupancy.set(0.0)
            for name in ("suggest", "schedule", "harvest"):
                obs.loop_stalled.set(0.0, loop=name)

    # -- supervision plumbing ------------------------------------------------

    def _spawner(self, name: str, body):
        """Thread factory for the supervisor: ``spawn(gen)`` starts the loop
        body at generation ``gen``; crashes are recorded (not raised) so the
        supervisor sees a dead thread, classifies, and restarts it."""

        def spawn(gen: int) -> threading.Thread:
            def main():
                try:
                    body(gen)
                except Exception:
                    self._loop_errors[name] = (
                        f"{name} loop error:\n" + traceback.format_exc(limit=20)
                    )

            return get_clock().spawn(
                main, name=f"{name}-{self.exp.name}-g{gen}", daemon=True
            )

        return spawn

    def _current(self, name: str, gen: int) -> bool:
        """Generation fence: a restarted-over (stale) thread must stop
        touching shared state the moment a replacement exists."""
        sup = self._supervisor
        return sup is None or sup.generation(name) == gen

    def _beat(self, name: str) -> None:
        sup = self._supervisor
        if sup is not None:
            sup.beat(name)

    def _seam(self, name: str) -> None:
        """Chaos seam at the top of every loop iteration, OUTSIDE all
        engine locks (so an injected kill never strands a lock)."""
        inj = self.orch.fault_injector
        if inj is not None:
            inj.on_loop_iteration(name)

    def _suggest_has_work(self) -> bool:
        """Upstream-work predicate for stall-vs-starvation: the suggest
        loop is starved (idle through no fault of its own) while the bank
        is full, the budget is spent, the suggester is exhausted, or the
        breaker is cooling down."""
        if self._exhausted.is_set() or not self.breaker.allow():
            return False
        want = self._bank_deficit()
        if self.spec.max_trial_count is not None:
            want = min(want, self.spec.max_trial_count - len(self.exp.trials))
        return want > 0

    def _schedule_has_work(self) -> bool:
        """The schedule loop has work when something can actually MOVE:
        ready trials to pack, a bucket full or past its fill deadline, or a
        dispatchable head unit within the occupancy limit — a queue frozen
        by backpressure or drain is starvation, not a stall."""
        orch = self.orch
        if (
            orch._drain_requested.is_set()
            or orch._stop_requested.is_set()
            or self.stop_event.is_set()
        ):
            return False
        now = get_clock().monotonic()
        with self._queue_lock:
            if self._ready:
                return True
            for key, bucket in self._packing.items():
                if len(bucket) >= self.width:
                    return True
                if (
                    now - self._pack_ts.get(key, now)
                    >= self.spec.cohort_fill_deadline_seconds
                ):
                    return True
            if self._dispatchq:
                head = self._dispatchq[0]
                with self._futures_lock:
                    undone = self._undone_members()
                return undone == 0 or undone + len(head) <= self.member_limit
        return False

    def _on_loop_restart(self, name: str, gen: int, why: str, restarts: int) -> None:
        """Supervisor restart callback: audit the restart in the journal and
        re-seed any frontier state the dying loop dropped."""
        detail = self._loop_errors.pop(name, "")
        self.orch._jappend(
            "supervisor",
            self.exp,
            extra={
                "action": "restart",
                "loop": name,
                "generation": gen,
                "why": why,
                "restarts": restarts,
                "error": detail[-500:] if detail else "",
            },
        )
        self._reseed_lost()

    def _reseed_lost(self) -> None:
        """Rebuild the suggest->schedule frontier after a loop death: every
        non-terminal, non-drained trial that is in no queue and owned by no
        future goes back to the ready deque as PENDING.  ``exp.trials`` is
        the journal-backed state (``proposed``/``queued``/``started``
        records materialized it), so this is exactly what a process-level
        resume would reconstruct — done in-process, without the restart."""
        with self._state_lock, self._queue_lock, self._futures_lock:
            held = {t.name for t in self._ready}
            for bucket in self._packing.values():
                held.update(t.name for t in bucket)
            for unit in self._dispatchq:
                held.update(t.name for t in unit)
            for owner in self.futures.values():
                for t in owner if isinstance(owner, list) else [owner]:
                    held.add(t.name)
            for _orig, name, _clone in self._rivals.values():
                held.add(name)
            lost = [
                t
                for t in self.exp.trials.values()
                if not t.condition.is_terminal()
                and t.condition is not TrialCondition.DRAINED
                and t.name not in held
            ]
            for t in lost:
                t.condition = TrialCondition.PENDING
                self._ready.append(t)
        if lost:
            self._update_pending_gauge()

    def _fallback_to_sync(self, reason: str | None) -> None:
        """Restart budget exhausted: wind the async engine down WITHOUT
        failing the experiment.  Queued proposals go back to PENDING (the
        sync loop resubmits them), in-flight futures stay in the shared
        dict (the sync loop harvests them), and ``run()`` returns None."""
        orch, exp = self.orch, self.exp
        self._fallback_reason = reason or "supervisor fallback"
        # the sync loop owns the experiment from here: no surviving or
        # stale harvest thread may reach _terminal/_drain anymore
        with self._finalize_once:
            self._finalized = True
        self._stop_loops()
        self._cancel_rivals()
        self._reseed_lost()
        for t in self._drain_queues():
            t.condition = TrialCondition.PENDING
            t.message = "async engine fell back to sync; resubmitted"
        sup = self._supervisor
        orch._jappend(
            "supervisor",
            exp,
            extra={
                "action": "fallback",
                "reason": self._fallback_reason,
                "restarts": sup.restart_counts() if sup else {},
            },
        )
        self._record_stats()
        return None

    # -- suggest loop --------------------------------------------------------

    def _suggest_loop(self, gen: int = 0) -> None:
        orch, exp, spec = self.orch, self.exp, self.spec
        while not self._halt.is_set() and self._current("suggest", gen):
            self._seam("suggest")
            if self._exhausted.is_set():
                return
            # anticipatory refill: a refill of exactly (lookahead -
            # queued) arrives one suggester-latency late, by which time
            # the scheduler has consumed ~latency*throughput more — at
            # steady state the bank sits that much below target and the
            # mesh starves briefly every cycle.  Adding the members
            # consumed during the LAST call (a one-step rate estimate)
            # keeps the bank at the full lookahead when the call lands.
            want = self._bank_deficit()
            if spec.max_trial_count is not None:
                want = min(want, spec.max_trial_count - len(exp.trials))
            if want <= 0:
                get_clock().wait(self._halt, orch.poll_interval)
                continue
            if not self.breaker.allow():
                # cooling down after an error: not idle, not progress
                self._suggester_busy = True
                self._last_activity = get_clock().monotonic()
                get_clock().wait(self._halt, orch.poll_interval)
                continue
            self._suggester_busy = False
            sug_start = orch._tracer.elapsed() if orch._tracer else 0.0
            t0 = get_clock().perf_counter()
            with self._queue_lock:  # LCK001: the scheduler bumps it in _submit
                d0 = self._dispatched_total
            self._suggest_inflight = True
            try:
                # the deadline bounds a wedged/blocked get_suggestions:
                # it trips the breaker (bounded retries, then a diagnosed
                # terminal verdict) instead of freezing this loop until
                # the supervisor burns a restart on it.  Half the stall
                # deadline, so a call abandoned at its limit still returns
                # (and beats) before the supervisor classifies the loop
                # stalled — abandonment is the cheap recovery, a restart
                # is the expensive one
                proposals, outcome = call_suggester(
                    self.suggester,
                    exp,
                    want,
                    self.breaker,
                    orch.fault_injector,
                    deadline=0.5 * spec.loop_stall_deadline_seconds,
                    events=(self._halt,),
                )
            finally:
                self._suggest_inflight = False
            if not self._current("suggest", gen):
                # fenced: a replacement thread owns the frontier now —
                # these proposals were never journaled, drop them
                return
            self._beat("suggest")
            # LCK001 fix: the rate estimate is read by _bank_deficit on this
            # thread AND the supervisor's has_work probe on the caller
            # thread; write it under the same lock the counters live under
            with self._queue_lock:
                self._consumed_last_call = self._dispatched_total - d0
            dur = get_clock().perf_counter() - t0
            obs.suggestion_latency.observe(dur, algorithm=spec.algorithm.name)
            obs.suggest_seconds.observe(dur, algorithm=spec.algorithm.name)
            if orch._tracer is not None and (
                proposals or outcome in ("exhausted", "error") or dur >= 1e-3
            ):
                orch._tracer.record(
                    "suggest",
                    sug_start,
                    dur,
                    algorithm=spec.algorithm.name,
                    count=len(proposals),
                    outcome=outcome,
                )
            if outcome == "error":
                self._suggester_busy = True
                self._last_activity = get_clock().monotonic()
                obs.suggester_errors.inc(algorithm=spec.algorithm.name)
            if proposals:
                with self._state_lock:
                    trials = [
                        orch._materialize(
                            exp,
                            p,
                            # rules attach at DISPATCH (_refresh_rules),
                            # not here: a lookahead proposal materializes
                            # long before the history its rule snapshot
                            # would need
                            None,
                            self.suggester,
                            condition=TrialCondition.PENDING,
                            journal=False,
                        )
                        for p in proposals
                    ]
                # one durability barrier for the whole refill — per-trial
                # appends would serialize ~lookahead fsyncs between the
                # suggester returning and the first dispatch
                orch._jappend_group("proposed", exp, trials)
                with self._queue_lock:
                    self._ready.extend(trials)
                self._update_pending_gauge()
                with self._state_lock:
                    orch._persist_suggester(exp, self.suggester)
                    orch._publish(exp)
                self._last_activity = get_clock().monotonic()
            if outcome == "exhausted":
                # set AFTER the final proposals are queued, so the
                # terminal check never sees "exhausted + empty" early
                self._exhausted.set()
                return
            if not proposals:
                get_clock().wait(self._halt, orch.poll_interval)

    # -- schedule loop -------------------------------------------------------

    def _schedule_loop(self, gen: int = 0) -> None:
        orch = self.orch
        while not self._halt.is_set() and self._current("schedule", gen):
            self._seam("schedule")
            moved = self._pack_ready()
            flushed = self._flush_buckets()
            dispatched = self._dispatch_units()
            if moved or flushed or dispatched:
                self._update_pending_gauge()
                self._beat("schedule")
            else:
                get_clock().wait(self._halt, orch.poll_interval)

    def _cohort_key_for(self, trial: Trial) -> str | None:
        if not self._use_cohorts:
            return None
        key = trial.spec.labels.get(COHORT_KEY_LABEL) or self._default_key
        if key:
            # stamp it back so the journal/UI show which bucket it rode in
            trial.spec.labels.setdefault(COHORT_KEY_LABEL, key)
        return key

    def _pack_ready(self) -> int:
        """Move ready trials into packing buckets (keyless -> straight to
        the dispatch queue as singletons).  Journals the ``queued``
        hand-off records as one batched durability barrier."""
        moved: list[Trial] = []
        prewarms: list[list[Trial]] = []
        while True:
            with self._queue_lock:
                if not self._ready:
                    break
                trial = self._ready.popleft()
                key = self._cohort_key_for(trial)
                if key is None:
                    self._dispatchq.append([trial])
                else:
                    bucket = self._packing.setdefault(key, [])
                    if not bucket:
                        self._pack_ts[key] = get_clock().monotonic()
                    bucket.append(trial)
                    if len(bucket) & (len(bucket) - 1) == 0:
                        # speculative prewarm at each power-of-two fill
                        # level: the bucketed executable for the current
                        # size compiles while the bucket keeps filling
                        # (dedup in the worker makes superseded sizes
                        # cheap no-ops)
                        prewarms.append(list(bucket))
            moved.append(trial)
        if moved:
            self.orch._jappend_group("queued", self.exp, moved)
        for peek in prewarms:
            self.orch._submit_prewarm(self.spec, peek, self.mesh)
        return len(moved)

    def _flush_buckets(self) -> int:
        """Flush full buckets always; flush PARTIAL buckets when the fill
        deadline expires, the suggester is exhausted, or the remaining
        proposal budget can never complete them — the fix for a remainder
        smaller than the cohort width waiting forever."""
        spec = self.spec
        flushed = 0
        now = get_clock().monotonic()
        budget_left = (
            spec.max_trial_count - len(self.exp.trials)
            if spec.max_trial_count is not None
            else None
        )
        with self._queue_lock:
            for key in list(self._packing):
                bucket = self._packing[key]
                while len(bucket) >= self.width:
                    self._dispatchq.append(bucket[: self.width])
                    del bucket[: self.width]
                    self._pack_ts[key] = now
                    flushed += 1
                if not bucket:
                    del self._packing[key]
                    self._pack_ts.pop(key, None)
                    continue
                deadline_hit = (
                    now - self._pack_ts.get(key, now)
                    >= spec.cohort_fill_deadline_seconds
                )
                starved = self._exhausted.is_set() or (
                    budget_left is not None
                    and budget_left <= 0
                    and not self._ready
                )
                if deadline_hit or starved:
                    self._dispatchq.append(list(bucket))
                    del self._packing[key]
                    self._pack_ts.pop(key, None)
                    flushed += 1
        return flushed

    def _undone_members(self) -> int:  # lint: holds(_futures_lock)
        return sum(
            (len(o) if isinstance(o, list) else 1)
            for f, o in self.futures.items()
            if not f.done()
        )

    def _dispatch_units(self) -> int:
        """Submit queued units while occupancy allows.  The hand-off from
        dispatch queue to futures dict is atomic under the queue lock, so
        the terminal check never sees a unit in neither."""
        n = 0
        orch = self.orch
        while not self._halt.is_set():
            # drain/stop freeze dispatch immediately: a draining trial's
            # early return must not free a slot for a NEW trial in the
            # window before the harvest loop acts on the request (queued
            # units become PENDING leftovers / cancelled instead)
            if (
                orch._drain_requested.is_set()
                or orch._stop_requested.is_set()
                or self.stop_event.is_set()
            ):
                return n
            with self._queue_lock:
                if not self._dispatchq:
                    return n
                unit = self._dispatchq[0]
                with self._futures_lock:
                    undone = self._undone_members()
                if undone > 0 and undone + len(unit) > self.member_limit:
                    return n
            # early-stopping rules snapshot at DISPATCH time, not propose
            # time: lookahead materializes trials before any history
            # exists, so a rule frozen at _materialize would be
            # permanently empty.  Outside the queue lock (state > queue
            # ordering); the head is stable because this thread is the
            # only popper while the loops run.
            self._refresh_rules(unit)
            with self._queue_lock:
                if not self._dispatchq or self._dispatchq[0] is not unit:
                    continue
                self._dispatchq.popleft()
                self._submit(unit)
            n += 1
        return n

    def _refresh_rules(self, unit: list[Trial]) -> None:
        es = self.early_stopper
        if es is None:
            return
        # settle completed-but-unharvested futures first: sub-second
        # trials outrun the harvest poll, and the median needs every
        # finished trial counted as SUCCEEDED, not merely future-done
        with self._state_lock, self._futures_lock:
            self.orch._harvest(self.exp, self.futures)
            rules = es.get_rules(self.exp)
        if not rules:
            return
        for t in unit:
            if not t.spec.early_stopping_rules:
                t.spec.early_stopping_rules = rules

    def _submit(self, unit: list[Trial]) -> None:  # lint: holds(_queue_lock)
        orch, exp = self.orch, self.exp
        orch._submit_prewarm(self.spec, unit, self.mesh)
        now = get_clock().time()
        for t in unit:
            t.condition = TrialCondition.RUNNING
            t.start_time = now
        orch._jappend_group("started", exp, unit)
        if len(unit) == 1:
            fut = get_clock().submit(self.pool, orch._execute, exp, unit[0], self.mesh)
            owner: Trial | list[Trial] = unit[0]
        else:
            fut = get_clock().submit(self.pool, orch._execute_cohort, exp, unit, self.mesh)
            owner = unit
        with self._futures_lock:
            self.futures[fut] = owner
            self._fut_meta[fut] = get_clock().monotonic()
        self._dispatched_total += len(unit)
        self._last_activity = get_clock().monotonic()
        # the harvest loop republishes status.json soon after: without
        # this, a run whose trials all dispatch between publishes would
        # never show a Running trial to external watchers
        self._publish_dirty = True

    # -- harvest loop (thread) ----------------------------------------------

    def _harvest_loop(self, gen: int = 0) -> None:
        """Thread body: poll/settle until a terminal (or drained) verdict,
        published to the supervising caller thread via ``_result`` +
        ``_done``.  Returning ``None`` from the cycle means this thread was
        fenced out (restarted over) or lost the finalize race — the
        replacement owns the verdict."""
        result = self._harvest_cycle(gen)
        if result is not None:
            self._result = result
            self._done.set()

    def _finalize(self, fn):
        """First-finalizer-wins: a stale harvest thread waking up mid
        wind-down must not run ``_terminal``/``_drain`` a second time."""
        with self._finalize_once:
            if self._finalized:
                return None
            self._finalized = True
        return fn()

    def _harvest_cycle(self, gen: int) -> Experiment | None:
        orch, exp = self.orch, self.exp
        while not self._halt.is_set() and self._current("harvest", gen):
            self._seam("harvest")
            with self._state_lock, self._futures_lock:
                orch._harvest(exp, self.futures)
            self._note_settled_futures()
            self._check_speculations()
            if self.spec.speculative_redispatch:
                self._maybe_speculate()
            with self._futures_lock:
                # busy in MEMBER trials: a running cohort future fills
                # width slots' worth of the mesh on one pool thread
                busy = sum(
                    (len(o) if isinstance(o, list) else 1)
                    for f, o in self.futures.items()
                    if f.running()
                )
                undone = sum(1 for f in self.futures if not f.done())
            obs.mesh_occupancy.set(self.meter.update(busy))
            if self._publish_dirty:
                self._publish_dirty = False
                with self._state_lock:
                    orch._publish(exp)

            if orch._stop_requested.is_set():
                self.stop_event.set()
            if self.stop_event.is_set():
                return self._finalize(
                    lambda: self._terminal(
                        ExperimentCondition.FAILED, message="experiment stopped"
                    )
                )
            if orch._drain_requested.is_set():
                return self._finalize(self._drain)

            queued = self._queued_count()
            exhausted_eff = self._exhausted.is_set() and queued == 0
            # LCK001 fix: _check_terminal's exhaustion arm tests the futures
            # dict while the scheduler may be inserting — hold both locks
            # (state > futures, same order as the harvest call above)
            with self._state_lock, self._futures_lock:
                verdict = orch._check_terminal(exp, exhausted_eff, self.futures)
            if verdict is not None:
                return self._finalize(lambda: self._terminal(verdict))

            if self.breaker.tripped:
                msg = (
                    f"suggester failed {self.breaker.failures} consecutive "
                    f"times (suggester_max_errors="
                    f"{self.spec.suggester_max_errors}); last error:\n"
                    + self.breaker.last_failure
                )
                return self._finalize(
                    lambda: self._terminal(ExperimentCondition.FAILED, message=msg)
                )

            # livelock guard (the sync loop's 30s stall cap): nothing in
            # flight, nothing queued, suggester idle and answering nothing
            if (
                undone == 0
                and queued == 0
                and not self._exhausted.is_set()
                and not self._suggester_busy
                and not self._suggest_inflight
            ):
                if get_clock().monotonic() - self._last_activity > _STALL_SECONDS:
                    return self._finalize(
                        lambda: self._terminal(
                            ExperimentCondition.FAILED,
                            message=(
                                "orchestrator stalled: suggester proposes "
                                "nothing with no trials in flight"
                            ),
                        )
                    )
            else:
                self._last_activity = max(self._last_activity, get_clock().monotonic() - 1.0)
            self._beat("harvest")
            get_clock().sleep(orch.poll_interval)
        return None

    # -- speculative straggler re-dispatch -----------------------------------

    def _note_settled_futures(self) -> None:
        """Record settle durations (dispatch -> harvested) for the straggler
        median; a future gone from the shared dict was settled/cancelled."""
        now = get_clock().monotonic()
        with self._futures_lock:
            gone = [f for f in self._fut_meta if f not in self.futures]
            for f in gone:
                self._settle_durations.append(now - self._fut_meta.pop(f))

    def _maybe_speculate(self) -> None:
        """Re-dispatch stragglers as singleton rivals on free slots.  Needs
        >= 3 settled durations for a meaningful median; one rival per trial
        per run; rivals only use slack under ``member_limit`` so speculation
        never delays first-run work."""
        # LCK001 fix: _note_settled_futures appends on this thread, but a
        # restarted-over stale harvest generation can still be unwinding —
        # snapshot under the lock before taking the median
        with self._futures_lock:
            durations = list(self._settle_durations)
        if len(durations) < 3:
            return
        threshold = self.spec.straggler_factor * statistics.median(durations)
        now = get_clock().monotonic()
        candidates: list[tuple[object, Trial]] = []
        with self._futures_lock:
            free = self.member_limit - self._undone_members() - len(
                [f for f in self._rivals if not f.done()]
            )
            if free <= 0:
                return
            for f, owner in self.futures.items():
                if f.done():
                    continue
                t0 = self._fut_meta.get(f)
                if t0 is None or now - t0 < threshold:
                    continue
                for t in owner if isinstance(owner, list) else [owner]:
                    if t.name not in self._speculated:
                        candidates.append((f, t))
        for f, t in candidates[: max(0, free)]:
            self._dispatch_rival(f, t)

    def _dispatch_rival(self, orig_fut, trial: Trial) -> None:
        """Submit a speculative singleton rival for ``trial``.  The rival
        executes a CLONE (separate object, suffixed checkpoint dir) so the
        straggling attempt and the rival never write the same Trial or the
        same checkpoint files; metrics land under the same trial name, so
        adoption needs no metric surgery."""
        clone = copy.deepcopy(trial)
        if clone.checkpoint_dir:
            clone.checkpoint_dir = clone.checkpoint_dir + "-speculative"
        clone.condition = TrialCondition.RUNNING
        clone.message = ""
        fut = get_clock().submit(self.pool, self.orch._execute, self.exp, clone, self.mesh)
        with self._futures_lock:
            # LCK001 fix: _maybe_speculate filters candidates against
            # _speculated under this lock; the add used to race it bare
            self._speculated.add(trial.name)
            self._rivals[fut] = (orig_fut, trial.name, clone)
        obs.speculative_dispatches.inc()
        self._last_activity = get_clock().monotonic()

    def _check_speculations(self) -> None:
        """First-settle-wins arbitration.  A rival that finishes with a
        usable result while the original is still unsettled is ADOPTED: the
        clone becomes ``exp.trials[name]`` and its future joins the shared
        dict, so the very next ``_harvest`` settles it through the normal
        exactly-once path; the original future is evicted, and its eventual
        result hits the stale-owner guard.  A rival that loses the race or
        fails is discarded — speculation can never fail a trial that might
        still succeed."""
        # LCK001 fix: the empty-check early-return used to peek at _rivals
        # bare; fold it into the lock (uncontended acquire, same fast path)
        with self._futures_lock:
            if not self._rivals:
                return
            done = [f for f in self._rivals if f.done()]
        for f in done:
            with self._futures_lock:
                orig_fut, name, clone = self._rivals.pop(f)
            try:
                result = f.result()  # _execute never raises
            except Exception:
                continue
            live = self.exp.trials.get(name)
            if live is None or live.condition.is_terminal():
                continue  # the original settled first; rival discarded
            if result.condition not in (
                TrialCondition.SUCCEEDED,
                TrialCondition.EARLY_STOPPED,
            ):
                continue
            with self._state_lock, self._futures_lock:
                self.futures.pop(orig_fut, None)
                self._fut_meta.pop(orig_fut, None)
                self.futures[f] = clone
                self._fut_meta.setdefault(f, get_clock().monotonic())
                self.exp.trials[name] = clone
            self._spec_wins += 1
            obs.speculative_wins.inc()

    def _cancel_rivals(self) -> None:
        with self._futures_lock:
            rivals = list(self._rivals)
            self._rivals.clear()
        for f in rivals:
            f.cancel()

    # -- wind-down -----------------------------------------------------------

    def _queued_count(self) -> int:
        with self._queue_lock:
            return self._queued_count_locked()

    def _queued_count_locked(self) -> int:  # lint: holds(_queue_lock)
        return (
            len(self._ready)
            + sum(len(b) for b in self._packing.values())
            + sum(len(u) for u in self._dispatchq)
        )

    def _bank_deficit(self) -> int:
        """How many proposals the bank is short of ``lookahead``, with the
        one-step consumption estimate folded in — read atomically under the
        queue lock (the counters move with the queues they describe)."""
        with self._queue_lock:
            return (
                self.lookahead
                - self._queued_count_locked()
                + self._consumed_last_call
            )

    def _update_pending_gauge(self) -> None:
        # straggler-reset fix: run()'s finally zeroes this gauge after the
        # halt flag is raised; a loop thread still unwinding through here
        # must not republish a nonzero count after that reset
        if self._halt.is_set():
            return
        obs.pending_proposals.set(float(self._queued_count()))

    def _drain_queues(self) -> list[Trial]:
        with self._queue_lock:
            leftovers = list(self._ready)
            self._ready.clear()
            for bucket in self._packing.values():
                leftovers.extend(bucket)
            self._packing.clear()
            self._pack_ts.clear()
            for unit in self._dispatchq:
                leftovers.extend(unit)
            self._dispatchq.clear()
        return leftovers

    def _stop_loops(self) -> None:
        """Halt the loop threads and JOIN the current-generation ones before
        the caller touches the queues or cancels futures — without the join,
        a dispatch racing the wind-down could submit a unit after
        ``_cancel_pending`` already ran.  Stale (restarted-over) threads are
        already fenced out of shared state and left to die as daemons."""
        self._halt.set()
        sup = self._supervisor
        threads = sup.threads() if sup is not None else []
        for t in threads:
            if t is not threading.current_thread():
                get_clock().join_thread(t, timeout=_JOIN_TIMEOUT)

    def _terminal(
        self, verdict: ExperimentCondition, message: str | None = None
    ) -> Experiment:
        orch, exp = self.orch, self.exp
        self._stop_loops()
        self._cancel_rivals()
        self.stop_event.set()
        with self._futures_lock:
            orch._cancel_pending(self.futures)
        with self._state_lock, self._futures_lock:
            orch._harvest(exp, self.futures, wait_running=True)
        # proposed-but-undispatched trials mirror the sync loop's
        # cancelled-future semantics: settled KILLED, budget consumed
        now = get_clock().time()
        for t in self._drain_queues():
            t.condition = TrialCondition.KILLED
            t.message = "cancelled: experiment terminal before dispatch"
            t.completion_time = now
            if not t.start_time:
                t.start_time = now
            obs.trials_killed.inc()
            orch._jappend("settled", exp, trial=t)
            orch._observe_trial_duration(t)
        exp.condition = verdict
        exp.message = message if message is not None else orch._terminal_message(verdict)
        exp.completion_time = get_clock().time()
        exp.update_optimal()
        self._record_stats()
        orch._finish(exp)
        return exp

    def _drain(self) -> Experiment:
        orch, exp = self.orch, self.exp
        self._stop_loops()
        self._cancel_rivals()
        # undispatched trials never started: back to PENDING so the resumed
        # run re-seeds them into its ready queue (no budget slot consumed)
        for t in self._drain_queues():
            t.condition = TrialCondition.PENDING
            t.message = "drained before start; resubmitted on resume"
            orch._jappend("drained", exp, trial=t)
        self._record_stats()
        return orch._drain_and_exit(
            exp,
            self.futures,  # lint: unguarded-ok(wind-down: loops joined by _stop_loops, single-threaded from here)
            self.suggester,
            self.stop_event,
            self.drain_event,
        )

    def _record_stats(self) -> None:
        """Publish the run's sustained-occupancy + supervision summary for
        bench/CI/chaos assertions."""
        exp = self.exp
        sup = self._supervisor
        elapsed = self.meter.elapsed()
        settled = sum(1 for t in exp.trials.values() if t.condition.is_terminal())
        self.orch.async_stats = {
            "sustained_occupancy": round(self.meter.sustained(), 4),
            "elapsed_s": round(elapsed, 4),
            "trials_settled": settled,
            "trials_per_sec": round(settled / elapsed, 4) if elapsed > 0 else 0.0,
            "lookahead": self.lookahead,
            "width": self.width,
            "member_limit": self.member_limit,
            "loop_restarts": sup.restart_counts() if sup is not None else {},
            "fallback": self._fallback_reason,
            "speculative_dispatches": len(self._speculated),  # lint: unguarded-ok(wind-down: _record_stats runs after _stop_loops joined the loops)
            "speculative_wins": self._spec_wins,
        }
        obs.mesh_occupancy.set(0.0)
