"""Podracer-style asynchronous orchestration: three decoupled loops joined
by bounded queues (the Podracer architectures pattern from PAPERS.md applied
to HPO control flow).

The synchronous run loop interleaves propose -> execute -> harvest on one
thread, so the mesh idles whenever the suggester is thinking, a cohort is
short of members, or harvest is settling.  This engine splits the loop:

- **suggest loop** (thread): keeps ``suggest_lookahead`` proposals journaled
  and ready ahead of the scheduler, so suggester latency hides behind
  training instead of gating dispatch.  Budget-aware: never materializes
  past ``max_trial_count``.
- **schedule loop** (thread): heterogeneous cohort packing — ready trials
  accumulate into per-key shape buckets (``compile/buckets.py`` pads the
  dispatched width to a power of two, so a 5-member flush reuses the
  8-wide executable) and flush on *any* of: full width, the
  ``cohort_fill_deadline_seconds`` timeout, suggester exhaustion, or a
  remaining budget that can never fill the bucket — a partial cohort never
  waits indefinitely.  Dispatch backpressure is driven by slot occupancy
  (``occupancy_target``) rather than a fixed trial count, and each flushed
  bucket's compile signature feeds the prewarm worker before submit.
- **harvest loop** (the caller's thread): settles completions through the
  exactly-once journal path (``Orchestrator._harvest``) and owns terminal
  verdicts, stop, drain, and the livelock guard.

The event journal is the coordination substrate: ``proposed`` (suggest),
``queued`` (entered a packing bucket), ``started`` (dispatched) and the
existing ``settled`` records mean a crash at any hand-off point leaves
non-terminal trials that resume re-seeds into the ready queue —
exactly-once settlement keyed on (trial, retry epoch) is unchanged.

Locking discipline (acquire order: state > queue > futures):

- ``_state_lock`` — inserts into ``exp.trials`` (materialize) vs the
  iterations harvest / ``update_optimal`` / terminal checks perform.  The
  suggester call itself runs OUTSIDE the lock (only its own thread
  inserts), so a slow suggester never stalls settlement or dispatch.
- ``_queue_lock`` — the ready deque, packing buckets, and dispatch queue
  move atomically, so the terminal check can never observe a trial
  "in neither queue nor futures" mid-hand-off.
- ``_futures_lock`` — the shared futures dict (scheduler inserts while
  harvest iterates).

Pool threads (``_execute`` / ``_execute_cohort``) take no engine locks, so
the mesh critical path is untouched.
"""

from __future__ import annotations

import collections
import threading
import time
import traceback

from katib_tpu.core.types import (
    COHORT_KEY_LABEL,
    Experiment,
    ExperimentCondition,
    Trial,
    TrialCondition,
)
from katib_tpu.runner.cohort import cohort_fn_of
from katib_tpu.suggest.base import call_suggester
from katib_tpu.utils import observability as obs

#: how long the wind-down waits for the suggest/schedule threads to notice
#: the halt flag (a suggester blocked mid-call is abandoned on its daemon
#: thread — the breaker/watchdog own misbehaving suggesters, not drain)
_JOIN_TIMEOUT = 5.0

#: livelock guard threshold, matching the synchronous loop's 30s stall cap
_STALL_SECONDS = 30.0


class OccupancyMeter:
    """Time-weighted mean busy-slot fraction.

    The clock starts lazily at the FIRST dispatch (running > 0), so the
    unavoidable cold ramp — the first suggester call before any trial can
    exist — does not dilute the sustained number; what is measured is
    "once work started flowing, how full did the mesh stay".
    """

    def __init__(self, slots: int):
        self.slots = max(1, int(slots))
        self._t0: float | None = None
        self._last = 0.0
        self._frac = 0.0
        self._area = 0.0

    def update(self, busy: int) -> float:
        now = time.monotonic()
        frac = min(1.0, busy / self.slots)
        if self._t0 is None:
            if busy <= 0:
                return frac
            self._t0 = self._last = now
            self._frac = frac
            return frac
        self._area += self._frac * (now - self._last)
        self._last = now
        self._frac = frac
        return frac

    def elapsed(self) -> float:
        return 0.0 if self._t0 is None else self._last - self._t0

    def sustained(self) -> float:
        el = self.elapsed()
        return (self._area / el) if el > 0 else 0.0


class AsyncLoops:
    """One experiment's async engine; ``run()`` replaces the synchronous
    while-loop body inside ``Orchestrator.run``'s pool context and returns
    the terminal (or drained) experiment."""

    def __init__(
        self,
        orch,
        exp: Experiment,
        suggester,
        early_stopper,
        mesh,
        pool,
        breaker,
        stop_event: threading.Event,
        drain_event: threading.Event,
        futures: dict,
        initial_ready: list[Trial] = (),
    ):
        self.orch = orch
        self.exp = exp
        self.spec = exp.spec
        self.suggester = suggester
        self.early_stopper = early_stopper
        self.mesh = mesh
        self.pool = pool
        self.breaker = breaker
        self.stop_event = stop_event
        self.drain_event = drain_event
        self.futures = futures

        self._state_lock = threading.Lock()
        self._queue_lock = threading.Lock()
        self._futures_lock = threading.Lock()

        #: proposed trials awaiting packing (suggest -> schedule hand-off)
        self._ready: collections.deque[Trial] = collections.deque(initial_ready)
        #: per-cohort-key packing buckets + first-arrival timestamps
        self._packing: dict[str, list[Trial]] = {}
        self._pack_ts: dict[str, float] = {}
        #: flushed units awaiting a free slot (schedule -> pool hand-off)
        self._dispatchq: collections.deque[list[Trial]] = collections.deque()

        self._halt = threading.Event()       # internal: stop both loops
        self._exhausted = threading.Event()  # suggester returned exhausted
        self._suggest_inflight = False       # a get_suggestions call is running
        self._suggester_busy = False         # erroring / cooling down, not idle
        self._errors: list[str] = []
        self._last_activity = time.monotonic()
        #: members dispatched since engine start (consumption-rate estimator
        #: for the suggest loop's anticipatory refill)
        self._dispatched_total = 0
        self._consumed_last_call = 0
        #: set by _submit; the harvest loop owes a status.json publish
        self._publish_dirty = False

        spec = self.spec
        trial_devices = 1
        if mesh is not None:
            from katib_tpu.parallel.mesh import trial_axis_size

            trial_devices = trial_axis_size(mesh)
        self.width = max(spec.cohort_width, trial_devices)
        self._use_cohorts = self.width > 1 and cohort_fn_of(spec.train_fn) is not None
        self._default_key = spec.cohort_key or (
            orch._TRIAL_MESH_KEY if trial_devices > 1 else None
        )
        # proposal lookahead: deep for non-adaptive suggesters (the points
        # never depend on results), clamped to the in-flight width for
        # adaptive ones (ASHA/BO/PBT) — racing them ahead of observations
        # burns the budget on uninformed proposals (see Suggester.adaptive)
        base_width = max(spec.parallel_trial_count, self.width)
        self.lookahead = spec.suggest_lookahead or (
            base_width if getattr(suggester, "adaptive", True) else 4 * base_width
        )
        # occupancy backpressure, counted in MEMBER trials (a cohort future
        # carries width members on one slot): ``parallel_trial_count`` is
        # the concurrency contract the sync loop enforces via _shortfall,
        # scaled down by occupancy_target to deliberately throttle.  A unit
        # wider than the limit dispatches alone (the sync loop can never
        # build one, but an explicit suggestLookahead + wide mesh can).
        self.member_limit = max(
            1, round(spec.parallel_trial_count * spec.occupancy_target)
        )
        self.meter = OccupancyMeter(spec.parallel_trial_count)

    # -- entry point ---------------------------------------------------------

    def run(self) -> Experiment:
        self._threads = [
            threading.Thread(
                target=self._suggest_loop,
                name=f"suggest-{self.exp.name}",
                daemon=True,
            ),
            threading.Thread(
                target=self._schedule_loop,
                name=f"schedule-{self.exp.name}",
                daemon=True,
            ),
        ]
        for t in self._threads:
            t.start()
        try:
            return self._harvest_loop()
        finally:
            self._stop_loops()
            obs.pending_proposals.set(0.0)

    # -- suggest loop --------------------------------------------------------

    def _suggest_loop(self) -> None:
        orch, exp, spec = self.orch, self.exp, self.spec
        try:
            while not self._halt.is_set():
                if self._exhausted.is_set():
                    return
                # anticipatory refill: a refill of exactly (lookahead -
                # queued) arrives one suggester-latency late, by which time
                # the scheduler has consumed ~latency*throughput more — at
                # steady state the bank sits that much below target and the
                # mesh starves briefly every cycle.  Adding the members
                # consumed during the LAST call (a one-step rate estimate)
                # keeps the bank at the full lookahead when the call lands.
                want = (
                    self.lookahead
                    - self._queued_count()
                    + self._consumed_last_call
                )
                if spec.max_trial_count is not None:
                    want = min(want, spec.max_trial_count - len(exp.trials))
                if want <= 0:
                    self._halt.wait(orch.poll_interval)
                    continue
                if not self.breaker.allow():
                    # cooling down after an error: not idle, not progress
                    self._suggester_busy = True
                    self._last_activity = time.monotonic()
                    self._halt.wait(orch.poll_interval)
                    continue
                self._suggester_busy = False
                sug_start = orch._tracer.elapsed() if orch._tracer else 0.0
                t0 = time.perf_counter()
                d0 = self._dispatched_total
                self._suggest_inflight = True
                try:
                    proposals, outcome = call_suggester(
                        self.suggester, exp, want, self.breaker, orch.fault_injector
                    )
                finally:
                    self._suggest_inflight = False
                self._consumed_last_call = self._dispatched_total - d0
                dur = time.perf_counter() - t0
                obs.suggestion_latency.observe(dur, algorithm=spec.algorithm.name)
                obs.suggest_seconds.observe(dur, algorithm=spec.algorithm.name)
                if orch._tracer is not None and (
                    proposals or outcome in ("exhausted", "error") or dur >= 1e-3
                ):
                    orch._tracer.record(
                        "suggest",
                        sug_start,
                        dur,
                        algorithm=spec.algorithm.name,
                        count=len(proposals),
                        outcome=outcome,
                    )
                if outcome == "error":
                    self._suggester_busy = True
                    self._last_activity = time.monotonic()
                    obs.suggester_errors.inc(algorithm=spec.algorithm.name)
                if proposals:
                    with self._state_lock:
                        trials = [
                            orch._materialize(
                                exp,
                                p,
                                # rules attach at DISPATCH (_refresh_rules),
                                # not here: a lookahead proposal materializes
                                # long before the history its rule snapshot
                                # would need
                                None,
                                self.suggester,
                                condition=TrialCondition.PENDING,
                                journal=False,
                            )
                            for p in proposals
                        ]
                    # one durability barrier for the whole refill — per-trial
                    # appends would serialize ~lookahead fsyncs between the
                    # suggester returning and the first dispatch
                    orch._jappend_group("proposed", exp, trials)
                    with self._queue_lock:
                        self._ready.extend(trials)
                    self._update_pending_gauge()
                    with self._state_lock:
                        orch._persist_suggester(exp, self.suggester)
                        orch._publish(exp)
                    self._last_activity = time.monotonic()
                if outcome == "exhausted":
                    # set AFTER the final proposals are queued, so the
                    # terminal check never sees "exhausted + empty" early
                    self._exhausted.set()
                    return
                if not proposals:
                    self._halt.wait(orch.poll_interval)
        except Exception:
            self._errors.append(
                "suggest loop error:\n" + traceback.format_exc(limit=20)
            )
            self._halt.set()

    # -- schedule loop -------------------------------------------------------

    def _schedule_loop(self) -> None:
        orch = self.orch
        try:
            while not self._halt.is_set():
                moved = self._pack_ready()
                flushed = self._flush_buckets()
                dispatched = self._dispatch_units()
                if moved or flushed or dispatched:
                    self._update_pending_gauge()
                else:
                    self._halt.wait(orch.poll_interval)
        except Exception:
            self._errors.append(
                "schedule loop error:\n" + traceback.format_exc(limit=20)
            )
            self._halt.set()

    def _cohort_key_for(self, trial: Trial) -> str | None:
        if not self._use_cohorts:
            return None
        key = trial.spec.labels.get(COHORT_KEY_LABEL) or self._default_key
        if key:
            # stamp it back so the journal/UI show which bucket it rode in
            trial.spec.labels.setdefault(COHORT_KEY_LABEL, key)
        return key

    def _pack_ready(self) -> int:
        """Move ready trials into packing buckets (keyless -> straight to
        the dispatch queue as singletons).  Journals the ``queued``
        hand-off records as one batched durability barrier."""
        moved: list[Trial] = []
        prewarms: list[list[Trial]] = []
        while True:
            with self._queue_lock:
                if not self._ready:
                    break
                trial = self._ready.popleft()
                key = self._cohort_key_for(trial)
                if key is None:
                    self._dispatchq.append([trial])
                else:
                    bucket = self._packing.setdefault(key, [])
                    if not bucket:
                        self._pack_ts[key] = time.monotonic()
                    bucket.append(trial)
                    if len(bucket) & (len(bucket) - 1) == 0:
                        # speculative prewarm at each power-of-two fill
                        # level: the bucketed executable for the current
                        # size compiles while the bucket keeps filling
                        # (dedup in the worker makes superseded sizes
                        # cheap no-ops)
                        prewarms.append(list(bucket))
            moved.append(trial)
        if moved:
            self.orch._jappend_group("queued", self.exp, moved)
        for peek in prewarms:
            self.orch._submit_prewarm(self.spec, peek, self.mesh)
        return len(moved)

    def _flush_buckets(self) -> int:
        """Flush full buckets always; flush PARTIAL buckets when the fill
        deadline expires, the suggester is exhausted, or the remaining
        proposal budget can never complete them — the fix for a remainder
        smaller than the cohort width waiting forever."""
        spec = self.spec
        flushed = 0
        now = time.monotonic()
        budget_left = (
            spec.max_trial_count - len(self.exp.trials)
            if spec.max_trial_count is not None
            else None
        )
        with self._queue_lock:
            for key in list(self._packing):
                bucket = self._packing[key]
                while len(bucket) >= self.width:
                    self._dispatchq.append(bucket[: self.width])
                    del bucket[: self.width]
                    self._pack_ts[key] = now
                    flushed += 1
                if not bucket:
                    del self._packing[key]
                    self._pack_ts.pop(key, None)
                    continue
                deadline_hit = (
                    now - self._pack_ts.get(key, now)
                    >= spec.cohort_fill_deadline_seconds
                )
                starved = self._exhausted.is_set() or (
                    budget_left is not None
                    and budget_left <= 0
                    and not self._ready
                )
                if deadline_hit or starved:
                    self._dispatchq.append(list(bucket))
                    del self._packing[key]
                    self._pack_ts.pop(key, None)
                    flushed += 1
        return flushed

    def _undone_members(self) -> int:
        # called under _futures_lock
        return sum(
            (len(o) if isinstance(o, list) else 1)
            for f, o in self.futures.items()
            if not f.done()
        )

    def _dispatch_units(self) -> int:
        """Submit queued units while occupancy allows.  The hand-off from
        dispatch queue to futures dict is atomic under the queue lock, so
        the terminal check never sees a unit in neither."""
        n = 0
        orch = self.orch
        while not self._halt.is_set():
            # drain/stop freeze dispatch immediately: a draining trial's
            # early return must not free a slot for a NEW trial in the
            # window before the harvest loop acts on the request (queued
            # units become PENDING leftovers / cancelled instead)
            if (
                orch._drain_requested.is_set()
                or orch._stop_requested.is_set()
                or self.stop_event.is_set()
            ):
                return n
            with self._queue_lock:
                if not self._dispatchq:
                    return n
                unit = self._dispatchq[0]
                with self._futures_lock:
                    undone = self._undone_members()
                if undone > 0 and undone + len(unit) > self.member_limit:
                    return n
            # early-stopping rules snapshot at DISPATCH time, not propose
            # time: lookahead materializes trials before any history
            # exists, so a rule frozen at _materialize would be
            # permanently empty.  Outside the queue lock (state > queue
            # ordering); the head is stable because this thread is the
            # only popper while the loops run.
            self._refresh_rules(unit)
            with self._queue_lock:
                if not self._dispatchq or self._dispatchq[0] is not unit:
                    continue
                self._dispatchq.popleft()
                self._submit(unit)
            n += 1
        return n

    def _refresh_rules(self, unit: list[Trial]) -> None:
        es = self.early_stopper
        if es is None:
            return
        # settle completed-but-unharvested futures first: sub-second
        # trials outrun the harvest poll, and the median needs every
        # finished trial counted as SUCCEEDED, not merely future-done
        with self._state_lock, self._futures_lock:
            self.orch._harvest(self.exp, self.futures)
            rules = es.get_rules(self.exp)
        if not rules:
            return
        for t in unit:
            if not t.spec.early_stopping_rules:
                t.spec.early_stopping_rules = rules

    def _submit(self, unit: list[Trial]) -> None:
        # called under _queue_lock
        orch, exp = self.orch, self.exp
        orch._submit_prewarm(self.spec, unit, self.mesh)
        now = time.time()
        for t in unit:
            t.condition = TrialCondition.RUNNING
            t.start_time = now
        orch._jappend_group("started", exp, unit)
        if len(unit) == 1:
            fut = self.pool.submit(orch._execute, exp, unit[0], self.mesh)
            owner: Trial | list[Trial] = unit[0]
        else:
            fut = self.pool.submit(orch._execute_cohort, exp, unit, self.mesh)
            owner = unit
        with self._futures_lock:
            self.futures[fut] = owner
        self._dispatched_total += len(unit)
        self._last_activity = time.monotonic()
        # the harvest loop republishes status.json soon after: without
        # this, a run whose trials all dispatch between publishes would
        # never show a Running trial to external watchers
        self._publish_dirty = True

    # -- harvest loop (caller thread) ---------------------------------------

    def _harvest_loop(self) -> Experiment:
        orch, exp = self.orch, self.exp
        while True:
            if self._errors:
                raise RuntimeError("; ".join(self._errors))
            with self._state_lock, self._futures_lock:
                orch._harvest(exp, self.futures)
            with self._futures_lock:
                # busy in MEMBER trials: a running cohort future fills
                # width slots' worth of the mesh on one pool thread
                busy = sum(
                    (len(o) if isinstance(o, list) else 1)
                    for f, o in self.futures.items()
                    if f.running()
                )
                undone = sum(1 for f in self.futures if not f.done())
            obs.mesh_occupancy.set(self.meter.update(busy))
            if self._publish_dirty:
                self._publish_dirty = False
                with self._state_lock:
                    orch._publish(exp)

            if orch._stop_requested.is_set():
                self.stop_event.set()
            if self.stop_event.is_set():
                return self._terminal(
                    ExperimentCondition.FAILED, message="experiment stopped"
                )
            if orch._drain_requested.is_set():
                return self._drain()

            queued = self._queued_count()
            exhausted_eff = self._exhausted.is_set() and queued == 0
            with self._state_lock:
                verdict = orch._check_terminal(exp, exhausted_eff, self.futures)
            if verdict is not None:
                return self._terminal(verdict)

            if self.breaker.tripped:
                return self._terminal(
                    ExperimentCondition.FAILED,
                    message=(
                        f"suggester failed {self.breaker.failures} consecutive "
                        f"times (suggester_max_errors="
                        f"{self.spec.suggester_max_errors}); last error:\n"
                        + self.breaker.last_failure
                    ),
                )

            # livelock guard (the sync loop's 30s stall cap): nothing in
            # flight, nothing queued, suggester idle and answering nothing
            if (
                undone == 0
                and queued == 0
                and not self._exhausted.is_set()
                and not self._suggester_busy
                and not self._suggest_inflight
            ):
                if time.monotonic() - self._last_activity > _STALL_SECONDS:
                    return self._terminal(
                        ExperimentCondition.FAILED,
                        message=(
                            "orchestrator stalled: suggester proposes nothing "
                            "with no trials in flight"
                        ),
                    )
            else:
                self._last_activity = max(self._last_activity, time.monotonic() - 1.0)
            time.sleep(orch.poll_interval)

    # -- wind-down -----------------------------------------------------------

    def _queued_count(self) -> int:
        with self._queue_lock:
            return (
                len(self._ready)
                + sum(len(b) for b in self._packing.values())
                + sum(len(u) for u in self._dispatchq)
            )

    def _update_pending_gauge(self) -> None:
        obs.pending_proposals.set(float(self._queued_count()))

    def _drain_queues(self) -> list[Trial]:
        with self._queue_lock:
            leftovers = list(self._ready)
            self._ready.clear()
            for bucket in self._packing.values():
                leftovers.extend(bucket)
            self._packing.clear()
            self._pack_ts.clear()
            for unit in self._dispatchq:
                leftovers.extend(unit)
            self._dispatchq.clear()
        return leftovers

    def _stop_loops(self) -> None:
        """Halt the suggest/schedule threads and JOIN them before the
        caller touches the queues or cancels futures — without the join, a
        dispatch racing the wind-down could submit a unit after
        ``_cancel_pending`` already ran."""
        self._halt.set()
        for t in getattr(self, "_threads", ()):
            if t is not threading.current_thread():
                t.join(timeout=_JOIN_TIMEOUT)

    def _terminal(
        self, verdict: ExperimentCondition, message: str | None = None
    ) -> Experiment:
        orch, exp = self.orch, self.exp
        self._stop_loops()
        self.stop_event.set()
        with self._futures_lock:
            orch._cancel_pending(self.futures)
        with self._state_lock, self._futures_lock:
            orch._harvest(exp, self.futures, wait_running=True)
        # proposed-but-undispatched trials mirror the sync loop's
        # cancelled-future semantics: settled KILLED, budget consumed
        now = time.time()
        for t in self._drain_queues():
            t.condition = TrialCondition.KILLED
            t.message = "cancelled: experiment terminal before dispatch"
            t.completion_time = now
            if not t.start_time:
                t.start_time = now
            obs.trials_killed.inc()
            orch._jappend("settled", exp, trial=t)
            orch._observe_trial_duration(t)
        exp.condition = verdict
        exp.message = message if message is not None else orch._terminal_message(verdict)
        exp.completion_time = time.time()
        exp.update_optimal()
        self._record_stats()
        orch._finish(exp)
        return exp

    def _drain(self) -> Experiment:
        orch, exp = self.orch, self.exp
        self._stop_loops()
        # undispatched trials never started: back to PENDING so the resumed
        # run re-seeds them into its ready queue (no budget slot consumed)
        for t in self._drain_queues():
            t.condition = TrialCondition.PENDING
            t.message = "drained before start; resubmitted on resume"
            orch._jappend("drained", exp, trial=t)
        self._record_stats()
        return orch._drain_and_exit(
            exp, self.futures, self.suggester, self.stop_event, self.drain_event
        )

    def _record_stats(self) -> None:
        """Publish the run's sustained-occupancy summary for bench/CI."""
        exp = self.exp
        elapsed = self.meter.elapsed()
        settled = sum(1 for t in exp.trials.values() if t.condition.is_terminal())
        self.orch.async_stats = {
            "sustained_occupancy": round(self.meter.sustained(), 4),
            "elapsed_s": round(elapsed, 4),
            "trials_settled": settled,
            "trials_per_sec": round(settled / elapsed, 4) if elapsed > 0 else 0.0,
            "lookahead": self.lookahead,
            "width": self.width,
            "member_limit": self.member_limit,
        }
        obs.mesh_occupancy.set(0.0)
