"""Experiment orchestrator — the in-process replacement for the reference's
controller triad (experiment/suggestion/trial reconcilers,
``pkg/controller.v1beta1/``).

Where the reference coordinates through CR status updates bounced off the
API server, this is a single event loop owning the whole experiment:

- budget math: ``parallel_trial_count`` in flight, stop at
  ``max_trial_count``, fail the experiment past ``max_failed_trial_count``
  (reference ``experiment_controller.go:274-330`` ReconcileTrials);
- suggestion sync: ask the suggester for exactly the shortfall
  (reference ``suggestionclient.go:83-96`` requests - suggestionCount);
- trial naming ``<experiment>-<rand8>`` unless the suggester names the trial
  (PBT uids) — reference ``suggestionclient.go:171-192``;
- early-stopping rules attached to each trial before launch (reference
  ``suggestionclient.go:130-189``);
- optimal-trial tracking and goal short-circuit
  (reference ``experiment/util/status_util.go``);
- trials run on a thread pool; JAX releases the GIL during device compute so
  parallel trials on one host overlap host-side work with TPU steps.  A
  multi-slice scheduler plugs in behind the same ``submit`` seam.
"""

from __future__ import annotations

import concurrent.futures as cf
import os
import secrets
import shutil
import threading
import traceback

from katib_tpu.core.types import (
    COHORT_KEY_LABEL as _COHORT_KEY_LABEL,
    DEVICES_LABEL as _DEVICES_LABEL,
    Experiment,
    ExperimentCondition,
    ExperimentSpec,
    ResumePolicy,
    Trial,
    TrialCondition,
    TrialSpec,
)
from katib_tpu.core.validation import validate_experiment
from katib_tpu.earlystop.rules import make_early_stopper
from katib_tpu.runner.cohort import cohort_fn_of, run_cohort
from katib_tpu.runner.trial_runner import (
    TrialResult,
    init_compile_cache,
    run_trial,
)
from katib_tpu.store.base import MemoryObservationStore, ObservationStore
from katib_tpu.suggest.base import call_suggester, make_suggester
from katib_tpu.utils import faults
from katib_tpu.utils import observability as obs
from katib_tpu.utils.clock import get_clock
from katib_tpu.utils import tracing
from katib_tpu.utils.watchdog import Watchdog

#: process exit code `katib-tpu run` returns after a graceful drain —
#: EX_TEMPFAIL (75), already in faults.RETRYABLE_EXIT_CODES, so a supervisor
#: (or a katib-tpu black-box parent!) reads it as "re-run me with --resume"
DRAIN_EXIT_CODE = 75


class Orchestrator:
    def __init__(
        self,
        store: ObservationStore | None = None,
        workdir: str = "katib_runs",
        mesh=None,
        poll_interval: float = 0.02,
        config=None,
        slice_allocator=None,
        fault_injector: faults.FaultInjector | None = None,
        preflight: bool | None = None,
        run_trial_fn=None,
        run_cohort_fn=None,
        token_hex=None,
        journal_snapshot_every: int | None = None,
        status_publish_interval: float = 0.0,
        suggester_fn=None,
    ):
        self.store = store if store is not None else MemoryObservationStore()
        # a defaulted store may be upgraded to the durable sqlite backend at
        # run() time for resumable experiments; an explicit store never is
        self._store_defaulted = store is None
        self.workdir = workdir
        self.mesh = mesh
        # SliceAllocator (parallel/distributed.py): concurrent trials lease
        # disjoint sub-meshes of the machine instead of sharing one mesh —
        # the chip-level analog of parallelTrialCount pod scheduling
        self.slice_allocator = slice_allocator
        self.poll_interval = poll_interval
        # KatibConfig (core/config.py): runtime registry of per-algorithm
        # defaults + profiler flags, merged into specs at run() time — the
        # analog of the reference resolving KatibConfig at reconcile time
        # (``katibconfig/config.go:60``)
        self.config = config
        # deterministic chaos harness (utils.faults.FaultInjector): threaded
        # through the suggester call and every trial attempt so tests and
        # `katib-tpu chaos` exercise the recovery paths on demand
        self.fault_injector = fault_injector
        # device preflight gate (utils.meshhealth): probe every visible
        # device under a deadline before opening the trial pool, so a wedged
        # accelerator pool fails the experiment fast with a per-device
        # health report instead of hanging in the first compile.  Explicit
        # argument wins; else opt-in via KATIB_PREFLIGHT=1 (the CLI `run`
        # verb enables it by default, library embedding stays opt-in).
        if preflight is None:
            preflight = os.environ.get("KATIB_PREFLIGHT") == "1"
        self.preflight = bool(preflight)
        # jax.profiler is a process-global singleton; only one trial may
        # trace at a time — others run unprofiled rather than crash
        self._profile_lock = threading.Lock()
        # per-run background compile prewarmer (katib_tpu/compile/prewarm.py)
        self._prewarm = None
        # per-run crash-consistent event journal (orchestrator/journal.py);
        # opened by run(), closed in its finally
        self._journal = None
        # external stop request (client delete / shutdown): sticky so a stop
        # issued before run() enters its loop is not lost; each run() has its
        # own wind-down event for in-flight trials
        self._stop_requested = threading.Event()
        self._stop_event = threading.Event()
        # graceful-drain request (preemption SIGTERM/SIGINT on the CLI):
        # sticky like stop; the per-run _drain_event asks in-flight trials to
        # checkpoint-and-exit at their next step boundary
        self._drain_requested = threading.Event()
        self._drain_event = threading.Event()
        #: True after run() returned via a drain — the CLI maps this to
        #: DRAIN_EXIT_CODE so supervisors re-launch with --resume
        self.drained = False
        #: set by the CLI only: after the grace window, stragglers that
        #: cannot be joined must not block process exit — journal, then
        #: os._exit(DRAIN_EXIT_CODE).  Library callers keep the default
        #: (False): cooperative stragglers are joined on pool shutdown.
        self.drain_hard_exit = False
        # hang watchdog shared by every trial of a run (monitor thread
        # starts lazily on the first progress_deadline_seconds trial)
        self._watchdog: Watchdog | None = None
        # trials whose checkpoint dir belongs to the suggester (PBT lineage)
        # — exempt from retain-cleanup
        self._suggester_owned_ckpts: set[str] = set()
        # per-experiment span tracer (utils.tracing); opened in run(), closed
        # by _finish(); trial pool threads pick it up via self._tracer
        self._tracer: tracing.Tracer | None = None
        self._prev_tracer: tracing.Tracer | None = None
        self._exp_span_start = 0.0
        #: sustained-occupancy / throughput summary of the most recent async
        #: run (orchestrator/async_loops.py); None under the sync path —
        #: bench.py and the CI async smoke read it after run() returns
        self.async_stats: dict | None = None
        # Dispatch seams: the virtual-time simulator (katib_tpu/sim) swaps
        # ONLY these — a modeled executor with seeded durations replaces the
        # real runner while every scheduling/settlement path stays real.
        self._run_trial_fn = run_trial_fn
        self._run_cohort_fn = run_cohort_fn
        # Trial-name entropy seam: secrets.token_hex in production, a seeded
        # stream under the simulator so journals are byte-reproducible.
        self._token_hex = token_hex if token_hex is not None else secrets.token_hex
        # Journal compaction cadence override (None = journal default).  At
        # 50k simulated trials the default every-32-settlements snapshot is
        # O(N^2/32) serialization work.
        self._journal_snapshot_every = journal_snapshot_every
        # Suggester construction seam (None = make_suggester): the simulator
        # wraps the real suggester with a modeled latency distribution.
        self._suggester_fn = suggester_fn
        # status.json republish throttle in clock seconds (0 = every call).
        # Each write serializes EVERY trial; at scale that dominates.
        self._status_publish_interval = float(status_publish_interval)
        self._status_published_at: float | None = None

    def stop(self) -> None:
        """Request the experiment wind down (the reference's experiment
        deletion path, ``experiment_controller.go:362-403``).  Sticky: a
        stopped orchestrator will not run further experiments."""
        self._stop_requested.set()
        self._stop_event.set()

    def drain(self) -> None:
        """Request a graceful drain (preemption semantics): stop proposing,
        ask running trials/cohorts to checkpoint-and-exit at their next step
        boundary, flush journal + suggester state, and return with the
        experiment still non-terminal so ``--resume`` continues it.  Bounded
        by ``ExperimentSpec.drain_grace_seconds``; see :data:`DRAIN_EXIT_CODE`.
        A second signal should call :meth:`stop` instead (abandon drain)."""
        self._drain_requested.set()
        self._drain_event.set()
        obs.drain_requested.set(1)

    # -- public API ---------------------------------------------------------

    def load_experiment(self, spec: ExperimentSpec) -> Experiment | None:
        """Reconstruct a previously journaled experiment from the workdir
        (``status.json``), or None when no journal exists.  Pass the result
        to :meth:`run` to resume across a process restart (the reference
        resurrects experiments from CR state + the suggestion PVC,
        ``suggestion_controller.go:181-193``)."""
        from katib_tpu.orchestrator.resume import load_experiment

        return load_experiment(spec, self.workdir)

    def run(
        self,
        spec: ExperimentSpec,
        experiment: Experiment | None = None,
        resume: bool = False,
    ) -> Experiment:
        """Run an experiment to a terminal condition; returns it with full
        trial history and optimal-trial status.  Pass an existing
        ``experiment`` — or ``resume=True`` to load one from the status
        journal — to resume (``ResumePolicy`` semantics: a completed
        experiment re-opens when ``max_trial_count`` was raised, reference
        ``experiment_controller.go:187-206``)."""
        if self.config is not None:
            spec = self.config.apply_to(spec)
        validate_experiment(spec)
        # persistent XLA compilation cache (KATIB_COMPILE_CACHE env wins,
        # spec field second); process-global, first writer wins
        init_compile_cache(spec.compile_cache)
        # shared serialized-executable tier (KATIB_ARTIFACT_DIR env wins,
        # spec field second); same first-caller-wins contract
        from katib_tpu.compile.artifacts import ARTIFACTS

        ARTIFACTS.configure(spec.artifact_dir)
        if resume and experiment is None:
            experiment = self.load_experiment(spec)
        exp = experiment or Experiment(spec=spec)
        if experiment is not None:
            exp.spec = spec
            if exp.condition.is_terminal():
                if spec.resume_policy is ResumePolicy.NEVER:
                    raise RuntimeError(
                        f"experiment {exp.name} is terminal and resume_policy=Never"
                    )
                exp.condition = ExperimentCondition.RESTARTING
                exp.completion_time = 0.0

        suggester = (self._suggester_fn or make_suggester)(spec)
        # restore durable suggester state (ENAS controller pytree, PBT job
        # queue) — the FromVolume PVC analog, FENCED against the experiment
        # journal: a pickle written before settlements the journal proves
        # (hard kill between a settle and the next persist) is stale and is
        # discarded — the replay-derived fresh suggester rebuilds from trial
        # history instead of trusting it blindly.  Never-policy experiments
        # keep no state on disk, matching the reference tearing the service
        # down with nothing to resurrect from.
        if experiment is not None and spec.resume_policy is not ResumePolicy.NEVER:
            from katib_tpu.orchestrator import journal as _journal_mod
            from katib_tpu.orchestrator.resume import load_suggester_state

            load_suggester_state(
                suggester,
                self.workdir,
                exp.name,
                settled_fence=_journal_mod.last_settled_seq(self.workdir, exp.name),
            )
        # Durable-by-default observations: a defaulted in-memory store is
        # upgraded to the sqlite WAL backend for EVERY run, so a hard kill
        # never loses reported series (the reference's observations live in
        # the DB-manager's SQL table and survive controller restarts for
        # free — ``mysql/init.go:35``) and early stopping reads TRUE
        # per-trial series across restarts instead of _backfill_store's
        # one-point approximation.  An explicitly passed store is never
        # touched.
        if self._store_defaulted:
            from katib_tpu.store.sqlite import SqliteObservationStore

            os.makedirs(self.workdir, exist_ok=True)
            self.store = SqliteObservationStore(
                os.path.join(self.workdir, "observations.sqlite")
            )
            self._store_defaulted = False  # keep it for later runs too
        # crash-consistent event journal (orchestrator/journal.py): the
        # durable source of truth for resume; status.json stays the derived
        # CLI/UI view.  Best-effort open — an unwritable workdir degrades to
        # the pre-journal behavior rather than failing the experiment.
        try:
            from katib_tpu.orchestrator.journal import ExperimentJournal

            if self._journal_snapshot_every is not None:
                self._journal = ExperimentJournal(
                    self.workdir, exp.name,
                    snapshot_every=self._journal_snapshot_every,
                )
            else:
                self._journal = ExperimentJournal(self.workdir, exp.name)
        except OSError:
            self._journal = None
        if experiment is not None:
            self._backfill_store(exp)
        early_stopper = make_early_stopper(spec)
        if early_stopper is not None and hasattr(early_stopper, "bind_store"):
            early_stopper.bind_store(self.store)

        exp.condition = ExperimentCondition.RUNNING
        self._jappend(
            "experiment",
            exp,
            extra={"name": exp.name, "algorithm": spec.algorithm.name},
        )
        obs.experiments_created.inc(algorithm=spec.algorithm.name)
        obs.experiments_current.inc()
        # open the span journal (append-mode: a resumed experiment continues
        # from the prior max elapsed offset); tracing is best-effort — an
        # unwritable workdir must not fail the experiment, and KATIB_TRACE=0
        # suppresses it entirely
        try:
            self._tracer = (
                tracing.Tracer(
                    tracing.trace_path(self.workdir, exp.name),
                    experiment=exp.name,
                )
                if tracing.enabled()
                else None
            )
        except OSError:
            self._tracer = None
        self._exp_span_start = self._tracer.elapsed() if self._tracer else 0.0
        self._prev_tracer = tracing.activate(self._tracer)
        self._publish(exp)
        exhausted = False
        stalled_polls = 0
        # suggester fault isolation: absorb up to suggester_max_errors - 1
        # CONSECUTIVE get_suggestions exceptions (counted + cooled down with
        # backoff) while in-flight trials keep running; the Nth trips the
        # breaker and fails the experiment with the last traceback
        breaker = faults.CircuitBreaker(threshold=spec.suggester_max_errors)
        # value is the submitted unit: one Trial, or the member list of a
        # vectorized cohort (runner/cohort.py) sharing a single future
        futures: dict[cf.Future, Trial | list[Trial]] = {}
        # per-run wind-down signal for in-flight trials, set on a terminal
        # verdict or an external stop() (the reference deletes running trial
        # jobs, experiment_controller.go:362).  A fresh run() (resume) gets a
        # fresh event; the sticky _stop_requested flag survives so a stop()
        # racing run() startup is never lost.
        stop_event = threading.Event()
        self._stop_event = stop_event
        if self._stop_requested.is_set():
            stop_event.set()
        # fresh per-run drain event (a resumed run must not inherit the
        # previous process's drain); the sticky request flag is honored on
        # the first loop iteration
        drain_event = threading.Event()
        self._drain_event = drain_event
        if self._drain_requested.is_set():
            drain_event.set()
        self.drained = False
        obs.drain_requested.set(1.0 if self._drain_requested.is_set() else 0.0)
        self._watchdog = Watchdog()
        # background compile prewarmer (katib_tpu/compile/): fed with each
        # upcoming group's shape signature below, stopped in the finally —
        # strictly best-effort, a dead worker only means cold first steps
        if spec.prewarm:
            from katib_tpu.compile.prewarm import PrewarmWorker

            self._prewarm = PrewarmWorker()
        else:
            self._prewarm = None

        # a bad mesh config must still settle the experiments_current gauge
        # and the status journal before surfacing
        try:
            mesh = self._resolve_mesh(spec)
            self._validate_mesh(spec, mesh)
        except Exception:
            exp.condition = ExperimentCondition.FAILED
            exp.message = "mesh config error:\n" + traceback.format_exc(limit=5)
            exp.completion_time = get_clock().time()
            exp.update_optimal()
            self._finish(exp)
            raise

        # device preflight gate: a wedged pool fails the experiment FAST
        # (terminal + journaled machine-readable report) instead of hanging
        # in the first trial's compile.  Runs after tracer activation so the
        # "preflight" span lands in the trace journal.
        if self.preflight:
            from katib_tpu.utils import meshhealth

            report = meshhealth.preflight(injector=self.fault_injector)
            if not report.ok():
                exp.condition = ExperimentCondition.FAILED
                exp.message = "device preflight failed: " + report.summary()
                exp.completion_time = get_clock().time()
                exp.update_optimal()
                self._finish(exp)
                raise RuntimeError(exp.message)

        # Podracer-style async engine (orchestrator/async_loops.py): default
        # ON; spec.async_orch wins, else the KATIB_ASYNC_ORCH env var — "0"
        # is the one-release escape hatch back to the synchronous loop
        self.async_stats = None
        use_async = (
            spec.async_orch
            if spec.async_orch is not None
            else os.environ.get("KATIB_ASYNC_ORCH", "1") != "0"
        )

        with cf.ThreadPoolExecutor(
            max_workers=spec.parallel_trial_count, thread_name_prefix=f"trial-{exp.name}"
        ) as pool:
          try:
            # trials orphaned by a process restart (journaled non-terminal →
            # PENDING): same name/assignments/checkpoint dir, so a
            # checkpoint-aware train_fn resumes mid-trial — the analog of
            # trial jobs surviving a controller restart in the reference.
            # The sync loop resubmits them directly; the async engine seeds
            # them into its ready queue so they flow through cohort packing
            # and occupancy backpressure like any other proposal.
            orphans: list[Trial] = []
            for trial in exp.trials.values():
                if trial.condition in (TrialCondition.PENDING, TrialCondition.CREATED):
                    if early_stopper is not None and not trial.spec.early_stopping_rules:
                        trial.spec.early_stopping_rules = early_stopper.get_rules(exp)
                    if hasattr(suggester, "checkpoint_dir_for"):
                        self._suggester_owned_ckpts.add(trial.name)
                    if use_async:
                        trial.condition = TrialCondition.PENDING
                        orphans.append(trial)
                        continue
                    trial.condition = TrialCondition.RUNNING
                    trial.start_time = get_clock().time()
                    self._jappend("started", exp, trial=trial)
                    futures[get_clock().submit(pool, self._execute, exp, trial, mesh)] = trial
            if use_async:
                from katib_tpu.orchestrator.async_loops import AsyncLoops

                engine = AsyncLoops(
                    self,
                    exp,
                    suggester,
                    early_stopper,
                    mesh,
                    pool,
                    breaker,
                    stop_event,
                    drain_event,
                    futures,
                    initial_ready=orphans,
                )
                result = engine.run()
                if result is not None:
                    return result
                # supervisor exhausted its loop-restart budget: degrade to
                # this synchronous loop instead of dying.  In-flight futures
                # stay live in the shared dict and are harvested below; the
                # engine already journaled the fallback and put queued
                # proposals back to PENDING — resubmit them here like
                # restart orphans.
                exhausted = engine._exhausted.is_set()
                inflight: set[str] = set()
                for owner in futures.values():
                    for t in owner if isinstance(owner, list) else [owner]:
                        inflight.add(t.name)
                resubmit = [
                    t
                    for t in exp.trials.values()
                    if t.condition
                    in (TrialCondition.PENDING, TrialCondition.CREATED)
                    and t.name not in inflight
                ]
                for trial in resubmit:
                    trial.condition = TrialCondition.RUNNING
                    trial.start_time = get_clock().time()
                    futures[get_clock().submit(pool, self._execute, exp, trial, mesh)] = trial
                self._jappend_group("started", exp, resubmit)
            while True:
                self._harvest(exp, futures)
                if self._stop_requested.is_set():
                    stop_event.set()
                if stop_event.is_set():
                    # external stop: cancel queued work, wait out running
                    # trials (they observe the event via their context)
                    self._cancel_pending(futures)
                    self._harvest(exp, futures, wait_running=True)
                    exp.condition = ExperimentCondition.FAILED
                    exp.message = "experiment stopped"
                    exp.completion_time = get_clock().time()
                    exp.update_optimal()
                    self._finish(exp)
                    return exp
                if self._drain_requested.is_set():
                    # preemption drain: checkpoint-and-exit within the grace
                    # window, journal everything, return NON-terminal so the
                    # next process resumes from the checkpointed steps
                    return self._drain_and_exit(
                        exp, futures, suggester, stop_event, drain_event
                    )
                verdict = self._check_terminal(exp, exhausted, futures)
                if verdict is not None:
                    stop_event.set()
                    self._cancel_pending(futures)
                    self._harvest(exp, futures, wait_running=True)
                    exp.condition = verdict
                    exp.completion_time = get_clock().time()
                    exp.update_optimal()
                    exp.message = self._terminal_message(verdict)
                    self._finish(exp)
                    return exp

                want = self._shortfall(exp, futures)
                proposals = []
                suggester_busy = False  # erroring or cooling down, not idle
                if want > 0 and not exhausted:
                    if not breaker.allow():
                        # bounded retry-with-backoff: skip the call while the
                        # breaker cools down, keep harvesting in-flight trials
                        suggester_busy = True
                    else:
                        sug_start = self._tracer.elapsed() if self._tracer else 0.0
                        t_sug = get_clock().perf_counter()
                        proposals, outcome = call_suggester(
                            suggester, exp, want, breaker, self.fault_injector
                        )
                        if outcome == "exhausted":
                            exhausted = True
                        elif outcome == "error":
                            suggester_busy = True
                            obs.suggester_errors.inc(algorithm=spec.algorithm.name)
                        sug_dur = get_clock().perf_counter() - t_sug
                        obs.suggestion_latency.observe(
                            sug_dur, algorithm=spec.algorithm.name
                        )
                        # don't journal the thousands of sub-ms not-ready polls a
                        # rung-gated suggester (Hyperband/ENAS) answers per trial
                        if self._tracer is not None and (
                            proposals
                            or outcome in ("exhausted", "error")
                            or sug_dur >= 1e-3
                        ):
                            self._tracer.record(
                                "suggest",
                                sug_start,
                                sug_dur,
                                algorithm=spec.algorithm.name,
                                count=len(proposals),
                                outcome=outcome,
                            )
                        for group in self._group_proposals(spec, proposals, mesh):
                            trials = [
                                self._materialize(exp, p, early_stopper, suggester)
                                for p in group
                            ]
                            # queue the group's compile signature on the
                            # prewarm worker: while the pool is busy with
                            # earlier cohorts, this group's program compiles
                            # in the background so its first step is warm
                            self._submit_prewarm(spec, trials, mesh)
                            if len(trials) == 1:
                                futures[
                                    get_clock().submit(pool, self._execute, exp, trials[0], mesh)
                                ] = trials[0]
                            else:
                                # one pool slot runs the whole cohort; the
                                # member list keeps _shortfall's budget honest
                                futures[
                                    get_clock().submit(pool, self._execute_cohort, exp, trials, mesh)
                                ] = trials
                        if proposals:
                            self._persist_suggester(exp, suggester)
                            # journal the newly in-flight trials so a crash here
                            # leaves resubmittable orphans (and the UI sees them)
                            self._publish(exp)

                if breaker.tripped:
                    # N consecutive suggester failures: terminal.  Wind down
                    # in-flight trials, surface the last traceback.
                    stop_event.set()
                    self._cancel_pending(futures)
                    self._harvest(exp, futures, wait_running=True)
                    exp.condition = ExperimentCondition.FAILED
                    exp.message = (
                        f"suggester failed {breaker.failures} consecutive times "
                        f"(suggester_max_errors={spec.suggester_max_errors}); "
                        "last error:\n" + breaker.last_failure
                    )
                    exp.completion_time = get_clock().time()
                    exp.update_optimal()
                    self._finish(exp)
                    return exp

                # livelock guard: nothing running, nothing proposed, not
                # exhausted — a buggy suggester would spin here forever.  A
                # cooling/erroring suggester is the breaker's problem, not a
                # stall: its own threshold terminates the experiment.
                if not futures and not proposals and not exhausted and not suggester_busy:
                    stalled_polls += 1
                    if stalled_polls * self.poll_interval > 30.0:
                        exp.condition = ExperimentCondition.FAILED
                        exp.message = (
                            "orchestrator stalled: suggester proposes nothing "
                            "with no trials in flight"
                        )
                        exp.completion_time = get_clock().time()
                        exp.update_optimal()
                        self._finish(exp)
                        return exp
                else:
                    stalled_polls = 0
                get_clock().sleep(self.poll_interval)
          except Exception:
            # bookkeeping must not be skipped on an orchestrator/suggester
            # bug: wind down in-flight trials, record the failure, balance
            # the experiments_current gauge, then surface the bug
            stop_event.set()
            self._cancel_pending(futures)
            self._harvest(exp, futures, wait_running=True)
            exp.condition = ExperimentCondition.FAILED
            exp.message = "orchestrator error:\n" + traceback.format_exc(limit=20)
            exp.completion_time = get_clock().time()
            exp.update_optimal()
            self._finish(exp)
            raise
          finally:
            watchdog, self._watchdog = self._watchdog, None
            if watchdog is not None:
                watchdog.stop()
            # wind down the prewarm worker (bounded; an in-flight compile is
            # abandoned on its daemon thread — nothing waits on it)
            prewarm, self._prewarm = self._prewarm, None
            if prewarm is not None:
                prewarm.stop()
            # final durable-state write so a completed-then-reopened
            # experiment (raised max_trial_count) resumes the suggester too
            self._persist_suggester(exp, suggester)
            # suggester teardown (remote services evict their per-experiment
            # state — the analog of deleting the algorithm Deployment,
            # ``suggestion_controller.go:132-143``); best-effort
            closer = getattr(suggester, "close", None)
            if closer is not None:
                try:
                    closer(exp)
                except Exception:
                    pass
            journal, self._journal = self._journal, None
            if journal is not None:
                journal.close()

    # -- internals ----------------------------------------------------------

    def _journal_exp_state(self, exp: Experiment) -> dict:
        """The experiment-level slice every journal record carries so replay
        is state-identical to a status.json resume (trial dicts ride
        separately per record)."""
        return {
            "condition": exp.condition.value,
            "message": exp.message,
            "start_time": exp.start_time,
            "completion_time": exp.completion_time,
            "algorithm_settings": dict(exp.algorithm_settings),
            "optimal": (
                None
                if exp.optimal is None
                else {
                    "trial_name": exp.optimal.trial_name,
                    "objective_value": exp.optimal.objective_value,
                    "assignments": {
                        a.name: a.value for a in exp.optimal.assignments
                    },
                }
            ),
            "optimal_history": list(exp.optimal_history),
        }

    def _jappend(
        self,
        event: str,
        exp: Experiment,
        trial: Trial | None = None,
        extra: dict | None = None,
    ) -> None:
        """Durably journal one state transition; best-effort like _publish —
        a full disk must degrade resume fidelity, not kill the run loop.
        Thread-safe (the journal locks internally): retry records arrive
        from trial pool threads."""
        j = self._journal
        if j is None:
            return
        try:
            from katib_tpu.orchestrator.status import trial_to_dict

            data: dict = {"exp": self._journal_exp_state(exp)}
            if trial is not None:
                data["trial"] = trial_to_dict(trial)
            if extra:
                data.update(extra)
            j.append(
                event,
                trial=trial.name if trial is not None else None,
                epoch=trial.retry_count if trial is not None else 0,
                data=data,
            )
        except (OSError, ValueError):
            pass

    def _jappend_group(
        self, event: str, exp: Experiment, trials: list[Trial]
    ) -> None:
        """Journal one state transition for a batch of trials with a single
        durability barrier (``Journal.append_group``) — the async engine's
        bulk hand-offs would otherwise pay one fsync per trial."""
        j = self._journal
        if j is None or not trials:
            return
        try:
            from katib_tpu.orchestrator.status import trial_to_dict

            exp_state = self._journal_exp_state(exp)
            j.append_group(
                [
                    (
                        event,
                        t.name,
                        t.retry_count,
                        {"exp": exp_state, "trial": trial_to_dict(t)},
                    )
                    for t in trials
                ]
            )
        except (OSError, ValueError):
            pass

    def _materialize(
        self,
        exp: Experiment,
        proposal,
        early_stopper,
        suggester,
        condition: TrialCondition = TrialCondition.RUNNING,
        journal: bool = True,
    ) -> Trial:
        name = proposal.name or f"{exp.name}-{self._token_hex(4)}"
        rules = list(proposal.early_stopping_rules)
        if early_stopper is not None and not rules:
            rules = early_stopper.get_rules(exp)
        # PBT pre-populates lineage checkpoints in its own directory layout
        if hasattr(suggester, "checkpoint_dir_for"):
            ckpt = suggester.checkpoint_dir_for(name)
            self._suggester_owned_ckpts.add(name)
        else:
            ckpt = os.path.join(self.workdir, exp.name, name)
        trial = Trial(
            name=name,
            experiment_name=exp.name,
            spec=TrialSpec(
                assignments=list(proposal.assignments),
                early_stopping_rules=rules,
                labels=dict(proposal.labels),
                train_fn=exp.spec.train_fn,
                command=list(exp.spec.command) if exp.spec.command else None,
                metrics_collector=exp.spec.metrics_collector,
                retain=exp.spec.retain,
                max_runtime_seconds=exp.spec.max_trial_runtime_seconds,
                metrics_retries=exp.spec.metrics_retries,
                max_retries=exp.spec.max_retries,
                retry_backoff_seconds=exp.spec.retry_backoff_seconds,
                progress_deadline_seconds=exp.spec.progress_deadline_seconds,
                compile_deadline_seconds=exp.spec.compile_deadline_seconds,
            ),
            # async proposals wait in the ready queue as PENDING (started at
            # dispatch); the sync loop submits immediately as RUNNING
            condition=condition,
            start_time=get_clock().time() if condition is TrialCondition.RUNNING else 0.0,
            checkpoint_dir=ckpt,
        )
        exp.trials[name] = trial
        # journal=False lets the async engine batch a whole refill's
        # ``proposed`` records into one append_group durability barrier
        if journal:
            self._jappend("proposed", exp, trial=trial)
        obs.trials_created.inc()
        return trial

    def _resolve_mesh(self, spec: ExperimentSpec):
        """Explicit mesh wins; otherwise the config registry decides —
        per-algorithm ``runtime.algorithms.<name>.mesh_axes`` over the
        ``init.mesh_axes`` default (the analog of per-algorithm resource
        registration, ``composer.go:72``)."""
        if self.mesh is not None or self.config is None:
            return self.mesh
        axes = self.config.mesh_axes_for(spec.algorithm.name)
        if not axes:
            return None
        import math as _math

        import jax

        from katib_tpu.parallel.mesh import make_mesh

        # a trial mesh may cover a subset of the machine (multiple trials
        # share the slice); take the first prod(axes) devices
        want = _math.prod(axes.values())
        return make_mesh(axes, devices=jax.devices()[:want])

    #: trial label naming how many devices its lease should span (elastic
    #: allocator only) — suggesters/users raise it per rung the way
    #: Hyperband raises epochs; one shared jax-free definition in core.types
    DEVICES_LABEL = _DEVICES_LABEL

    def _validate_mesh(self, spec: ExperimentSpec, mesh) -> None:
        """Mesh/spec cross-checks that only the orchestrator can make (spec
        validation never sees the mesh): a ``trial`` axis shards vmap-batched
        cohort members, which only white-box train_fn trials can become."""
        if mesh is None:
            return
        from katib_tpu.parallel.mesh import trial_axis_size

        if trial_axis_size(mesh) > 1 and spec.train_fn is None:
            raise ValueError(
                "mesh carries a trial axis of size "
                f"{trial_axis_size(mesh)}, but the experiment runs black-box "
                "command trials — the trial axis shards white-box cohort "
                "members only (drop the axis or use a train_fn)"
            )

    #: implicit cohort key stamped when a trial-axis mesh is configured but
    #: neither the proposals nor the spec name one — the slice should fill
    #: without every caller re-declaring the obvious
    _TRIAL_MESH_KEY = "trial-mesh"

    def _group_proposals(
        self, spec: ExperimentSpec, proposals: list, mesh=None
    ) -> list[list]:
        """Partition a batch of proposals into cohort groups (each submitted
        as ONE vmap-batched program, ``runner/cohort.py``).

        Grouping needs an effective cohort width > 1 AND a train_fn with a
        declared cohort twin.  The width is ``spec.cohort_width`` raised to
        the mesh's trial-axis size when one is configured — a v5e-8 with a
        ``{trial: 8}`` mesh fills all 8 chips per cohort even when the spec
        says ``cohortWidth: 1``, so Hyperband/random sweeps saturate the
        slice without spec changes.  Compatibility key: the per-proposal
        ``katib-tpu/cohort-key`` label (suggesters stamp it when members
        must share a compiled program), falling back to the spec-wide
        ``cohort_key`` and, on a trial-axis mesh, an implicit key (members
        that disagree structurally still settle correctly via the runtime
        ``shared()`` check + serial fallback, just slower — group
        heterogeneous sweeps under explicit keys).  Keyless proposals stay
        singletons.  The key is stamped back into the proposal labels so
        the journal/UI show which cohort a trial rode in."""
        trial_devices = 1
        if mesh is not None:
            from katib_tpu.parallel.mesh import trial_axis_size

            trial_devices = trial_axis_size(mesh)
        width = max(spec.cohort_width, trial_devices)
        if width <= 1 or cohort_fn_of(spec.train_fn) is None:
            return [[p] for p in proposals]
        default_key = spec.cohort_key or (
            self._TRIAL_MESH_KEY if trial_devices > 1 else None
        )
        groups: list[list] = []
        buckets: dict[str, list] = {}
        for p in proposals:
            key = p.labels.get(_COHORT_KEY_LABEL) or default_key
            if not key:
                groups.append([p])
                continue
            p.labels.setdefault(_COHORT_KEY_LABEL, key)
            buckets.setdefault(key, []).append(p)
        for bucket in buckets.values():
            for i in range(0, len(bucket), width):
                groups.append(bucket[i : i + width])
        return groups

    def _submit_prewarm(self, spec: ExperimentSpec, trials: list[Trial], mesh) -> None:
        """Enqueue one group's compile signature on the prewarm worker.
        Best-effort and non-blocking: no worker, no prewarm twin, a full
        queue, or an already-registered signature all silently no-op, and
        nothing here may fail the submit path."""
        worker = self._prewarm
        if worker is None:
            return
        try:
            from katib_tpu.compile.buckets import bucketed_cohort_size
            from katib_tpu.compile.prewarm import PrewarmRequest
            from katib_tpu.compile.registry import shared_structural
            from katib_tpu.parallel.mesh import padded_cohort_size, trial_axis_size

            sig_mesh = mesh
            if len(trials) > 1:
                # mirror CohortContext.padded_size / cohort_mesh so the
                # prewarmed signature matches the one run_cohort classifies
                # against (a mesh without a trial axis runs cohorts as a
                # single-device vmap — cohort_mesh is None there)
                k = (
                    bucketed_cohort_size(len(trials), mesh)
                    if spec.cohort_buckets
                    else padded_cohort_size(len(trials), mesh)
                )
                program_fn = cohort_fn_of(spec.train_fn)
                if trial_axis_size(mesh) <= 1:
                    sig_mesh = None
            else:
                k = 1
                program_fn = None
            worker.submit(
                PrewarmRequest(
                    train_fn=spec.train_fn,
                    shared=shared_structural([t.params() for t in trials]),
                    k=k,
                    mesh=sig_mesh,
                    program_fn=program_fn,
                )
            )
        except Exception:
            pass  # prewarm must never take down the submit loop

    def _execute_cohort(self, exp: Experiment, trials: list[Trial], mesh):
        """Run a cohort on one pool thread; returns ``{name: TrialResult}``.
        Never raises (harvest calls ``f.result()`` bare).

        Retry semantics for members mirror the serial ``_execute_with_retry``
        families, but a retried member REJOINS AS A SINGLETON: its cohort
        peers have already finished, so the re-run goes through the ordinary
        serial path (same name + checkpoint dir, full remaining budget)."""
        with tracing.use_tracer(self._tracer):
            try:
                results = (self._run_cohort_fn or run_cohort)(
                    trials,
                    self.store,
                    exp.spec.objective,
                    mesh=mesh,
                    stop_event=self._stop_event,
                    injector=self.fault_injector,
                    watchdog=self._watchdog,
                    drain_event=self._drain_event,
                    buckets=exp.spec.cohort_buckets,
                )
            except Exception as e:  # defense: run_cohort itself never raises
                results = {
                    t.name: TrialResult(
                        TrialCondition.FAILED,
                        traceback.format_exc(limit=20),
                        failure_kind=faults.classify_exception(e),
                    )
                    for t in trials
                }
            for t in trials:
                r = results.get(t.name)
                if r is None:
                    results[t.name] = TrialResult(
                        TrialCondition.FAILED,
                        "cohort returned no result for member",
                        failure_kind=faults.FailureKind.PERMANENT,
                    )
                    continue
                if (
                    r.condition is TrialCondition.FAILED
                    and r.failure_kind is not None
                    and r.failure_kind.retryable
                    and t.retry_count < t.spec.max_retries
                    and not self._stop_event.is_set()
                    and not self._drain_event.is_set()
                ):
                    t.retry_count += 1
                    t.failure_kind = r.failure_kind.value
                    obs.trials_retried.inc(kind=r.failure_kind.value)
                    # kill window: budget spent in memory, not yet durable —
                    # the journal record below is what makes it crash-proof
                    faults.crash_point("retry.budget")
                    self._jappend("retried", exp, trial=t)
                    self._publish(exp)
                    results[t.name] = self._execute(exp, t, mesh)
                elif (
                    r.condition is TrialCondition.METRICS_UNAVAILABLE
                    and t.spec.metrics_retries > 0
                    and not self._stop_event.is_set()
                ):
                    results[t.name] = self._execute(exp, t, mesh)
            return results

    def _execute(self, exp: Experiment, trial: Trial, mesh):
        # invariant: never raises — _harvest calls f.result() bare.
        # Runs on a pool thread: adopt the experiment tracer as this thread's
        # ambient tracer so runner/NAS spans land in the same journal, and
        # bracket the whole attempt in a "trial" span.
        with tracing.use_tracer(self._tracer):
            with tracing.span("trial", trial=trial.name) as sp:
                result = self._execute_inner(exp, trial, mesh)
                sp.set(condition=result.condition.value)
                try:
                    # roofline attrs the runner's heartbeats published on
                    # this thread (empty when the trial observed no cost)
                    from katib_tpu import costmodel

                    attrs = costmodel.span_attrs()
                    if attrs:
                        sp.set(**attrs)
                except Exception:
                    pass
                return result

    def _execute_inner(self, exp: Experiment, trial: Trial, mesh):
        if self.slice_allocator is not None and mesh is None:
            try:
                kwargs = {}
                want = trial.spec.labels.get(self.DEVICES_LABEL)
                if want is not None:
                    from katib_tpu.parallel.distributed import ElasticSliceAllocator

                    if not isinstance(self.slice_allocator, ElasticSliceAllocator):
                        if not getattr(self, "_warned_devices_label", False):
                            self._warned_devices_label = True
                            import warnings

                            warnings.warn(
                                f"trials carry the {self.DEVICES_LABEL} label but "
                                "the orchestrator's allocator is fixed-size; use "
                                "ElasticSliceAllocator for rung-scalable leases",
                                RuntimeWarning,
                                stacklevel=2,
                            )
                    if isinstance(self.slice_allocator, ElasticSliceAllocator):
                        # clamp both directions: a suggester that keeps
                        # doubling the budget past the machine gets the whole
                        # machine (top-rung survivors must not FAIL), and
                        # garbage parses as the 1-device minimum
                        try:
                            n = int(float(want))
                        except (TypeError, ValueError):
                            n = 1
                        kwargs["n_devices"] = min(
                            max(1, n), self.slice_allocator.n_devices
                        )
                with self.slice_allocator.slice_mesh(**kwargs) as trial_mesh:
                    return self._execute_with_retry(exp, trial, trial_mesh)
            except Exception as e:
                return TrialResult(
                    TrialCondition.FAILED,
                    traceback.format_exc(limit=20),
                    failure_kind=faults.classify_exception(e),
                )
        return self._execute_with_retry(exp, trial, mesh)

    def _execute_with_retry(self, exp: Experiment, trial: Trial, mesh):
        """Bounded re-execution of one trial slot; both retry families share
        the exponential-backoff helper (jittered, capped at ~30s, responsive
        to ``stop_event`` so a requested stop is never delayed by a pending
        retry):

        - **transient failures** (``max_retries``): preemptions /
          RESOURCE_EXHAUSTED / retryable exit codes re-run under the same
          name and checkpoint dir so a checkpoint-aware ``train_fn`` resumes
          mid-trial; PERMANENT failures (ValueError/assertion/shape errors)
          classify immediately.  Each spent retry bumps ``trial.retry_count``
          and is journaled *before* the backoff sleep, so resume-after-crash
          continues with the budget already spent instead of resetting it.
        - **metrics-unavailable re-runs** (``metrics_retries``): the trial
          exited cleanly but never reported the objective — the analog of
          the reference requeueing metrics-not-reported trials after 1s
          (``trial_controller.go:182-185``).

        The trial stays non-terminal throughout, so it consumes exactly one
        ``max_trial_count`` slot regardless of attempts."""
        backoff = faults.Backoff(
            base=trial.spec.retry_backoff_seconds,
            cap=30.0,
            seed=f"{exp.name}:{trial.name}",
        )
        attempts = 1
        result = self._execute_on(exp, trial, mesh)
        while (
            result.condition is TrialCondition.FAILED
            and result.failure_kind is not None
            and result.failure_kind.retryable  # TRANSIENT and HANG re-run
            and trial.retry_count < trial.spec.max_retries
            and not self._stop_event.is_set()
            and not self._drain_event.is_set()  # draining: journal, don't re-run
        ):
            trial.retry_count += 1
            trial.failure_kind = result.failure_kind.value
            obs.trials_retried.inc(kind=result.failure_kind.value)
            # journal the spent retry before sleeping: a crash mid-backoff
            # must not reset the per-trial retry budget on resume.  The
            # crash point covers the window where the bump is memory-only.
            faults.crash_point("retry.budget")
            self._jappend("retried", exp, trial=trial)
            self._publish(exp)
            if not backoff.wait(trial.retry_count, self._stop_event):
                break
            attempts += 1
            result = self._execute_on(exp, trial, mesh)
        for i in range(trial.spec.metrics_retries):
            if result.condition is not TrialCondition.METRICS_UNAVAILABLE:
                break
            if self._drain_event.is_set():
                break
            if not backoff.wait(i + 1, self._stop_event):
                break
            attempts += 1
            result = self._execute_on(exp, trial, mesh)
        obs.trial_attempts.observe(float(attempts))
        return result

    def _execute_on(self, exp: Experiment, trial: Trial, mesh):
        want_profile = self.config is not None and self.config.init.enable_profiler
        if want_profile and self._profile_lock.acquire(blocking=False):
            try:
                from katib_tpu.costmodel import profiler as costprofiler

                trace_dir = os.path.join(trial.checkpoint_dir, "profile")
                # capture() registers the dir (served by /api/status and
                # `katib-tpu profile --list`) and brackets the attempt in a
                # profile.capture span carrying trace_dir, so the capture
                # stays discoverable after the run
                with costprofiler.capture(
                    trace_dir, trial=trial.name, experiment=exp.name
                ):
                    return (self._run_trial_fn or run_trial)(
                        trial, self.store, exp.spec.objective,
                        mesh=mesh, stop_event=self._stop_event,
                        injector=self.fault_injector,
                        watchdog=self._watchdog,
                        drain_event=self._drain_event,
                    )
            except Exception as e:
                return TrialResult(
                    TrialCondition.FAILED,
                    traceback.format_exc(limit=20),
                    failure_kind=faults.classify_exception(e),
                )
            finally:
                self._profile_lock.release()
        return (self._run_trial_fn or run_trial)(
            trial,
            self.store,
            exp.spec.objective,
            mesh=mesh,
            stop_event=self._stop_event,
            injector=self.fault_injector,
            watchdog=self._watchdog,
            drain_event=self._drain_event,
        )

    def _finish(self, exp: Experiment) -> None:
        """Terminal bookkeeping: observability counters + final status write
        (reference ``prometheus_metrics.go`` experiment counters)."""
        obs.experiments_current.dec()
        if exp.condition is ExperimentCondition.FAILED:
            obs.experiments_failed.inc(algorithm=exp.spec.algorithm.name)
        else:
            obs.experiments_succeeded.inc(algorithm=exp.spec.algorithm.name)
        duration = (exp.completion_time or get_clock().time()) - exp.start_time
        obs.experiment_duration.observe(
            max(duration, 0.0),
            algorithm=exp.spec.algorithm.name,
            condition=exp.condition.value,
        )
        tracer, self._tracer = self._tracer, None
        if tracer is not None:
            tracer.record(
                "experiment",
                self._exp_span_start,
                tracer.elapsed() - self._exp_span_start,
                algorithm=exp.spec.algorithm.name,
                condition=exp.condition.value,
                trials=len(exp.trials),
            )
            tracing.deactivate(self._prev_tracer)
            tracer.close()
        # terminal record + final snapshot: a later resume replays one
        # snapshot instead of the whole event log
        self._jappend("experiment", exp)
        if self._journal is not None:
            try:
                from katib_tpu.orchestrator.status import experiment_to_dict

                self._journal.snapshot(experiment_to_dict(exp))
            except (OSError, ValueError):
                pass
        self._publish(exp, force=True)

    def _drain_and_exit(
        self,
        exp: Experiment,
        futures: dict,
        suggester,
        stop_event: threading.Event,
        drain_event: threading.Event,
    ) -> Experiment:
        """Graceful preemption wind-down (the run loop's drain branch).

        Ordering is the whole point: (1) stop proposing and cancel queued
        futures, (2) raise the drain flag every running trial/cohort observes
        through its context, (3) wait out ``drain_grace_seconds`` harvesting
        trials that checkpoint-and-exit (settled ``Drained``), (4) journal
        stragglers as ``Drained`` anyway and set the stop event so their
        threads wind down, (5) flush suggester state + status.json, record
        the ``drain`` span, and return with the experiment NON-terminal —
        the resumed process re-submits every Drained/Pending trial under its
        original name and checkpoint dir.  With ``drain_hard_exit`` (the CLI)
        a wedged straggler cannot block process exit: journal first, then
        ``os._exit(DRAIN_EXIT_CODE)``."""
        spec = exp.spec
        grace = max(0.0, spec.drain_grace_seconds)
        obs.drain_requested.set(1.0)
        drain_start = self._tracer.elapsed() if self._tracer else 0.0
        t0 = get_clock().perf_counter()
        self._cancel_pending(futures)
        drain_event.set()
        if futures:
            get_clock().wait_futures(futures, timeout=grace)
        self._harvest(exp, futures, drain=True)
        checkpointed = sum(
            1 for t in exp.trials.values() if t.condition is TrialCondition.DRAINED
        )
        # stragglers: still running past the grace window — journal them
        # Drained (resume re-runs them from their last voluntary checkpoint)
        # and fire the stop event so their threads/subprocesses wind down
        stragglers: list[Trial] = []
        for f in list(futures):
            owner = futures.pop(f)
            members = owner if isinstance(owner, list) else [owner]
            for trial in members:
                trial.condition = TrialCondition.DRAINED
                trial.message = (
                    "preempted: no checkpoint boundary within "
                    f"drain_grace_seconds={grace:g}; resuming from last checkpoint"
                )
                stragglers.append(trial)
                self._jappend("drained", exp, trial=trial)
        stop_event.set()
        exp.update_optimal()
        self._persist_suggester(exp, suggester)
        exp.message = (
            f"drained after preemption signal ({checkpointed} trial(s) "
            f"checkpointed, {len(stragglers)} killed at the grace window); "
            "resumable with --resume"
        )
        self.drained = True
        self._jappend("experiment", exp)
        duration = get_clock().perf_counter() - t0
        obs.experiments_current.dec()
        tracer, self._tracer = self._tracer, None
        if tracer is not None:
            tracer.record(
                "drain",
                drain_start,
                duration,
                checkpointed=checkpointed,
                killed=len(stragglers),
                grace=grace,
            )
            tracer.record(
                "experiment",
                self._exp_span_start,
                tracer.elapsed() - self._exp_span_start,
                algorithm=spec.algorithm.name,
                condition="Drained",
                trials=len(exp.trials),
            )
            tracing.deactivate(self._prev_tracer)
            tracer.close()
        self._publish(exp)
        if stragglers and self.drain_hard_exit:
            # a wedged train_fn cannot be joined; everything durable is
            # flushed, so trade the stuck threads for a prompt resumable exit
            os._exit(DRAIN_EXIT_CODE)
        return exp

    @staticmethod
    def _observe_trial_duration(trial: Trial) -> None:
        obs.trial_duration.observe(
            max(trial.completion_time - trial.start_time, 0.0),
            condition=trial.condition.value,
        )

    _TRIAL_COUNTERS = {
        TrialCondition.SUCCEEDED: obs.trials_succeeded,
        TrialCondition.FAILED: obs.trials_failed,
        TrialCondition.EARLY_STOPPED: obs.trials_early_stopped,
        TrialCondition.KILLED: obs.trials_killed,
        TrialCondition.METRICS_UNAVAILABLE: obs.trials_metrics_unavailable,
    }

    def _backfill_store(self, exp: Experiment) -> None:
        """A restarted process starts with an empty in-memory observation
        store while the journal holds each trial's reduced observation; the
        median early stopper reads per-trial logs from the store
        (``earlystop/medianstop.py``), so seed completed trials' reduced
        metrics back as single points.  An approximation of the lost series
        (the reduced value stands in for the first ``start_step`` points) —
        durable store backends (sqlite/native) that still hold the real
        series are left untouched."""
        import math as _math

        for t in exp.trials.values():
            if t.observation is None or not t.condition.is_terminal():
                continue
            if self.store.get(t.name):
                continue
            for m in t.observation.metrics:
                if not _math.isnan(m.value):
                    self.store.report_point(t.name, m.name, m.value)

    def _persist_suggester(self, exp: Experiment, suggester) -> None:
        """Journal durable suggester state (ENAS pytree, PBT queue) for
        restart resume — the FromVolume PVC analog.  Never-policy
        experiments skip it; best-effort like the status journal."""
        if exp.spec.resume_policy is ResumePolicy.NEVER:
            return
        try:
            from katib_tpu.orchestrator.resume import save_suggester_state

            save_suggester_state(
                suggester,
                self.workdir,
                exp.name,
                fence=self._journal.seq if self._journal is not None else None,
            )
        except Exception:
            # best-effort like the status journal: an unpicklable custom
            # state_dict (TypeError, not just PicklingError) must never mask
            # the experiment result from run()'s finally block
            pass

    def _publish(self, exp: Experiment, force: bool = False) -> None:
        """Journal status for CLI/UI views (``status.json`` per experiment);
        never lets a status-write failure kill the run loop.  Throttled by
        ``status_publish_interval`` (clock seconds) unless ``force``d —
        terminal states always publish."""
        if not force and self._status_publish_interval > 0.0:
            now = get_clock().monotonic()
            last = self._status_published_at
            if last is not None and now - last < self._status_publish_interval:
                return
            self._status_published_at = now
        try:
            from katib_tpu.orchestrator.status import write_status

            write_status(exp, self.workdir)
        except OSError:
            pass

    def _harvest(
        self,
        exp: Experiment,
        futures: dict,
        wait_running: bool = False,
        drain: bool = False,
    ) -> None:
        done = [f for f in futures if f.done()]
        if wait_running and futures:
            done = list(get_clock().wait_futures(futures).done)
        for f in done:
            # A future owns either one trial (serial) or a list (cohort);
            # cohort futures resolve to a {name: TrialResult} dict.
            owner = futures.pop(f)
            members = owner if isinstance(owner, list) else [owner]
            if f.cancelled():
                for trial in members:
                    if drain:
                        # never started: back to PENDING so the resumed run
                        # submits it fresh (no budget slot consumed)
                        trial.condition = TrialCondition.PENDING
                        trial.message = "drained before start; resubmitted on resume"
                        self._jappend("drained", exp, trial=trial)
                        continue
                    trial.condition = TrialCondition.KILLED
                    trial.completion_time = get_clock().time()
                    obs.trials_killed.inc()
                    self._jappend("settled", exp, trial=trial)
                    self._observe_trial_duration(trial)
                continue
            try:
                result = f.result()  # _execute / _execute_cohort never raise
            except Exception as exc:
                # the contract above is defense-in-depth, not a certainty: a
                # pool-level failure for ONE future must settle its members
                # as failed (classified through FailureKind), never raise
                # out of the harvest loop and kill the whole experiment
                kind = faults.classify_exception(exc)
                result = TrialResult(
                    TrialCondition.FAILED,
                    f"settle failed: {exc!r}",
                    failure_kind=kind,
                )
            results = (
                result if isinstance(result, dict) else {members[0].name: result}
            )
            settled: list[Trial] = []
            for trial in members:
                live = exp.trials.get(trial.name)
                if (live is not None and live is not trial) or (
                    trial.condition.is_terminal()
                ):
                    # speculative first-settle-wins: a rival already settled
                    # this member (the winner's object owns exp.trials[name])
                    # — the loser's result is discarded, never re-journaled
                    continue
                try:
                    res = results.get(trial.name)
                    if res is None:  # defense: _execute_cohort backfills missing
                        res = TrialResult(
                            TrialCondition.FAILED,
                            "cohort returned no result for member",
                            failure_kind=faults.FailureKind.PERMANENT,
                        )
                    trial.condition = res.condition
                    trial.message = res.message
                    fk = getattr(res, "failure_kind", None)
                    if fk is not None:
                        trial.failure_kind = fk.value
                    elif not trial.retry_count:
                        # keep the last failure's classification on a recovered
                        # retry (journal answers "what did this trial survive?");
                        # clean first-attempt results clear any resumed leftover
                        trial.failure_kind = None
                    trial.completion_time = get_clock().time()
                    if trial.condition in (
                        TrialCondition.SUCCEEDED,
                        TrialCondition.EARLY_STOPPED,
                    ):
                        trial.observation = self.store.observation_for(
                            trial.name, exp.spec.objective
                        )
                        if trial.observation is None:
                            trial.condition = TrialCondition.METRICS_UNAVAILABLE
                    counter = self._TRIAL_COUNTERS.get(trial.condition)
                    if counter is not None:
                        counter.inc()
                    self._observe_trial_duration(trial)
                    self._cleanup_trial(trial)
                except Exception as exc:
                    # per-member isolation: a bad metrics read / cleanup for
                    # one member fails THAT member, classified, and the rest
                    # of the cohort still settles normally
                    kind = faults.classify_exception(exc)
                    trial.condition = TrialCondition.FAILED
                    trial.message = f"settle failed: {exc!r}"
                    trial.failure_kind = kind.value
                    if not trial.completion_time:
                        trial.completion_time = get_clock().time()
                    obs.trials_failed.inc()
                settled.append(trial)
            members = settled
            # incremental: fold only this settle batch into the optimal —
            # the full recompute per batch is quadratic at sweep scale
            exp.update_optimal(members)
            # durably journal each member's outcome: terminal conditions are
            # exactly-once settlements keyed by (trial, attempt epoch);
            # Drained stays non-terminal (resubmitted on resume).  The
            # "reported" record carries the reduced observation separately
            # so replay can restore metrics for trials the settle record of
            # which is ever lost to a torn tail.  The whole batch goes
            # through one append_group — record content and order are
            # identical to per-trial appends, but the batch pays a single
            # durability barrier instead of two per member.
            if self._journal is not None:
                try:
                    from katib_tpu.orchestrator.status import (
                        _observation_to_dict,
                        trial_to_dict,
                    )

                    exp_state = self._journal_exp_state(exp)
                    records = []
                    for trial in members:
                        tdict = trial_to_dict(trial)
                        if trial.condition is TrialCondition.DRAINED:
                            records.append((
                                "drained",
                                trial.name,
                                trial.retry_count,
                                {"exp": exp_state, "trial": tdict},
                            ))
                            continue
                        if trial.observation is not None:
                            records.append((
                                "reported",
                                trial.name,
                                trial.retry_count,
                                {
                                    "exp": exp_state,
                                    "trial": tdict,
                                    "observation": _observation_to_dict(
                                        trial.observation
                                    ),
                                },
                            ))
                        records.append((
                            "settled",
                            trial.name,
                            trial.retry_count,
                            {"exp": exp_state, "trial": tdict},
                        ))
                    self._journal.append_group(records)
                except (OSError, ValueError):
                    pass
        if done:
            if self._journal is not None:
                try:
                    from katib_tpu.orchestrator.status import experiment_to_dict

                    self._journal.maybe_compact(lambda: experiment_to_dict(exp))
                except (OSError, ValueError):
                    pass
            self._publish(exp)

    def _cleanup_trial(self, trial: Trial) -> None:
        """Honor ``retain`` (the reference deletes the trial job on
        completion unless retained, ``trial_controller.go:297-306``): prune
        the bulky Orbax step directories of an orchestrator-owned checkpoint
        dir, keeping small artifacts (genotype.json, profiles).  Suggester-
        owned dirs (PBT lineage) are never touched — exploit copies need
        parent weights after the parent completes."""
        if (
            trial.spec.retain
            or trial.checkpoint_dir is None
            or trial.name in self._suggester_owned_ckpts
            or trial.condition is not TrialCondition.SUCCEEDED
            # nothing was ever checkpointed — skip the per-step scan
            or not os.path.isdir(trial.checkpoint_dir)
        ):
            return
        from katib_tpu.utils.checkpoint import (
            TrialCheckpointer,
            _manifest_path,
            _step_path,
        )

        try:
            ck = TrialCheckpointer(trial.checkpoint_dir, max_to_keep=0)
            for step in ck.all_steps():
                shutil.rmtree(_step_path(trial.checkpoint_dir, step), ignore_errors=True)
                try:
                    os.unlink(_manifest_path(trial.checkpoint_dir, step))
                except OSError:
                    pass
        except (OSError, ValueError):
            pass

    @staticmethod
    def _budget_used(exp: Experiment) -> int:
        """Terminal trials of every kind consume the budget — the reference
        counts succeeded + failed + killed + early-stopped as completed
        (``experiment_controller.go:280-281``)."""
        return sum(1 for t in exp.trials.values() if t.condition.is_terminal())

    def _shortfall(self, exp: Experiment, futures: dict) -> int:
        """Reference budget math (``experiment_controller.go:274-330``):
        keep ``parallel_trial_count`` active, never exceed ``max_trial_count``
        counting every terminal trial plus the ones in flight."""
        spec = exp.spec
        # Cohort futures carry multiple trials on one pool slot; the budget
        # counts members, not futures.
        active = sum(
            len(v) if isinstance(v, list) else 1 for v in futures.values()
        )
        slots = spec.parallel_trial_count - active
        if spec.max_trial_count is not None:
            slots = min(slots, spec.max_trial_count - self._budget_used(exp) - active)
        return max(0, slots)

    def _check_terminal(
        self, exp: Experiment, exhausted: bool, futures: dict
    ) -> ExperimentCondition | None:
        spec = exp.spec
        if (
            spec.max_failed_trial_count is not None
            and exp.failed_count > 0
            and exp.failed_count >= spec.max_failed_trial_count
        ):
            return ExperimentCondition.FAILED
        # exp.optimal is maintained incrementally by _harvest per settle
        # batch (trials terminal-ize nowhere else while the loops run); a
        # full update_optimal() here ran once per poll — quadratic at
        # sweep scale
        if exp.optimal is not None and spec.objective.is_goal_reached(
            exp.optimal.objective_value
        ):
            return ExperimentCondition.GOAL_REACHED
        if (
            spec.max_trial_count is not None
            # terminal trials <= all trials: the O(1) guard keeps the O(n)
            # budget scan off the poll loop until the budget can actually
            # be reached (the final lookahead window)
            and len(exp.trials) >= spec.max_trial_count
            and self._budget_used(exp) >= spec.max_trial_count
        ):
            return ExperimentCondition.MAX_TRIALS_REACHED
        if exhausted and not futures:
            return ExperimentCondition.SUCCEEDED
        return None

    @staticmethod
    def _terminal_message(cond: ExperimentCondition) -> str:
        return {
            ExperimentCondition.GOAL_REACHED: "objective goal reached",
            ExperimentCondition.MAX_TRIALS_REACHED: "max trial count reached",
            ExperimentCondition.FAILED: "max failed trial count exceeded",
            ExperimentCondition.SUCCEEDED: "search space exhausted",
        }.get(cond, "")

    @staticmethod
    def _cancel_pending(futures: dict) -> None:
        for f in futures:
            f.cancel()
