"""Loop supervision for the async orchestrator (and anything loop-shaped).

The async engine's three loops (suggest / schedule / harvest,
``async_loops.py``) are plain daemon threads: before this module, a loop
that died or wedged silently starved the mesh until the run was killed by
hand — the "wedged pool, zero diagnosis" failure mode the multi-host and
multi-tenant layers must never inherit (ROADMAP items 2/3).  The
:class:`LoopSupervisor` closes that gap with the primitives the repo
already has:

- **watermarks** — each loop owns a :class:`~katib_tpu.utils.watchdog.
  Heartbeat` (the same registry the hang watchdog uses, ``start=False`` so
  no second monitor thread exists) that the loop ``beat()``s on *real
  progress only*: proposals queued, units dispatched, futures settled.
- **classification** — every ``tick()`` each loop is classified:

  ========== ==========================================================
  OK          thread alive, watermark fresh
  STARVED     thread alive but its upstream has no work — idle silence
              is *not* the loop's fault and never counts toward a stall
              (the heartbeat is ``silence()``d while starved)
  STALLED     thread alive, work available, watermark frozen past the
              ``loopStallDeadlineSeconds`` spec knob
  CRASHED     thread dead without reaching a clean exit condition
  RESTARTING  a restart is scheduled (jittered backoff) but not yet due
  DONE        thread exited and its ``finished`` predicate holds
  ========== ==========================================================

- **recovery** — a CRASHED/STALLED loop is respawned at ``generation+1``
  (the engine fences stale-generation threads out of shared state) under a
  bounded per-loop restart budget with full-jitter backoff
  (``utils/faults.Backoff``).  Restarts are scheduled, not slept: ``tick``
  never blocks, so one ailing loop cannot delay supervision of the others.
- **graceful degradation** — when any loop exhausts its budget the
  supervisor raises the ``fallback`` flag instead of dying; the engine
  finishes in-flight work and degrades to the synchronous path
  (``KATIB_ASYNC_ORCH=0`` semantics).

The supervisor is engine-agnostic and clock-injectable: loops are
``add()``-ed as (spawn, has_work, finished) closures, so the unit tests
drive classification deterministically with a fake clock and bare threads.

Known limitation: restarting a loop wedged while *holding an engine lock*
cannot help (the replacement blocks on the same lock).  The engine places
its chaos seams at iteration tops, outside all locks; a real in-lock wedge
degrades to fallback once the replacement stalls too, which is still a
diagnosed exit rather than a silent hang.
"""

from __future__ import annotations

import threading
from typing import Callable

from katib_tpu.analysis import guarded_by, make_lock
from katib_tpu.utils import observability as obs
from katib_tpu.utils.clock import get_clock
from katib_tpu.utils.faults import Backoff
from katib_tpu.utils.watchdog import Watchdog

#: classification states returned by :meth:`LoopSupervisor.tick`
OK = "ok"
STALLED = "stalled"
STARVED = "starved"
CRASHED = "crashed"
RESTARTING = "restarting"
DONE = "done"


class _Loop:
    """Supervisor-internal record for one supervised loop."""

    __slots__ = (
        "name", "spawn", "has_work", "finished", "thread", "hb", "gen",
        "restarts", "next_restart_at", "ail_reason",
    )

    def __init__(self, name, spawn, has_work, finished, thread, hb):
        self.name = name
        self.spawn = spawn
        self.has_work = has_work
        self.finished = finished
        self.thread = thread
        self.hb = hb
        self.gen = 0
        self.restarts = 0
        self.next_restart_at: float | None = None
        self.ail_reason: str | None = None


class LoopSupervisor:
    """Heartbeat/classify/restart supervisor over named worker loops.

    ``add()`` registers a loop and spawns its generation-0 thread;
    ``tick()`` classifies every loop, performs due restarts, and returns
    ``{name: state}``.  ``beat(name)`` is the progress watermark bump the
    loop bodies call.  Thread-safety: ``tick`` runs on one thread (the
    engine's caller thread); ``beat``/``generation`` are safe from any.
    """

    # the loop registry is read by beat()/generation() from the loop
    # threads while tick()/add() mutate it on the caller thread; the
    # per-loop _Loop fields themselves are tick-thread-only by contract
    # (beat touches only the Heartbeat, which is lock-free by design)
    _GUARDS = guarded_by(_gen_lock=("_loops",))

    def __init__(
        self,
        stall_deadline: float = 60.0,
        restart_budget: int = 3,
        backoff: Backoff | None = None,
        clock=None,
        on_restart: Callable[[str, int, str, int], None] | None = None,
        on_fallback: Callable[[str], None] | None = None,
    ):
        self.stall_deadline = float(stall_deadline)
        self.restart_budget = int(restart_budget)
        # full jitter decorrelates restart storms; seeded so chaos runs
        # reproduce the same schedule
        self.backoff = backoff or Backoff(
            base=0.5, factor=2.0, cap=10.0, full_jitter=True, seed=0
        )
        # None = the ambient injectable clock (utils.clock); tests still
        # inject bare callables for deterministic classification.
        self._clock = clock if clock is not None else (lambda: get_clock().monotonic())
        self.on_restart = on_restart
        self.on_fallback = on_fallback
        # registry only — no monitor thread; tick() is the scan
        self._wd = Watchdog(clock=self._clock, start=False)
        self._loops: dict[str, _Loop] = {}
        self._gen_lock = make_lock("supervisor.gen")
        self._fallback_reason: str | None = None

    # -- registration / watermarks ------------------------------------------

    def add(
        self,
        name: str,
        spawn: Callable[[int], threading.Thread],
        has_work: Callable[[], bool] = lambda: True,
        finished: Callable[[], bool] = lambda: False,
    ) -> None:
        """Register loop ``name`` and start its generation-0 thread.
        ``spawn(gen)`` must return an already-started thread; ``has_work``
        says whether upstream work is available (False → idle silence is
        STARVED, not STALLED); ``finished`` says whether a dead thread is a
        clean completion (DONE) rather than a crash."""
        hb = self._wd.register(
            f"loop:{name}", self.stall_deadline, count_metric=False
        )
        lp = _Loop(name, spawn, has_work, finished, spawn(0), hb)
        # LCK001 fix: the generation-0 thread is already running and may
        # beat()/generation() concurrently — publish the record under the
        # same lock those readers take
        with self._gen_lock:
            self._loops[name] = lp

    def beat(self, name: str) -> None:
        """Progress watermark bump — call on real work only (enqueue,
        dispatch, settle), never on an idle poll."""
        with self._gen_lock:  # LCK001: add() publishes records concurrently
            lp = self._loops.get(name)
        if lp is not None:
            lp.hb.beat()

    def generation(self, name: str) -> int:
        """Current generation of ``name`` — loop bodies compare against the
        generation they were spawned with to fence stale threads out."""
        with self._gen_lock:
            lp = self._loops.get(name)
            return lp.gen if lp is not None else 0

    # -- introspection -------------------------------------------------------

    @property
    def fallback(self) -> bool:
        """True once any loop exhausted its restart budget — the engine
        should degrade to the synchronous path."""
        return self._fallback_reason is not None

    @property
    def fallback_reason(self) -> str | None:
        return self._fallback_reason

    def restart_counts(self) -> dict[str, int]:
        with self._gen_lock:  # LCK001: registry snapshot vs concurrent add()
            return {name: lp.restarts for name, lp in self._loops.items()}

    def threads(self) -> list[threading.Thread]:
        """Current-generation threads (stale wedged ones are abandoned)."""
        with self._gen_lock:  # LCK001: registry snapshot vs concurrent add()
            return [lp.thread for lp in self._loops.values()]

    # -- the scan ------------------------------------------------------------

    def tick(self) -> dict[str, str]:
        """Classify every loop, perform due restarts, return name→state."""
        now = self._clock()
        # snapshot, then classify OUTSIDE the lock: _restart bumps the
        # generation under _gen_lock (non-reentrant), and spawn/on_restart
        # callbacks may call generation() themselves
        with self._gen_lock:
            loops = list(self._loops.items())
        return {name: self._tick_loop(lp, now) for name, lp in loops}

    def _tick_loop(self, lp: _Loop, now: float) -> str:
        if lp.finished() and not lp.thread.is_alive():
            lp.hb.silence()
            obs.loop_stalled.set(0.0, loop=lp.name)
            return DONE
        if lp.next_restart_at is not None:
            if now < lp.next_restart_at:
                return RESTARTING
            self._restart(lp)
            return OK
        if self.fallback:
            # budget spent somewhere: freeze classification, no new restarts
            return lp.ail_reason or OK
        if not lp.thread.is_alive():
            self._ail(lp, CRASHED, now)
            return CRASHED
        if not lp.has_work():
            # upstream empty: not the loop's fault — stop the stall clock
            lp.hb.silence()
            obs.loop_stalled.set(0.0, loop=lp.name)
            return STARVED
        if lp.hb._silenced:
            # work just became available: the deadline measures from now
            lp.hb.reset()
        if now - lp.hb.last > self.stall_deadline:
            obs.loop_stalled.set(1.0, loop=lp.name)
            self._ail(lp, STALLED, now)
            return STALLED
        obs.loop_stalled.set(0.0, loop=lp.name)
        return OK

    def _ail(self, lp: _Loop, why: str, now: float) -> None:
        lp.ail_reason = why
        if lp.restarts >= self.restart_budget:
            self._fallback_reason = (
                f"loop {lp.name!r} {why} after {lp.restarts} restart(s) "
                f"(loopRestartBudget={self.restart_budget}); degrading to "
                "the synchronous orchestrator"
            )
            if self.on_fallback is not None:
                try:
                    self.on_fallback(self._fallback_reason)
                except Exception:
                    pass
            return
        lp.next_restart_at = now + self.backoff.delay(lp.restarts + 1)

    def _restart(self, lp: _Loop) -> None:
        lp.restarts += 1
        lp.next_restart_at = None
        with self._gen_lock:
            lp.gen += 1
            gen = lp.gen
        obs.loop_restarts.inc(loop=lp.name)
        if self.on_restart is not None:
            try:
                self.on_restart(lp.name, gen, lp.ail_reason or "", lp.restarts)
            except Exception:
                pass  # a bad callback must not kill supervision
        lp.ail_reason = None
        # watermark restarts clean: the new thread gets a full deadline
        lp.hb.reset()
        lp.thread = lp.spawn(gen)
