from katib_tpu.orchestrator.orchestrator import Orchestrator  # noqa: F401
