"""Seeded chaos soak for the supervised async orchestrator.

``katib-tpu chaos --soak SECONDS --seed N`` drives this harness: a
deterministic, time-bounded sequence of small white-box experiments, each
run under the async engine with a fresh :class:`~katib_tpu.utils.faults.
FaultInjector` planting one scripted failure mix — the loop-kill and
suggester-stall seams this PR added plus the pre-existing trial faults —
and after every round the same invariants are asserted:

- the experiment reaches a terminal condition and is not FAILED;
- journal replay (``orchestrator/journal.py``) reports **zero duplicate
  settlements** and every in-memory terminal trial is terminal with the
  same condition in the replayed state (no settled trial lost);
- per-trial retry budgets are respected;
- per-loop restart counts stay within ``loopRestartBudget`` and the
  engine did not silently degrade to the sync path (no fallback) unless
  the round scripted budget exhaustion;
- a killed loop was actually restarted (the supervisor healed it).

The schedule is a pure function of ``--seed``: the same seed replays the
same fault mix, iteration arms, and round order, so a CI failure
reproduces locally with one flag.  Core rounds (baseline, one kill per
loop, a suggester stall past its deadline, a speculation round) always
run; extra seeded mixed rounds fill whatever remains of the time budget.
The final round repeats the clean baseline and asserts post-fault
sustained occupancy recovered to >= ``OCCUPANCY_RECOVERY`` x the
pre-fault baseline — the "did the mesh come back" check.
"""

from __future__ import annotations

import os
import random

from katib_tpu.utils.clock import get_clock

#: post-fault sustained occupancy must recover to this fraction of the
#: pre-fault baseline (acceptance bar from the supervision issue)
OCCUPANCY_RECOVERY = 0.7

#: trainer step sleep; slow trials multiply this (see _soak_trainer)
_STEP_SLEEP = 0.02
_SLOW_STEP_SLEEP = 0.35
#: lr above this is a deterministic straggler (the random suggester is
#: seeded, so which trials straggle is a function of the round seed) —
#: only when the speculation round arms ``_SLOW_ENV``, so every other
#: round keeps uniform trial durations and a stable occupancy signal
_SLOW_LR = 0.14
_SLOW_ENV = "KATIB_SOAK_STRAGGLERS"


def _soak_trainer(ctx):
    """Checkpoint-aware toy trainer (module-level so crash-round children
    can import it).  With ``KATIB_SOAK_STRAGGLERS=1``, trials whose lr
    exceeds ``_SLOW_LR`` run ~17x slower — deterministic stragglers for
    the speculation round."""
    os.makedirs(ctx.checkpoint_dir, exist_ok=True)
    marker = os.path.join(ctx.checkpoint_dir, "progress.txt")
    start = 0
    if os.path.exists(marker):
        with open(marker) as f:
            start = int(f.read().strip() or 0)
    x = float(ctx.params["lr"])
    slow = os.environ.get(_SLOW_ENV) == "1" and x > _SLOW_LR
    sleep = _SLOW_STEP_SLEEP if slow else _STEP_SLEEP
    for step in range(start, 3):
        with open(marker, "w") as f:
            f.write(str(step + 1))
        get_clock().sleep(sleep)
        if not ctx.report(
            step=step, accuracy=(1.0 - 0.2 * (x - 0.05) ** 2) * (step + 1) / 3
        ):
            return


def _make_spec(
    name: str,
    seed: int,
    trials: int,
    parallel: int,
    stall_deadline: float = 2.0,
    restart_budget: int = 3,
    speculative: bool = False,
):
    from katib_tpu.core.types import (
        AlgorithmSpec,
        ExperimentSpec,
        FeasibleSpace,
        ObjectiveSpec,
        ObjectiveType,
        ParameterSpec,
        ParameterType,
    )

    return ExperimentSpec(
        name=name,
        algorithm=AlgorithmSpec(name="random", settings={"seed": str(seed)}),
        objective=ObjectiveSpec(
            type=ObjectiveType.MAXIMIZE, objective_metric_name="accuracy"
        ),
        parameters=[
            ParameterSpec(
                "lr", ParameterType.DOUBLE, FeasibleSpace(min=0.01, max=0.2)
            )
        ],
        max_trial_count=trials,
        parallel_trial_count=parallel,
        max_retries=2,
        retry_backoff_seconds=0.01,
        suggester_max_errors=3,
        async_orch=True,
        loop_stall_deadline_seconds=stall_deadline,
        loop_restart_budget=restart_budget,
        speculative_redispatch=speculative,
        straggler_factor=2.0,
        train_fn=_soak_trainer,
    )


class _Round:
    """One soak round: a name, an injector-arming closure, spec overrides,
    and round-specific extra assertions."""

    def __init__(
        self, name, arm=None, expect_restart=None, expect_seam=None, **spec_kw
    ):
        self.name = name
        self.arm = arm  # fn(injector) -> None
        self.expect_restart = expect_restart  # loop name or None
        self.expect_seam = expect_seam  # injector.log seam that must fire
        self.spec_kw = spec_kw


def _check_round(rnd, exp, orch, workdir, spec, injector):
    """The invariants every round must satisfy; returns a failures list."""
    from katib_tpu.core.types import ExperimentCondition
    from katib_tpu.orchestrator import journal as jr

    failures: list[str] = []
    tag = f"[{rnd.name}]"
    if not exp.condition.is_terminal():
        failures.append(f"{tag} experiment not terminal: {exp.condition.value}")
    if exp.condition is ExperimentCondition.FAILED:
        head = exp.message.splitlines()[0] if exp.message else ""
        failures.append(f"{tag} experiment failed: {head}")
    # exactly-once settlement: the durable journal must agree with memory
    state, stats = jr.replay_journal(workdir, spec.name)
    if stats.duplicates:
        failures.append(
            f"{tag} journal replay dropped {stats.duplicates} duplicate "
            "settlement record(s) — something settled twice"
        )
    replayed = (state or {}).get("trials") or {}
    for t in exp.trials.values():
        if not t.condition.is_terminal():
            continue
        rt = replayed.get(t.name)
        if rt is None:
            failures.append(f"{tag} settled trial lost from the journal: {t.name}")
        elif rt.get("condition") != t.condition.value:
            failures.append(
                f"{tag} settled trial {t.name} diverges from the journal: "
                f"memory={t.condition.value} journal={rt.get('condition')}"
            )
    for t in exp.trials.values():
        if t.retry_count > spec.max_retries:
            failures.append(
                f"{tag} retry budget exceeded: {t.name} retried "
                f"{t.retry_count} > {spec.max_retries}"
            )
    st = orch.async_stats or {}
    for loop, n in (st.get("loop_restarts") or {}).items():
        if n > spec.loop_restart_budget:
            failures.append(
                f"{tag} loop {loop!r} restarted {n} times, over the "
                f"budget of {spec.loop_restart_budget}"
            )
    if st.get("fallback"):
        failures.append(
            f"{tag} async engine fell back to sync: {st['fallback']}"
        )
    if rnd.expect_restart is not None:
        n = (st.get("loop_restarts") or {}).get(rnd.expect_restart, 0)
        if n < 1:
            failures.append(
                f"{tag} killed loop {rnd.expect_restart!r} was never "
                "restarted by the supervisor"
            )
    if rnd.expect_seam is not None and not any(
        e.get("seam") == rnd.expect_seam for e in injector.log
    ):
        failures.append(f"{tag} armed {rnd.expect_seam!r} fault never fired")
    return failures


def run_soak(
    seconds: float,
    seed: int = 0,
    trials: int = 10,
    parallel: int = 4,
    verbose: bool = True,
) -> int:
    """Run the seeded soak for ~``seconds``; returns a process exit code
    (0 = every round's invariants held)."""
    import tempfile

    from katib_tpu.utils.faults import FaultInjector

    # the shared determinism seam: fault schedule and durations flow
    # through the ambient clock (real for a wall soak; a VirtualClock when
    # driven from the simulator) and an explicit seeded rng — the same
    # (clock, rng) injection the sim's ModeledExecutor uses
    clock = get_clock()
    rng = random.Random(seed)
    start = clock.monotonic()
    deadline = start + float(seconds)
    failures: list[str] = []
    occupancy: dict[str, float] = {}

    def kill(loop):
        # arm inside the first few iterations so work definitely remains
        # when the thread dies — recovery, not a lucky clean exit
        it = rng.randint(1, 3)
        return lambda inj: inj.kill_loop(loop, at_iteration=it)

    def stall(inj):
        # three times the round's stall deadline: the deadline-bounded
        # suggester call must abandon the worker and trip the breaker
        # instead of freezing the suggest loop.  Call 1 — the lookahead
        # bank usually covers the whole budget in one or two calls
        inj.stall_suggester(seconds=2.25, call=1)

    core = [
        _Round("baseline"),
        _Round(
            "kill-suggest", kill("suggest"),
            expect_restart="suggest", expect_seam="kill-loop",
        ),
        _Round(
            "kill-schedule", kill("schedule"),
            expect_restart="schedule", expect_seam="kill-loop",
        ),
        _Round(
            "kill-harvest", kill("harvest"),
            expect_restart="harvest", expect_seam="kill-loop",
        ),
        _Round(
            "stall-suggester", stall,
            expect_seam="suggester-stall", stall_deadline=0.75,
        ),
        _Round("speculation", speculative=True),
        _Round("post-fault"),
    ]

    def mixed_round(i):
        actions = []
        loops = ["suggest", "schedule", "harvest"]
        picks = rng.sample(
            ["kill", "fail", "flake", "stall"], k=rng.randint(1, 2)
        )
        loop = rng.choice(loops)
        it = rng.randint(1, 4)
        k, j = rng.randrange(trials), rng.randint(1, 2)
        rate = round(rng.uniform(0.05, 0.2), 3)

        def arm(inj):
            for p in picks:
                if p == "kill":
                    inj.kill_loop(loop, at_iteration=it)
                    actions.append(f"kill-{loop}@{it}")
                elif p == "fail":
                    inj.fail_trial(k, j)
                    actions.append(f"fail-trial{k}:{j}")
                elif p == "flake":
                    inj.flake(rate)
                    actions.append(f"flake{rate}")
                elif p == "stall":
                    inj.stall_suggester(seconds=2.25, call=1)
                    actions.append("stall@1")

        expect = loop if "kill" in picks else None
        r = _Round(f"mixed-{i}", arm, expect_restart=expect)
        if "kill" in picks:
            r.expect_seam = "kill-loop"
        if "stall" in picks:
            r.spec_kw["stall_deadline"] = 0.75
        return r

    def run_one(rnd, round_seed):
        injector = FaultInjector(
            seed=round_seed, rng=random.Random(round_seed), clock=clock
        )
        if rnd.arm is not None:
            rnd.arm(injector)
        spec = _make_spec(
            name=f"soak-{rnd.name}",
            seed=round_seed,
            trials=trials,
            parallel=parallel,
            **rnd.spec_kw,
        )
        from katib_tpu.orchestrator import Orchestrator

        if spec.speculative_redispatch:
            os.environ[_SLOW_ENV] = "1"
        try:
            with tempfile.TemporaryDirectory(prefix="katib-soak-") as workdir:
                orch = Orchestrator(workdir=workdir, fault_injector=injector)
                t0 = clock.monotonic()
                exp = orch.run(spec)
                dt = clock.monotonic() - t0
                errs = _check_round(rnd, exp, orch, workdir, spec, injector)
        finally:
            os.environ.pop(_SLOW_ENV, None)
        st = orch.async_stats or {}
        occupancy[rnd.name] = float(st.get("sustained_occupancy") or 0.0)
        if verbose:
            restarts = {
                k: v for k, v in (st.get("loop_restarts") or {}).items() if v
            }
            print(
                f"  {rnd.name:<16} {exp.condition.value:<10} {dt:5.1f}s  "
                f"occ={occupancy[rnd.name]:.2f}  restarts={restarts or '-'}  "
                f"spec={st.get('speculative_dispatches', 0)}/"
                f"{st.get('speculative_wins', 0)}  "
                f"faults={len(injector.log)}"
                + (f"  FAIL: {'; '.join(errs)}" if errs else "")
            )
        failures.extend(errs)

    if verbose:
        print(f"soak: seed={seed} budget={seconds:.0f}s trials={trials}/round")

    # core rounds always run (the post-fault baseline is pulled off the
    # tail so it is genuinely last); extra seeded mixed rounds fill the
    # remaining budget
    post = core.pop()
    for i, rnd in enumerate(core):
        run_one(rnd, seed * 1000 + i)
    i = len(core)
    while clock.monotonic() < deadline - 10.0 and i < 50:
        run_one(mixed_round(i), seed * 1000 + i)
        i += 1
    run_one(post, seed * 1000 + i)

    base, after = occupancy.get("baseline", 0.0), occupancy.get("post-fault", 0.0)
    if base > 0 and after < OCCUPANCY_RECOVERY * base:
        # best of two: single short rounds on a loaded box make the
        # time-weighted occupancy noisy; a genuine regression fails both
        run_one(_Round("post-fault"), seed * 1000 + i + 1)
        after = max(after, occupancy.get("post-fault", 0.0))
    if base > 0 and after < OCCUPANCY_RECOVERY * base:
        failures.append(
            f"post-fault occupancy did not recover: {after:.2f} < "
            f"{OCCUPANCY_RECOVERY} x baseline {base:.2f}"
        )
    # lock-order witness (KATIB_LOCK_WITNESS=1): every engine lock acquired
    # across every round fed the process-wide acquisition graph; an observed
    # inversion of the documented order (state > queue > futures, plus the
    # registry/metrics/watchdog locks) fails the soak even if no round
    # actually deadlocked — the witness sees the near-miss
    from katib_tpu.analysis import witness_enabled
    from katib_tpu.analysis.witness import format_summary, witness_cycles

    if witness_enabled():
        cycles = witness_cycles()
        if verbose or cycles:
            print(format_summary())
        if cycles:
            failures.append(
                f"lock-order witness observed {len(cycles)} inversion(s) "
                "of the documented acquire order: "
                + "; ".join(" -> ".join(c) for c in cycles[:3])
            )
    elapsed = clock.monotonic() - start
    if failures:
        print(
            f"SOAK FAIL ({elapsed:.0f}s, {i + 2} rounds): "
            + "; ".join(failures[:10])
        )
        return 1
    print(
        f"SOAK PASS: {i + 2} rounds in {elapsed:.0f}s, zero lost or "
        f"duplicated settlements, occupancy {base:.2f} -> {after:.2f}"
    )
    return 0
