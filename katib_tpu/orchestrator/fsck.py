"""Validate and repair an experiment directory — ``katib-tpu fsck``.

The crash-consistency story (orchestrator/journal.py) guarantees a killed
process leaves a *recoverable* directory, not a pristine one: the journal
may end in a torn tail, a snapshot temp file may have been renamed but
never verified, the suggester pickle may be fenced behind the journal.
``fsck`` is the offline half of that contract — it walks one experiment
dir, verifies every durable artifact, repairs what is mechanically
repairable, and reports what resume will rebuild:

- **journal**: every record's checksum and seq monotonicity is verified;
  a torn tail (crash mid-append) is truncated to the valid prefix;
  mid-file corruption is reported (replay already skips it);
- **snapshots**: each ``snapshot-<seq>.json`` must parse and match its
  embedded checksum; unverifiable ones are quarantined (renamed to
  ``*.quarantined``) so replay can never trust them;
- **suggester fence**: the pickle's recorded fence is compared against
  the journal's last settled seq — a mismatch is *reported*, not
  repaired (resume rebuilds the suggester from trial history; deleting
  the pickle here would destroy post-mortem evidence);
- **status.json**: must parse; a corrupt one is reported (the journal
  supersedes it for resume, so this is not fatal).

Repairs bump ``katib_fsck_repairs_total``.  The CLI exits 0 when the
directory is consistent AFTER repairs, 1 when damage remains that fsck
cannot mechanically fix (or when ``--dry-run`` found repairable damage).
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field

from katib_tpu.orchestrator import journal as jr
from katib_tpu.orchestrator.status import STATUS_FILE


@dataclass
class FsckReport:
    exp_dir: str = ""
    journal_records: int = 0
    torn_tail_bytes: int = 0
    bad_records: int = 0
    snapshots_ok: int = 0
    snapshots_quarantined: list[str] = field(default_factory=list)
    #: "ok" | "stale" | "ahead" | "unfenced" | "absent" | "no-journal"
    fence: str = "no-journal"
    status_json: str = "absent"  # "ok" | "corrupt" | "absent"
    repairs: list[str] = field(default_factory=list)
    problems: list[str] = field(default_factory=list)

    def ok(self) -> bool:
        """Consistent after repairs: nothing left that resume cannot
        handle.  A stale fence and a corrupt status.json are NOT failures
        — resume rebuilds both from the journal — but they are reported."""
        return not self.problems

    def lines(self) -> list[str]:
        out = [f"fsck {self.exp_dir}"]
        out.append(
            f"  journal: {self.journal_records} record(s) verified, "
            f"{self.bad_records} bad record(s) skipped, "
            f"torn tail {self.torn_tail_bytes} byte(s)"
        )
        out.append(
            f"  snapshots: {self.snapshots_ok} verified, "
            f"{len(self.snapshots_quarantined)} quarantined"
        )
        out.append(f"  suggester fence: {self.fence}")
        out.append(f"  status.json: {self.status_json}")
        for r in self.repairs:
            out.append(f"  repaired: {r}")
        for p in self.problems:
            out.append(f"  PROBLEM: {p}")
        out.append("  result: " + ("consistent" if self.ok() else "INCONSISTENT"))
        return out


def fsck_experiment(exp_dir: str, repair: bool = True) -> FsckReport:
    """Validate (and with ``repair`` fix) one experiment directory."""
    from katib_tpu.utils import observability as obs

    exp_dir = os.path.abspath(exp_dir)
    report = FsckReport(exp_dir=exp_dir)
    if not os.path.isdir(exp_dir):
        report.problems.append(f"not a directory: {exp_dir}")
        return report
    workdir, name = os.path.split(exp_dir.rstrip(os.sep))

    # -- journal -----------------------------------------------------------
    jpath = jr.journal_path(workdir, name)
    has_journal = os.path.exists(jpath)
    if has_journal:
        scan = jr.scan_journal(jpath)
        report.journal_records = len(scan.records)
        report.bad_records = scan.bad_records
        report.torn_tail_bytes = scan.torn_bytes
        if scan.torn_bytes:
            if repair:
                with open(jpath, "rb+") as f:
                    f.truncate(scan.valid_bytes)
                    f.flush()
                    os.fsync(f.fileno())
                report.repairs.append(
                    f"truncated torn journal tail ({scan.torn_bytes} bytes)"
                )
                obs.fsck_repairs.inc()
            else:
                report.problems.append(
                    f"torn journal tail ({scan.torn_bytes} bytes); rerun "
                    "without --dry-run to truncate"
                )
        if scan.bad_records:
            # replay skips them, but mid-file corruption means records were
            # lost — surface it, nothing mechanical can restore them
            report.problems.append(
                f"{scan.bad_records} corrupt mid-file journal record(s) "
                "(skipped by replay; their transitions are lost)"
            )

    # -- snapshots ---------------------------------------------------------
    for seq, path in jr.list_snapshots(exp_dir):
        if jr.load_snapshot(path) is not None:
            report.snapshots_ok += 1
            continue
        if repair:
            target = path + ".quarantined"
            suffix = 0
            while os.path.exists(target):
                suffix += 1
                target = f"{path}.quarantined.{suffix}"
            os.replace(path, target)
            report.snapshots_quarantined.append(os.path.basename(target))
            report.repairs.append(
                f"quarantined unverifiable snapshot {os.path.basename(path)}"
            )
            obs.fsck_repairs.inc()
        else:
            report.problems.append(
                f"unverifiable snapshot {os.path.basename(path)}; rerun "
                "without --dry-run to quarantine"
            )

    # -- suggester fence ---------------------------------------------------
    from katib_tpu.orchestrator.resume import (
        read_suggester_fence,
        suggester_state_path,
    )

    if not has_journal and not jr.list_snapshots(exp_dir):
        report.fence = "no-journal"
    elif not os.path.exists(suggester_state_path(workdir, name)):
        report.fence = "absent"
    else:
        fence = read_suggester_fence(workdir, name)
        settled = jr.last_settled_seq(workdir, name)
        if fence is None:
            report.fence = "unfenced (legacy pickle; resume treats it as stale)"
        elif fence < settled:
            report.fence = (
                f"stale (pickle fence {fence} < journal settled seq {settled}; "
                "resume rebuilds the suggester from trial history)"
            )
        elif settled == 0 and fence > 0 and report.journal_records == 0:
            report.fence = (
                f"ahead (pickle fence {fence} but journal is empty — journal "
                "was truncated or replaced; resume rebuilds from history)"
            )
        else:
            report.fence = "ok"

    # -- status.json -------------------------------------------------------
    spath = os.path.join(exp_dir, STATUS_FILE)
    if os.path.exists(spath):
        try:
            with open(spath) as f:
                json.load(f)
            report.status_json = "ok"
        except (OSError, json.JSONDecodeError):
            report.status_json = "corrupt"
            if not has_journal:
                report.problems.append(
                    "status.json is corrupt and no journal exists — the "
                    "experiment is not resumable"
                )
    elif not has_journal:
        report.problems.append("neither journal nor status.json present")
    return report
