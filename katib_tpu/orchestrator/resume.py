"""Durable experiment resume — reconstruct an :class:`Experiment` from the
status journal so a killed orchestrator process can pick up where it left
off.

The reference survives controller restarts because all state lives in CRs on
the API server plus the suggestion PVC (``suggestion_controller.go:181-193``
``FromVolume``; ``experiment_controller.go:187-206`` re-open on raised
``maxTrialCount``).  Here the equivalents are:

- trial history + optimal + mutable ``algorithm_settings`` (Hyperband's
  state-in-CR round trip) — journaled to ``<workdir>/<exp>/status.json`` on
  every trial completion (``status.py``), read back by
  :func:`experiment_from_dict`;
- in-memory suggester state (ENAS controller pytree, PBT job queue) —
  pickled to ``<workdir>/<exp>/suggester_state.pkl`` by the orchestrator
  (the PVC analog), reloaded through the suggester's
  ``load_state_dict`` hook.

Trials that were still running when the process died are re-materialized
with their original name/assignments/checkpoint dir and resubmitted — the
analog of the job controller recreating pods for a trial CR that still
exists (reference trials keep running across controller restarts; ours
cannot, so they are re-run).  Their Orbax checkpoint dir survives, so a
``train_fn`` that restores from its last step resumes mid-trial.
"""

from __future__ import annotations

import math
import os
import pickle

from katib_tpu.core.types import (
    Experiment,
    ExperimentCondition,
    ExperimentSpec,
    Metric,
    Observation,
    OptimalTrial,
    ParameterAssignment,
    Trial,
    TrialCondition,
    TrialSpec,
)

SUGGESTER_STATE_FILE = "suggester_state.pkl"


def _coerce_assignments(spec: ExperimentSpec, raw: dict) -> list[ParameterAssignment]:
    """Journal values are JSON scalars; cast back through the parameter spec
    where the name matches (NAS/PBT string parameters pass through as-is)."""
    out = []
    by_name = {p.name: p for p in spec.parameters}
    for name, value in raw.items():
        p = by_name.get(name)
        if p is not None:
            try:
                value = p.cast(value)
            except (TypeError, ValueError):
                pass
        out.append(ParameterAssignment(name=name, value=value))
    return out


def _observation_from_list(metrics: list[dict] | None) -> Observation | None:
    if metrics is None:
        return None
    nan = float("nan")

    def f(v):
        return nan if v is None else float(v)

    return Observation(
        metrics=[
            Metric(
                name=m["name"],
                value=f(m.get("value")),
                min=f(m.get("min", nan)),
                max=f(m.get("max", nan)),
                latest=f(m.get("latest", nan)),
            )
            for m in metrics
        ]
    )


def trial_from_dict(spec: ExperimentSpec, data: dict) -> Trial:
    """Rebuild one trial.  The journal does not persist callables or
    early-stopping rules; those come from the experiment spec (rules are
    re-derived if the trial is resubmitted)."""
    condition = TrialCondition(data["condition"])
    resubmit = not condition.is_terminal()
    return Trial(
        name=data["name"],
        experiment_name=spec.name,
        spec=TrialSpec(
            assignments=_coerce_assignments(spec, data.get("assignments", {})),
            labels=dict(data.get("labels", {})),
            train_fn=spec.train_fn,
            command=list(spec.command) if spec.command else None,
            metrics_collector=spec.metrics_collector,
            retain=spec.retain,
            max_runtime_seconds=spec.max_trial_runtime_seconds,
            metrics_retries=spec.metrics_retries,
            max_retries=spec.max_retries,
            retry_backoff_seconds=spec.retry_backoff_seconds,
            progress_deadline_seconds=spec.progress_deadline_seconds,
        ),
        # non-terminal journal entries become PENDING: run() resubmits them.
        # Drained trials (preemption) land here by design: same name +
        # checkpoint dir, so a checkpoint-aware train_fn continues from the
        # step it saved during the drain window instead of step 0.
        condition=TrialCondition.PENDING if resubmit else condition,
        observation=_observation_from_list(data.get("observation")),
        message=data.get("message", "") if not resubmit else "resubmitted after restart",
        start_time=data.get("start_time") or 0.0,
        completion_time=data.get("completion_time") or 0.0,
        checkpoint_dir=data.get("checkpoint_dir"),
        # restoring the spent retry budget is what makes the budget crash-proof:
        # a trial that burned 2 of 3 retries before the crash gets 1 more, not 3
        retry_count=int(data.get("retry_count") or 0),
        failure_kind=data.get("failure_kind"),
    )


def experiment_from_dict(spec: ExperimentSpec, status: dict) -> Experiment:
    """Rebuild the :class:`Experiment` a journal dict describes.

    The caller supplies the spec (callables cannot round-trip through JSON);
    ``status["name"]`` must match ``spec.name``.
    """
    if status.get("name") != spec.name:
        raise ValueError(
            f"journal is for experiment {status.get('name')!r}, spec is {spec.name!r}"
        )
    exp = Experiment(
        spec=spec,
        condition=ExperimentCondition(status.get("condition", "Created")),
        start_time=status.get("start_time") or 0.0,
        completion_time=status.get("completion_time") or 0.0,
        message=status.get("message", ""),
    )
    if status.get("algorithm_settings"):
        exp.algorithm_settings = dict(status["algorithm_settings"])
    # restore the convergence curve BEFORE recomputing the optimal, so the
    # recompute extends the journaled history instead of restarting it
    exp.optimal_history = [
        dict(row) for row in status.get("optimal_history") or ()
    ]
    for name, tdata in (status.get("trials") or {}).items():
        exp.trials[name] = trial_from_dict(spec, tdata)
    exp.update_optimal()
    if not status.get("optimal_history") and exp.optimal_history:
        # pre-curve journal: the row just appended was clocked at load time,
        # charging process downtime; re-anchor it to the optimal trial's own
        # completion time (the best information the old journal carries)
        best_trial = exp.trials.get(exp.optimal_history[-1]["trial_name"])
        if best_trial is not None and best_trial.completion_time:
            exp.optimal_history[-1]["elapsed_s"] = round(
                max(best_trial.completion_time - exp.start_time, 0.0), 3
            )
    # sanity: journal's recorded optimal should agree; recompute wins because
    # it is derived from the same trial set
    if exp.optimal is None and status.get("optimal"):
        o = status["optimal"]
        v = o.get("objective_value")
        if v is not None and not math.isnan(float(v)):
            exp.optimal = OptimalTrial(
                trial_name=o.get("trial_name", ""),
                objective_value=float(v),
                assignments=_coerce_assignments(spec, o.get("assignments", {})),
                observation=Observation(),
            )
    return exp


def load_experiment(spec: ExperimentSpec, workdir: str) -> Experiment | None:
    """Rebuild an Experiment from its durable state; None when none exists
    (fresh run).

    The crash-consistent event journal (``orchestrator/journal.py``) is the
    source of truth when present: replay applies snapshot + suffix with
    exactly-once settlement, so a hard kill mid-publish can neither lose a
    settled trial nor settle one twice.  ``status.json`` remains the
    fallback for pre-journal experiment dirs (and stays the view the
    CLI/UI read)."""
    from katib_tpu.orchestrator import journal as jr
    from katib_tpu.orchestrator.status import read_status
    from katib_tpu.utils import observability as obs

    if os.path.exists(jr.journal_path(workdir, spec.name)) or jr.list_snapshots(
        os.path.join(workdir, spec.name)
    ):
        status, stats = jr.replay_journal(workdir, spec.name)
        if status is not None:
            obs.journal_replayed_events.inc(stats.applied)
            if stats.duplicates:
                obs.settlement_duplicates.inc(stats.duplicates)
            return experiment_from_dict(spec, status)
    status = read_status(workdir, spec.name)
    if status is None:
        return None
    return experiment_from_dict(spec, status)


# -- suggester state (the FromVolume PVC analog) ----------------------------


def suggester_state_path(workdir: str, experiment_name: str) -> str:
    return os.path.join(workdir, experiment_name, SUGGESTER_STATE_FILE)


#: wrapper marker for fenced pickles; bare (legacy) pickles still load
_FENCE_MARKER = "__katib_suggester_state__"


def save_suggester_state(
    suggester, workdir: str, experiment_name: str, fence: int | None = None
) -> bool:
    """Durably pickle ``suggester.state_dict()``; no-op (False) for
    replay-derived suggesters that expose no state hook.

    ``fence`` is the experiment journal's sequence number at persist time.
    It rides inside the pickle so a resume can tell whether the state is
    CURRENT (fence ≥ the journal's last settled seq) or STALE — written
    before settlements the journal proves happened, e.g. a hard kill
    between a trial settling and the next suggester persist.  Stale state
    is discarded and the suggester rebuilds from replayed trial history
    instead of being trusted blindly."""
    from katib_tpu.utils.fsio import atomic_replace

    state_fn = getattr(suggester, "state_dict", None)
    if state_fn is None:
        return False
    exp_dir = os.path.join(workdir, experiment_name)
    os.makedirs(exp_dir, exist_ok=True)
    path = suggester_state_path(workdir, experiment_name)
    payload = pickle.dumps({_FENCE_MARKER: 1, "fence": fence, "state": state_fn()})
    atomic_replace(path, payload, prefix=".sugg-", crash_site="suggester.pickle")
    return True


def read_suggester_fence(workdir: str, experiment_name: str) -> int | None:
    """The fence recorded in the pickled suggester state; None when the
    file is absent/legacy/unreadable.  Used by ``katib-tpu fsck`` to report
    fence mismatches without mutating anything."""
    path = suggester_state_path(workdir, experiment_name)
    try:
        with open(path, "rb") as f:
            state = pickle.load(f)
    except Exception:
        return None
    if isinstance(state, dict) and state.get(_FENCE_MARKER):
        fence = state.get("fence")
        return int(fence) if fence is not None else None
    return None


def load_suggester_state(
    suggester,
    workdir: str,
    experiment_name: str,
    settled_fence: int | None = None,
) -> bool:
    """Restore a previously pickled state into the suggester; False when the
    file or the hook is absent — or when the state is FENCED OUT:
    ``settled_fence`` (the journal's last settled seq) newer than the
    pickle's recorded fence means the state predates settlements the
    journal proves, so it is discarded and the caller's replay-derived
    fresh suggester stands (counted in
    ``katib_suggester_fence_rebuilds_total``)."""
    load_fn = getattr(suggester, "load_state_dict", None)
    if load_fn is None:
        return False
    path = suggester_state_path(workdir, experiment_name)
    try:
        with open(path, "rb") as f:
            state = pickle.load(f)
        fenced = isinstance(state, dict) and state.get(_FENCE_MARKER)
        fence = state.get("fence") if fenced else None
        # a journal that proves settlements fences out any pickle that
        # cannot prove it saw them — including legacy bare pickles, which
        # record no fence at all.  Journal-less dirs (settled_fence 0) keep
        # loading legacy pickles unconditionally.
        if (
            settled_fence is not None
            and settled_fence > 0
            and (fence is None or int(fence) < settled_fence)
        ):
            import logging

            from katib_tpu.utils import observability as obs

            obs.suggester_fence_rebuilds.inc()
            logging.getLogger(__name__).warning(
                "suggester state at %s is stale (fence=%s < journal settled "
                "seq %d); rebuilding from replayed trial history",
                path,
                fence,
                settled_fence,
            )
            return False
        if fenced:
            state = state["state"]
        load_fn(state)
    except Exception:
        # a truncated/corrupt pickle (crash between replace and flush) or a
        # state-schema mismatch must not make the experiment un-resumable:
        # fall back to the replay-derived fresh suggester
        import logging

        logging.getLogger(__name__).warning(
            "suggester state at %s unusable; resuming from trial history only",
            path,
            exc_info=True,
        )
        return False
    return True
