"""Experiment status persistence for CLI/UI views.

The reference exposes experiment/trial status through CR status fields that
the UI backend reads (``pkg/ui/v1beta1/backend.go:86-617``).  Here the
orchestrator journals the same information to
``<workdir>/<experiment>/status.json`` on every trial completion, so
``katib-tpu list/describe`` (and any external dashboard) can watch progress
without holding a reference to the running process.
"""

from __future__ import annotations

import json
import os

from katib_tpu.core.types import Experiment, Observation, Trial

STATUS_FILE = "status.json"


def _observation_to_dict(obs: Observation | None) -> list[dict] | None:
    if obs is None:
        return None
    return [
        {"name": m.name, "value": m.value, "min": m.min, "max": m.max, "latest": m.latest}
        for m in obs.metrics
    ]


def trial_to_dict(trial: Trial) -> dict:
    return {
        "name": trial.name,
        "condition": trial.condition.value,
        "assignments": {a.name: a.value for a in trial.spec.assignments},
        "labels": dict(trial.spec.labels),
        "observation": _observation_to_dict(trial.observation),
        "message": trial.message,
        "start_time": trial.start_time,
        "completion_time": trial.completion_time,
        "checkpoint_dir": trial.checkpoint_dir,
        # fault-tolerance state: journaled so a resumed process continues
        # the retry budget instead of resetting it (utils/faults.py taxonomy)
        "retry_count": trial.retry_count,
        "failure_kind": trial.failure_kind,
    }


def experiment_to_dict(exp: Experiment) -> dict:
    return {
        "name": exp.name,
        "condition": exp.condition.value,
        "message": exp.message,
        "algorithm": exp.spec.algorithm.name,
        "objective_metric": exp.spec.objective.objective_metric_name,
        "objective_type": exp.spec.objective.type.value,
        "goal": exp.spec.objective.goal,
        "start_time": exp.start_time,
        "completion_time": exp.completion_time,
        "counts": {
            "trials": len(exp.trials),
            "succeeded": exp.succeeded_count,
            "failed": exp.failed_count,
            "early_stopped": exp.early_stopped_count,
            "metrics_unavailable": exp.metrics_unavailable_count,
            "running": exp.running_count,
            # preemption drain: non-terminal, resubmitted on resume
            "drained": sum(
                1 for t in exp.trials.values() if t.condition.value == "Drained"
            ),
            # total transient retries spent across all trials (surfaced in
            # the UI counter strip and `katib-tpu describe`)
            "retried": sum(t.retry_count for t in exp.trials.values()),
        },
        # mutable algorithm settings (Hyperband bracket state lives here) —
        # persisting them is what makes the journal a full resume source
        # (reference: state-in-CR, ``suggestionclient.go:194-196``)
        "algorithm_settings": dict(exp.algorithm_settings),
        "optimal": (
            None
            if exp.optimal is None
            else {
                "trial_name": exp.optimal.trial_name,
                "objective_value": exp.optimal.objective_value,
                "assignments": {a.name: a.value for a in exp.optimal.assignments},
            }
        ),
        # best-objective@wallclock rows (the BASELINE driver metric)
        "optimal_history": list(exp.optimal_history),
        # last device-preflight verdict of this process (utils/meshhealth):
        # None until a preflight/doctor probe has run
        "device_health": _device_health(),
        "trials": {name: trial_to_dict(t) for name, t in exp.trials.items()},
    }


def _device_health() -> dict | None:
    from katib_tpu.utils.meshhealth import last_report_dict

    return last_report_dict()


def write_status(exp: Experiment, workdir: str) -> str:
    """Atomically AND durably write the experiment's status file; returns
    its path.  The temp file is fsync'd before the rename and the directory
    after it (utils/fsio.py) — rename-only atomicity still loses the data
    blocks on some filesystems when a hard kill lands right after the
    replace, which is exactly the window ``chaos --crash-at status.write``
    exercises."""
    from katib_tpu.utils.fsio import atomic_replace

    exp_dir = os.path.join(workdir, exp.name)
    os.makedirs(exp_dir, exist_ok=True)
    path = os.path.join(exp_dir, STATUS_FILE)
    payload = json.dumps(experiment_to_dict(exp), indent=1, default=str)
    atomic_replace(
        path, payload.encode(), prefix=".status-", crash_site="status.write"
    )
    return path


def read_status(workdir: str, experiment_name: str) -> dict | None:
    # the name may arrive from a URL (UI backend routes); refuse anything
    # that could escape the workdir ("..", separators, NUL, absolute paths)
    from katib_tpu.utils.names import is_safe_path_component

    if not is_safe_path_component(experiment_name):
        return None
    path = os.path.join(workdir, experiment_name, STATUS_FILE)
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


def find_trial_log(workdir: str, trial_name: str) -> str | None:
    """Locate a black-box trial's captured stdout (``trial.log``), shared by
    the CLI and UI so the lookup cannot drift.

    Resolution order per experiment journal: the trial's recorded
    ``checkpoint_dir`` (suggester-owned dirs — PBT lineage — live outside
    the ``<workdir>/<exp>/<trial>`` convention), then the conventional
    path.  Returns the log's path or None."""
    from katib_tpu.utils.names import is_safe_path_component

    if not is_safe_path_component(trial_name):
        return None
    try:
        exp_dirs = sorted(os.listdir(workdir))
    except OSError:
        return None
    for exp in exp_dirs:
        status = read_status(workdir, exp)
        candidates = []
        if status is not None:
            tdata = (status.get("trials") or {}).get(trial_name)
            if tdata and tdata.get("checkpoint_dir"):
                candidates.append(os.path.join(tdata["checkpoint_dir"], "trial.log"))
        candidates.append(os.path.join(workdir, exp, trial_name, "trial.log"))
        for path in candidates:
            if os.path.isfile(path):
                return path
    return None


def read_trial_log(workdir: str, trial_name: str) -> str | None:
    """Contents of a trial's captured stdout, or None when absent."""
    path = find_trial_log(workdir, trial_name)
    if path is None:
        return None
    try:
        with open(path, errors="replace") as f:
            return f.read()
    except OSError:
        return None


def list_statuses(workdir: str) -> list[dict]:
    out = []
    try:
        entries = sorted(os.listdir(workdir))
    except OSError:
        return []
    for name in entries:
        status = read_status(workdir, name)
        if status is not None:
            out.append(status)
    return out
