"""Crash-consistent experiment event journal.

``status.json`` is a rewrite-the-world snapshot: every publish replaces the
whole file, so the instant before the rename there is a window where the
only complete copy of the experiment's history is in process memory.  The
reference never has this problem — its state lives in CRs on the API
server plus the suggestion PVC (``experiment_controller.go`` re-open,
``FromVolume``) and survives any controller death.  This module is the
single-process analog: an append-only JSONL journal of state transitions
that is the durable source of truth for resume, while ``status.json``
remains a derived view for the CLI/UI.

Format — one JSON object per line::

    {"seq": 17, "ts": ..., "event": "settled", "trial": "exp-a1b2",
     "epoch": 0, "data": {...}, "crc": "9f3a01c2"}

- ``seq`` is a strictly-increasing sequence number (the journal's clock —
  also the fence the suggester pickle carries, see below);
- ``event`` is one of ``proposed / queued / started / reported / settled /
  retried / drained / experiment`` (``queued`` is the async scheduler's
  queue-handoff record: the trial left the suggest queue and entered a
  packing bucket, so crash/resume can restore all three loops' in-flight
  state);
- ``epoch`` is the trial's attempt epoch (``retry_count`` at append time):
  settlement is exactly-once per ``(trial, epoch)`` key, so a record
  duplicated by a crash-then-resume cycle is dropped on replay, counted in
  ``katib_settlement_duplicates_total``;
- ``crc`` is a CRC-32 of the record minus the crc field itself (canonical
  sorted-key JSON), so a torn or bit-flipped line is detected, not trusted.

Durability: every append is flushed and fsync'd before the caller
proceeds.  A crash mid-append leaves a torn tail; loading tolerates it
(the valid prefix wins, the torn bytes are truncated away on open — the
same rule ``compile/registry.py`` applies to its shape registry).

Compaction: every ``snapshot_every`` settlements the owner writes a
checksummed snapshot (``snapshot-<seq>.json``, durable via
``fsio.atomic_replace``) and the journal is truncated to records newer
than the snapshot, so replay cost stays bounded by the snapshot interval
instead of experiment length.  The ordering makes the crash windows safe:
snapshot first (journal still covers everything), truncate second
(records ≤ snapshot seq are redundant; replay drops them as
already-applied if a crash leaves them behind).

Everything here is stdlib-only and jax-free.
"""

from __future__ import annotations

import json
import os
import threading
import time
import zlib
from dataclasses import dataclass, field

from katib_tpu.utils.clock import get_clock
from katib_tpu.utils.fsio import atomic_replace, fsync_dir

# Durability kill switch for the virtual-time simulator (katib_tpu/sim):
# per-append fsync costs nothing in virtual time but dominates wall time at
# 50k trials.  Production never sets this; the crash windows stay identical
# either way (bytes are still written + flushed before the crash point).
SYNC_ENV = "KATIB_JOURNAL_SYNC"


def _sync_enabled() -> bool:
    return os.environ.get(SYNC_ENV, "1") != "0"


JOURNAL_FILE = "journal.jsonl"
SNAPSHOT_PREFIX = "snapshot-"

#: trial-terminal events subject to exactly-once replay
SETTLED_EVENT = "settled"

#: every event the replayer understands, for fsck and docs
EVENTS = (
    "proposed",
    "queued",
    "started",
    "reported",
    "settled",
    "retried",
    "drained",
    "experiment",
    # supervisor audit trail: loop restarts / fallback decisions (no trial
    # payload; replay merges any "exp" data and otherwise skips them)
    "supervisor",
)


def journal_path(workdir: str, experiment_name: str) -> str:
    return os.path.join(workdir, experiment_name, JOURNAL_FILE)


def _crc(record: dict) -> str:
    """CRC-32 (hex) over the canonical serialization sans the crc field."""
    body = {k: v for k, v in record.items() if k != "crc"}
    raw = json.dumps(body, sort_keys=True, default=str).encode()
    return f"{zlib.crc32(raw) & 0xFFFFFFFF:08x}"


def _encode_record(rec: dict) -> str:
    """One-pass writer-side serialization: the canonical sort_keys JSON of
    the crc-less record with the crc spliced onto the end.  The reader's
    :func:`_crc` recomputes from the *parsed* dict with ``sort_keys=True``,
    so field order on disk is irrelevant — this is byte-compatible with the
    verification path while serializing each record once instead of twice
    (the append path dominates sweep-scale runs)."""
    raw = json.dumps(rec, sort_keys=True, default=str)
    crc = f"{zlib.crc32(raw.encode()) & 0xFFFFFFFF:08x}"
    return f'{raw[:-1]}, "crc": "{crc}"}}\n'


def _snapshot_name(seq: int) -> str:
    return f"{SNAPSHOT_PREFIX}{seq:012d}.json"


def _snapshot_seq(filename: str) -> int | None:
    stem = filename[len(SNAPSHOT_PREFIX) : -len(".json")]
    try:
        return int(stem)
    except ValueError:
        return None


def list_snapshots(exp_dir: str) -> list[tuple[int, str]]:
    """(seq, path) for every well-named snapshot file, oldest first."""
    out = []
    try:
        names = os.listdir(exp_dir)
    except OSError:
        return []
    for name in names:
        if name.startswith(SNAPSHOT_PREFIX) and name.endswith(".json"):
            seq = _snapshot_seq(name)
            if seq is not None:
                out.append((seq, os.path.join(exp_dir, name)))
    out.sort()
    return out


def load_snapshot(path: str) -> tuple[int, dict] | None:
    """(seq, state) when the snapshot parses AND its checksum verifies;
    None otherwise (fsck quarantines such files)."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    if not isinstance(doc, dict) or "state" not in doc or "seq" not in doc:
        return None
    want = doc.get("crc")
    got = f"{zlib.crc32(json.dumps(doc['state'], sort_keys=True, default=str).encode()) & 0xFFFFFFFF:08x}"
    if want != got:
        return None
    return int(doc["seq"]), doc["state"]


@dataclass
class ScanResult:
    """What one pass over a journal file found."""

    records: list[dict] = field(default_factory=list)
    #: byte offset of the end of the last VALID record (truncation point)
    valid_bytes: int = 0
    #: trailing bytes that failed to parse/verify (torn tail), 0 if clean
    torn_bytes: int = 0
    #: mid-file records dropped for bad checksum / non-monotonic seq
    bad_records: int = 0


def scan_journal(path: str) -> ScanResult:
    """Read every verifiable record in order.  A bad line mid-file is
    dropped (counted); a bad TRAILING region is the torn tail a crash
    mid-append leaves — its byte extent is reported so the caller (open /
    fsck) can truncate it away."""
    res = ScanResult()
    try:
        f = open(path, "rb")
    except OSError:
        return res
    last_seq = 0
    with f:
        offset = 0
        trailing_bad = 0
        for raw in f:
            line_len = len(raw)
            line = raw.decode("utf-8", errors="replace").strip()
            offset += line_len
            if not line:
                res.valid_bytes = offset if not trailing_bad else res.valid_bytes
                continue
            ok = False
            try:
                rec = json.loads(line)
                if (
                    isinstance(rec, dict)
                    and rec.get("crc") == _crc(rec)
                    and isinstance(rec.get("seq"), int)
                ):
                    ok = True
            except (json.JSONDecodeError, TypeError):
                ok = False
            # a record must also end in a newline: a valid-looking JSON line
            # at EOF without one may still be mid-write
            if ok and not raw.endswith(b"\n"):
                ok = False
            if ok and rec["seq"] <= last_seq:
                # duplicate / out-of-order (e.g. re-appended after a partial
                # compaction): drop, count, keep scanning
                res.bad_records += 1
                res.valid_bytes = offset
                continue
            if ok:
                last_seq = rec["seq"]
                res.records.append(rec)
                res.valid_bytes = offset
                if trailing_bad:
                    # bad region was mid-file after all
                    res.bad_records += trailing_bad
                    trailing_bad = 0
            else:
                trailing_bad += 1
        res.torn_bytes = offset - res.valid_bytes if trailing_bad else 0
    return res


class ExperimentJournal:
    """Append-only event log for one experiment.  Thread-safe: the
    orchestrator appends from the run loop AND from trial pool threads
    (retry-budget records)."""

    def __init__(
        self, workdir: str, experiment_name: str, snapshot_every: int = 32
    ) -> None:
        self.exp_dir = os.path.join(workdir, experiment_name)
        os.makedirs(self.exp_dir, exist_ok=True)
        self.path = os.path.join(self.exp_dir, JOURNAL_FILE)
        self.snapshot_every = max(1, snapshot_every)
        self._lock = threading.Lock()
        self._settled_since_snapshot = 0
        # recover the sequence clock from disk (resume case) and drop any
        # torn tail NOW, so this process appends after the valid prefix
        # instead of concatenating onto garbage
        seq = 0
        if os.path.exists(self.path):
            scan = scan_journal(self.path)
            if scan.records:
                seq = scan.records[-1]["seq"]
            if scan.torn_bytes:
                with open(self.path, "rb+") as f:
                    f.truncate(scan.valid_bytes)
                    f.flush()
                    os.fsync(f.fileno())
        for snap_seq, _ in list_snapshots(self.exp_dir):
            seq = max(seq, snap_seq)
        self.seq = seq
        self._sync = _sync_enabled()
        self._f = open(self.path, "a", encoding="utf-8")

    # -- writing -----------------------------------------------------------

    def append(
        self,
        event: str,
        trial: str | None = None,
        epoch: int = 0,
        data: dict | None = None,
    ) -> int:
        """Durably append one record; returns its seq."""
        from katib_tpu.utils.faults import crash_point

        with self._lock:
            self.seq += 1
            rec = {
                "seq": self.seq,
                "ts": round(get_clock().time(), 3),
                "event": event,
                "trial": trial,
                "epoch": int(epoch),
                "data": data or {},
            }
            self._f.write(_encode_record(rec))
            self._f.flush()
            # the deterministic kill window: bytes written, not yet fsync'd —
            # a crash here is exactly the torn tail the loader tolerates
            crash_point("journal.append")
            if self._sync:
                os.fsync(self._f.fileno())
            if event == SETTLED_EVENT:
                self._settled_since_snapshot += 1
            return self.seq

    def append_group(
        self, records: list[tuple[str, str | None, int, dict | None]]
    ) -> int:
        """Durably append several records with ONE fsync (the async
        scheduler's batch hand-offs: 32 ``proposed``/``queued`` records cost
        one disk sync instead of 32).  Each record is still written and
        flushed individually — the per-record crash window (bytes written,
        not yet fsync'd) is identical to sequential :meth:`append` calls —
        only the final durability barrier is amortized.  Returns the last
        seq."""
        from katib_tpu.utils.faults import crash_point

        with self._lock:
            for event, trial, epoch, data in records:
                self.seq += 1
                rec = {
                    "seq": self.seq,
                    "ts": round(get_clock().time(), 3),
                    "event": event,
                    "trial": trial,
                    "epoch": int(epoch),
                    "data": data or {},
                }
                self._f.write(_encode_record(rec))
                self._f.flush()
                crash_point("journal.append")
                if event == SETTLED_EVENT:
                    self._settled_since_snapshot += 1
            if self._sync:
                os.fsync(self._f.fileno())
            return self.seq

    def maybe_compact(self, state_fn) -> bool:
        """Snapshot + truncate when enough settlements accumulated.
        ``state_fn`` lazily produces the full experiment state dict (the
        ``status.py`` ``experiment_to_dict`` shape)."""
        with self._lock:
            if self._settled_since_snapshot < self.snapshot_every:
                return False
        self.snapshot(state_fn())
        return True

    def snapshot(self, state: dict) -> str:
        """Durably write a checksummed snapshot at the current seq, then
        compact: truncate the journal (its records are now ≤ snapshot seq)
        and prune older snapshots."""
        with self._lock:
            seq = self.seq
            doc = {
                "seq": seq,
                "crc": f"{zlib.crc32(json.dumps(state, sort_keys=True, default=str).encode()) & 0xFFFFFFFF:08x}",
                "state": state,
            }
            path = os.path.join(self.exp_dir, _snapshot_name(seq))
            atomic_replace(
                path,
                json.dumps(doc, default=str).encode(),
                prefix=".snap-",
                crash_site="journal.snapshot",
            )
            # snapshot durable → the journal prefix is redundant; truncate.
            # A crash between these two steps only leaves already-applied
            # records, which replay drops by seq.
            self._f.close()
            with open(self.path, "w") as f:
                f.flush()
                os.fsync(f.fileno())
            fsync_dir(self.exp_dir)
            self._f = open(self.path, "a", encoding="utf-8")
            for old_seq, old_path in list_snapshots(self.exp_dir):
                if old_seq < seq:
                    try:
                        os.unlink(old_path)
                    except OSError:
                        pass
            self._settled_since_snapshot = 0
            return path

    def close(self) -> None:
        with self._lock:
            try:
                self._f.flush()
                os.fsync(self._f.fileno())
            except (OSError, ValueError):
                pass
            try:
                self._f.close()
            except OSError:
                pass


# ---------------------------------------------------------------------------
# Replay
# ---------------------------------------------------------------------------


@dataclass
class ReplayStats:
    applied: int = 0
    duplicates: int = 0       # settled records dropped by the (trial, epoch) key
    stale: int = 0            # records at/below the snapshot seq (post-crash leftovers)
    bad_records: int = 0
    torn_bytes: int = 0
    last_seq: int = 0
    #: highest seq among applied *settled* records — the suggester fence
    #: threshold: a pickle whose fence is older than this is missing
    #: observations and must be rebuilt from trial history
    last_settled_seq: int = 0
    snapshot_seq: int | None = None


def _blank_state(name: str | None) -> dict:
    return {
        "name": name,
        "condition": "Created",
        "message": "",
        "start_time": 0.0,
        "completion_time": 0.0,
        "algorithm_settings": {},
        "optimal": None,
        "optimal_history": [],
        "trials": {},
    }


def _apply(state: dict, rec: dict, stats: ReplayStats, settled_keys: set) -> None:
    event = rec.get("event")
    data = rec.get("data") or {}
    trial = rec.get("trial")
    if event == SETTLED_EVENT:
        key = (trial, rec.get("epoch", 0))
        if key in settled_keys:
            stats.duplicates += 1
            return
        settled_keys.add(key)
        stats.last_settled_seq = max(stats.last_settled_seq, rec.get("seq", 0))
    # trial payload: the full trial_to_dict dict under "trial"
    tdata = data.get("trial")
    if trial is not None and isinstance(tdata, dict):
        state.setdefault("trials", {})[trial] = tdata
    elif trial is not None and event == "reported" and isinstance(data.get("observation"), list):
        t = state.setdefault("trials", {}).get(trial)
        if t is not None:
            t["observation"] = data["observation"]
    # experiment-level payload: merged last-writer-wins
    edata = data.get("exp")
    if isinstance(edata, dict):
        for k, v in edata.items():
            state[k] = v
    if event == "experiment":
        for k in ("name", "start_time", "algorithm"):
            if k in data:
                state[k] = data[k]
    stats.applied += 1


def replay_journal(
    workdir: str, experiment_name: str
) -> tuple[dict | None, ReplayStats]:
    """Rebuild the status-dict view of an experiment from its snapshot +
    journal suffix.  Returns ``(None, stats)`` when neither exists.

    Exactly-once settlement: records are applied in seq order; a settled
    record whose ``(trial, epoch)`` key was already settled — or any record
    at/below the snapshot's seq — is dropped and counted, never re-applied.
    """
    exp_dir = os.path.join(workdir, experiment_name)
    stats = ReplayStats()
    state: dict | None = None
    base_seq = 0
    # newest verifiable snapshot wins; unverifiable ones are skipped here
    # (fsck quarantines them) and replay falls back to the full log
    for seq, path in reversed(list_snapshots(exp_dir)):
        loaded = load_snapshot(path)
        if loaded is not None:
            base_seq, state = loaded
            stats.snapshot_seq = base_seq
            break
    scan = scan_journal(journal_path(workdir, experiment_name))
    stats.bad_records = scan.bad_records
    stats.torn_bytes = scan.torn_bytes
    if state is None and not scan.records:
        return None, stats
    if state is None:
        state = _blank_state(experiment_name)
    # seed the settled-key set from the snapshot's TERMINAL trials so
    # post-compaction leftovers can't double-settle; non-terminal trials
    # stay unkeyed — their genuine settlement is still ahead in the log
    _TERMINAL = {
        "Succeeded", "Killed", "Failed", "EarlyStopped", "MetricsUnavailable"
    }
    settled_keys: set = set()
    for tname, tdata in (state.get("trials") or {}).items():
        if isinstance(tdata, dict) and tdata.get("condition") in _TERMINAL:
            settled_keys.add((tname, int(tdata.get("retry_count") or 0)))
    stats.last_settled_seq = base_seq
    stats.last_seq = base_seq
    for rec in scan.records:
        if rec["seq"] <= base_seq:
            stats.stale += 1
            continue
        _apply(state, rec, stats, settled_keys)
        stats.last_seq = rec["seq"]
    return state, stats


def last_settled_seq(workdir: str, experiment_name: str) -> int:
    """The fence threshold: highest seq the journal proves settled work at.
    0 when no journal exists (fencing disabled)."""
    _, stats = replay_journal(workdir, experiment_name)
    return stats.last_settled_seq
