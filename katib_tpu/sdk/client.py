"""User-facing client API.

Parity with the reference Python SDK's ``KatibClient``
(``sdk/python/v1beta1/kubeflow/katib/api/katib_client.py:78,152``): the two
entry points users actually touch are ``tune()`` (objective function +
search-space dict in, best hyperparameters out) and experiment CRUD.  The
reference serializes the objective into a container image and round-trips
everything through CRDs; here trials are white-box JAX functions and the
client drives the in-process orchestrator directly — same surface, no
cluster.
"""

from __future__ import annotations

import inspect
import threading
from typing import Any, Callable, Mapping

from katib_tpu.core.types import (
    AlgorithmSpec,
    EarlyStoppingSpec,
    Experiment,
    ExperimentCondition,
    ExperimentSpec,
    MetricsCollectorKind,
    MetricsCollectorSpec,
    ObjectiveSpec,
    ObjectiveType,
)
from katib_tpu.orchestrator.orchestrator import Orchestrator
from katib_tpu.sdk.search import make_parameters
from katib_tpu.store.base import ObservationStore


def _wrap_objective(objective: Callable, metric_name: str) -> Callable:
    """Adapt a user objective to the trial ``train_fn(ctx)`` contract.

    Accepted shapes (the reference's ``tune()`` only takes
    ``objective(parameters)`` that prints metric lines — we keep that and add
    richer forms):

    - ``f(params) -> float``            return value reported as the objective
    - ``f(params) -> dict``             all keys reported as metrics
    - ``f(params, ctx)`` / ``f(ctx)``   full control: ``ctx.report(...)`` per step
    """
    sig = inspect.signature(objective)
    n_pos = len(
        [
            p
            for p in sig.parameters.values()
            if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)
        ]
    )
    wants_ctx_only = n_pos == 1 and next(iter(sig.parameters)) in ("ctx", "context")

    def train_fn(ctx) -> None:
        if wants_ctx_only:
            result = objective(ctx)
        elif n_pos >= 2:
            result = objective(ctx.params, ctx)
        else:
            result = objective(ctx.params)
        if result is None:
            return
        if isinstance(result, Mapping):
            ctx.report(**{k: float(v) for k, v in result.items()})
        else:
            ctx.report(**{metric_name: float(result)})

    return train_fn


def make_experiment_spec(
    name: str,
    search_space: dict[str, Any] | None = None,
    *,
    objective: Callable | None = None,
    command: list[str] | None = None,
    objective_metric_name: str = "objective",
    objective_type: ObjectiveType | str = ObjectiveType.MAXIMIZE,
    additional_metric_names: tuple[str, ...] = (),
    goal: float | None = None,
    algorithm: str = "random",
    algorithm_settings: Mapping[str, str] | None = None,
    early_stopping: str | None = None,
    early_stopping_settings: Mapping[str, str] | None = None,
    max_trial_count: int | None = None,
    parallel_trial_count: int = 3,
    max_failed_trial_count: int | None = None,
    metrics_collector: MetricsCollectorSpec | None = None,
) -> ExperimentSpec:
    """Assemble a validated ExperimentSpec from tune()-style keyword args."""
    if (objective is None) == (command is None):
        raise ValueError("exactly one of objective= / command= is required")
    if metrics_collector is None:
        metrics_collector = MetricsCollectorSpec(
            kind=MetricsCollectorKind.PUSH
            if objective is not None
            else MetricsCollectorKind.STDOUT
        )
    return ExperimentSpec(
        name=name,
        objective=ObjectiveSpec(
            type=ObjectiveType(objective_type),
            objective_metric_name=objective_metric_name,
            goal=goal,
            additional_metric_names=tuple(additional_metric_names),
        ),
        algorithm=AlgorithmSpec(name=algorithm, settings=dict(algorithm_settings or {})),
        early_stopping=(
            EarlyStoppingSpec(name=early_stopping, settings=dict(early_stopping_settings or {}))
            if early_stopping
            else None
        ),
        parameters=make_parameters(search_space or {}),
        max_trial_count=max_trial_count,
        parallel_trial_count=parallel_trial_count,
        max_failed_trial_count=max_failed_trial_count,
        metrics_collector=metrics_collector,
        train_fn=_wrap_objective(objective, objective_metric_name) if objective else None,
        command=list(command) if command else None,
    )


class KatibClient:
    """Experiment CRUD + wait/optimal accessors (reference ``katib_client.py``).

    Experiments run on daemon threads so ``create_experiment`` returns
    immediately (the reference's CR creation is likewise async); ``tune``
    blocks by default because that is how the reference's notebook flow is
    used in practice.
    """

    def __init__(
        self,
        store: ObservationStore | None = None,
        workdir: str = "katib_runs",
        mesh=None,
    ):
        self._orchestrators: dict[str, Orchestrator] = {}
        self._experiments: dict[str, Experiment] = {}
        self._threads: dict[str, threading.Thread] = {}
        self._errors: dict[str, BaseException] = {}
        self._store = store
        self._workdir = workdir
        self._mesh = mesh
        self._lock = threading.Lock()

    # -- lifecycle ----------------------------------------------------------

    def create_experiment(self, spec: ExperimentSpec) -> Experiment:
        """Start an experiment asynchronously; returns the live object whose
        status the orchestrator mutates in place."""
        with self._lock:
            if spec.name in self._experiments and not self._experiments[
                spec.name
            ].condition.is_terminal():
                raise ValueError(f"experiment {spec.name!r} already running")
            orch = Orchestrator(store=self._store, workdir=self._workdir, mesh=self._mesh)
            exp = Experiment(spec=spec)
            self._orchestrators[spec.name] = orch
            self._experiments[spec.name] = exp
            self._errors.pop(spec.name, None)

            def _run() -> None:
                # surface pre-run failures (bad algorithm, invalid space) —
                # a bare daemon thread would swallow them and leave the
                # experiment stuck non-terminal
                try:
                    orch.run(spec, exp)
                except BaseException as e:  # noqa: BLE001
                    import time as _time

                    exp.condition = ExperimentCondition.FAILED
                    exp.message = f"{type(e).__name__}: {e}"
                    exp.completion_time = _time.time()
                    self._errors[spec.name] = e

            t = threading.Thread(target=_run, name=f"exp-{spec.name}", daemon=True)
            self._threads[spec.name] = t
            t.start()
            return exp

    def tune(self, name: str, objective: Callable, search_space: dict, **kwargs) -> Experiment:
        """Blocking hyperparameter tuning (reference ``katib_client.py:152``)."""
        spec = make_experiment_spec(name, search_space, objective=objective, **kwargs)
        self.create_experiment(spec)
        return self.wait_for_experiment_condition(name)

    # -- accessors ----------------------------------------------------------

    def get_experiment(self, name: str) -> Experiment:
        return self._experiments[name]

    def list_experiments(self) -> list[Experiment]:
        return list(self._experiments.values())

    def is_experiment_succeeded(self, name: str) -> bool:
        cond = self._experiments[name].condition
        return cond in (
            ExperimentCondition.SUCCEEDED,
            ExperimentCondition.GOAL_REACHED,
            ExperimentCondition.MAX_TRIALS_REACHED,
        )

    def wait_for_experiment_condition(
        self, name: str, timeout: float | None = None
    ) -> Experiment:
        """Block until the experiment reaches a terminal condition (reference
        ``wait_for_experiment_condition``, default watches for Succeeded)."""
        t = self._threads[name]
        t.join(timeout)
        if t.is_alive():
            raise TimeoutError(f"experiment {name!r} still running after {timeout}s")
        if name in self._errors:
            raise self._errors[name]
        return self._experiments[name]

    def get_optimal_hyperparameters(self, name: str) -> dict[str, Any]:
        """Best parameter assignment found (reference
        ``katib_client.py`` ``get_optimal_hyperparameters``)."""
        exp = self._experiments[name]
        if exp.optimal is None:
            return {}
        return {a.name: a.value for a in exp.optimal.assignments}

    def get_trials(self, name: str):
        return list(self._experiments[name].trials.values())

    def delete_experiment(self, name: str) -> None:
        """Stop (if running) and forget an experiment."""
        with self._lock:
            orch = self._orchestrators.pop(name, None)
            self._experiments.pop(name, None)
            t = self._threads.pop(name, None)
            self._errors.pop(name, None)
        if orch is not None:
            orch.stop()
        if t is not None:
            t.join(timeout=30)


def tune(
    objective: Callable,
    search_space: dict[str, Any],
    *,
    name: str = "tune",
    store: ObservationStore | None = None,
    workdir: str = "katib_runs",
    mesh=None,
    **kwargs,
) -> Experiment:
    """One-call tuning without instantiating a client — the module-level
    convenience the reference exposes as ``KatibClient().tune(...)``."""
    spec = make_experiment_spec(name, search_space, objective=objective, **kwargs)
    orch = Orchestrator(store=store, workdir=workdir, mesh=mesh)
    return orch.run(spec)
