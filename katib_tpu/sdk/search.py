"""Search-space helper constructors.

Parity with the reference SDK's ``kubeflow.katib.search`` helpers
(``sdk/python/v1beta1/kubeflow/katib/api/search.py:19,37,55``): terse
factories users call inside a ``tune()`` search-space dict.  Values come back
as typed ``ParameterSpec`` templates; the parameter name is filled in from the
dict key by ``tune()``/``make_parameters``.
"""

from __future__ import annotations

import builtins
from typing import Any, Sequence

from katib_tpu.core.types import (
    Distribution,
    FeasibleSpace,
    ParameterSpec,
    ParameterType,
)


class _Unnamed:
    """A ParameterSpec missing only its name (bound later from the dict key)."""

    def __init__(self, type: ParameterType, feasible: FeasibleSpace):
        self.type = type
        self.feasible = feasible

    def bind(self, name: str) -> ParameterSpec:
        return ParameterSpec(name=name, type=self.type, feasible=self.feasible)


def double(
    min: float,
    max: float,
    step: float | None = None,
    distribution: Distribution | str = Distribution.UNIFORM,
) -> _Unnamed:
    return _Unnamed(
        ParameterType.DOUBLE,
        FeasibleSpace(
            min=float(min),
            max=float(max),
            step=step,
            distribution=Distribution(distribution),
        ),
    )


def loguniform(min: float, max: float) -> _Unnamed:
    return double(min, max, distribution=Distribution.LOG_UNIFORM)


def int_(
    min: int,
    max: int,
    step: int | None = None,
    distribution: Distribution | str = Distribution.UNIFORM,
) -> _Unnamed:
    return _Unnamed(
        ParameterType.INT,
        FeasibleSpace(
            min=builtins.int(min),
            max=builtins.int(max),
            step=step,
            distribution=Distribution(distribution),
        ),
    )


# the reference names this `search.int`; keep that spelling available (the
# module-global shadows the builtin, hence the explicit builtins. references)
globals()["int"] = int_


def discrete(values: Sequence[float]) -> _Unnamed:
    return _Unnamed(ParameterType.DISCRETE, FeasibleSpace(list=tuple(values)))


def categorical(values: Sequence[Any]) -> _Unnamed:
    return _Unnamed(ParameterType.CATEGORICAL, FeasibleSpace(list=tuple(values)))


def make_parameters(space: dict[str, Any]) -> list[ParameterSpec]:
    """Turn a ``{name: helper-or-spec-or-literal-list}`` dict into parameter
    specs.  Literal lists/tuples become categorical parameters; numeric
    ``(min, max)`` 2-tuples become doubles."""
    params: list[ParameterSpec] = []
    for name, v in space.items():
        if isinstance(v, _Unnamed):
            params.append(v.bind(name))
        elif isinstance(v, ParameterSpec):
            params.append(v)
        elif (
            isinstance(v, tuple)
            and len(v) == 2
            and all(
                isinstance(x, (builtins.int, float)) and not isinstance(x, bool)
                for x in v
            )
        ):
            params.append(double(v[0], v[1]).bind(name))
        elif isinstance(v, (list, tuple)):
            params.append(categorical(v).bind(name))
        else:
            raise TypeError(
                f"search-space entry {name!r}: expected a katib_tpu.sdk.search "
                f"helper, a ParameterSpec, a (min, max) tuple or a list of "
                f"choices; got {type(v).__name__}"
            )
    return params
