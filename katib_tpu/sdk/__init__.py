"""User-facing SDK (reference ``sdk/python/v1beta1/kubeflow/katib``)."""

from katib_tpu.sdk import search
from katib_tpu.sdk.client import KatibClient, make_experiment_spec, tune

__all__ = ["KatibClient", "make_experiment_spec", "search", "tune"]
