"""Katib-style Experiment YAML → ``ExperimentSpec``.

Accepts the reference's Experiment CR shape (``apiVersion: kubeflow.org/...``
``kind: Experiment`` with ``metadata.name`` + ``spec.{objective, algorithm,
parameters, ...}`` — see ``examples/v1beta1/hp-tuning/random.yaml``), so an
unmodified Katib CR loads: a nested K8s ``trialTemplate.trialSpec`` has its
primary container's argv extracted with trialParameter placeholders
rewritten, or the template carries a flat ``command`` argv directly.
White-box JAX trials come from ``trialTemplate.trainFn`` (a dotted import
path to a ``train_fn(ctx)``) or by setting ``train_fn`` via the SDK.
"""

from __future__ import annotations

import re
from typing import Any, Mapping

import yaml

from katib_tpu.core.types import (
    AlgorithmSpec,
    Distribution,
    EarlyStoppingSpec,
    ExperimentSpec,
    FeasibleSpace,
    GraphConfig,
    MetricsCollectorKind,
    MetricsCollectorSpec,
    MetricStrategy,
    MetricStrategyType,
    NasConfig,
    NasOperation,
    ObjectiveSpec,
    ObjectiveType,
    ParameterSpec,
    ParameterType,
    ResumePolicy,
)


class SpecError(ValueError):
    pass


def _num(value: Any) -> float:
    # the reference CR encodes feasibleSpace numbers as strings
    return float(value)


def _settings_list(raw: Any) -> dict[str, str]:
    """algorithmSettings come as [{name, value}] in the CR; accept plain
    mappings too."""
    if raw is None:
        return {}
    if isinstance(raw, Mapping):
        return {str(k): str(v) for k, v in raw.items()}
    out: dict[str, str] = {}
    for item in raw:
        out[str(item["name"])] = str(item["value"])
    return out


def _parse_parameter(raw: Mapping[str, Any]) -> ParameterSpec:
    try:
        name = raw["name"]
        ptype = ParameterType(raw.get("parameterType", raw.get("type")))
    except (KeyError, ValueError) as e:
        raise SpecError(f"bad parameter entry {raw!r}: {e}") from e
    fs = raw.get("feasibleSpace", raw.get("feasible", {})) or {}
    dist = fs.get("distribution", "uniform")
    try:
        distribution = Distribution(dist)
    except ValueError as e:
        raise SpecError(f"parameter {name!r}: unknown distribution {dist!r}") from e
    values = fs.get("list")
    if ptype in (ParameterType.DOUBLE, ParameterType.INT):
        feasible = FeasibleSpace(
            min=_num(fs["min"]) if "min" in fs else None,
            max=_num(fs["max"]) if "max" in fs else None,
            step=_num(fs["step"]) if fs.get("step") is not None else None,
            distribution=distribution,
        )
    else:
        if values is None:
            raise SpecError(f"parameter {name!r}: {ptype.value} requires a list")
        if ptype is ParameterType.DISCRETE:
            values = tuple(_num(v) for v in values)
        else:
            values = tuple(str(v) for v in values)
        feasible = FeasibleSpace(list=values, distribution=distribution)
    return ParameterSpec(name=name, type=ptype, feasible=feasible)


def _parse_objective(raw: Mapping[str, Any]) -> ObjectiveSpec:
    try:
        otype = ObjectiveType(raw["type"])
        metric = raw["objectiveMetricName"]
    except (KeyError, ValueError) as e:
        raise SpecError(f"bad objective {raw!r}: {e}") from e
    strategies = tuple(
        MetricStrategy(name=s["name"], value=MetricStrategyType(s["value"]))
        for s in raw.get("metricStrategies") or ()
    )
    return ObjectiveSpec(
        type=otype,
        objective_metric_name=metric,
        goal=float(raw["goal"]) if raw.get("goal") is not None else None,
        additional_metric_names=tuple(raw.get("additionalMetricNames") or ()),
        metric_strategies=strategies,
    )


def _parse_collector(raw: Mapping[str, Any] | None) -> MetricsCollectorSpec:
    if not raw:
        return MetricsCollectorSpec(kind=MetricsCollectorKind.STDOUT)
    # CR shape: {collector: {kind}, source: {filter: {metricsFormat: [...]},
    # fileSystemPath: {path, kind}, httpGet: {port, path}}}; flat shape:
    # {kind, path, filter, port, scrapeInterval}
    kind_raw = (raw.get("collector") or {}).get("kind", raw.get("kind", "StdOut"))
    # the reference CRD spells this kind "PrometheusMetric"
    # (``common_types.go:216``); accept it so upstream YAMLs round-trip
    if kind_raw == "PrometheusMetric":
        kind_raw = "Prometheus"
    try:
        kind = MetricsCollectorKind(kind_raw)
    except ValueError as e:
        raise SpecError(f"unknown metrics collector kind {kind_raw!r}") from e
    source = raw.get("source") or {}
    formats = (source.get("filter") or {}).get("metricsFormat") or []
    http_get = source.get("httpGet") or {}
    path = (
        (source.get("fileSystemPath") or {}).get("path")
        or http_get.get("path")
        or raw.get("path")
    )
    filter_ = formats[0] if formats else raw.get("filter")
    port = http_get.get("port", raw.get("port"))
    interval = raw.get("scrapeInterval", raw.get("scrape_interval", 1.0))
    return MetricsCollectorSpec(
        kind=kind,
        path=path,
        filter=filter_,
        port=int(port) if port is not None else None,
        scrape_interval=float(interval),
    )


def _parse_nas_config(raw: Mapping[str, Any] | None) -> NasConfig | None:
    """CR shape (reference ``experiment_types.go:304-320``):
    {graphConfig: {numLayers, inputSizes, outputSizes},
     operations: [{operationType, parameters: [...]}]}."""
    if not raw:
        return None
    gc_raw = raw.get("graphConfig") or raw.get("graph_config") or {}
    graph = GraphConfig(
        num_layers=int(gc_raw.get("numLayers", gc_raw.get("num_layers", 8))),
        input_sizes=tuple(int(v) for v in gc_raw.get("inputSizes", gc_raw.get("input_sizes")) or ()),
        output_sizes=tuple(int(v) for v in gc_raw.get("outputSizes", gc_raw.get("output_sizes")) or ()),
    )
    operations = tuple(
        NasOperation(
            operation_type=op.get("operationType", op.get("operation_type")),
            parameters=tuple(_parse_parameter(p) for p in op.get("parameters") or ()),
        )
        for op in raw.get("operations") or ()
    )
    return NasConfig(graph_config=graph, operations=operations)


def _find_containers(node: Any) -> list:
    """Collect EVERY ``containers`` list inside an arbitrary K8s manifest
    (Job, TFJob, PyTorchJob... all nest pod templates differently — the
    reference's trial job is an arbitrary GVK, ``trial_types.go:42``).  All
    of them, not the first: a multi-replica TFJob's primary container can
    live in any replica's pod template."""
    out: list = []
    if isinstance(node, Mapping):
        got = node.get("containers")
        if isinstance(got, list):
            out.extend(c for c in got if isinstance(c, Mapping))
        for v in node.values():
            out.extend(_find_containers(v))
    elif isinstance(node, list):
        for v in node:
            out.extend(_find_containers(v))
    return out


def _command_from_trial_spec(template: Mapping[str, Any]) -> list[str] | None:
    """Extract the primary container's argv from a reference-style nested
    ``trialTemplate.trialSpec`` (K8s Job manifest) and rewrite its
    ``${trialParameters.<name>}`` placeholders to the experiment parameter
    each trialParameter references — the loader-side analog of the
    reference's manifest generator substitution (``manifest/generator.go:
    79-126``), so an unmodified Katib CR round-trips (the container image
    itself does not transfer; the user points the argv at a local trainer).
    """
    containers = _find_containers(template.get("trialSpec"))
    if not containers:
        return None
    primary = template.get("primaryContainerName")
    if primary:
        container = next((c for c in containers if c.get("name") == primary), None)
        if container is None:
            # a silent containers[0] fallback would extract a sidecar's argv
            raise SpecError(
                f"primaryContainerName {primary!r} matches no container in "
                f"trialSpec (found: {[c.get('name') for c in containers]})"
            )
    else:
        container = containers[0]
    argv = list(container.get("command") or []) + list(container.get("args") or [])
    if not argv:
        return None
    return _apply_trial_parameter_renames(argv, template)


# single simultaneous pass: sequential str.replace would chain when one
# trialParameter's reference is another trialParameter's name
_TRIAL_PARAM_REF = re.compile(r"\$\{trialParameters\.([^}]+)\}")


def _apply_trial_parameter_renames(
    argv: list, template: Mapping[str, Any]
) -> list[str]:
    """Rewrite ``${trialParameters.<name>}`` placeholders through the
    template's ``trialParameters`` name->reference table (applies to flat
    ``command`` templates and extracted K8s trialSpec argv alike)."""
    renames = {
        str(tp["name"]): str(tp["reference"])
        for tp in template.get("trialParameters") or ()
        if isinstance(tp, Mapping) and tp.get("name") and tp.get("reference")
    }
    if not renames:
        return [str(token) for token in argv]

    def rewrite(m: "re.Match[str]") -> str:
        name = m.group(1)
        ref = renames.get(name, name)
        if ref.startswith("${trialSpec."):
            # metadata reference (reference generator.go:148-171): keep the
            # raw ${trialSpec.*} form — the trial runner resolves it against
            # the materialized trial, not the parameter assignments
            return ref
        return "${trialParameters." + ref + "}"

    return [_TRIAL_PARAM_REF.sub(rewrite, str(token)) for token in argv]


def experiment_spec_from_dict(data: Mapping[str, Any]) -> ExperimentSpec:
    """Build an ExperimentSpec from a CR-shaped or flat mapping."""
    if "spec" in data:  # CR shape
        name = (data.get("metadata") or {}).get("name")
        spec = data["spec"]
    else:
        name = data.get("name")
        spec = data
    if not name:
        raise SpecError("experiment name missing (metadata.name or name)")
    if "objective" not in spec:
        raise SpecError("spec.objective is required")

    algo_raw = spec.get("algorithm") or {}
    algorithm = AlgorithmSpec(
        name=algo_raw.get("algorithmName", algo_raw.get("name", "random")),
        settings=_settings_list(
            algo_raw.get("algorithmSettings", algo_raw.get("settings"))
        ),
    )
    early_stopping = None
    es_raw = spec.get("earlyStopping")
    if es_raw:
        early_stopping = EarlyStoppingSpec(
            name=es_raw.get("algorithmName", es_raw.get("name", "medianstop")),
            settings=_settings_list(
                es_raw.get("algorithmSettings", es_raw.get("settings"))
            ),
        )

    # trialTemplate: only the command argv carries over (the reference's
    # ${trialParameters.X} placeholders work unchanged); K8s job fields are
    # meaningless here.  A full reference CR with a nested K8s Job trialSpec
    # also loads: the primary container's argv is extracted and its
    # trialParameter names rewritten to the parameter names they reference.
    command = spec.get("command")
    template = spec.get("trialTemplate") or {}
    if command is None:
        command = template.get("command")
        if command is not None:
            command = _apply_trial_parameter_renames(command, template)
    if command is None and template.get("trialSpec"):
        command = _command_from_trial_spec(template)

    # white-box trials from YAML: ``trialTemplate.trainFn`` names a dotted
    # import path to a ``train_fn(ctx)`` (e.g. the packaged workloads in
    # models/ and nas/) — the CR analog of passing train_fn in Python
    train_fn = None
    train_fn_path = template.get("trainFn") or spec.get("trainFn")
    if train_fn_path:
        import importlib

        mod_name, _, attr = str(train_fn_path).rpartition(".")
        if not mod_name:
            raise SpecError(f"trainFn {train_fn_path!r} must be module.attr")
        try:
            train_fn = getattr(importlib.import_module(mod_name), attr)
        except (ImportError, AttributeError) as e:
            raise SpecError(f"trainFn {train_fn_path!r} not importable: {e}") from e

    resume = spec.get("resumePolicy", "Never")
    try:
        resume_policy = ResumePolicy(resume)
    except ValueError as e:
        raise SpecError(f"unknown resumePolicy {resume!r}") from e

    return ExperimentSpec(
        name=name,
        objective=_parse_objective(spec["objective"]),
        algorithm=algorithm,
        parameters=[_parse_parameter(p) for p in spec.get("parameters") or ()],
        early_stopping=early_stopping,
        parallel_trial_count=int(spec.get("parallelTrialCount", 3)),
        max_trial_count=(
            int(spec["maxTrialCount"]) if spec.get("maxTrialCount") is not None else None
        ),
        max_failed_trial_count=(
            int(spec["maxFailedTrialCount"])
            if spec.get("maxFailedTrialCount") is not None
            else None
        ),
        resume_policy=resume_policy,
        metrics_collector=_parse_collector(spec.get("metricsCollectorSpec")),
        command=[str(c) for c in command] if command else None,
        train_fn=train_fn,
        nas_config=_parse_nas_config(spec.get("nasConfig")),
        retain=bool(spec.get("retain", template.get("retain", False))),
        max_trial_runtime_seconds=(
            float(spec["maxTrialRuntimeSeconds"])
            if spec.get("maxTrialRuntimeSeconds") is not None
            else None
        ),
        metrics_retries=int(spec.get("metricsRetries", 0)),
        max_retries=int(spec.get("maxRetries", 0)),
        retry_backoff_seconds=float(spec.get("retryBackoffSeconds", 1.0)),
        suggester_max_errors=int(spec.get("suggesterMaxErrors", 5)),
        progress_deadline_seconds=(
            float(spec["progressDeadlineSeconds"])
            if spec.get("progressDeadlineSeconds") is not None
            else None
        ),
        drain_grace_seconds=float(spec.get("drainGraceSeconds", 30.0)),
        cohort_width=int(spec.get("cohortWidth", 1)),
        cohort_key=(
            str(spec["cohortKey"]) if spec.get("cohortKey") is not None else None
        ),
        cohort_buckets=bool(spec.get("cohortBuckets", True)),
        prewarm=bool(spec.get("prewarm", True)),
        compile_cache=(
            str(spec["compileCache"]) if spec.get("compileCache") is not None else None
        ),
        artifact_dir=(
            str(spec["artifactDir"]) if spec.get("artifactDir") is not None else None
        ),
        compile_deadline_seconds=(
            float(spec["compileDeadlineSeconds"])
            if spec.get("compileDeadlineSeconds") is not None
            else None
        ),
        async_orch=(
            bool(spec["asyncOrch"]) if spec.get("asyncOrch") is not None else None
        ),
        suggest_lookahead=(
            int(spec["suggestLookahead"])
            if spec.get("suggestLookahead") is not None
            else None
        ),
        occupancy_target=float(spec.get("occupancyTarget", 1.0)),
        cohort_fill_deadline_seconds=float(spec.get("cohortFillDeadlineSeconds", 2.0)),
        loop_stall_deadline_seconds=float(spec.get("loopStallDeadlineSeconds", 60.0)),
        loop_restart_budget=int(spec.get("loopRestartBudget", 3)),
        speculative_redispatch=bool(spec.get("speculativeRedispatch", False)),
        straggler_factor=float(spec.get("stragglerFactor", 4.0)),
        pbt_ondevice=(
            bool(spec["pbtOnDevice"]) if spec.get("pbtOnDevice") is not None else None
        ),
    )


def load_experiment_yaml(path: str) -> ExperimentSpec:
    with open(path) as f:
        data = yaml.safe_load(f)
    if not isinstance(data, Mapping):
        raise SpecError(f"{path} must contain a mapping")
    return experiment_spec_from_dict(data)
