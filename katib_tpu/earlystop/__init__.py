from katib_tpu.earlystop.medianstop import MedianStop  # noqa: F401
from katib_tpu.earlystop.rules import (  # noqa: F401
    EarlyStopper,
    RuleEvaluator,
    make_early_stopper,
    register_early_stopper,
)
