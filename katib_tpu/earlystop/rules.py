"""Early-stopping rule evaluation.

The reference evaluates rules inside the metrics-collector sidecar while
tailing the log file (``cmd/metricscollector/v1beta1/file-metricscollector/
main.go:332-393``), then SIGTERMs the training process.  Here trials are
white-box functions, so the evaluator is wired into the metrics path: every
``ctx.report(...)`` updates it, and the training loop stops cooperatively at
the next step boundary (black-box subprocess trials are still terminated by
the runner).

Semantics preserved from the reference:
- a rule with ``start_step`` only fires once its metric has been reported at
  least ``start_step`` times (``main.go:341-346``);
- for the objective metric the *best-so-far* value is compared, not the
  latest (``main.go:346-361``, the documented medianstop workaround), so a
  transient dip doesn't kill a trial that was already above the bar.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field

from katib_tpu.core.types import (
    EarlyStoppingRule,
    ObjectiveSpec,
    ObjectiveType,
)


@dataclass
class RuleState:
    rule: EarlyStoppingRule
    count: int = 0
    best: float | None = None


class RuleEvaluator:
    """Tracks one trial's metric stream against its stop rules (thread-safe:
    JAX host callbacks may report from non-main threads)."""

    def __init__(
        self, rules: list[EarlyStoppingRule], objective: ObjectiveSpec | None = None
    ):
        self._states = [RuleState(rule=r) for r in rules]
        self._objective = objective
        self._lock = threading.Lock()
        self._triggered: EarlyStoppingRule | None = None

    @property
    def triggered(self) -> EarlyStoppingRule | None:
        return self._triggered

    def should_stop(self) -> bool:
        return self._triggered is not None

    def observe(self, metric_name: str, value: float) -> bool:
        """Feed one metric point; returns True if the trial should stop."""
        with self._lock:
            if self._triggered is not None:
                return True
            for st in self._states:
                if st.rule.name != metric_name:
                    continue
                st.count += 1
                observed = value
                if self._objective and metric_name == self._objective.objective_metric_name:
                    # best-so-far semantics for the objective metric
                    if st.best is None or self._objective.type.better(value, st.best):
                        st.best = value
                    observed = st.best
                if st.count < max(st.rule.start_step, 1):
                    continue
                if st.rule.comparison.holds(observed, st.rule.value):
                    self._triggered = st.rule
                    return True
        return False


@dataclass
class StopDecision:
    stopped: bool
    rule: EarlyStoppingRule | None = None
    message: str = ""


class EarlyStopper:
    """Rule-generator contract — the analog of the gRPC ``EarlyStopping``
    service (``api.proto:42-45``): produce rules for a trial before it starts,
    from the history of completed trials."""

    name: str = ""

    def __init__(self, spec) -> None:  # ExperimentSpec
        self.spec = spec

    def get_rules(self, experiment) -> list[EarlyStoppingRule]:
        raise NotImplementedError


_ES_REGISTRY: dict[str, type] = {}


def register_early_stopper(name: str):
    def deco(cls):
        cls.name = name
        _ES_REGISTRY[name] = cls
        return cls

    return deco


def make_early_stopper(spec) -> EarlyStopper | None:
    """Instantiate the configured early-stopping algorithm, or None."""
    from katib_tpu.earlystop import medianstop  # noqa: F401 registration

    if spec.early_stopping is None:
        return None
    name = spec.early_stopping.name
    if name not in _ES_REGISTRY:
        raise ValueError(
            f"unknown early-stopping algorithm {name!r}; registered: {sorted(_ES_REGISTRY)}"
        )
    return _ES_REGISTRY[name](spec)
