"""Median-stopping rule generator.

Parity with the reference ``medianstop`` service
(``pkg/earlystopping/v1beta1/medianstop/service.py:100-184``): for every
succeeded trial take the running average of its first ``start_step`` objective
values, aggregate across trials, and stop any new trial whose best-so-far
objective is on the wrong side of that aggregate after ``start_step`` reports.

Two deliberate differences:
- the aggregate is a true median (the reference computes an arithmetic mean
  despite the name, ``service.py:147``); the median is what the algorithm
  (Golovin et al., Vizier) specifies and is robust to divergent trials;
- per-trial averages are recomputed from the observation store on demand
  instead of cached in service memory, so the stopper is restart-safe.

Settings: ``min_trials_required`` (default 3), ``start_step`` (default 4).
"""

from __future__ import annotations

import statistics

from katib_tpu.core.types import (
    ComparisonOp,
    EarlyStoppingRule,
    ObjectiveType,
    TrialCondition,
)
from katib_tpu.earlystop.rules import EarlyStopper, register_early_stopper


@register_early_stopper("medianstop")
class MedianStop(EarlyStopper):
    def __init__(self, spec):
        super().__init__(spec)
        settings = spec.early_stopping.settings if spec.early_stopping else {}
        self.min_trials_required = int(settings.get("min_trials_required", 3))
        self.start_step = int(settings.get("start_step", 4))
        if self.min_trials_required < 1:
            raise ValueError("min_trials_required must be >= 1")
        if self.start_step < 1:
            raise ValueError("start_step must be >= 1")
        self._store = None  # injected by the orchestrator

    def bind_store(self, store) -> None:
        self._store = store

    def _trial_average(self, trial_name: str) -> float | None:
        metric = self.spec.objective.objective_metric_name
        logs = self._store.get(trial_name, metric) if self._store else []
        if not logs:
            return None
        head = [l.value for l in logs[: self.start_step]]
        return sum(head) / len(head)

    def get_rules(self, experiment) -> list[EarlyStoppingRule]:
        averages = []
        for t in experiment.trials.values():
            if t.condition is not TrialCondition.SUCCEEDED:
                continue
            avg = self._trial_average(t.name)
            if avg is not None:
                averages.append(avg)
        if len(averages) < self.min_trials_required:
            return []
        median = statistics.median(averages)
        comparison = (
            ComparisonOp.LESS
            if self.spec.objective.type is ObjectiveType.MAXIMIZE
            else ComparisonOp.GREATER
        )
        return [
            EarlyStoppingRule(
                name=self.spec.objective.objective_metric_name,
                value=float(median),
                comparison=comparison,
                start_step=self.start_step,
            )
        ]
