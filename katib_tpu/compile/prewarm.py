"""Background compile prewarm worker: strictly best-effort, never on the
critical path.

While the current cohort trains, the orchestrator already knows the next
groups' trial twins, structural parameters, bucketed widths, and mesh —
everything a compile needs except the data.  The worker drains those
signatures on a daemon thread and calls each train function's *prewarm
twin*, which builds the exact jitted step functions the real cohort will
use (through the same module-level step caches) and runs them once on
dummy operands of the right shapes.  That populates the in-process jit
cache — and, with ``init_compile_cache`` wired, the persistent XLA cache —
so the cohort's first step deserializes instead of recompiling.

A train function opts in like the cohort protocol::

    def my_trial(ctx): ...
    def my_prewarm(shared, k, mesh=None): ...   # compile, don't train
    attach_prewarm_fn(my_trial, my_prewarm)

``prewarm(shared, k, mesh)`` receives the member-agreed structural
parameters, the padded/bucketed cohort width, and the mesh; it must be
side-effect free beyond compilation (no dataset downloads, no metric
reports).

Failure contract: the worker can be killed, starved, or blow up
mid-compile and nothing downstream notices — every exception is logged
and swallowed, ``stop()`` bounds its wait, and the thread is a daemon so
process exit never blocks on it.  Duplicate submissions dedupe against
the shape registry, so a queued signature compiles exactly once.
"""

from __future__ import annotations

import logging
import queue
import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Mapping

from katib_tpu.analysis import guarded_by, make_lock
from katib_tpu.compile.registry import (
    REGISTRY,
    CompileSignature,
    ShapeRegistry,
    _program_name,
    _shapes_of,
    mesh_signature,
)
from katib_tpu.utils import observability as obs

_log = logging.getLogger(__name__)

_PREWARM_ATTR = "__prewarm_fn__"


def attach_prewarm_fn(train_fn: Callable, prewarm_fn: Callable) -> Callable:
    """Declare ``prewarm_fn(shared, k, mesh)`` as the compile-only twin of
    ``train_fn``; returns ``train_fn`` (decorator-style one-liner)."""
    setattr(train_fn, _PREWARM_ATTR, prewarm_fn)
    return train_fn


def prewarm_fn_of(train_fn: Callable | None) -> Callable | None:
    if train_fn is None:
        return None
    return getattr(train_fn, _PREWARM_ATTR, None)


@dataclass
class PrewarmRequest:
    """One upcoming program: who compiles it and with what shapes."""

    train_fn: Callable
    shared: Mapping[str, Any] = field(default_factory=dict)
    k: int = 1
    mesh: Any = None
    # the cohort twin (if any) names the program, matching the signature
    # run_cohort classifies against
    program_fn: Callable | None = None

    def signature(self) -> CompileSignature:
        return CompileSignature(
            program=_program_name(self.program_fn or self.train_fn),
            shapes=_shapes_of(
                {n: v for n, v in self.shared.items() if not isinstance(v, float)}
            ),
            k=int(self.k),
            mesh=mesh_signature(self.mesh),
        )


class PrewarmWorker:
    """Daemon-thread compile worker over a bounded queue of requests.

    With the artifact cache wired (``compile/artifacts.py``), each
    request first tries a *fetch*: a signature whose serialized
    executables already exist in a tier loads them instead of compiling
    (and marks the registry warm).  After a cold twin compile, ``publish``
    mode serializes every step program the twin observed
    (``costmodel.observe_program`` mirrors them into the artifact offer
    slot) and publishes one content-addressed envelope per program — one
    host's compile warms the whole fleet.  ``fetch_only`` skips the cold
    compile entirely (a new host syncing executables without paying for
    the misses).
    """

    # the worker thread bumps the counters; the CLI/tests read them after
    # drain() — both sides go through _lock, like the thread handle itself
    _GUARDS = guarded_by(
        _lock=("_thread", "compiled", "failed", "fetched", "published")
    )

    def __init__(
        self,
        registry: ShapeRegistry = REGISTRY,
        max_queue: int = 64,
        publish: bool = True,
        fetch_only: bool = False,
        force: bool = False,
    ):
        self._registry = registry
        self._queue: queue.Queue = queue.Queue(maxsize=max_queue)
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        self._lock = make_lock("prewarm.worker")
        self._publish = publish
        self._fetch_only = fetch_only
        # force: bypass the registry dedupe (CLI --publish re-runs want to
        # ensure artifacts exist even for already-registered signatures;
        # the artifact content address still dedupes the actual writes)
        self._force = force
        self.compiled = 0  # successful prewarm compiles (tests/CLI)
        self.failed = 0
        self.fetched = 0  # requests satisfied by an artifact fetch
        self.published = 0  # programs serialized into an artifact tier

    def submit(self, request: PrewarmRequest) -> bool:
        """Enqueue a request; returns False (without queuing) when the
        train_fn never opted in, the signature is already registered, or
        the queue is full — submission never blocks the caller."""
        if prewarm_fn_of(request.train_fn) is None:
            return False
        if not self._force and self._registry.seen(request.signature()):
            return False
        try:
            self._queue.put_nowait(request)
        except queue.Full:
            return False  # backpressure: drop, the trial compiles live
        self._ensure_thread()
        return True

    def _ensure_thread(self) -> None:
        with self._lock:
            if self._thread is None or not self._thread.is_alive():
                self._stop.clear()
                self._thread = threading.Thread(
                    target=self._run, name="katib-prewarm", daemon=True
                )
                self._thread.start()

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                req = self._queue.get(timeout=0.2)
            except queue.Empty:
                continue
            try:
                self._compile(req)
            except Exception:
                with self._lock:  # LCK001: counter read from the caller thread
                    self.failed += 1
                _log.warning(
                    "prewarm compile failed for %s (best-effort, trial will "
                    "compile live)",
                    _program_name(req.train_fn),
                    exc_info=True,
                )
            finally:
                self._queue.task_done()

    def _compile(self, req: PrewarmRequest) -> None:
        sig = req.signature()
        if not self._force and self._registry.seen(sig):
            return  # raced with a trial (or a duplicate submit): already warm
        fn = prewarm_fn_of(req.train_fn)
        if fn is None:
            return
        import time

        from katib_tpu import costmodel
        from katib_tpu.compile import artifacts

        # cheapest warm path: someone in the fleet already published this
        # signature's executables — load them instead of compiling
        loaded = artifacts.ARTIFACTS.fetch_family(sig)
        if loaded:
            with self._lock:  # LCK001: counter read from the caller thread
                self.fetched += 1
            if self._publish:
                # a local-tier hit in publish mode still syncs the shared
                # tier (content-address dedupe makes this cheap)
                for la in loaded:
                    if artifacts.ARTIFACTS.replicate(la):
                        with self._lock:
                            self.published += 1
            return
        if self._fetch_only:
            return  # sync-only mode: misses stay cold, nothing compiles
        costmodel.clear_active()  # worker thread is reused across requests
        artifacts.clear_observed()
        started = time.perf_counter()
        fn(dict(req.shared), int(req.k), req.mesh)
        elapsed = time.perf_counter() - started
        if self._registry.record(sig, source="prewarm", compile_seconds=elapsed):
            with self._lock:  # LCK001: counter read from the caller thread
                self.compiled += 1
            obs.prewarm_compiles.inc(program=sig.program)
        # twins observe their program cost into the ambient slot
        # (costmodel.observe_program) — persist it next to the signature so
        # `katib-tpu cost` can print the roofline table without a run
        active = costmodel.active_cost()
        if active is not None:
            try:
                self._registry.record_cost(sig, active[0].as_dict())
            except Exception:
                pass  # cost is telemetry; the prewarm itself succeeded
        if self._publish:
            # serialize every step program the twin just observed into the
            # artifact tiers, linked to the request signature so a fresh
            # host's fetch_family collects them all (best-effort)
            n = artifacts.publish_observed(sig)
            if n:
                with self._lock:  # LCK001: CLI reads after drain()
                    self.published += n

    def drain(self, timeout: float = 30.0) -> bool:
        """Wait (bounded) for the queue to empty — CLI verb / tests only;
        the orchestrator never blocks on the worker."""
        import time

        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self._queue.unfinished_tasks == 0:
                return True
            time.sleep(0.02)
        return False

    def stop(self, timeout: float = 1.0) -> None:
        """Ask the worker to wind down; bounded, never raises.  A compile
        in flight keeps running on the daemon thread and is abandoned at
        process exit — by design, nothing waits on it."""
        self._stop.set()
        with self._lock:  # LCK001: _ensure_thread writes _thread under _lock
            t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout)
