"""Compile amortization: shape bucketing, a compile-signature registry, and
a background prewarm worker.

BENCH_r05 puts a live XLA compile at 470s against a 0.54s step — at fleet
trial volumes compilation, not training, is the bill.  Three coordinated
pieces keep cohort dispatches on a warm cache:

- :mod:`katib_tpu.compile.buckets` quantizes cohort width K onto a few
  padded power-of-two sizes, so heterogeneous cohorts collapse onto a
  handful of cached executables (the inert ghost-member padding from
  ``runner/cohort.py`` makes the extra rows free);
- :mod:`katib_tpu.compile.registry` records every (program, shapes, mesh,
  donation) signature compiled and classifies each trial's first step
  warm/cold, exporting hit/miss counters and compile-time histograms;
- :mod:`katib_tpu.compile.prewarm` runs a strictly best-effort background
  worker that compiles upcoming cohort programs (fed by the orchestrator's
  proposal groups) while current trials execute, so the next cohort's
  first step deserializes instead of recompiling;
- :mod:`katib_tpu.compile.artifacts` makes compiled executables portable
  *across hosts*: serialized AOT executables in a content-addressed,
  tiered artifact cache (local dir → shared dir → cold compile) keyed by
  compile signature + environment fingerprint, so a brand-new host's
  first step fetches instead of compiling.
"""

from katib_tpu.compile.artifacts import (  # noqa: F401
    ARTIFACTS,
    ArtifactCache,
    DirectoryBackend,
    LoadedArtifact,
    env_fingerprint,
    fsck_artifacts,
    is_artifact_dir,
    resolve,
)
from katib_tpu.compile.buckets import (  # noqa: F401
    bucket_size,
    bucket_table,
    bucketed_cohort_size,
    next_pow2,
)
from katib_tpu.compile.prewarm import (  # noqa: F401
    PrewarmRequest,
    PrewarmWorker,
    attach_prewarm_fn,
    prewarm_fn_of,
)
from katib_tpu.compile.registry import (  # noqa: F401
    REGISTRY,
    CompileSignature,
    ShapeRegistry,
    cohort_signature,
    shared_structural,
    trial_signature,
)
