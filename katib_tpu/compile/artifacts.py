"""Serialized AOT executables in a content-addressed, tiered artifact cache.

PR 8's prewarm worker and the persistent XLA cache amortize compilation
*within* one host: the first process pays the 470s (live) / 1554s (AOT)
compile and every later process on the same cache dir deserializes.  A
brand-new host still starts cold — which is exactly the step the
multi-host async dispatch (ROADMAP items 2 and 4) cannot afford.  This
module makes compiled executables *portable*: one host serializes its
AOT-compiled programs (``jax.experimental.serialize_executable``) into
checksummed envelopes published to a shared artifact tier, and a fresh
host's first step deserializes a fetched envelope instead of compiling.

Lookup order (cheapest first)::

    in-process loaded map -> local tier (<compile_cache>/artifacts)
        -> shared tier (KATIB_ARTIFACT_DIR / ExperimentSpec.artifact_dir)
        -> cold compile

Artifacts are **content-addressed**: the file name is the SHA-256 of the
:class:`~katib_tpu.compile.registry.CompileSignature` key plus an
*environment fingerprint* (jax/jaxlib/libtpu versions, platform, device
kind, topology).  A toolchain or topology change therefore produces a
different address — stale artifacts invalidate by construction instead
of misloading.  Defense in depth on the fetch path: every envelope
carries its own checksum and fingerprint, and anything corrupt,
truncated, or mismatched is **quarantined** (renamed ``*.quarantined``,
same idiom as ``orchestrator/fsck.py`` snapshots) and counted — a fetch
failure always degrades to a cold compile, never a crash.

The shared tier speaks through the small :class:`ArtifactBackend`
interface (get/put/exists/list/delete) so a directory today can become
an object store later without touching the cache logic.  Publication is
atomic (temp file + rename via ``utils/fsio.py``) so concurrent
publishers — a whole fleet warming at once — can never surface a torn
envelope, and publish dedupes on the content address.

Cost records (``costmodel.CostRecord``) ride inside the envelope, so a
fetched program publishes its MFU/roofline gauges without re-tracing
(``costmodel.live.observe_program`` consults :meth:`ArtifactCache.cost_for`
before paying the extra trace).

Everything here is strictly best-effort telemetry-grade plumbing: an
unreadable tier, an unserializable executable, or a full disk never
fails a trial — the jit path is always the fallback.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import pickle
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Iterable, Mapping

from katib_tpu.analysis import guarded_by, make_lock
from katib_tpu.compile.registry import REGISTRY, CompileSignature, _cache_dir
from katib_tpu.utils import observability as obs
from katib_tpu.utils.fsio import atomic_replace

_log = logging.getLogger(__name__)

MAGIC = b"KATIBART1\n"
SUFFIX = ".katibx"
QUARANTINE_SUFFIX = ".quarantined"
_ENV_VAR = "KATIB_ARTIFACT_DIR"


class ArtifactCorrupt(Exception):
    """Envelope failed integrity verification (magic/header/checksum)."""


class ArtifactMismatch(Exception):
    """Envelope is intact but belongs to a different signature or
    environment than its address claims (tampered or misplaced file)."""


# -- environment fingerprint --------------------------------------------------

_FP_CACHE: dict | None = None


def _libtpu_version() -> str:
    """Installed libtpu version, best-effort ('' off-TPU)."""
    try:
        from importlib import metadata

        for dist in ("libtpu", "libtpu-nightly"):
            try:
                return f"{dist}-{metadata.version(dist)}"
            except metadata.PackageNotFoundError:
                continue
    except Exception:
        pass
    return ""


def env_fingerprint(refresh: bool = False) -> dict:
    """The fields that decide whether a serialized executable from another
    process can safely load here: toolchain versions, platform, device
    kind, and topology.  Computed once per process (``refresh`` for
    tests).  Serialized executables are XLA-version- and target-specific;
    two hosts agreeing on this fingerprint can exchange them."""
    global _FP_CACHE
    if _FP_CACHE is not None and not refresh:
        return dict(_FP_CACHE)
    fp = {
        "jax": "?",
        "jaxlib": "?",
        "libtpu": _libtpu_version(),
        "platform": "?",
        "device_kind": "?",
        "device_count": 0,
        "process_count": 1,
    }
    try:
        import jax
        import jaxlib

        fp["jax"] = jax.__version__
        fp["jaxlib"] = jaxlib.__version__
        devs = jax.devices()
        fp["platform"] = devs[0].platform
        fp["device_kind"] = devs[0].device_kind
        fp["device_count"] = len(devs)
        fp["process_count"] = jax.process_count()
    except Exception:
        pass  # a deviceless/odd env still fingerprints (just coarsely)
    _FP_CACHE = fp
    return dict(fp)


def fingerprint_key(fp: Mapping[str, Any]) -> str:
    return json.dumps(dict(fp), sort_keys=True)


def artifact_name(sig_key: str, fp: Mapping[str, Any]) -> str:
    """Content address: SHA-256 over (signature key, env fingerprint).
    A different toolchain/topology yields a different name, so a stale
    artifact is simply never looked up — invalidation by construction."""
    digest = hashlib.sha256(
        (sig_key + "\x00" + fingerprint_key(fp)).encode()
    ).hexdigest()
    return digest + SUFFIX


def sig_from_key(key: str) -> CompileSignature:
    """Reconstruct a :class:`CompileSignature` from its ``key()`` json
    (artifact headers carry the key; replication and family fetches need
    the structured form back)."""
    rec = json.loads(key)
    return CompileSignature(
        program=str(rec.get("program", "?")),
        shapes=tuple((str(a), str(b)) for a, b in rec.get("shapes") or []),
        k=int(rec.get("k", 1)),
        mesh=str(rec.get("mesh", "")),
        donation=bool(rec.get("donation", True)),
    )


def _aval_list(tree: Any) -> list[list]:
    """Flattened [(shape, dtype)] of a pytree of arrays/avals — the
    envelope's calling-convention record and the (program, avals) index
    key the dispatch seam matches against."""
    import jax

    out = []
    for leaf in jax.tree_util.tree_leaves(tree):
        shape = tuple(getattr(leaf, "shape", ()))
        dtype = str(getattr(leaf, "dtype", type(leaf).__name__))
        out.append([list(int(d) for d in shape), dtype])
    return out


def aval_digest(tree: Any) -> str:
    return hashlib.sha256(
        json.dumps(_aval_list(tree), sort_keys=True).encode()
    ).hexdigest()


# -- envelope (checksummed container) -----------------------------------------


def pack_envelope(
    sig: CompileSignature,
    fp: Mapping[str, Any],
    payload: bytes,
    in_tree: Any,
    out_tree: Any,
    *,
    avals: list | None = None,
    cost: Mapping[str, Any] | None = None,
    parent: str | None = None,
) -> bytes:
    """``MAGIC + header-json + \\n + body``: the body is the pickled
    (serialized executable, in/out treedefs) and the header carries the
    signature identity, the environment fingerprint, the program's input
    avals, the optional cost record, and the body's length + SHA-256."""
    body = pickle.dumps(
        {"payload": payload, "in_tree": in_tree, "out_tree": out_tree},
        protocol=pickle.HIGHEST_PROTOCOL,
    )
    header = {
        "version": 1,
        "key": sig.key(),
        "program": sig.program,
        "k": sig.k,
        "mesh": sig.mesh,
        "shapes": dict(sig.shapes),
        "donation": sig.donation,
        "fingerprint": dict(fp),
        "avals": avals or [],
        "cost": dict(cost) if cost else None,
        # the request-level signature this program was compiled under —
        # a prewarm twin observes several step programs, each published
        # as its own envelope; fetch_family collects them by this link
        "parent": parent,
        "created": time.time(),
        "body_len": len(body),
        "body_sha256": hashlib.sha256(body).hexdigest(),
    }
    return MAGIC + json.dumps(header, sort_keys=True).encode() + b"\n" + body


def unpack_envelope(data: bytes) -> tuple[dict, dict]:
    """Parse + verify an envelope; returns ``(header, body_dict)``.
    Raises :class:`ArtifactCorrupt` on any structural or checksum
    failure — callers quarantine and degrade, never crash."""
    if not data.startswith(MAGIC):
        raise ArtifactCorrupt("bad magic")
    rest = data[len(MAGIC):]
    nl = rest.find(b"\n")
    if nl < 0:
        raise ArtifactCorrupt("no header terminator")
    try:
        header = json.loads(rest[:nl].decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise ArtifactCorrupt(f"unparseable header: {e}") from e
    if not isinstance(header, dict):
        raise ArtifactCorrupt("header is not an object")
    body = rest[nl + 1:]
    if len(body) != int(header.get("body_len", -1)):
        raise ArtifactCorrupt(
            f"body length {len(body)} != declared {header.get('body_len')}"
        )
    if hashlib.sha256(body).hexdigest() != header.get("body_sha256"):
        raise ArtifactCorrupt("body checksum mismatch")
    try:
        body_dict = pickle.loads(body)
    except Exception as e:
        raise ArtifactCorrupt(f"unpicklable body: {e}") from e
    if not isinstance(body_dict, dict) or "payload" not in body_dict:
        raise ArtifactCorrupt("body missing payload")
    return header, body_dict


def read_header(data: bytes) -> dict:
    """Header-only parse with the same integrity checks minus the body
    unpickle (``cache``/``fsck`` inspection: no executable load)."""
    if not data.startswith(MAGIC):
        raise ArtifactCorrupt("bad magic")
    rest = data[len(MAGIC):]
    nl = rest.find(b"\n")
    if nl < 0:
        raise ArtifactCorrupt("no header terminator")
    try:
        header = json.loads(rest[:nl].decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise ArtifactCorrupt(f"unparseable header: {e}") from e
    if not isinstance(header, dict):
        raise ArtifactCorrupt("header is not an object")
    body = rest[nl + 1:]
    if len(body) != int(header.get("body_len", -1)):
        raise ArtifactCorrupt(
            f"body length {len(body)} != declared {header.get('body_len')}"
        )
    if hashlib.sha256(body).hexdigest() != header.get("body_sha256"):
        raise ArtifactCorrupt("body checksum mismatch")
    return header


# -- backends (object-store-shaped) -------------------------------------------


class ArtifactBackend:
    """Minimal blob-store surface a tier needs.  A directory implements it
    today; an object store (GCS/S3) implements the same five methods
    later without the cache logic changing."""

    def get(self, name: str) -> bytes | None:  # pragma: no cover - interface
        raise NotImplementedError

    def put(self, name: str, data: bytes) -> None:  # pragma: no cover
        raise NotImplementedError

    def exists(self, name: str) -> bool:  # pragma: no cover - interface
        raise NotImplementedError

    def list(self) -> list[str]:  # pragma: no cover - interface
        raise NotImplementedError

    def delete(self, name: str) -> None:  # pragma: no cover - interface
        raise NotImplementedError

    def quarantine(self, name: str) -> bool:
        """Move a blob out of the lookup namespace, preserving the bytes
        for diagnosis.  Default: copy-then-delete through the interface."""
        data = self.get(name)
        if data is None:
            return False
        self.put(name + QUARANTINE_SUFFIX, data)
        self.delete(name)
        return True

    def describe(self) -> str:  # pragma: no cover - interface
        return type(self).__name__


class DirectoryBackend(ArtifactBackend):
    """Shared-filesystem tier: one envelope file per artifact, atomic
    publication (temp + rename) so concurrent publishers and readers
    never see a torn file."""

    def __init__(self, root: str):
        self.root = os.path.abspath(root)

    def _path(self, name: str) -> str:
        # content addresses are hex digests — no separators — but never
        # trust a name to stay inside the root
        safe = os.path.basename(name)
        return os.path.join(self.root, safe)

    def get(self, name: str) -> bytes | None:
        try:
            with open(self._path(name), "rb") as f:
                return f.read()
        except OSError:
            return None

    def put(self, name: str, data: bytes) -> None:
        os.makedirs(self.root, exist_ok=True)
        # durable atomic replace: a concurrent reader sees the old file or
        # the new one, never a prefix — and a same-content racer is
        # harmless because both write identical bytes
        atomic_replace(self._path(name), data, prefix=".pub-")

    def exists(self, name: str) -> bool:
        return os.path.exists(self._path(name))

    def list(self) -> list[str]:
        try:
            return sorted(
                n for n in os.listdir(self.root) if n.endswith(SUFFIX)
            )
        except OSError:
            return []

    def delete(self, name: str) -> None:
        try:
            os.unlink(self._path(name))
        except OSError:
            pass

    def quarantine(self, name: str) -> bool:
        src = self._path(name)
        try:
            os.replace(src, src + QUARANTINE_SUFFIX)
            return True
        except OSError:
            return False

    def describe(self) -> str:
        return self.root


# -- loaded artifacts ---------------------------------------------------------


@dataclass
class LoadedArtifact:
    """A fetched, deserialized executable ready to dispatch."""

    sig_key: str
    program: str
    compiled: Any  # jax.stages.Compiled
    tier: str
    avals: list = field(default_factory=list)
    aval_key: str = ""
    cost: dict | None = None
    parent: str | None = None

    def __call__(self, *args):
        return self.compiled(*args)

    def dummy_args(self) -> tuple:
        """Zero-filled concrete operands matching the executable's input
        avals — enough to execute one real step (bench/CLI verification:
        a fetched executable that cannot run is worse than a cold
        compile, so prove it dispatches)."""
        import jax
        import jax.numpy as jnp

        def zero(a):
            return jnp.zeros(a.shape, a.dtype)

        info = self.compiled.args_info
        # AOT Compiled reports ((args...), {kwargs}) — unwrap to the
        # positional tuple (empty kwargs: these programs are jit steps)
        if (
            isinstance(info, tuple)
            and len(info) == 2
            and isinstance(info[1], dict)
            and not info[1]
        ):
            info = info[0]
        return tuple(jax.tree_util.tree_map(zero, tuple(info)))


# -- the tiered cache ---------------------------------------------------------


class ArtifactCache:
    """Process-wide tiered executable cache with per-tier hit/miss
    telemetry.

    Reached from the prewarm worker thread, trial pool threads (the
    runner's pre-trace fetch), and the caller thread (CLI verbs) — the
    loaded maps and the shared-dir config go through ``_lock``.  Fetch
    deserialization happens outside the lock (it is slow and jax-side
    thread-safe); a racing duplicate load is harmless, last-in wins.
    """

    _GUARDS = guarded_by(
        _lock=("_loaded", "_by_program", "_families", "_misses", "_shared_dir")
    )

    def __init__(self) -> None:
        self._lock = make_lock("compile.artifacts")
        self._loaded: dict[str, LoadedArtifact] = {}
        self._by_program: dict[tuple[str, str], LoadedArtifact] = {}
        self._families: dict[str, list[LoadedArtifact]] = {}
        # signatures whose family fetch came up empty: every trial's
        # dispatch seam probes, and rescanning the tier directories per
        # trial would be pure waste — a publish() invalidates this
        self._misses: set[str] = set()
        self._shared_dir: str | None = None

    # -- configuration -------------------------------------------------------

    def configure(self, shared_dir: str | None = None) -> str | None:
        """Wire the shared tier: ``KATIB_ARTIFACT_DIR`` env var first, then
        the argument (``ExperimentSpec.artifact_dir``).  First caller
        wins, like ``init_compile_cache`` — a second caller asking for a
        different directory gets a ``RuntimeWarning`` and the original.
        Returns the effective dir (None = shared tier disabled)."""
        resolved = os.environ.get(_ENV_VAR) or shared_dir
        with self._lock:
            if self._shared_dir is not None:
                if resolved and os.path.abspath(resolved) != self._shared_dir:
                    import warnings

                    warnings.warn(
                        "shared artifact tier already wired to "
                        f"{self._shared_dir!r}; ignoring the requested "
                        f"{os.path.abspath(resolved)!r} (first caller wins)",
                        RuntimeWarning,
                        stacklevel=2,
                    )
                return self._shared_dir
            if not resolved:
                return None
            self._shared_dir = os.path.abspath(resolved)
            return self._shared_dir

    def shared_dir(self) -> str | None:
        with self._lock:
            d = self._shared_dir
        return d or (os.environ.get(_ENV_VAR) or None)

    def local_dir(self) -> str | None:
        """The local artifact tier rides next to the persistent XLA cache
        (``<compile_cache>/artifacts``): wiring one cache dir wires both
        halves of the "local" story."""
        d = _cache_dir()
        return os.path.join(d, "artifacts") if d else None

    def tiers(self) -> list[tuple[str, ArtifactBackend]]:
        """Ordered (name, backend) lookup chain, cheapest first."""
        out: list[tuple[str, ArtifactBackend]] = []
        local = self.local_dir()
        if local:
            out.append(("local", DirectoryBackend(local)))
        shared = self.shared_dir()
        if shared:
            out.append(("shared", DirectoryBackend(shared)))
        return out

    def enabled(self) -> bool:
        return bool(self.tiers())

    # -- publish -------------------------------------------------------------

    def publish(
        self,
        sig: CompileSignature,
        compiled: Any,
        *,
        cost: Mapping[str, Any] | None = None,
        parent: str | None = None,
    ) -> list[str]:
        """Serialize ``compiled`` and publish the envelope to every
        configured tier (deduped on the content address).  Returns the
        tier names actually written.  Never raises — an executable the
        backend cannot serialize (no unloaded form) publishes nowhere."""
        tiers = self.tiers()
        if not tiers:
            return []
        try:
            from jax.experimental import serialize_executable as se

            payload, in_tree, out_tree = se.serialize(compiled)
            avals = _aval_list(compiled.args_info)
            fp = env_fingerprint()
            data = pack_envelope(
                sig,
                fp,
                payload,
                in_tree,
                out_tree,
                avals=avals,
                cost=cost,
                parent=parent,
            )
            name = artifact_name(sig.key(), fp)
        except Exception:
            _log.warning(
                "artifact serialize failed for %s (trial unaffected)",
                sig.program,
                exc_info=True,
            )
            return []
        written: list[str] = []
        for tier, backend in tiers:
            try:
                if backend.exists(name):
                    continue  # fleet publish dedupe: first writer wins
                backend.put(name, data)
                obs.artifact_publishes.inc(tier=tier)
                written.append(tier)
            except Exception:
                _log.warning(
                    "artifact publish to %s tier failed", tier, exc_info=True
                )
        # same-process reuse: the publisher's own dispatch seam can adopt
        # the executable it just serialized
        la = LoadedArtifact(
            sig_key=sig.key(),
            program=sig.program,
            compiled=compiled,
            tier="published",
            avals=avals,
            aval_key=hashlib.sha256(
                json.dumps(avals, sort_keys=True).encode()
            ).hexdigest(),
            cost=dict(cost) if cost else None,
            parent=parent,
        )
        self._adopt(la)
        return written

    def replicate(self, la: LoadedArtifact) -> list[str]:
        """Re-publish a loaded artifact so it exists in *every* configured
        tier (publish mode: a local-tier hit still warms the fleet's
        shared tier).  Dedupe makes this a no-op where it already lives."""
        try:
            sig = sig_from_key(la.sig_key)
        except Exception:
            return []
        return self.publish(sig, la.compiled, cost=la.cost, parent=la.parent)

    # -- fetch ---------------------------------------------------------------

    def _adopt(self, la: LoadedArtifact) -> None:
        with self._lock:
            self._loaded[la.sig_key] = la
            if la.aval_key:
                self._by_program[(la.program, la.aval_key)] = la
            # new material invalidates negative family-fetch results
            self._misses.clear()

    def lookup_loaded(self, sig: CompileSignature) -> LoadedArtifact | None:
        with self._lock:
            return self._loaded.get(sig.key())

    def fetch(self, sig: CompileSignature) -> LoadedArtifact | None:
        """Walk the tiers for ``sig``'s artifact under the current env
        fingerprint.  On a hit: verify, deserialize, promote a shared hit
        into the local tier, register the signature warm, and index the
        executable for the dispatch seam.  On any integrity failure:
        quarantine + keep walking.  Returns None on a full miss (callers
        compile cold).  Never raises."""
        try:
            loaded = self.lookup_loaded(sig)
            if loaded is not None:
                return loaded
            tiers = self.tiers()
            if not tiers:
                return None
            key = sig.key()
            fp = env_fingerprint()
            name = artifact_name(key, fp)
            for tier, backend in tiers:
                data = backend.get(name)
                if data is None:
                    obs.artifact_misses.inc(tier=tier)
                    continue
                try:
                    la = self._load(tier, data, key, fp)
                except (ArtifactCorrupt, ArtifactMismatch) as e:
                    _log.warning(
                        "quarantining %s artifact %s: %s", tier, name, e
                    )
                    try:
                        backend.quarantine(name)
                    except Exception:
                        pass
                    obs.artifact_quarantines.inc(tier=tier)
                    obs.artifact_misses.inc(tier=tier)
                    continue
                obs.artifact_hits.inc(tier=tier)
                if tier != "local":
                    self._promote_local(name, data)
                self._adopt(la)
                # the registry is how first steps classify warm and how
                # `katib-tpu cache`/cost see the program without a run
                REGISTRY.record(sig, source=f"artifact:{tier}")
                if la.cost:
                    try:
                        REGISTRY.record_cost(sig, la.cost)
                    except Exception:
                        pass
                return la
            return None
        except Exception:
            _log.warning(
                "artifact fetch failed for %s (degrading to cold compile)",
                sig.program,
                exc_info=True,
            )
            return None

    def _load(
        self, tier: str, data: bytes, key: str, fp: Mapping[str, Any]
    ) -> LoadedArtifact:
        header, body = unpack_envelope(data)
        if header.get("key") != key:
            raise ArtifactMismatch("signature key != address")
        if header.get("fingerprint") != dict(fp):
            # the content address should make this unreachable; a file
            # renamed/copied across envs is exactly what it catches
            raise ArtifactMismatch("environment fingerprint mismatch")
        from jax.experimental import serialize_executable as se

        try:
            compiled = se.deserialize_and_load(
                body["payload"], body["in_tree"], body["out_tree"]
            )
        except Exception as e:
            raise ArtifactCorrupt(f"executable deserialize failed: {e}") from e
        avals = header.get("avals") or []
        return LoadedArtifact(
            sig_key=key,
            program=str(header.get("program", "?")),
            compiled=compiled,
            tier=tier,
            avals=avals,
            aval_key=hashlib.sha256(
                json.dumps(avals, sort_keys=True).encode()
            ).hexdigest(),
            cost=header.get("cost") if isinstance(header.get("cost"), dict) else None,
            parent=header.get("parent"),
        )

    def fetch_family(self, sig: CompileSignature) -> list[LoadedArtifact]:
        """Everything published under ``sig``: the exact-signature
        envelope (if any) plus every program envelope whose ``parent``
        links back to it — a prewarm twin publishes one envelope per step
        program it observes, and a fresh host wants all of them loaded
        before tracing.  One hit/miss per tier for the family as a whole;
        corrupt/misaddressed members quarantine like :meth:`fetch`.  Any
        hit marks ``sig`` warm in the registry.  Never raises."""
        try:
            key = sig.key()
            with self._lock:
                cached = self._families.get(key)
                missed = key in self._misses
            if cached is not None:
                return list(cached)
            if missed:
                return []
            tiers = self.tiers()
            if not tiers:
                return []
            fp = env_fingerprint()
            fp_key = fingerprint_key(fp)
            exact_name = artifact_name(key, fp)
            out: list[LoadedArtifact] = []
            loaded_names: set[str] = set()
            hit_tiers: list[str] = []
            for tier, backend in tiers:
                tier_hit = False
                for name in backend.list():
                    if name in loaded_names:
                        continue
                    data = backend.get(name)
                    if data is None:
                        continue
                    try:
                        header = read_header(data)
                    except ArtifactCorrupt as e:
                        # family scans read every header anyway, so a
                        # corrupt envelope quarantines on sight even when
                        # it belongs to some other signature
                        _log.warning(
                            "quarantining %s artifact %s: %s", tier, name, e
                        )
                        try:
                            backend.quarantine(name)
                        except Exception:
                            pass
                        obs.artifact_quarantines.inc(tier=tier)
                        continue
                    mine = name == exact_name or header.get("parent") == key
                    if not mine:
                        continue
                    if fingerprint_key(header.get("fingerprint") or {}) != fp_key:
                        continue  # another environment's build of this program
                    hkey = str(header.get("key", ""))
                    if artifact_name(hkey, header.get("fingerprint") or {}) != name:
                        _log.warning(
                            "quarantining misaddressed %s artifact %s",
                            tier,
                            name,
                        )
                        try:
                            backend.quarantine(name)
                        except Exception:
                            pass
                        obs.artifact_quarantines.inc(tier=tier)
                        continue
                    try:
                        la = self._load(tier, data, hkey, fp)
                    except (ArtifactCorrupt, ArtifactMismatch) as e:
                        _log.warning(
                            "quarantining %s artifact %s: %s", tier, name, e
                        )
                        try:
                            backend.quarantine(name)
                        except Exception:
                            pass
                        obs.artifact_quarantines.inc(tier=tier)
                        continue
                    tier_hit = True
                    loaded_names.add(name)
                    if tier != "local":
                        self._promote_local(name, data)
                    self._adopt(la)
                    if la.cost:
                        try:
                            REGISTRY.record_cost(sig_from_key(hkey), la.cost)
                        except Exception:
                            pass
                    out.append(la)
                if tier_hit:
                    obs.artifact_hits.inc(tier=tier)
                    hit_tiers.append(tier)
                else:
                    obs.artifact_misses.inc(tier=tier)
            if out:
                REGISTRY.record(sig, source=f"artifact:{hit_tiers[0]}")
                with self._lock:
                    self._families[key] = list(out)
            else:
                with self._lock:
                    self._misses.add(key)
            return out
        except Exception:
            _log.warning(
                "artifact family fetch failed for %s (degrading to cold "
                "compile)",
                sig.program,
                exc_info=True,
            )
            return []

    def _promote_local(self, name: str, data: bytes) -> None:
        """A shared-tier hit seeds the local tier so this host's next
        process fetches locally (and keeps working if the shared tier
        disappears)."""
        local = self.local_dir()
        if not local:
            return
        try:
            backend = DirectoryBackend(local)
            if not backend.exists(name):
                backend.put(name, data)
        except Exception:
            pass  # promotion is an optimization, never a failure

    # -- dispatch + cost seams -----------------------------------------------

    def program_for(self, program: str, args: tuple) -> LoadedArtifact | None:
        """The loaded executable matching ``program`` at exactly these
        input avals, or None — the dispatch seam's lookup."""
        try:
            key = (program, aval_digest(args))
        except Exception:
            return None
        with self._lock:
            return self._by_program.get(key)

    def cost_for(self, program: str, args: tuple) -> dict | None:
        """The cost record riding with a loaded artifact for ``program``
        at these avals — lets ``costmodel.observe_program`` skip the
        extra trace for fetched programs."""
        la = self.program_for(program, args)
        return dict(la.cost) if la is not None and la.cost else None

    # -- introspection / tests -----------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            loaded = len(self._loaded)
        tiers = {
            tier: {"dir": backend.describe(), "artifacts": len(backend.list())}
            for tier, backend in self.tiers()
        }
        return {"loaded": loaded, "tiers": tiers}

    def reset(self) -> None:
        """Forget loaded executables and the shared-dir wiring (tests);
        on-disk tiers are left alone."""
        with self._lock:
            self._loaded.clear()
            self._by_program.clear()
            self._families.clear()
            self._misses.clear()
            self._shared_dir = None


ARTIFACTS = ArtifactCache()


# -- the dispatch seam --------------------------------------------------------


class _ResolvedProgram:
    """Callable wrapper binding a jitted fn to a possibly-fetched
    executable.  The first call decides: if a loaded artifact matches the
    program name and the exact input avals, dispatch goes through the
    deserialized executable (arming the ambient cost slot from the
    artifact's record); otherwise — or after any artifact-call failure —
    every call goes through the ordinary jit fn.  Single-trial-thread
    object: no locking, mirrors how the step objects themselves are used.
    Attribute access (``.lower`` for costmodel) delegates to the fn."""

    def __init__(self, fn: Callable, program: str, per_report: int = 1):
        self._fn = fn
        self._program = program
        self._per_report = per_report
        self._target: Callable | None = None
        self.source = "jit"  # "artifact" once adopted (tests/telemetry)

    def _bind(self, args: tuple) -> Callable:
        la = ARTIFACTS.program_for(self._program, args)
        if la is None:
            return self._fn
        self.source = "artifact"
        if la.cost:
            try:
                from katib_tpu.costmodel.live import set_active_cost
                from katib_tpu.costmodel.record import CostRecord

                set_active_cost(
                    CostRecord.from_dict(la.cost), per_report=self._per_report
                )
            except Exception:
                pass
        return la

    def __call__(self, *args):
        if self._target is None:
            self._target = self._bind(args)
        try:
            return self._target(*args)
        except Exception:
            if self._target is self._fn:
                raise
            # a fetched executable that cannot dispatch degrades to the
            # jit path permanently (cold compile beats a dead trial); the
            # aval match makes this effectively unreachable, but a bad
            # artifact must never be worse than no artifact
            _log.warning(
                "fetched executable for %s failed to dispatch; falling "
                "back to jit",
                self._program,
                exc_info=True,
            )
            self._target = self._fn
            self.source = "jit-fallback"
            return self._fn(*args)

    def __getattr__(self, name: str):
        return getattr(self._fn, name)


def resolve(fn: Callable, *, program: str, per_report: int = 1) -> Callable:
    """Wrap a jitted step fn so its first dispatch prefers a fetched
    artifact executable (model-side opt-in, like
    ``costmodel.observe_program``).  Free when no artifact is loaded:
    one dict probe on the first call, then direct dispatch."""
    return _ResolvedProgram(fn, program, per_report=per_report)


# -- publish-side ambient offer (prewarm twins) -------------------------------

# the worker needs the jitted fn + representative args a twin just
# compiled in order to AOT-serialize it; twins already hand exactly that
# pair to costmodel.observe_program, which mirrors it here (thread-local,
# same pattern as the ambient cost slot)
import threading  # noqa: E402  (module-scope slot)

_tls = threading.local()


def note_observed(
    fn: Any,
    args: tuple,
    *,
    program: str = "?",
    cost: Mapping[str, Any] | None = None,
) -> None:
    """Record a (jitted fn, args, cost) this thread observed — a publish
    candidate, keyed by program label (latest observation of a label
    wins).  Called by ``costmodel.live.observe_program``; best-effort."""
    offered = getattr(_tls, "offered", None)
    if offered is None:
        offered = _tls.offered = {}
    offered[program] = (fn, args, program, dict(cost) if cost else None)


def take_observed() -> list[tuple[Any, tuple, str, dict | None]]:
    """Drain this thread's publish candidates (prewarm worker, post-twin)."""
    offered = getattr(_tls, "offered", None)
    _tls.offered = None
    return list(offered.values()) if offered else []


def clear_observed() -> None:
    _tls.offered = None


def serialize_compiled(fn: Any, args: tuple) -> Any:
    """AOT-compile ``fn`` at ``args``' avals (sharding-preserving) into a
    serializable ``jax.stages.Compiled``.  With the persistent XLA cache
    wired — the prewarm contract — the twin's just-finished compile makes
    this a deserialization, not a second XLA run.  Raises on programs
    jax cannot AOT here; callers treat that as "don't publish"."""
    import jax

    def aval(a):
        kw = {}
        sharding = getattr(a, "sharding", None)
        if sharding is not None:
            kw["sharding"] = sharding
        return jax.ShapeDtypeStruct(a.shape, a.dtype, **kw)

    avals = jax.tree_util.tree_map(aval, tuple(args))
    return fn.lower(*avals).compile()


def publish_observed(sig: CompileSignature) -> int:
    """Drain this thread's observed programs and publish each as an
    artifact linked to ``sig`` — the prewarm worker's post-twin step,
    shared with benches/CLI paths that ran a twin inline.  Returns how
    many programs actually published (dedupe and failures both skip)."""
    offers = take_observed()
    if not offers or not ARTIFACTS.enabled():
        return 0
    n = 0
    for ofn, oargs, oprog, ocost in offers:
        try:
            compiled = serialize_compiled(ofn, oargs)
            derived = CompileSignature(
                program=oprog,
                shapes=sig.shapes,
                k=sig.k,
                mesh=sig.mesh,
                donation=sig.donation,
            )
            if ARTIFACTS.publish(
                derived, compiled, cost=ocost, parent=sig.key()
            ):
                n += 1
        except Exception:
            _log.warning(
                "artifact publish failed for %s (the compile itself "
                "succeeded)",
                oprog,
                exc_info=True,
            )
    return n


# -- artifact-dir maintenance (fsck / cache verbs) ----------------------------


@dataclass
class ArtifactFsckReport:
    """What ``katib-tpu fsck`` found (and fixed) in an artifact dir."""

    root: str = ""
    scanned: int = 0
    valid: int = 0
    stale: list[str] = field(default_factory=list)  # other-env, intact
    corrupt: list[str] = field(default_factory=list)
    quarantined: list[str] = field(default_factory=list)
    misaddressed: list[str] = field(default_factory=list)

    @property
    def consistent(self) -> bool:
        """True when every remaining envelope is intact and correctly
        addressed (stale-but-intact artifacts are fine: they serve other
        environments sharing the tier)."""
        bad = set(self.corrupt) | set(self.misaddressed)
        return not (bad - set(self.quarantined))

    def summary(self) -> str:
        return (
            f"{self.scanned} artifact(s): {self.valid} valid, "
            f"{len(self.stale)} stale(other-env), "
            f"{len(self.corrupt)} corrupt, "
            f"{len(self.misaddressed)} misaddressed, "
            f"{len(self.quarantined)} quarantined"
        )


def is_artifact_dir(path: str) -> bool:
    """True when ``path`` holds artifact envelopes (``fsck``'s dispatch:
    an experiment workdir and an artifact tier share one verb)."""
    try:
        names = os.listdir(path)
    except OSError:
        return False
    if any(n.endswith(SUFFIX) for n in names):
        return True
    return os.path.basename(os.path.normpath(path)) == "artifacts" or any(
        n.endswith(SUFFIX + QUARANTINE_SUFFIX) for n in names
    )


def fsck_artifacts(path: str, repair: bool = True) -> ArtifactFsckReport:
    """Verify every envelope under an artifact dir: structural integrity,
    checksum, and address correctness (file name == content address of
    its own header).  ``repair`` quarantines corrupt/misaddressed files;
    stale-fingerprint artifacts are reported but left — they are valid
    for the environment that published them."""
    backend = DirectoryBackend(path)
    report = ArtifactFsckReport(root=backend.root)
    fp_now = fingerprint_key(env_fingerprint())
    for name in backend.list():
        report.scanned += 1
        data = backend.get(name)
        if data is None:
            continue  # raced a concurrent quarantine/delete
        try:
            header = read_header(data)
        except ArtifactCorrupt:
            report.corrupt.append(name)
            if repair and backend.quarantine(name):
                report.quarantined.append(name)
                obs.artifact_quarantines.inc(tier="fsck")
            continue
        expect = artifact_name(
            str(header.get("key", "")), header.get("fingerprint") or {}
        )
        if expect != name:
            report.misaddressed.append(name)
            if repair and backend.quarantine(name):
                report.quarantined.append(name)
                obs.artifact_quarantines.inc(tier="fsck")
            continue
        if fingerprint_key(header.get("fingerprint") or {}) != fp_now:
            report.stale.append(name)
        else:
            report.valid += 1
    return report


def scan_dir(path: str) -> list[dict]:
    """Header inventory of an artifact dir (the ``cache`` verb's table):
    one row per envelope with identity, env match, size, and cost."""
    backend = DirectoryBackend(path)
    fp_now = fingerprint_key(env_fingerprint())
    rows: list[dict] = []
    for name in backend.list():
        data = backend.get(name)
        if data is None:
            continue
        row: dict = {"name": name, "bytes": len(data)}
        try:
            header = read_header(data)
        except ArtifactCorrupt as e:
            row.update(status="corrupt", error=str(e))
            rows.append(row)
            continue
        fp = header.get("fingerprint") or {}
        row.update(
            status="ok" if fingerprint_key(fp) == fp_now else "stale",
            program=header.get("program", "?"),
            k=header.get("k", 1),
            mesh=header.get("mesh", ""),
            platform=fp.get("platform", "?"),
            device_kind=fp.get("device_kind", "?"),
            jax=fp.get("jax", "?"),
            cost=bool(header.get("cost")),
            created=header.get("created", 0),
        )
        rows.append(row)
    return rows
