"""Cohort shape bucketing: quantize cohort width K onto few padded sizes.

Every distinct stacked leading dimension K is a distinct XLA program — a
sweep whose cohorts arrive as K=7, K=5, K=3 (tail groups, early-stopped
members, elastic degradation) pays a full compile per width even though
the members are byte-identical programs otherwise.  Rounding K up to the
next power of two collapses those widths onto one executable: the extra
rows are inert ghost members (they train on member 0's hyperparameters
and their metric rows never reach the store — ``runner/cohort.py``), so
the padding costs FLOPs that were already idle, not correctness.

The trial mesh axis interacts: a sharded cohort must carry a member count
divisible by the trial-axis size D, so a bucket is the power of two
rounded up to a multiple of D.  With D itself a power of two (device
counts are), the bucket set is simply {D, 2D, 4D, ...} ∪ {1, 2, ..., D}.
"""

from __future__ import annotations


def next_pow2(n: int) -> int:
    """Smallest power of two >= ``n`` (1 for n <= 1)."""
    if n <= 1:
        return 1
    return 1 << (n - 1).bit_length()


def bucket_size(k: int, multiple: int = 1) -> int:
    """The padded bucket for a K-member cohort: next power of two, then
    rounded up to a multiple of ``multiple`` (the trial-axis size)."""
    if k < 1:
        raise ValueError(f"cohort width must be >= 1, got {k}")
    m = max(int(multiple), 1)
    b = next_pow2(k)
    return -(-b // m) * m


def bucketed_cohort_size(k: int, mesh=None) -> int:
    """Mesh-aware :func:`bucket_size` — the bucketed twin of
    ``parallel.mesh.padded_cohort_size`` (which pads to the trial-axis
    multiple only)."""
    from katib_tpu.parallel.mesh import trial_axis_size

    return bucket_size(k, trial_axis_size(mesh))


def bucket_table(max_k: int, multiple: int = 1) -> list[tuple[int, int]]:
    """The K -> bucket mapping for widths 1..max_k (docs/tests/CLI view)."""
    return [(k, bucket_size(k, multiple)) for k in range(1, max_k + 1)]


def prewarm_widths(
    max_width: int, buckets: bool = True, multiple: int = 1
) -> list[int]:
    """Every padded width the orchestrator's grouping can produce for a
    sweep with ``cohortWidth = max_width``: the singleton program plus
    the (bucketed) cohort sizes 2..max_width.  This is the width set the
    ``prewarm`` CLI verb compiles/publishes and the new-host smoke
    fetches — one shared definition so they cannot drift."""
    widths = {1}
    for size in range(2, max(1, int(max_width)) + 1):
        widths.add(bucket_size(size, multiple) if buckets else size)
    return sorted(widths)
