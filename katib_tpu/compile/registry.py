"""Compile-signature registry: what has been compiled, and was it warm?

A *compile signature* is the coarse identity of a jitted trial program:
which train function, which structural hyperparameters (the ones baked
into the trace — model widths, batch sizes, optimizer family), the padded
cohort width K, the mesh layout, and whether the carried state is donated.
Two executions with the same signature trace the same program, so the
second one should hit the in-process jit cache or the persistent XLA
compilation cache (``init_compile_cache``) instead of recompiling.

The registry records every signature compiled (by trials, by the prewarm
worker, by the CLI ``prewarm`` verb) and classifies each trial's first
step warm/cold against it, exporting
``katib_compile_cache_hits_total`` / ``katib_compile_cache_misses_total``
and the warm-vs-cold ``katib_first_step_compile_seconds`` histogram so a
cache regression shows up as the miss counter climbing.

When the persistent compilation cache is wired, signatures also persist
to ``<cache_dir>/shape_registry.jsonl`` — a prewarm subprocess (or an
earlier run of the same sweep) warms classification for later processes
sharing the cache directory.  Everything here is best-effort telemetry:
an unreadable registry file, an unhashable value, or a full disk never
fails a trial.

Classification heuristics (documented, deliberate):

- float-valued parameters are excluded from the signature — the model
  fns in this repo carry lr/momentum as runtime operands
  (``optax.inject_hyperparams``), so floats don't change the program;
- cohort signatures use only the parameters every member agrees on
  (per-member varying values are runtime rows by construction);
- over-keying (a shared float that *doesn't* change the program) errs
  toward classifying cold — conservative, never falsely warm.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Any, Callable, Iterable, Mapping, Sequence

from katib_tpu.analysis import guarded_by, make_lock
from katib_tpu.utils import observability as obs

_REGISTRY_FILENAME = "shape_registry.jsonl"


def _program_name(fn: Callable | None) -> str:
    if fn is None:
        return "<none>"
    return getattr(fn, "__qualname__", getattr(fn, "__name__", repr(fn)))


def mesh_signature(mesh: Any) -> str:
    """Stable cross-process mesh identity: axis layout + platform (device
    ids are process-local and recycle; the compiled program depends on the
    shape of the mesh, not which physical chips back it)."""
    if mesh is None:
        return ""
    try:
        axes = ",".join(f"{n}={s}" for n, s in mesh.shape.items())
        platform = next(iter(mesh.devices.flat)).platform
        return f"{axes}:{platform}"
    except Exception:
        return repr(mesh)


def _structural(value: Any) -> bool:
    """True for values baked into the trace (ints, strs, bools); floats ride
    as runtime operands through inject_hyperparams and are excluded."""
    return isinstance(value, (int, str, bool)) and not isinstance(value, float)


@dataclass(frozen=True)
class CompileSignature:
    """Coarse identity of one compiled trial program."""

    program: str
    shapes: tuple[tuple[str, str], ...] = ()
    k: int = 1
    mesh: str = ""
    donation: bool = True

    def key(self) -> str:
        return json.dumps(
            {
                "program": self.program,
                "shapes": list(self.shapes),
                "k": self.k,
                "mesh": self.mesh,
                "donation": self.donation,
            },
            sort_keys=True,
        )


def shared_structural(param_dicts: Sequence[Mapping[str, Any]]) -> dict[str, Any]:
    """Structural parameters every member agrees on — the signature's shape
    component.  Per-member varying values (lr, momentum, seeds) drop out
    here exactly because they vary: they are runtime rows, not trace
    constants."""
    if not param_dicts:
        return {}
    out: dict[str, Any] = {}
    first = param_dicts[0]
    for name, value in first.items():
        if not _structural(value):
            continue
        if all(p.get(name) == value for p in param_dicts[1:]):
            out[name] = value
    return out


def _shapes_of(shared: Mapping[str, Any]) -> tuple[tuple[str, str], ...]:
    return tuple(sorted((str(k), str(v)) for k, v in shared.items()))


def cohort_signature(
    cohort_fn: Callable | None,
    trials: Sequence[Any],
    k: int,
    mesh: Any = None,
) -> CompileSignature:
    """Signature of a cohort execution: the cohort twin's program, the
    member-agreed structural parameters, and the padded/bucketed width
    ``k`` the stacked state will actually carry."""
    params = [t.params() for t in trials]
    return CompileSignature(
        program=_program_name(cohort_fn),
        shapes=_shapes_of(shared_structural(params)),
        k=int(k),
        mesh=mesh_signature(mesh),
    )


def trial_signature(train_fn: Callable | None, trial: Any, mesh: Any = None) -> CompileSignature:
    """Signature of a singleton white-box trial (k=1)."""
    params = trial.params()
    shared = {n: v for n, v in params.items() if _structural(v)}
    return CompileSignature(
        program=_program_name(train_fn),
        shapes=_shapes_of(shared),
        k=1,
        mesh=mesh_signature(mesh),
    )


def _cache_dir() -> str | None:
    """The wired persistent-compile-cache dir, or None — read from the live
    jax config (set by ``init_compile_cache``) so a prewarm subprocess with
    the same env shares the registry file without an import cycle."""
    try:
        import jax

        d = getattr(jax.config, "jax_compilation_cache_dir", None)
        return str(d) if d else None
    except Exception:
        return None


class ShapeRegistry:
    """Thread-safe compiled-signature set with optional JSONL persistence.

    Reached from the caller thread (trial runner first steps), the async
    harvest thread (settlement-time classification), and the prewarm
    worker — every access to the signature map, the loaded-dir marker,
    and the torn-tail truncation offset goes through ``_lock``, including
    the JSONL append (``_append`` orders truncate-then-append against
    concurrent recorders).
    """

    _GUARDS = guarded_by(_lock=("_seen", "_loaded_dir", "_truncate_to"))

    def __init__(self) -> None:
        self._lock = make_lock("compile.registry")
        self._seen: dict[str, dict] = {}
        self._loaded_dir: str | None = None
        # byte length of the valid prefix when the registry file ends in a
        # torn/corrupt line (crash mid-append); the next _append truncates
        # to here first so the file heals instead of growing garbage
        self._truncate_to: int | None = None

    # -- persistence (best-effort) ----------------------------------------

    def _path(self) -> str | None:
        d = _cache_dir()
        return os.path.join(d, _REGISTRY_FILENAME) if d else None

    def _maybe_load(self) -> None:  # lint: holds(_lock)
        """Lazily fold the cache dir's registry file into memory, once per
        directory (a later init_compile_cache of a different dir reloads)."""
        d = _cache_dir()
        if d is None or d == self._loaded_dir:
            return
        self._loaded_dir = d
        self._truncate_to = None
        path = os.path.join(d, _REGISTRY_FILENAME)
        try:
            with open(path, "rb") as f:
                offset = 0
                valid_end = 0
                torn = 0
                dupes = 0
                for raw in f:
                    offset += len(raw)
                    line = raw.decode("utf-8", errors="replace").strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except json.JSONDecodeError:
                        # same torn-tail rule as the experiment journal:
                        # tolerate the bad line, remember where the valid
                        # prefix ends so the next append truncates it away
                        torn += 1
                        continue
                    torn = 0
                    valid_end = offset
                    key = rec.get("key") if isinstance(rec, dict) else None
                    if key:
                        cur = self._seen.setdefault(key, rec)
                        if cur is not rec:
                            dupes += 1
                            if isinstance(rec.get("cost"), dict):
                                # first record wins for identity fields,
                                # but a later cost-bearing line
                                # (record_cost re-appends the row) carries
                                # the freshest XLA analysis
                                cur["cost"] = rec["cost"]
                if torn:
                    import warnings

                    warnings.warn(
                        f"shape registry {path} ends in {torn} torn/corrupt "
                        f"line(s) ({offset - valid_end} bytes) — skipped; "
                        "will truncate on next append",
                        RuntimeWarning,
                        stacklevel=2,
                    )
                    self._truncate_to = valid_end
                if dupes:
                    # record_cost re-appends its row on every cost change,
                    # so a long-lived cache dir accretes duplicate lines
                    # without bound: compact to one merged row per key.
                    # The durable rewrite (tmp + fsync + rename, same
                    # recipe as the journal) also heals any torn tail.
                    self._compact(path)
        except OSError:
            pass

    def _compact(self, path: str) -> None:  # lint: holds(_lock)
        """Durably rewrite the registry file as one merged row per
        signature (the in-memory view).  A concurrent reader sees the old
        file or the compacted one, never a partial rewrite."""
        try:
            from katib_tpu.utils.fsio import atomic_replace

            body = "".join(
                json.dumps(rec) + "\n" for rec in self._seen.values()
            )
            atomic_replace(path, body.encode("utf-8"), prefix=".compact-")
            self._truncate_to = None
        except OSError:
            pass  # compaction is housekeeping, never a failure

    def _append(self, rec: dict) -> None:  # lint: holds(_lock)
        path = self._path()
        if path is None:
            return
        try:
            if self._truncate_to is not None:
                # heal the torn tail _maybe_load found before appending
                # after it (appending after garbage would orphan every
                # later record for pre-fix readers)
                with open(path, "rb+") as f:
                    f.truncate(self._truncate_to)
                self._truncate_to = None
            with open(path, "a") as f:
                f.write(json.dumps(rec) + "\n")
        except OSError:
            pass  # registry persistence is telemetry, never a failure

    # -- the registry proper ----------------------------------------------

    def seen(self, sig: CompileSignature) -> bool:
        with self._lock:
            self._maybe_load()
            return sig.key() in self._seen

    def record(
        self,
        sig: CompileSignature,
        source: str = "trial",
        compile_seconds: float | None = None,
    ) -> bool:
        """Record a compiled signature; returns True when it was new."""
        key = sig.key()
        rec = {
            "key": key,
            "program": sig.program,
            "k": sig.k,
            "mesh": sig.mesh,
            "shapes": dict(sig.shapes),
            "donation": sig.donation,
            "source": source,
        }
        if compile_seconds is not None:
            rec["compile_seconds"] = round(float(compile_seconds), 4)
        with self._lock:
            self._maybe_load()
            fresh = key not in self._seen
            if fresh:
                self._seen[key] = rec
                # LCK001 fix: _append reads/clears _truncate_to and must
                # order truncate-then-append against concurrent recorders
                # (harvest thread vs. caller thread both classify here) —
                # it used to run after the lock was dropped
                self._append(rec)
        return fresh

    def record_cost(self, sig: CompileSignature, cost: Mapping[str, Any]) -> bool:
        """Merge an XLA cost record (``costmodel.CostRecord.as_dict()``)
        into the signature's row and re-append it so registry-sharing
        processes (and ``katib-tpu cost``) see the analysis.  Idempotent:
        an unchanged cost neither rewrites memory nor grows the file.
        Returns True when the row changed."""
        key = sig.key()
        cost = dict(cost)
        with self._lock:
            self._maybe_load()
            rec = self._seen.get(key)
            if rec is None:
                # cost can arrive before record() (e.g. a model observing
                # its program mid-first-epoch) — synthesize the row
                rec = {
                    "key": key,
                    "program": sig.program,
                    "k": sig.k,
                    "mesh": sig.mesh,
                    "shapes": dict(sig.shapes),
                    "donation": sig.donation,
                    "source": "cost",
                }
                self._seen[key] = rec
            if rec.get("cost") == cost:
                return False
            rec["cost"] = cost
            self._append(rec)
        return True

    def cost_of(self, sig: CompileSignature) -> dict | None:
        """The persisted cost record for a signature, or None."""
        with self._lock:
            self._maybe_load()
            rec = self._seen.get(sig.key())
        cost = rec.get("cost") if isinstance(rec, dict) else None
        return dict(cost) if isinstance(cost, dict) else None

    def classify(self, sig: CompileSignature) -> str:
        """``"warm"`` when the signature was compiled before (this process
        or a registry-sharing one), else ``"cold"`` — no counter side
        effects (see :meth:`note_first_step`)."""
        return "warm" if self.seen(sig) else "cold"

    def note_first_step(
        self, sig: CompileSignature, seconds: float, source: str = "trial"
    ) -> str:
        """Classify a first step warm/cold, bump the hit/miss counters,
        feed the warm-vs-cold histogram, and record the signature so the
        next same-shape execution classifies warm.  Returns the label."""
        label = self.classify(sig)
        if label == "warm":
            obs.compile_cache_hits.inc(program=sig.program)
        else:
            obs.compile_cache_misses.inc(program=sig.program)
        try:
            obs.first_step_compile_seconds.observe(float(seconds), cache=label)
        except (TypeError, ValueError):
            pass
        self.record(sig, source=source, compile_seconds=seconds)
        return label

    def signatures(self) -> list[dict]:
        with self._lock:
            self._maybe_load()
            return list(self._seen.values())

    def reset(self) -> None:
        """Forget everything (tests); the on-disk file is left alone."""
        with self._lock:
            self._seen.clear()
            self._loaded_dir = None


REGISTRY = ShapeRegistry()
