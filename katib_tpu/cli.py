"""``katib-tpu`` command-line interface (``python -m katib_tpu``).

The CLI replaces the reference's UI backend + kubectl surface
(``pkg/ui/v1beta1/backend.go:86-617``: list experiments, trial detail,
metric logs) with local commands over the orchestrator's status journal and
observation store:

- ``run <experiment.yaml>``   create + run an experiment to completion (--resume)
- ``prewarm <experiment.yaml>``  compile the experiment's programs into the persistent cache
- ``list``                    experiments in the workdir with live counts
- ``describe <experiment>``   trials, assignments, observations, optimal, curve
- ``metrics <trial>``         raw metric log for one trial
- ``logs <trial>``            captured black-box stdout
- ``export <experiment>``     trials as CSV/JSONL for analysis
- ``ui``                      serve the REST API + HTML dashboard (TLS optional)
- ``suggest-server``          suggestion-as-a-service daemon
- ``db-manager``              native observation-log daemon (``--db`` = durable journal)
- ``conformance``             packaged e2e invariants check (conformance/run.sh parity)
- ``chaos``                   deterministic fault-injection run (fault-tolerance invariants;
                              ``--crash-at``/``--kill-at`` hard-kill a child at a
                              registered persistence site and assert crash recovery)
- ``fsck <workdir>/<exp>``    validate + repair an experiment dir (torn journal tail,
                              snapshot checksums, suggester fence)
- ``doctor``                  environment report (devices, native runtime)
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

from katib_tpu.core.config import KatibConfig


def _fmt_age(start: float, end: float) -> str:
    if not start:
        return "-"
    secs = int((end or time.time()) - start)
    if secs < 60:
        return f"{secs}s"
    if secs < 3600:
        return f"{secs // 60}m{secs % 60:02d}s"
    return f"{secs // 3600}h{(secs % 3600) // 60:02d}m"


def _table(rows: list[list[str]], header: list[str]) -> str:
    widths = [
        max(len(str(r[i])) for r in [header] + rows) for i in range(len(header))
    ]
    lines = ["  ".join(str(h).ljust(w) for h, w in zip(header, widths))]
    for r in rows:
        lines.append("  ".join(str(c).ljust(w) for c, w in zip(r, widths)))
    return "\n".join(lines)


def _install_drain_handlers(orch) -> None:
    """SIGTERM/SIGINT → graceful drain; a second signal escalates to a hard
    stop (running trials are killed at the next boundary instead of being
    given the drain grace window).  Mirrors kubelet pod termination: TERM
    first, impatience escalates."""
    import signal

    seen = {"count": 0}

    def _on_signal(signum, frame):  # noqa: ARG001 - signal handler shape
        seen["count"] += 1
        if seen["count"] == 1:
            print(
                f"received {signal.Signals(signum).name}: draining "
                "(checkpoint running trials, flush journal; signal again to "
                "stop immediately)",
                file=sys.stderr,
            )
            orch.drain()
        else:
            print(
                f"received {signal.Signals(signum).name} again: stopping now",
                file=sys.stderr,
            )
            orch.stop()

    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            signal.signal(sig, _on_signal)
        except (ValueError, OSError):
            # not the main thread (embedded use) — drain stays API-only
            return


def cmd_run(args: argparse.Namespace) -> int:
    from katib_tpu.sdk.yaml_spec import load_experiment_yaml

    cfg = KatibConfig.load(args.config)
    if args.workdir:
        cfg.init.workdir = args.workdir
    spec = load_experiment_yaml(args.experiment)
    if spec.command is None and spec.train_fn is None:
        print(
            "error: experiment file defines no trial command "
            "(spec.command or spec.trialTemplate.command)",
            file=sys.stderr,
        )
        return 2
    # persistent XLA compile cache, process-global: initialize before any
    # jit so the first trial's trace can hit a prior run's executables
    # (KATIB_COMPILE_CACHE env wins over the spec's compileCache field)
    from katib_tpu.runner.trial_runner import init_compile_cache

    init_compile_cache(spec.compile_cache)
    orch = cfg.make_orchestrator()
    # CLI runs own the process, so a drain that leaves wedged trial threads
    # behind may hard-exit with the resumable code after journaling
    # (library callers keep the default cooperative wind-down instead)
    orch.drain_hard_exit = True
    # device preflight gate: on by default for CLI runs — a wedged pool
    # fails fast with a per-device health report instead of hanging in the
    # first compile.  `--no-preflight` (or leaving KATIB_PREFLIGHT unset in
    # library embedding) skips the probe.
    orch.preflight = not args.no_preflight
    if args.drain_grace_seconds is not None:
        spec.drain_grace_seconds = args.drain_grace_seconds
    _install_drain_handlers(orch)
    if args.resume:
        existing = orch.load_experiment(spec)
        if existing is None:
            print(
                f"note: no journal for {spec.name!r} under {orch.workdir}; "
                "starting fresh",
                file=sys.stderr,
            )
        try:
            exp = orch.run(spec, experiment=existing)
        except RuntimeError as e:
            # e.g. terminal experiment with resumePolicy: Never
            print(f"error: {e}", file=sys.stderr)
            return 2
    else:
        exp = orch.run(spec)
    if orch.drained:
        # resumable preemption exit: SIGTERM arrived, running trials were
        # checkpointed (or journaled Drained), the journal + suggester state
        # were flushed — rerun with --resume to continue where this left off
        print(
            f"experiment {exp.name}: drained ({exp.message}); "
            f"rerun with --resume to continue",
            file=sys.stderr,
        )
        from katib_tpu.orchestrator.orchestrator import DRAIN_EXIT_CODE

        return DRAIN_EXIT_CODE
    status = "ok" if exp.condition.value != "Failed" else "FAILED"
    print(f"experiment {exp.name}: {exp.condition.value} ({exp.message}) [{status}]")
    if exp.optimal is not None:
        print(
            f"optimal trial {exp.optimal.trial_name}: "
            f"{exp.spec.objective.objective_metric_name}={exp.optimal.objective_value}"
        )
        for name, value in sorted(
            {a.name: a.value for a in exp.optimal.assignments}.items()
        ):
            print(f"  {name} = {value}")
    return 0 if exp.condition.value != "Failed" else 1


def _pinned_structural(spec) -> dict:
    """Parameters pinned to a single structural value — the shapes that
    join a prewarm/cost signature; everything else rides the workload's
    own defaults (exactly what an unpinned sweep's signature carries at
    run time; unstepped doubles are runtime operands, not shapes)."""
    from katib_tpu.compile.registry import _structural

    shared = {}
    for p in spec.parameters:
        try:
            vals = p.grid_values()
        except Exception:
            continue
        if len(vals) == 1 and _structural(vals[0]):
            shared[p.name] = vals[0]
    return shared


def cmd_prewarm(args: argparse.Namespace) -> int:
    """Compile an experiment's programs into the persistent cache ahead of a
    run: the fleet analog of the orchestrator's in-run prewarm worker.  Runs
    meshless (single-host default placement) — sharded-mesh executables warm
    in-run instead."""
    from katib_tpu.compile.artifacts import ARTIFACTS
    from katib_tpu.compile.buckets import prewarm_widths
    from katib_tpu.compile.prewarm import (
        PrewarmRequest,
        PrewarmWorker,
        prewarm_fn_of,
    )
    from katib_tpu.compile.registry import REGISTRY
    from katib_tpu.runner.cohort import cohort_fn_of
    from katib_tpu.runner.trial_runner import init_compile_cache
    from katib_tpu.sdk.yaml_spec import load_experiment_yaml

    spec = load_experiment_yaml(args.experiment)
    if spec.train_fn is None or prewarm_fn_of(spec.train_fn) is None:
        print(
            "error: the experiment's train_fn declares no prewarm twin "
            "(see katib_tpu.compile.prewarm.attach_prewarm_fn)",
            file=sys.stderr,
        )
        return 2
    cache = init_compile_cache(spec.compile_cache)
    if not cache:
        print(
            "note: no persistent compile cache wired (compileCache / "
            "KATIB_COMPILE_CACHE) — prewarming helps only this process",
            file=sys.stderr,
        )
    artifact_dir = ARTIFACTS.configure(
        getattr(args, "artifact_dir", None) or spec.artifact_dir
    )
    if args.fetch_only and not artifact_dir:
        print(
            "error: --fetch-only needs a shared artifact tier "
            "(--artifact-dir / artifactDir / KATIB_ARTIFACT_DIR)",
            file=sys.stderr,
        )
        return 2
    shared = _pinned_structural(spec)
    cohort_fn = cohort_fn_of(spec.train_fn)
    if args.widths:
        widths = sorted({max(1, int(w)) for w in args.widths.split(",")})
    elif spec.cohort_width > 1 and cohort_fn is not None:
        # every padded width the orchestrator's grouping can produce: the
        # singleton program plus (bucketed) cohort sizes up to cohortWidth
        widths = prewarm_widths(spec.cohort_width, buckets=spec.cohort_buckets)
    else:
        widths = [1]
    # --publish forces submission past the registry dedupe so a re-run can
    # backfill artifacts for signatures that are already warm locally (the
    # content address dedupes the actual writes)
    worker = PrewarmWorker(
        publish=args.publish,
        fetch_only=args.fetch_only,
        force=args.publish,
    )
    queued = 0
    for k in widths:
        req = PrewarmRequest(
            train_fn=spec.train_fn,
            shared=shared,
            k=k,
            program_fn=cohort_fn if k > 1 else None,
        )
        if worker.submit(req):
            queued += 1
        else:
            print(f"k={k}: already registered (warm), skipped")
    done = worker.drain(timeout=args.timeout)
    worker.stop()
    if not done:
        print(
            f"warning: timed out after {args.timeout}s with compiles still "
            "queued (rerun to continue — finished work is cached)",
            file=sys.stderr,
        )
    rows = [
        [s["program"], s["k"], s.get("source", "?"), s.get("compile_seconds", "-")]
        for s in sorted(REGISTRY.signatures(), key=lambda s: (s["program"], s["k"]))
    ]
    print(
        f"prewarm: {queued} queued, {worker.compiled} compiled, "
        f"{worker.fetched} fetched, {worker.published} published, "
        f"{worker.failed} failed (cache: {cache or '<in-process only>'}"
        f"{', artifacts: ' + artifact_dir if artifact_dir else ''})"
    )
    if rows:
        print(_table(rows, ["program", "k", "source", "compile_s"]))
    return 0 if worker.failed == 0 and done else 1


def _read_registry_dir(d: str) -> list[dict]:
    """Fold ``shape_registry.jsonl`` rows under ``d`` (a compile-cache dir,
    or a workdir with cache dirs one level down) — same first-record-wins /
    latest-cost-wins merge the live registry applies."""
    import glob as _glob
    import json as _json

    from katib_tpu.compile.registry import _REGISTRY_FILENAME

    paths = [os.path.join(d, _REGISTRY_FILENAME)]
    paths += sorted(_glob.glob(os.path.join(d, "*", _REGISTRY_FILENAME)))
    by_key: dict[str, dict] = {}
    for path in paths:
        try:
            with open(path, errors="replace") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = _json.loads(line)
                    except ValueError:
                        continue
                    if not isinstance(rec, dict) or not rec.get("key"):
                        continue
                    cur = by_key.setdefault(rec["key"], rec)
                    if cur is not rec and isinstance(rec.get("cost"), dict):
                        cur["cost"] = rec["cost"]
        except OSError:
            continue
    return list(by_key.values())


def cmd_cost(args: argparse.Namespace) -> int:
    """Deviceless roofline table: each compiled program's XLA cost record
    (shape registry) joined against the device-kind peaks table — flops
    and bytes per step, arithmetic intensity, which roofline (compute or
    HBM bandwidth) binds, the floor step time, and the MFU ceiling.  No
    TPU needed: given a YAML with nothing costed yet, the experiment's
    prewarm twins run in-process and observe the cost as a side effect."""
    from katib_tpu import costmodel

    target = args.target
    if os.path.isdir(target):
        recs = _read_registry_dir(target)
    else:
        from katib_tpu.compile.buckets import prewarm_widths
        from katib_tpu.compile.prewarm import (
            PrewarmRequest,
            PrewarmWorker,
            prewarm_fn_of,
        )
        from katib_tpu.compile.registry import REGISTRY
        from katib_tpu.runner.cohort import cohort_fn_of
        from katib_tpu.runner.trial_runner import init_compile_cache
        from katib_tpu.sdk.yaml_spec import load_experiment_yaml

        spec = load_experiment_yaml(target)
        init_compile_cache(spec.compile_cache)
        recs = REGISTRY.signatures()
        needs_warm = not any(isinstance(r.get("cost"), dict) for r in recs)
        if needs_warm and spec.train_fn is not None and prewarm_fn_of(spec.train_fn):
            cohort_fn = cohort_fn_of(spec.train_fn)
            if spec.cohort_width > 1 and cohort_fn is not None:
                widths = prewarm_widths(
                    spec.cohort_width, buckets=spec.cohort_buckets
                )
            else:
                widths = [1]
            worker = PrewarmWorker()
            for k in sorted(widths):
                worker.submit(
                    PrewarmRequest(
                        train_fn=spec.train_fn,
                        shared=_pinned_structural(spec),
                        k=k,
                        program_fn=cohort_fn if k > 1 else None,
                    )
                )
            worker.drain(timeout=args.timeout)
            worker.stop()
            recs = REGISTRY.signatures()
    costed = [r for r in recs if isinstance(r.get("cost"), dict)]
    if not costed:
        print(
            "no cost records on file — run the experiment (or `katib-tpu "
            "prewarm`) with a persistent compile cache first, or point at "
            "an experiment YAML whose train_fn has a prewarm twin",
            file=sys.stderr,
        )
        return 1
    pk = costmodel.peaks_for(args.device)
    print(
        f"roofline vs {pk.device_kind}: "
        f"{pk.peak_flops('bf16') / 1e12:.1f} TFLOP/s bf16 peak, "
        f"{pk.hbm_bandwidth / 1e9:.0f} GB/s HBM, "
        f"ridge {pk.ridge_intensity:.0f} flops/byte "
        "(bytes are pre-fusion: floors are lower bounds, max_mfu an upper bound)"
    )
    rows = []
    for r in sorted(costed, key=lambda r: (str(r.get("program")), int(r.get("k", 1)))):
        rec = costmodel.CostRecord.from_dict(r["cost"])
        roof = rec.roofline(pk)
        rows.append(
            [
                r.get("program", "?"),
                r.get("k", 1),
                r.get("mesh", "") or "-",
                f"{rec.flops_per_step / 1e9:.3f}",
                f"{rec.bytes_per_step / 1e6:.2f}",
                f"{roof['arithmetic_intensity']:.1f}",
                roof["bound"].replace("-bound", ""),
                f"{roof['floor_step_secs'] * 1e3:.3f}",
                f"{roof['max_mfu']:.2f}",
                f"{rec.hbm_bytes / 2**30:.2f}" if rec.hbm_bytes else "-",
            ]
        )
    print(
        _table(
            rows,
            [
                "program", "k", "mesh", "gflop/step", "mb/step", "ai",
                "bound", "floor_ms", "max_mfu", "hbm_gb",
            ],
        )
    )
    return 0


def cmd_profile(args: argparse.Namespace) -> int:
    """On-demand ``jax.profiler`` capture + the capture inventory.

    ``--list`` discovers past captures under a workdir (per-trial
    ``enable_profiler`` directories and ``profile.capture`` spans in the
    trace journals).  With an experiment YAML it runs the experiment's
    prewarm twin under the profiler — an xprof trace of the exact
    compiled program, without scheduling a trial."""
    from katib_tpu.costmodel import profiler as costprofiler

    if args.list:
        entries = costprofiler.scan_profiles(args.workdir)
        if not entries:
            print(f"no profiler captures under {args.workdir}")
            return 0
        rows = [
            [
                e.get("experiment") or "-",
                e.get("trial") or "-",
                e.get("source", "-"),
                e.get("trace_dir", "?"),
            ]
            for e in entries
        ]
        print(_table(rows, ["experiment", "trial", "source", "trace_dir"]))
        return 0
    if not args.experiment:
        print(
            "error: pass an experiment YAML to capture, or --list to "
            "inventory past captures",
            file=sys.stderr,
        )
        return 2
    from katib_tpu.compile.prewarm import prewarm_fn_of
    from katib_tpu.runner.trial_runner import init_compile_cache
    from katib_tpu.sdk.yaml_spec import load_experiment_yaml

    spec = load_experiment_yaml(args.experiment)
    fn = prewarm_fn_of(spec.train_fn)
    if fn is None:
        print(
            "error: the experiment's train_fn declares no prewarm twin to "
            "profile (see katib_tpu.compile.prewarm.attach_prewarm_fn)",
            file=sys.stderr,
        )
        return 2
    init_compile_cache(spec.compile_cache)
    # default lands on the <workdir>/<experiment>/<trial>/profile layout
    # enable_profiler trials use, so `profile --list` discovers it
    out = args.out or os.path.join(args.workdir, spec.name, "adhoc", "profile")
    with costprofiler.capture(out, trial="adhoc", experiment=spec.name):
        fn(dict(_pinned_structural(spec)), 1, None)
    print(
        f"profiler trace: {out} (load with TensorBoard's profile plugin "
        "or xprof; listed by `katib-tpu profile --list`)"
    )
    return 0


def cmd_list(args: argparse.Namespace) -> int:
    from katib_tpu.orchestrator.status import list_statuses

    statuses = list_statuses(args.workdir)
    if not statuses:
        print(f"no experiments under {args.workdir}")
        return 0
    rows = []
    for s in statuses:
        counts = s.get("counts", {})
        optimal = s.get("optimal") or {}
        rows.append(
            [
                s.get("name", "?"),
                s.get("condition", "?"),
                s.get("algorithm", "?"),
                f"{counts.get('succeeded', 0)}/{counts.get('trials', 0)}",
                counts.get("failed", 0),
                optimal.get("objective_value", "-"),
                _fmt_age(s.get("start_time") or 0, s.get("completion_time") or 0),
            ]
        )
    print(_table(rows, ["NAME", "STATUS", "ALGORITHM", "SUCCEEDED", "FAILED", "BEST", "AGE"]))
    return 0


def cmd_describe(args: argparse.Namespace) -> int:
    from katib_tpu.orchestrator.status import read_status

    s = read_status(args.workdir, args.experiment)
    if s is None:
        print(f"experiment {args.experiment!r} not found under {args.workdir}", file=sys.stderr)
        return 1
    if args.json:
        print(json.dumps(s, indent=2))
        return 0
    print(f"Name:       {s['name']}")
    print(f"Status:     {s['condition']}  {s.get('message', '')}".rstrip())
    print(f"Algorithm:  {s['algorithm']}")
    goal = f" (goal {s['goal']})" if s.get("goal") is not None else ""
    print(f"Objective:  {s['objective_type']} {s['objective_metric']}{goal}")
    optimal = s.get("optimal")
    if optimal:
        print(
            f"Optimal:    {optimal['trial_name']} -> {optimal['objective_value']}  "
            + " ".join(f"{k}={v}" for k, v in sorted(optimal["assignments"].items()))
        )
    curve = s.get("optimal_history") or []
    if curve:
        # best-objective@wallclock, most recent improvements last
        shown = curve[-5:]
        prefix = "…, " if len(curve) > 5 else ""
        print(
            "Converge:   "
            + prefix
            + ", ".join(
                f"{r['objective_value']:.5g}@{r['elapsed_s']:.0f}s" for r in shown
            )
        )
    rows = []
    for t in s.get("trials", {}).values():
        obs = t.get("observation") or []
        objective = next(
            (m["value"] for m in obs if m["name"] == s["objective_metric"]), "-"
        )
        rows.append(
            [
                t["name"],
                t["condition"],
                objective,
                " ".join(f"{k}={v}" for k, v in sorted(t["assignments"].items())),
            ]
        )
    if rows:
        print()
        print(_table(rows, ["TRIAL", "STATUS", "OBJECTIVE", "ASSIGNMENTS"]))
    return 0


def cmd_metrics(args: argparse.Namespace) -> int:
    cfg = KatibConfig.load(args.config)
    store = cfg.store.make_store()
    logs = store.get(args.trial)
    if not logs:
        print(
            f"no metrics for trial {args.trial!r} in store backend "
            f"{cfg.store.backend!r} (persisted stores only: sqlite/remote)",
            file=sys.stderr,
        )
        return 1
    for l in logs:
        print(f"{l.timestamp:.3f}\t{l.step}\t{l.metric_name}\t{l.value}")
    return 0


def cmd_export(args: argparse.Namespace) -> int:
    """Dump an experiment's trials as CSV or JSONL for analysis — flat
    columns: trial, condition, one column per assignment, one per observed
    metric (the strategy-reduced value the journal records)."""
    from katib_tpu.orchestrator.status import read_status

    s = read_status(args.workdir, args.experiment)
    if s is None:
        print(f"experiment {args.experiment!r} not found", file=sys.stderr)
        return 1
    trials = list((s.get("trials") or {}).values())
    # pass 1: the full parameter-column set, so metric renaming can't depend
    # on trial order (a metric sharing a name with a parameter that only a
    # LATER trial introduces must still land in the metric: namespace)
    param_cols: list[str] = []
    for t in trials:
        for k in t.get("assignments") or {}:
            col = f"param:{k}" if k in ("trial", "condition") else k
            if col not in param_cols:
                param_cols.append(col)
    rows = []
    metric_cols: list[str] = []
    for t in trials:
        row: dict = {"trial": t["name"], "condition": t["condition"]}
        for k, v in (t.get("assignments") or {}).items():
            row[f"param:{k}" if k in ("trial", "condition") else k] = v
        for m in t.get("observation") or ():
            # metrics get their own namespace when they'd shadow a reserved
            # or parameter column (a metric literally named like a parameter
            # would otherwise silently overwrite the assignment)
            col = m["name"]
            if col in ("trial", "condition") or col in param_cols:
                col = f"metric:{col}"
            row[col] = m["value"]
            if col not in metric_cols:
                metric_cols.append(col)
        rows.append(row)
    if args.format == "jsonl":
        for row in rows:
            print(json.dumps(row))
        return 0
    import csv

    writer = csv.DictWriter(
        sys.stdout,
        fieldnames=["trial", "condition", *param_cols, *metric_cols],
        extrasaction="ignore",
    )
    writer.writeheader()
    writer.writerows(rows)
    return 0


def cmd_logs(args: argparse.Namespace) -> int:
    """Print a black-box trial's captured stdout (reference: UI pod-log
    fetch, ``backend.go:463``); lookup shared with the UI via
    ``status.read_trial_log``."""
    from katib_tpu.orchestrator.status import read_trial_log

    log = read_trial_log(args.workdir, args.trial)
    if log is None:
        print(
            f"no captured log for trial {args.trial!r} under {args.workdir} "
            "(white-box trials have no stdout log)",
            file=sys.stderr,
        )
        return 1
    sys.stdout.write(log)
    return 0


def cmd_conformance(args: argparse.Namespace) -> int:
    """Packaged conformance run (parity with the reference's
    ``conformance/run.sh``: deploy, run random-search e2e, assert the
    invariants from ``run-e2e-experiment.py:52-60``)."""
    import tempfile

    from katib_tpu.core.types import (
        AlgorithmSpec,
        ExperimentCondition,
        ExperimentSpec,
        FeasibleSpace,
        ObjectiveSpec,
        ObjectiveType,
        ParameterSpec,
        ParameterType,
    )
    from katib_tpu.orchestrator import Orchestrator

    def trainer(ctx):
        x = float(ctx.params["lr"])
        n = int(ctx.params["num_layers"])
        acc = 1.0 - 0.2 * (x - 0.05) ** 2 - 0.01 * abs(n - 3)
        for step in range(3):
            if not ctx.report(step=step, accuracy=acc * (step + 1) / 3):
                return

    spec = ExperimentSpec(
        name="conformance-random",
        algorithm=AlgorithmSpec(name="random"),
        objective=ObjectiveSpec(
            type=ObjectiveType.MAXIMIZE, objective_metric_name="accuracy"
        ),
        parameters=[
            ParameterSpec(
                "lr", ParameterType.DOUBLE, FeasibleSpace(min=0.01, max=0.2)
            ),
            ParameterSpec(
                "num_layers", ParameterType.INT, FeasibleSpace(min=1, max=5)
            ),
        ],
        max_trial_count=args.max_trials,
        parallel_trial_count=2,
        train_fn=trainer,
    )
    with tempfile.TemporaryDirectory(prefix="katib-conformance-") as workdir:
        exp = Orchestrator(workdir=workdir).run(spec)

    failures = []
    if exp.optimal is None:
        failures.append("best objective missing")
    if (
        exp.condition is ExperimentCondition.MAX_TRIALS_REACHED
        and exp.completed_count != spec.max_trial_count
    ):
        failures.append(
            f"MaxTrialsReached but completed {exp.completed_count} != {spec.max_trial_count}"
        )
    if exp.condition not in (
        ExperimentCondition.MAX_TRIALS_REACHED,
        ExperimentCondition.GOAL_REACHED,
        ExperimentCondition.SUCCEEDED,
    ):
        failures.append(f"experiment ended {exp.condition.value}: {exp.message}")
    if failures:
        print("CONFORMANCE FAIL: " + "; ".join(failures), file=sys.stderr)
        return 1
    print(
        f"CONFORMANCE PASS: {exp.condition.value}, "
        f"{exp.completed_count} trials, best={exp.optimal.objective_value:.4f}"
    )
    return 0


#: the child script for the crashpoint scenarios: a tiny resumable sweep
#: whose trainer exercises every registered persistence site (journal,
#: status, suggester pickle, checkpoint manifest, store report, retry
#: budget via one injected transient failure).  Run in a SUBPROCESS so the
#: armed crash point can genuinely kill it; the parent resumes and asserts.
_CRASH_CHILD_SCRIPT = """
import os, sys
sys.path[:0] = {syspath!r}
import jax
jax.config.update("jax_platforms", "cpu")
from katib_tpu.core.types import (
    AlgorithmSpec, ExperimentSpec, FeasibleSpace, ObjectiveSpec,
    ObjectiveType, ParameterSpec, ParameterType, ResumePolicy,
)
from katib_tpu.orchestrator import Orchestrator
from katib_tpu.utils.faults import FaultInjector
from katib_tpu.suggest.base import register
from katib_tpu.suggest.random_search import RandomSuggester

# random search carries no state; this wrapper adds the resume hooks so
# the suggester.pickle persistence site is actually exercised
@register("chaos-random")
class ChaosRandom(RandomSuggester):
    def state_dict(self):
        return {{"chaos": 1}}
    def load_state_dict(self, data):
        pass

def trainer(ctx):
    import jax.numpy as jnp
    from katib_tpu.utils.checkpoint import TrialCheckpointer
    os.makedirs(ctx.checkpoint_dir, exist_ok=True)
    ck = TrialCheckpointer(ctx.checkpoint_dir, max_to_keep=1)
    start = (ck.latest_step() or -1) + 1
    x = float(ctx.params["lr"])
    for step in range(start, 3):
        ck.save({{"step": jnp.asarray(step)}}, step)
        if not ctx.report(step=step, accuracy=(1.0 - (x - 0.05) ** 2) * (step + 1) / 3):
            return

injector = FaultInjector(seed=0)
injector.fail_trial(0, 1)  # guarantees the retry.budget site is reached
spec = ExperimentSpec(
    name="chaos-crash",
    algorithm=AlgorithmSpec(name="chaos-random", settings={{"seed": "0"}}),
    objective=ObjectiveSpec(type=ObjectiveType.MAXIMIZE, objective_metric_name="accuracy"),
    parameters=[ParameterSpec("lr", ParameterType.DOUBLE, FeasibleSpace(min=0.01, max=0.2))],
    max_trial_count={trials}, parallel_trial_count=1, max_retries=2,
    retry_backoff_seconds=0.01, resume_policy=ResumePolicy.LONG_RUNNING,
    train_fn=trainer,
)
orch = Orchestrator(workdir={workdir!r}, fault_injector=injector)
exp = orch.run(spec, resume=True)
print("child finished:", exp.condition.value)
"""


def _chaos_crash(args: argparse.Namespace) -> int:
    """The ``--crash-at`` / ``--kill-at`` scenario: arm one registered
    CrashPoint in a child process (via ``KATIB_CRASH_AT``), let it die
    mid-persistence, then resume IN-PROCESS from the journal and assert the
    crash-consistency invariants — no settled trial lost, no duplicate
    observation, retry budget monotone, optimal consistent.  Mirrors the
    ``--preempt-at`` drain scenario, but with no drain at all: the child is
    gone the instant the site fires."""
    import sqlite3
    import subprocess
    import tempfile

    from katib_tpu.core.types import (
        AlgorithmSpec,
        ExperimentSpec,
        FeasibleSpace,
        ObjectiveSpec,
        ObjectiveType,
        ParameterSpec,
        ParameterType,
        ResumePolicy,
        TrialCondition,
    )
    from katib_tpu.orchestrator import Orchestrator, journal as jr
    from katib_tpu.utils import faults

    site_spec = args.crash_at or args.kill_at
    site = site_spec.split(":", 1)[0]
    if site not in faults.registered_crash_points():
        print(
            f"unknown crash point {site!r}; registered: "
            f"{', '.join(faults.registered_crash_points())}",
            file=sys.stderr,
        )
        return 2
    mode = "kill" if args.kill_at else "exit"
    failures: list[str] = []
    with tempfile.TemporaryDirectory(prefix="katib-chaos-crash-") as workdir:
        env = dict(os.environ)
        env[faults.CRASH_AT_ENV] = site_spec
        env[faults.CRASH_MODE_ENV] = mode
        env.setdefault("JAX_PLATFORMS", "cpu")
        script = _CRASH_CHILD_SCRIPT.format(
            syspath=[p for p in sys.path if p],
            workdir=workdir,
            trials=args.trials,
        )
        proc = subprocess.run(
            [sys.executable, "-c", script],
            env=env,
            capture_output=True,
            text=True,
            timeout=300,
        )
        died = proc.returncode not in (0,)
        print(
            f"chaos crash-at={site_spec} mode={mode}: child exited "
            f"{proc.returncode}"
        )
        if not died:
            failures.append(
                f"crash point {site_spec!r} was never reached (child ran to "
                "completion); scenario proves nothing"
            )
        else:
            # what the journal PROVES happened before the kill
            pre_state, pre_stats = jr.replay_journal(workdir, "chaos-crash")
            pre_trials = (pre_state or {}).get("trials") or {}
            settled_before = {
                n: t
                for n, t in pre_trials.items()
                if TrialCondition(t.get("condition", "Created")).is_terminal()
            }
            # resume in this process — everything it knows comes from disk
            def trainer(ctx):
                import jax.numpy as jnp

                from katib_tpu.utils.checkpoint import TrialCheckpointer

                os.makedirs(ctx.checkpoint_dir, exist_ok=True)
                ck = TrialCheckpointer(ctx.checkpoint_dir, max_to_keep=1)
                start = (ck.latest_step() or -1) + 1
                x = float(ctx.params["lr"])
                for step in range(start, 3):
                    ck.save({"step": jnp.asarray(step)}, step)
                    if not ctx.report(
                        step=step,
                        accuracy=(1.0 - (x - 0.05) ** 2) * (step + 1) / 3,
                    ):
                        return

            from katib_tpu.suggest.base import register
            from katib_tpu.suggest.random_search import RandomSuggester

            # same stateful wrapper the child registered (see
            # _CRASH_CHILD_SCRIPT) — resume must resolve the algorithm name
            @register("chaos-random")
            class ChaosRandom(RandomSuggester):
                def state_dict(self):
                    return {"chaos": 1}

                def load_state_dict(self, data):
                    pass

            spec = ExperimentSpec(
                name="chaos-crash",
                algorithm=AlgorithmSpec(name="chaos-random", settings={"seed": "0"}),
                objective=ObjectiveSpec(
                    type=ObjectiveType.MAXIMIZE, objective_metric_name="accuracy"
                ),
                parameters=[
                    ParameterSpec(
                        "lr", ParameterType.DOUBLE, FeasibleSpace(min=0.01, max=0.2)
                    )
                ],
                max_trial_count=args.trials,
                parallel_trial_count=1,
                max_retries=2,
                retry_backoff_seconds=0.01,
                resume_policy=ResumePolicy.LONG_RUNNING,
                train_fn=trainer,
            )
            orch = Orchestrator(workdir=workdir)
            exp = orch.run(spec, resume=True)
            print(
                f"resumed: {exp.condition.value}, {len(exp.trials)} trial(s), "
                f"{pre_stats.applied} journal record(s) replayed"
            )
            if not exp.condition.is_terminal():
                failures.append(f"resumed experiment not terminal: {exp.condition.value}")
            # invariant 1: no settled trial lost or demoted
            for name, tdata in settled_before.items():
                t = exp.trials.get(name)
                if t is None:
                    failures.append(f"settled trial lost across the crash: {name}")
                elif t.condition.value != tdata["condition"]:
                    failures.append(
                        f"settled trial {name} changed condition across the "
                        f"crash: {tdata['condition']} -> {t.condition.value}"
                    )
            # invariant 2: no duplicate observations in the durable store
            db = os.path.join(workdir, "observations.sqlite")
            if os.path.exists(db):
                conn = sqlite3.connect(db)
                dups = conn.execute(
                    "SELECT trial_name, metric_name, step, COUNT(*) c FROM"
                    " observation_logs WHERE step >= 0 GROUP BY trial_name,"
                    " metric_name, step HAVING c > 1"
                ).fetchall()
                conn.close()
                if dups:
                    failures.append(f"duplicate observations in store: {dups[:5]}")
            # invariant 3: retry budget monotone across the crash
            for name, tdata in pre_trials.items():
                t = exp.trials.get(name)
                if t is not None and t.retry_count < int(tdata.get("retry_count") or 0):
                    failures.append(
                        f"retry budget reset across the crash for {name}: "
                        f"{tdata.get('retry_count')} -> {t.retry_count}"
                    )
            # invariant 4: the optimal trial is consistent with its own record
            if exp.optimal is not None:
                best = exp.trials.get(exp.optimal.trial_name)
                if best is None:
                    failures.append(
                        f"optimal trial {exp.optimal.trial_name} not in history"
                    )
                elif best.observation is None:
                    failures.append(
                        f"optimal trial {exp.optimal.trial_name} has no observation"
                    )
    if failures:
        print("CHAOS FAIL: " + "; ".join(failures), file=sys.stderr)
        return 1
    print(f"CHAOS PASS: hard kill at {site_spec} recovered with invariants intact")
    return 0


def cmd_fsck(args: argparse.Namespace) -> int:
    """Validate and repair an experiment directory (journal checksums,
    torn tails, snapshot integrity, suggester fence) — see
    ``orchestrator/fsck.py`` — or an artifact-cache directory (envelope
    checksums + content addresses, corrupt files quarantined) — see
    ``compile/artifacts.py``.  Exit 0 when consistent after repairs."""
    from katib_tpu.compile.artifacts import fsck_artifacts, is_artifact_dir

    if is_artifact_dir(args.path):
        report = fsck_artifacts(args.path, repair=not args.dry_run)
        print(f"artifact dir {report.root}")
        print(report.summary())
        for name in report.corrupt:
            print(f"  corrupt: {name}")
        for name in report.misaddressed:
            print(f"  misaddressed: {name}")
        for name in report.stale:
            print(f"  stale(other-env): {name}")
        for name in report.quarantined:
            print(f"  quarantined -> {name}{_QUARANTINE_NOTE}")
        return 0 if report.consistent else 1
    from katib_tpu.orchestrator.fsck import fsck_experiment

    report = fsck_experiment(args.path, repair=not args.dry_run)
    for line in report.lines():
        print(line)
    return 0 if report.ok() else 1


_QUARANTINE_NOTE = ".quarantined (inspect or delete; never auto-loaded)"


def cmd_cache(args: argparse.Namespace) -> int:
    """Inspect an artifact-cache tier: one row per serialized executable
    with its program, width, publishing environment, and whether this
    host's environment fingerprint can load it (``ok`` vs ``stale``)."""
    import json as _json

    from katib_tpu.compile.artifacts import (
        ARTIFACTS,
        env_fingerprint,
        scan_dir,
    )

    path = args.path or ARTIFACTS.shared_dir()
    if not path:
        print(
            "error: no artifact dir (pass a path or set KATIB_ARTIFACT_DIR)",
            file=sys.stderr,
        )
        return 2
    # a compile-cache dir holds its local tier under artifacts/
    sub = os.path.join(path, "artifacts")
    if not any(n.endswith(".katibx") for n in _ls(path)) and os.path.isdir(sub):
        path = sub
    rows = scan_dir(path)
    if args.json:
        print(_json.dumps({"dir": path, "artifacts": rows}, indent=2))
        return 0
    fp = env_fingerprint()
    print(
        f"artifact dir {os.path.abspath(path)} · this host: "
        f"jax {fp['jax']} · {fp['platform']}/{fp['device_kind']} "
        f"x{fp['device_count']}"
    )
    if not rows:
        print("(empty)")
        return 0
    table = [
        [
            r.get("program", "?"),
            r.get("k", "?"),
            r.get("status", "?"),
            f"{r.get('bytes', 0) / 1024:.0f}K",
            r.get("jax", "?"),
            f"{r.get('platform', '?')}/{r.get('device_kind', '?')}",
            "yes" if r.get("cost") else "-",
        ]
        for r in rows
    ]
    print(
        _table(
            table,
            ["program", "k", "status", "size", "jax", "target", "cost"],
        )
    )
    loadable = sum(1 for r in rows if r.get("status") == "ok")
    print(
        f"{len(rows)} artifact(s), {loadable} loadable here "
        f"({sum(1 for r in rows if r.get('status') == 'corrupt')} corrupt — "
        "run `katib-tpu fsck` to quarantine)"
    )
    return 0


def _ls(path: str) -> list[str]:
    try:
        return os.listdir(path)
    except OSError:
        return []


def cmd_chaos(args: argparse.Namespace) -> int:
    """Deterministic fault-injection run: a seeded ``FaultInjector`` plants
    transient trial failures and suggester exceptions in a small white-box
    experiment, then the exit status asserts the fault-tolerance invariants
    (transient retries recover with checkpoint resume, permanent failures
    don't retry, the suggester circuit breaker absorbs sub-threshold errors).
    The chaos analog of ``conformance``: same experiment, hostile weather."""
    if getattr(args, "crash_at", None) or getattr(args, "kill_at", None):
        if args.crash_at and args.kill_at:
            print("--crash-at and --kill-at are mutually exclusive", file=sys.stderr)
            return 2
        return _chaos_crash(args)
    if getattr(args, "soak", None):
        from katib_tpu.orchestrator.soak import run_soak

        # soak rounds want enough trials per round for occupancy and
        # mid-run kills to mean something; --trials can only raise it
        return run_soak(
            seconds=args.soak, seed=args.seed, trials=max(args.trials, 10)
        )
    import tempfile

    from katib_tpu.core.types import (
        AlgorithmSpec,
        ExperimentCondition,
        ExperimentSpec,
        FeasibleSpace,
        ObjectiveSpec,
        ObjectiveType,
        ParameterSpec,
        ParameterType,
        ResumePolicy,
        TrialCondition,
    )
    from katib_tpu.orchestrator import Orchestrator
    from katib_tpu.utils import observability as obs
    from katib_tpu.utils.faults import FailureKind, FaultInjector

    injector = FaultInjector(seed=args.seed)
    for spec_str in args.fail_trial or []:
        parts = spec_str.split(":")
        if len(parts) not in (2, 3):
            print(f"bad --fail-trial {spec_str!r} (want K:J[:kind])", file=sys.stderr)
            return 2
        kind = FailureKind(parts[2].capitalize()) if len(parts) == 3 else FailureKind.TRANSIENT
        injector.fail_trial(int(parts[0]), int(parts[1]), kind)
    for call in args.fail_suggester or []:
        injector.fail_suggester(int(call))
    for spec_str in args.hang_trial or []:
        parts = spec_str.split(":")
        if len(parts) not in (1, 2):
            print(f"bad --hang-trial {spec_str!r} (want K[:J])", file=sys.stderr)
            return 2
        injector.hang_trial(int(parts[0]), int(parts[1]) if len(parts) == 2 else 1)
    if args.preempt_at is not None:
        injector.preempt_at(args.preempt_at)
    if args.flake_rate:
        injector.flake(args.flake_rate)
    for spec_str in args.compile_hang or []:
        parts = spec_str.split(":")
        if len(parts) not in (1, 2):
            print(f"bad --compile-hang {spec_str!r} (want K[:J])", file=sys.stderr)
            return 2
        injector.compile_hang(int(parts[0]), int(parts[1]) if len(parts) == 2 else 1)
    wedge_devices = [int(d) for d in (args.wedge_device or [])]
    for d in wedge_devices:
        injector.wedge_device(d)
    killed_loops = []
    for spec_str in args.kill_loop or []:
        parts = spec_str.split(":")
        if parts[0] not in ("suggest", "schedule", "harvest") or len(parts) > 2:
            print(f"bad --kill-loop {spec_str!r} (want LOOP[:N])", file=sys.stderr)
            return 2
        injector.kill_loop(parts[0], int(parts[1]) if len(parts) == 2 else 1)
        killed_loops.append(parts[0])
    stall_calls = []
    for spec_str in args.stall_suggester or []:
        parts = spec_str.split(":")
        if len(parts) not in (1, 2):
            print(
                f"bad --stall-suggester {spec_str!r} (want SECONDS[:CALL])",
                file=sys.stderr,
            )
            return 2
        injector.stall_suggester(
            float(parts[0]), int(parts[1]) if len(parts) == 2 else 1
        )
        stall_calls.append(float(parts[0]))
    injected_any = (
        args.fail_trial
        or args.fail_suggester
        or args.flake_rate
        or args.hang_trial
        or args.compile_hang
        or wedge_devices
        or killed_loops
        or stall_calls
        or args.preempt_at is not None
    )
    if not injector.log and not injected_any:
        # default scenario: first trial is preempted twice, one suggester
        # call blows up — the experiment must shrug all of it off
        injector.fail_trial(0, 1).fail_trial(0, 2).fail_suggester(2)

    def trainer(ctx):
        # checkpoint-aware: progress survives transient retries because the
        # re-run reuses the same checkpoint dir
        os.makedirs(ctx.checkpoint_dir, exist_ok=True)
        marker = os.path.join(ctx.checkpoint_dir, "progress.txt")
        start = 0
        if os.path.exists(marker):
            with open(marker) as f:
                start = int(f.read().strip() or 0)
        x = float(ctx.params["lr"])
        for step in range(start, 3):
            with open(marker, "w") as f:
                f.write(str(step + 1))
            if not ctx.report(step=step, accuracy=(1.0 - 0.2 * (x - 0.05) ** 2) * (step + 1) / 3):
                return

    # --wedge-device scenario: a sharded trial-axis mesh over the visible
    # (virtual CPU) devices + a cohort-capable twin of the toy trainer, so
    # the injected device fault hits a real vmap cohort and must recover
    # through elastic degradation (narrower mesh -> vmap -> serial)
    mesh = None
    preflight_report = None
    if wedge_devices:
        # best-effort: only effective when jax has not initialized yet
        os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
        import jax

        from katib_tpu.parallel.mesh import TRIAL_AXIS, make_mesh
        from katib_tpu.utils import meshhealth

        devs = jax.devices()
        if len(devs) < 2:
            print(
                "chaos --wedge-device needs >= 2 devices; launch with "
                "XLA_FLAGS=--xla_force_host_platform_device_count=8",
                file=sys.stderr,
            )
            return 2
        t = min(4, len(devs))
        mesh = make_mesh({TRIAL_AXIS: t}, devices=devs[:t])
        # doctor-detection assertion input: the bounded probe must classify
        # the injector-wedged devices as wedged before the sweep starts
        preflight_report = meshhealth.probe_devices(
            devs[:t], deadline=10.0, injector=injector
        )

        def cohort_trainer(cctx):
            # checkpoint-aware twin of `trainer`: same progress markers per
            # member, metric rows stacked [K]
            starts = []
            for d in cctx.checkpoint_dirs:
                os.makedirs(d, exist_ok=True)
                m = os.path.join(d, "progress.txt")
                s = 0
                if os.path.exists(m):
                    with open(m) as f:
                        s = int(f.read().strip() or 0)
                starts.append(s)
            xs = [float(p["lr"]) for p in cctx.params_list]
            for step in range(min(starts), 3):
                for d in cctx.checkpoint_dirs:
                    with open(os.path.join(d, "progress.txt"), "w") as f:
                        f.write(str(step + 1))
                rows = [
                    (1.0 - 0.2 * (x - 0.05) ** 2) * (step + 1) / 3 for x in xs
                ]
                if not cctx.report(step=step, accuracy=rows):
                    return

        from katib_tpu.runner.cohort import attach_cohort_fn

        attach_cohort_fn(trainer, cohort_trainer)

    spec = ExperimentSpec(
        name="chaos-random",
        algorithm=AlgorithmSpec(name="random", settings={"seed": str(args.seed)}),
        objective=ObjectiveSpec(
            type=ObjectiveType.MAXIMIZE, objective_metric_name="accuracy"
        ),
        parameters=[
            ParameterSpec("lr", ParameterType.DOUBLE, FeasibleSpace(min=0.01, max=0.2)),
        ],
        max_trial_count=args.trials,
        # cohort members count against the parallel budget: the wedge
        # scenario needs a full cohort in one batch, everything else keeps
        # 1 so injector trial indices stay deterministic
        parallel_trial_count=(
            min(4, args.trials) if wedge_devices else 1
        ),
        max_retries=args.max_retries,
        retry_backoff_seconds=0.05,
        suggester_max_errors=args.suggester_max_errors,
        # hang watchdog only arms when a deadline is set; keep it off unless
        # the scenario injects hangs so the happy path stays unchanged
        progress_deadline_seconds=(
            args.progress_deadline if args.hang_trial else None
        ),
        # compile watchdog only arms for the --compile-hang scenario
        compile_deadline_seconds=(
            args.compile_deadline if args.compile_hang else None
        ),
        drain_grace_seconds=args.drain_grace,
        # loop-kill / suggester-stall scenarios exercise the async engine's
        # supervisor: force the async path on (env opt-out would silently
        # skip the seams) and tighten the stall deadline so a stalled
        # suggester call is abandoned within the run, not after 60s
        async_orch=(True if (killed_loops or stall_calls) else None),
        loop_stall_deadline_seconds=(
            args.loop_stall_deadline if (killed_loops or stall_calls) else 60.0
        ),
        # the preempt scenario spans two orchestrator lifetimes; a resumable
        # policy upgrades the store to the durable sqlite backend so metrics
        # reported before the SIGTERM survive into the resumed process
        resume_policy=(
            ResumePolicy.LONG_RUNNING
            if args.preempt_at is not None
            else ResumePolicy.NEVER
        ),
        train_fn=trainer,
    )
    errors_before = obs.suggester_errors.get(algorithm="random")
    retried_before = obs.trials_retried.get(kind=FailureKind.TRANSIENT.value)
    hangs_before = obs.trial_hangs.get()
    compile_hangs_before = obs.compile_hangs.get()
    degraded_before = obs.mesh_degraded.get()
    preempted = False
    completed_at_drain: set[str] = set()
    with tempfile.TemporaryDirectory(prefix="katib-chaos-") as workdir:
        orch = Orchestrator(workdir=workdir, mesh=mesh, fault_injector=injector)
        if args.preempt_at is not None:
            # the injected preempt delivers a real SIGTERM to this process:
            # install the same drain handlers `katib-tpu run` uses so the
            # orchestrator checkpoints, journals, and returns resumable state
            _install_drain_handlers(orch)
        exp = orch.run(spec)
        if orch.drained:
            preempted = True
            completed_at_drain = {
                t.name
                for t in exp.trials.values()
                if t.condition is TrialCondition.SUCCEEDED
            }
            drained_names = [
                t.name
                for t in exp.trials.values()
                if t.condition is TrialCondition.DRAINED
            ]
            print(
                f"preempted mid-experiment: {len(completed_at_drain)} trial(s) "
                f"completed, {len(drained_names)} drained "
                f"({', '.join(drained_names) or 'none'}); resuming from journal"
            )
            # fresh orchestrator = new process semantics: everything it knows
            # must come from the journal + suggester pickle, not live memory
            orch = Orchestrator(workdir=workdir, mesh=mesh, fault_injector=injector)
            _install_drain_handlers(orch)
            exp = orch.run(spec, experiment=orch.load_experiment(spec))

    print(f"chaos seed={args.seed}  experiment={exp.condition.value}")
    for t in sorted(exp.trials.values(), key=lambda t: t.start_time):
        print(
            f"  {t.name}: {t.condition.value:<20} attempts={t.retry_count + 1} "
            f"kind={t.failure_kind or '-'}"
        )
    print(
        f"injected: {len(injector.log)} faults; "
        f"retries={obs.trials_retried.get(kind=FailureKind.TRANSIENT.value) - retried_before:g}; "
        f"suggester errors absorbed={obs.suggester_errors.get(algorithm='random') - errors_before:g}; "
        f"hangs caught={obs.trial_hangs.get() - hangs_before:g}; "
        f"compile hangs caught={obs.compile_hangs.get() - compile_hangs_before:g}; "
        f"mesh degradations={obs.mesh_degraded.get() - degraded_before:g}"
    )

    failures = []
    if args.hang_trial:
        hung = [
            t
            for t in exp.trials.values()
            if t.failure_kind == FailureKind.HANG.value and t.retry_count > 0
        ]
        if obs.trial_hangs.get() - hangs_before <= 0:
            failures.append("injected hang was never caught by the watchdog")
        elif not hung:
            failures.append(
                "no trial journaled failure_kind=Hang with a retry "
                "(watchdog fired but retry machinery did not reclassify)"
            )
        elif not all(t.condition is TrialCondition.SUCCEEDED for t in hung):
            failures.append(
                "hung trial did not recover on retry: "
                f"{[(t.name, t.condition.value) for t in hung]}"
            )
    if args.compile_hang:
        if obs.compile_hangs.get() - compile_hangs_before <= 0:
            failures.append(
                "injected compile hang was never caught by the compile watchdog"
            )
        else:
            compile_hung = [
                t
                for t in exp.trials.values()
                if t.failure_kind == FailureKind.COMPILE_HANG.value
                and t.retry_count > 0
            ]
            if not compile_hung:
                failures.append(
                    "no trial journaled failure_kind=CompileHang with a retry"
                )
            elif not all(
                t.condition is TrialCondition.SUCCEEDED for t in compile_hung
            ):
                failures.append(
                    "compile-hung trial did not recover on retry: "
                    f"{[(t.name, t.condition.value) for t in compile_hung]}"
                )
    if wedge_devices:
        wedged_seen = {
            d.device for d in preflight_report.devices if d.status == "wedged"
        }
        if preflight_report.ok() or not wedged_seen:
            failures.append(
                "doctor probe did not classify the injected wedged device(s): "
                f"{preflight_report.summary()}"
            )
        if not any(e.get("seam") == "cohort-device" for e in injector.log):
            failures.append(
                "wedged device never intersected a cohort mesh "
                "(sharded cohort path was not exercised)"
            )
        if obs.mesh_degraded.get() - degraded_before <= 0:
            failures.append(
                "device fault did not trigger elastic mesh degradation"
            )
        not_completed = [
            t.name
            for t in exp.trials.values()
            if t.condition is not TrialCondition.SUCCEEDED
        ]
        if not_completed:
            failures.append(
                "trials lost to the device fault (elastic degradation should "
                f"complete all of them): {not_completed}"
            )
    if args.preempt_at is not None:
        if not preempted:
            failures.append(
                "injected preemption did not drain the orchestrator "
                "(SIGTERM handler or drain path broken)"
            )
        else:
            still_completed = {
                t.name
                for t in exp.trials.values()
                if t.condition is TrialCondition.SUCCEEDED
            }
            lost = completed_at_drain - still_completed
            if lost:
                failures.append(
                    f"completed trials lost across the drain/resume cycle: {sorted(lost)}"
                )
            leftover = [
                t.name
                for t in exp.trials.values()
                if t.condition is TrialCondition.DRAINED
            ]
            if leftover:
                failures.append(f"drained trials never resubmitted: {leftover}")
    if killed_loops:
        st = orch.async_stats or {}
        fired = {e.get("loop") for e in injector.log if e.get("seam") == "kill-loop"}
        for loop in killed_loops:
            if loop not in fired:
                failures.append(f"injected kill for the {loop!r} loop never fired")
            elif (st.get("loop_restarts") or {}).get(loop, 0) < 1:
                failures.append(
                    f"killed {loop!r} loop was never restarted by the supervisor"
                )
        if st.get("fallback"):
            failures.append(f"async engine fell back to sync: {st['fallback']}")
    if stall_calls:
        if not any(e.get("seam") == "suggester-stall" for e in injector.log):
            failures.append("injected suggester stall never fired")
        elif any(s > args.loop_stall_deadline for s in stall_calls) and (
            obs.suggester_errors.get(algorithm="random") - errors_before <= 0
        ):
            failures.append(
                "over-deadline suggester stall was not abandoned "
                "(deadline-bounded call should have tripped the breaker)"
            )
    if not exp.condition.is_terminal():
        failures.append(f"experiment not terminal: {exp.condition.value}")
    if exp.condition is ExperimentCondition.FAILED:
        failures.append(f"experiment failed: {exp.message.splitlines()[0] if exp.message else ''}")
    recovered = [
        t for t in exp.trials.values()
        if t.retry_count > 0 and t.condition is TrialCondition.SUCCEEDED
    ]
    injected_transient = [
        e
        for e in injector.log
        if e.get("seam") == "trial" and e.get("kind") == FailureKind.TRANSIENT.value
    ]
    if injected_transient and args.max_retries > 0 and not recovered:
        failures.append("no trial recovered from an injected transient fault")
    never_retried = [
        t.name
        for t in exp.trials.values()
        if t.failure_kind == FailureKind.PERMANENT.value and t.retry_count > 0
    ]
    if never_retried:
        failures.append(f"permanent failures were retried: {never_retried}")
    if failures:
        print("CHAOS FAIL: " + "; ".join(failures), file=sys.stderr)
        return 1
    print("CHAOS PASS: every injected fault was absorbed")
    return 0


def cmd_trace_export(args: argparse.Namespace) -> int:
    import json as _json

    from katib_tpu.utils import tracing

    journal = tracing.trace_path(args.workdir, args.experiment)
    if not os.path.exists(journal):
        print(f"no trace journal at {journal}", file=sys.stderr)
        return 1
    if args.out == "-":
        records = tracing.read_journal(journal)
        if not records:
            print(f"trace journal {journal} holds no valid spans", file=sys.stderr)
            return 1
        _json.dump(tracing.to_chrome_trace(records), sys.stdout)
        print()
        return 0
    out = args.out or os.path.join(args.workdir, args.experiment, "trace.json")
    n = tracing.export_chrome_trace(journal, out)
    if n == 0:
        print(f"trace journal {journal} holds no valid spans", file=sys.stderr)
        return 1
    print(f"wrote {n} spans to {out} (open in Perfetto / chrome://tracing)")
    return 0


def cmd_trace_summary(args: argparse.Namespace) -> int:
    import json as _json

    from katib_tpu.utils import tracing

    journal = tracing.trace_path(args.workdir, args.experiment)
    records = tracing.read_journal(journal)
    if not records:
        print(f"no spans found at {journal}", file=sys.stderr)
        return 1
    summary = tracing.summarize(records)
    slowest = _slowest_spans(records, args.top) if args.top else []
    if args.json:
        doc = {"summary": summary, "slowest": slowest} if args.top else summary
        _json.dump(doc, sys.stdout, indent=2)
        print()
        return 0
    rows = [
        [
            s["name"],
            s["count"],
            f"{s['total_s']:.3f}",
            f"{s['mean_s']:.4f}",
            f"{s['p50_s']:.4f}",
            f"{s['p95_s']:.4f}",
            f"{s['max_s']:.4f}",
        ]
        for s in summary
    ]
    print(_table(rows, ["SPAN", "COUNT", "TOTAL_S", "MEAN_S", "P50_S", "P95_S", "MAX_S"]))
    if slowest:
        rows = [
            [
                s["name"],
                f"{s['dur_s']:.3f}",
                s["who"],
                s["mfu"],
                s["roofline"],
                s["headroom"],
            ]
            for s in slowest
        ]
        print(f"\nslowest {len(rows)} spans (roofline attrs where costed):")
        print(_table(rows, ["SPAN", "DUR_S", "WHO", "MFU", "ROOFLINE", "HEADROOM"]))
    return 0


def _slowest_spans(records: list[dict], top: int) -> list[dict]:
    """The ``--top N`` view: individual spans by duration, surfacing the
    roofline attrs (``costmodel.publish_dispatch``) stamped on
    trial/cohort/darts.epoch spans — a slow span with low MFU and high
    headroom is leaving the accelerator idle, not compute-starved."""

    def _dur(rec: dict) -> float:
        try:
            return float(rec.get("dur", 0.0))
        except (TypeError, ValueError):
            return 0.0

    out = []
    for rec in sorted(records, key=_dur, reverse=True)[: max(0, top)]:
        args = rec.get("args", {}) or {}
        mfu = args.get("mfu")
        who = args.get("trial") or args.get("cohort") or args.get("epoch")
        out.append(
            {
                "name": str(rec.get("name", "?")),
                "dur_s": round(_dur(rec), 6),
                "who": str(who) if who is not None else "-",
                "mfu": f"{mfu:.4f}" if isinstance(mfu, (int, float)) else "-",
                "roofline": str(args.get("roofline", "-")),
                "headroom": str(args.get("roofline_headroom", "-")),
            }
        )
    return out


def cmd_db_manager(args: argparse.Namespace) -> int:
    """Run the native db-manager daemon standalone (the reference ships it
    as its own binary, ``cmd/db-manager/v1beta1/main.go:51``).  ``--db``
    enables the append-only frame journal: acked mutations survive kill -9
    and replay on the next start.  Blocks until interrupted; clients point
    a ``store: {backend: remote, host, port}`` config (or
    ``RemoteObservationStore``) at the printed address."""
    import signal as _signal

    from katib_tpu.native.dbmanager import spawn_db_manager

    # PDEATHSIG: the daemon dies with this wrapper, so even a SIGKILLed CLI
    # can't orphan a daemon holding the port + journal file
    handle = spawn_db_manager(
        host=args.host, port=args.port, db_path=args.db,
        kill_on_parent_exit=True,
    )
    print(
        f"katib-tpu db-manager: {args.host}:{handle.port} "
        f"({'journal: ' + args.db if args.db else 'in-memory'})",
        flush=True,
    )
    stopped_by_us = False

    def _on_term(signum, frame):
        nonlocal stopped_by_us
        stopped_by_us = True
        # signal only — calling proc.wait() here would deadlock on the
        # Popen lock the interrupted main-thread wait() already holds
        handle.proc.terminate()

    _signal.signal(_signal.SIGTERM, _on_term)
    try:
        handle.proc.wait()
    except KeyboardInterrupt:
        stopped_by_us = True
        handle.stop()
    # a shutdown we initiated is a clean exit, whatever signal killed the
    # daemon; only an unprompted daemon death propagates as failure
    if stopped_by_us:
        return 0
    rc = handle.proc.returncode
    return rc if rc and rc > 0 else (1 if rc else 0)


def cmd_suggest_server(args: argparse.Namespace) -> int:
    """Run the suggestion-as-a-service daemon (the reference's per-experiment
    algorithm Deployment entrypoint, ``cmd/suggestion/*/v1beta1/main.py``).
    The auth token comes from ``--token`` or ``KATIB_SUGGEST_TOKEN``;
    unset = open (localhost development)."""
    from katib_tpu.suggest.service import serve_suggestions

    token = args.token or os.environ.get("KATIB_SUGGEST_TOKEN") or None
    ssl_context = _maybe_tls(args)
    svc = serve_suggestions(
        port=args.port, host=args.host, token=token, ssl_context=ssl_context
    )
    scheme = "https" if ssl_context else "http"
    print(
        f"katib-tpu suggestion service: {scheme}://{args.host}:{svc.port} "
        f"(auth: {'bearer token' if token else 'open'})",
        flush=True,
    )
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        pass
    finally:
        svc.stop()
    return 0


def _maybe_tls(args: argparse.Namespace):
    """``--cert-dir`` turns a serving command into TLS: the rotator in
    ``utils.certgen`` (re)generates the self-signed bundle there and the
    server wraps its socket with it (reference ``certgenerator/generator.go``)."""
    cert_dir = getattr(args, "cert_dir", None)
    if not cert_dir:
        return None
    import ipaddress
    import socket

    from katib_tpu.utils.certgen import ensure_certs, server_ssl_context

    host = getattr(args, "host", "127.0.0.1")
    dns, ips = ["localhost"], ["127.0.0.1"]
    try:
        ip = ipaddress.ip_address(host)
        if ip.is_unspecified:
            # bound on all interfaces: remote clients will connect via the
            # machine's real addresses, so the leaf needs those SANs too
            dns.append(socket.gethostname())
            try:
                for addr in socket.gethostbyname_ex(socket.gethostname())[2]:
                    if addr not in ips:
                        ips.append(addr)
            except OSError:
                pass
        elif str(ip) != "127.0.0.1":
            ips.append(str(ip))
    except ValueError:
        dns.append(host)
    return server_ssl_context(
        ensure_certs(cert_dir, dns_names=tuple(dns), ip_addresses=tuple(ips))
    )


def cmd_ui(args: argparse.Namespace) -> int:
    from katib_tpu.ui import start_ui

    cfg = KatibConfig.load(args.config)
    store = cfg.store.make_store()
    token = args.token or os.environ.get("KATIB_UI_TOKEN") or None
    ssl_context = _maybe_tls(args)
    ui = start_ui(
        args.workdir, store, port=args.port, host=args.host, token=token,
        ssl_context=ssl_context,
    )
    scheme = "https" if ssl_context else "http"
    print(
        f"katib-tpu dashboard: {scheme}://{args.host}:{ui.port}/ "
        f"(writes: {'bearer token' if token else 'open'})"
    )
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        pass
    finally:
        ui.stop()
    return 0


def cmd_doctor(args: argparse.Namespace) -> int:
    """Bounded-time device preflight: probe every visible device with a tiny
    jitted program in a killable CHILD process (on a wedged accelerator pool
    even ``jax.devices()`` blocks forever, and a diagnostic tool that hangs
    is worse than the condition it diagnoses).  Exit 0 only when every
    enumerated device ran the probe within the deadline."""
    from katib_tpu.utils import meshhealth

    report = meshhealth.doctor_report(
        deadline=float(args.device_timeout),
        simulate_wedge=args.simulate_wedge or None,
    )
    if args.json:
        print(report.to_json())
        return 0 if report.ok() else 1

    print(report.summary())
    for d in sorted(report.devices, key=lambda d: d.device):
        line = f"  {d.device:<12} {d.status:<8} probe={d.probe_seconds:.2f}s"
        if d.error:
            line += f"  ({d.error})"
        print(line)
    if report.error:
        print(f"  error: {report.error}")

    from katib_tpu.native import build_error, native_available

    import jax

    print(f"jax {jax.__version__}")
    if native_available():
        print("native runtime: built")
    else:
        print(f"native runtime: unavailable ({build_error()})")
    cfg = KatibConfig.load(args.config)
    print(f"workdir: {cfg.init.workdir}")
    print(f"store: {cfg.store.backend}")
    return 0 if report.ok() else 1


def cmd_lint(args: argparse.Namespace) -> int:
    """Concurrency-discipline + JAX-hazard static analysis over the tree
    (see ``katib_tpu/analysis/``).  Exit non-zero on any finding whose
    fingerprint is not in the committed baseline — the ratchet: debt can
    only shrink, never silently grow."""
    from katib_tpu.analysis.lint import run_lint, write_baseline

    # a relative baseline names a file inside the scanned tree, not the cwd
    baseline = (
        args.baseline
        if os.path.isabs(args.baseline)
        else os.path.join(args.root, args.baseline)
    )
    report = run_lint(root=args.root, baseline_path=baseline)
    if args.update_baseline:
        write_baseline(baseline, report.findings)
        print(
            f"baseline updated: {baseline} "
            f"({len(report.findings)} accepted fingerprint(s))"
        )
        return 0
    if args.json:
        print(json.dumps(report.to_json(), indent=2))
        return report.exit_code
    for f in report.new:
        print(f.render())
    if report.baselined:
        print(f"{len(report.baselined)} baselined finding(s) suppressed")
    for fp in report.stale_baseline:
        print(f"stale baseline entry (finding fixed — prune it): {fp}")
    status = "FAIL" if report.new else "ok"
    print(
        f"lint {status}: {report.files_scanned} files scanned, "
        f"{len(report.new)} new finding(s), "
        f"{len(report.stale_baseline)} stale baseline entr(y/ies)"
    )
    return report.exit_code


def cmd_sim(args: argparse.Namespace) -> int:
    """Virtual-time scale simulation: run the real orchestrator stack
    (async loops, supervisor, journal, suggester) against a modeled trial
    executor under a discrete-event clock, inject the scenario's fault
    schedule, then gate on the journal-replay invariants — see
    ``katib_tpu/sim/``.  Exit 0 on PASS (zero violations)."""
    from katib_tpu.sim.runner import run_scenario
    from katib_tpu.sim.scenario import load_scenario

    verdict = run_scenario(
        load_scenario(args.scenario), seed=args.seed, workdir=args.workdir
    )
    if args.json:
        print(json.dumps(verdict, indent=2, sort_keys=True))
    else:
        print(
            f"{verdict['verdict']}: {verdict['scenario']} "
            f"seed={verdict['seed']} trials={verdict['trials']} "
            f"settled={verdict['settled']} "
            f"virtual={verdict['virtual_seconds']}s "
            f"wall={verdict['wall_seconds']}s "
            f"journal={verdict['journal_sha256'][:16]}"
        )
        for v in verdict["violations"]:
            print(f"  violation: {v}")
    return 0 if verdict["verdict"] == "PASS" else 1


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="katib-tpu", description="TPU-native AutoML framework CLI"
    )
    parser.add_argument("--config", default=None, help="KatibConfig YAML path")
    sub = parser.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("run", help="run an experiment from a YAML spec")
    p.add_argument("experiment")
    p.add_argument("--workdir", default=None)
    p.add_argument(
        "--resume",
        action="store_true",
        help="resume from the status journal (honors spec resumePolicy)",
    )
    p.add_argument(
        "--drain-grace-seconds",
        type=float,
        default=None,
        help="on SIGTERM/SIGINT, wait this long for running trials to reach "
        "a checkpoint boundary before journaling them Drained "
        "(overrides the spec's drainGraceSeconds)",
    )
    p.add_argument(
        "--no-preflight",
        action="store_true",
        help="skip the bounded device preflight probe that gates the run "
        "(KATIB_PREFLIGHT_DEADLINE bounds it; see `katib-tpu doctor`)",
    )
    p.set_defaults(fn=cmd_run)

    p = sub.add_parser(
        "prewarm",
        help="compile an experiment's programs into the persistent cache "
        "ahead of a run (requires a train_fn with a prewarm twin)",
    )
    p.add_argument("experiment", help="experiment YAML")
    p.add_argument(
        "--widths",
        default=None,
        help="comma-separated cohort widths to warm (default: derived from "
        "cohortWidth + shape bucketing)",
    )
    p.add_argument(
        "--timeout",
        type=float,
        default=600.0,
        help="max seconds to wait for queued compiles",
    )
    p.add_argument(
        "--publish",
        action="store_true",
        help="serialize compiled executables into the artifact tiers "
        "(--artifact-dir / artifactDir / KATIB_ARTIFACT_DIR) so other "
        "hosts fetch instead of compiling",
    )
    p.add_argument(
        "--fetch-only",
        action="store_true",
        help="only fetch published artifacts into the local tier (new-host "
        "sync: never compiles, misses stay cold)",
    )
    p.add_argument(
        "--artifact-dir",
        default=None,
        help="shared artifact tier directory (overrides the spec's "
        "artifactDir; KATIB_ARTIFACT_DIR wins over both)",
    )
    p.set_defaults(fn=cmd_prewarm)

    p = sub.add_parser("list", help="list experiments")
    p.add_argument("--workdir", default="katib_runs")
    p.set_defaults(fn=cmd_list)

    p = sub.add_parser("describe", help="describe one experiment")
    p.add_argument("experiment")
    p.add_argument("--workdir", default="katib_runs")
    p.add_argument("--json", action="store_true")
    p.set_defaults(fn=cmd_describe)

    p = sub.add_parser("metrics", help="dump a trial's metric log")
    p.add_argument("trial")
    p.set_defaults(fn=cmd_metrics)

    p = sub.add_parser("export", help="dump trials as CSV/JSONL for analysis")
    p.add_argument("experiment")
    p.add_argument("--format", choices=("csv", "jsonl"), default="csv")
    p.add_argument("--workdir", default="katib_runs")
    p.set_defaults(fn=cmd_export)

    p = sub.add_parser("logs", help="print a black-box trial's captured stdout")
    p.add_argument("trial")
    p.add_argument("--workdir", default="katib_runs")
    p.set_defaults(fn=cmd_logs)

    p = sub.add_parser("trace", help="export/summarize an experiment's span journal")
    trace_sub = p.add_subparsers(dest="trace_cmd", required=True)
    tp = trace_sub.add_parser(
        "export", help="trace journal -> Chrome-trace JSON (Perfetto-loadable)"
    )
    tp.add_argument("experiment")
    tp.add_argument("--workdir", default="katib_runs")
    tp.add_argument(
        "--out",
        default=None,
        help="output path (default <workdir>/<experiment>/trace.json; '-' for stdout)",
    )
    tp.set_defaults(fn=cmd_trace_export)
    tp = trace_sub.add_parser(
        "summary", help="per-span latency distribution (count/total/p50/p95)"
    )
    tp.add_argument("experiment")
    tp.add_argument("--workdir", default="katib_runs")
    tp.add_argument("--json", action="store_true")
    tp.add_argument(
        "--top",
        type=int,
        default=0,
        metavar="N",
        help="also list the N slowest individual spans with their roofline "
        "attrs (mfu / bound / headroom)",
    )
    tp.set_defaults(fn=cmd_trace_summary)

    p = sub.add_parser(
        "cost",
        help="deviceless roofline table from the shape registry's XLA cost records",
    )
    p.add_argument(
        "target",
        help="experiment YAML (compiles the prewarm twins if nothing is "
        "costed yet) or a compile-cache/workdir directory holding "
        "shape_registry.jsonl",
    )
    p.add_argument(
        "--device",
        default=None,
        help="device kind for the peaks table (v5e/v5p/v4/v3/cpu; default: "
        "detect, honoring PALLAS_AXON_TPU_GEN and KATIB_PEAK_* overrides)",
    )
    p.add_argument(
        "--timeout",
        type=float,
        default=300.0,
        help="prewarm-twin compile budget in seconds (YAML targets only)",
    )
    p.set_defaults(fn=cmd_cost)

    p = sub.add_parser(
        "profile",
        help="on-demand jax.profiler capture (or --list past captures)",
    )
    p.add_argument(
        "experiment",
        nargs="?",
        default=None,
        help="experiment YAML whose prewarm twin to run under the profiler",
    )
    p.add_argument("--workdir", default="katib_runs")
    p.add_argument(
        "--out",
        default=None,
        help="trace output dir (default <workdir>/<experiment>/adhoc/profile)",
    )
    p.add_argument(
        "--list",
        action="store_true",
        help="inventory captures under --workdir instead of capturing",
    )
    p.set_defaults(fn=cmd_profile)

    p = sub.add_parser("conformance", help="packaged e2e invariants check")
    p.add_argument("--max-trials", type=int, default=8)
    p.set_defaults(fn=cmd_conformance)

    p = sub.add_parser(
        "chaos", help="deterministic fault-injection run (fault-tolerance invariants)"
    )
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--trials", type=int, default=4)
    p.add_argument("--max-retries", type=int, default=3)
    p.add_argument("--suggester-max-errors", type=int, default=3)
    p.add_argument(
        "--fail-trial",
        action="append",
        metavar="K:J[:kind]",
        help="fail trial K's attempt J (0-based trial index, 1-based attempt; "
        "kind transient|permanent, default transient); repeatable",
    )
    p.add_argument(
        "--fail-suggester",
        action="append",
        metavar="N",
        help="raise inside the N-th (1-based) get_suggestions call; repeatable",
    )
    p.add_argument(
        "--flake-rate",
        type=float,
        default=0.0,
        help="seeded random per-attempt transient failure probability",
    )
    p.add_argument(
        "--hang-trial",
        action="append",
        metavar="K[:J]",
        help="wedge trial K's attempt J (default 1) until the hang watchdog "
        "interrupts it; repeatable",
    )
    p.add_argument(
        "--preempt-at",
        type=int,
        default=None,
        metavar="N",
        help="deliver a real SIGTERM to this process when trial N starts "
        "(drain -> journal -> in-process resume, asserting zero lost trials)",
    )
    p.add_argument(
        "--compile-hang",
        action="append",
        metavar="K[:J]",
        help="wedge trial K's attempt J (default 1) before its first report, "
        "inside the compile budget, until the compile watchdog interrupts "
        "it; repeatable",
    )
    p.add_argument(
        "--wedge-device",
        action="append",
        type=int,
        metavar="N",
        help="wedge device id N: the preflight probe classifies it wedged "
        "and any sharded cohort whose mesh contains it takes a DEVICE "
        "fault, asserting elastic degradation completes every trial; "
        "repeatable",
    )
    p.add_argument(
        "--progress-deadline",
        type=float,
        default=0.75,
        help="progressDeadlineSeconds used when --hang-trial is given",
    )
    p.add_argument(
        "--compile-deadline",
        type=float,
        default=0.5,
        help="compileDeadlineSeconds used when --compile-hang is given",
    )
    p.add_argument(
        "--drain-grace",
        type=float,
        default=5.0,
        help="drainGraceSeconds for the chaos experiment",
    )
    p.add_argument(
        "--crash-at",
        metavar="SITE[:N]",
        default=None,
        help="hard-crash (os._exit, no drain, no cleanup) a child sweep at "
        "the N-th (default 1st) hit of a registered persistence crash "
        "point, then resume in-process and assert no settled trial is "
        "lost, no observation duplicated, and the retry budget is "
        "monotone; sites: journal.append, journal.snapshot, "
        "suggester.pickle, status.write, checkpoint.manifest, "
        "retry.budget, store.report",
    )
    p.add_argument(
        "--kill-at",
        metavar="SITE[:N]",
        default=None,
        help="like --crash-at but the child dies by SIGKILL "
        "(indistinguishable from the OOM killer)",
    )
    p.add_argument(
        "--kill-loop",
        action="append",
        metavar="LOOP[:N]",
        help="kill the named async engine loop (suggest|schedule|harvest) at "
        "its N-th (default 1st) iteration; the supervisor must classify "
        "the dead thread and restart it without losing or double-settling "
        "any trial; repeatable",
    )
    p.add_argument(
        "--stall-suggester",
        action="append",
        metavar="SECONDS[:CALL]",
        help="wedge the CALL-th (default 1st) get_suggestions call for "
        "SECONDS; past --loop-stall-deadline the deadline-bounded call is "
        "abandoned and the circuit breaker absorbs it instead of freezing "
        "the suggest loop; repeatable",
    )
    p.add_argument(
        "--loop-stall-deadline",
        type=float,
        default=1.0,
        metavar="SECONDS",
        help="loopStallDeadlineSeconds used when --kill-loop or "
        "--stall-suggester is given",
    )
    p.add_argument(
        "--soak",
        type=float,
        default=None,
        metavar="SECONDS",
        help="seeded chaos soak: run scripted fault rounds (loop kills, "
        "suggester stalls, trial faults, speculation) for ~SECONDS, "
        "asserting zero lost/duplicated settlements, restart budgets "
        "respected, and post-fault occupancy recovery; deterministic "
        "per --seed",
    )
    p.set_defaults(fn=cmd_chaos)

    p = sub.add_parser(
        "fsck",
        help="validate and repair an experiment dir (journal, snapshots, fence)",
    )
    p.add_argument(
        "path",
        help="experiment directory to check, e.g. <workdir>/<experiment>",
    )
    p.add_argument(
        "--dry-run",
        action="store_true",
        help="report damage without repairing (nonzero exit if any found)",
    )
    p.set_defaults(fn=cmd_fsck)

    p = sub.add_parser(
        "sim",
        help="virtual-time scale simulation of the orchestrator with fault "
        "injection and invariant gates",
    )
    p.add_argument("scenario", help="scenario YAML path (see docs/operations.md)")
    p.add_argument(
        "--seed",
        type=int,
        default=None,
        help="override the scenario seed (same seed => identical journal)",
    )
    p.add_argument(
        "--workdir",
        default=None,
        help="keep sim artifacts here (default: fresh temp dir, removed "
        "on success)",
    )
    p.add_argument(
        "--json", action="store_true", help="machine-readable verdict"
    )
    p.set_defaults(fn=cmd_sim)

    p = sub.add_parser(
        "cache",
        help="inspect an artifact-cache tier (serialized executables: "
        "program, width, publishing env, loadable here?)",
    )
    p.add_argument(
        "path",
        nargs="?",
        default=None,
        help="artifact dir or compile-cache dir (default: KATIB_ARTIFACT_DIR)",
    )
    p.add_argument(
        "--json",
        action="store_true",
        help="machine-readable inventory",
    )
    p.set_defaults(fn=cmd_cache)

    p = sub.add_parser(
        "db-manager", help="run the native observation-log daemon"
    )
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=6789)
    p.add_argument(
        "--db", default=None,
        help="journal file: acked mutations survive crashes and replay on start",
    )
    p.set_defaults(fn=cmd_db_manager)

    p = sub.add_parser(
        "suggest-server", help="run the suggestion-as-a-service daemon"
    )
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=6789)
    p.add_argument("--token", default=None, help="bearer token (or KATIB_SUGGEST_TOKEN)")
    p.add_argument(
        "--cert-dir", default=None,
        help="serve over TLS with a self-signed bundle rotated in this dir",
    )
    p.set_defaults(fn=cmd_suggest_server)

    p = sub.add_parser("ui", help="serve the REST API + dashboard")
    p.add_argument("--workdir", default="katib_runs")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8080)
    p.add_argument(
        "--token", default=None, help="bearer token for write endpoints (or KATIB_UI_TOKEN)"
    )
    p.add_argument(
        "--cert-dir", default=None,
        help="serve over TLS with a self-signed bundle rotated in this dir",
    )
    p.set_defaults(fn=cmd_ui)

    p = sub.add_parser(
        "doctor",
        help="bounded-time device preflight + environment report "
        "(exit 0 = every device healthy)",
    )
    p.add_argument(
        "--device-timeout",
        default=30.0,
        type=float,
        help="seconds to wait for device enumeration + probes before "
        "declaring the pool wedged",
    )
    p.add_argument(
        "--json",
        action="store_true",
        help="print the machine-readable per-device health report only",
    )
    p.add_argument(
        "--simulate-wedge",
        action="append",
        type=int,
        metavar="N",
        help="treat device id N as wedged (testing the non-zero exit path); "
        "repeatable",
    )
    p.set_defaults(fn=cmd_doctor)

    p = sub.add_parser(
        "lint",
        help="concurrency-discipline + JAX-hazard static analysis "
        "(exit 0 = no findings beyond the committed baseline)",
    )
    p.add_argument(
        "--root", default=".", help="repository root to scan (default: cwd)"
    )
    p.add_argument(
        "--baseline",
        default=os.path.join("artifacts", "lint", "baseline.json"),
        help="accepted-findings fingerprint file (the ratchet)",
    )
    p.add_argument(
        "--update-baseline",
        action="store_true",
        help="rewrite the baseline to exactly the current findings "
        "(prunes stale entries; growing it needs review)",
    )
    p.add_argument(
        "--json",
        action="store_true",
        help="machine-readable report (new/baselined/stale findings)",
    )
    p.set_defaults(fn=cmd_lint)

    args = parser.parse_args(argv)
    try:
        return args.fn(args)
    except BrokenPipeError:
        # output piped into e.g. `head`; suppress the noise and let the
        # interpreter exit without re-raising on stdout flush
        devnull = os.open(os.devnull, os.O_WRONLY)
        os.dup2(devnull, sys.stdout.fileno())
        return 0


if __name__ == "__main__":
    sys.exit(main())
