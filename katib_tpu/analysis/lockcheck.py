"""AST lock-discipline checker.

Walks every class that declares ``_GUARDS = guarded_by(...)`` and flags:

- **LCK001** — a read or write of a guarded attribute (``self.<attr>``)
  outside a lexical ``with self.<lock>:`` scope for the declared lock.
- **LCK002** — a guarded attribute handed into ``Thread(target=...)`` or
  an executor submission (``.submit``/``.map``/``.apply_async``): the
  receiving thread runs outside the lock regardless of what the caller
  holds.

Escape hatches (see :mod:`~katib_tpu.analysis.guards`):
``# lint: holds(<lock>)`` on a ``def`` line declares locks every caller
holds; ``# lint: unguarded-ok(<reason>)`` suppresses a finding on that
line.  ``__init__`` is exempt from LCK001 — construction happens before
the object is published to other threads.

Limits (deliberate — this is a discipline checker, not an escape
analysis): lock scopes are lexical only (``.acquire()``/``.release()``
pairs are invisible), nested functions inherit the lexical held-set even
though a closure could outlive the scope, and aliasing
(``d = self._seen``) is not tracked.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .findings import Finding, hint_for
from .guards import is_suppressed, parse_annotations

_GUARDS_NAME = "_GUARDS"
_THREAD_CTORS = {"Thread", "Timer"}
_SUBMIT_METHODS = {"submit", "map", "apply_async", "run_in_executor"}


def _literal_str_tuple(node: ast.AST) -> Optional[Tuple[str, ...]]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            if not (isinstance(elt, ast.Constant) and isinstance(elt.value, str)):
                return None
            out.append(elt.value)
        return tuple(out)
    return None


def extract_guards(cls: ast.ClassDef) -> Dict[str, str]:
    """Read the ``_GUARDS = guarded_by(...)`` declaration literally."""
    for stmt in cls.body:
        targets = []
        value = None
        if isinstance(stmt, ast.Assign):
            targets, value = stmt.targets, stmt.value
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            targets, value = [stmt.target], stmt.value
        if not any(isinstance(t, ast.Name) and t.id == _GUARDS_NAME for t in targets):
            continue
        if not isinstance(value, ast.Call):
            continue
        func = value.func
        name = func.id if isinstance(func, ast.Name) else (
            func.attr if isinstance(func, ast.Attribute) else None
        )
        if name != "guarded_by":
            continue
        mapping: Dict[str, str] = {}
        for kw in value.keywords:
            if kw.arg is None:
                continue
            attrs = _literal_str_tuple(kw.value)
            if attrs is None:
                continue
            for attr in attrs:
                mapping[attr] = kw.arg
        return mapping
    return {}


def _self_attr(node: ast.AST) -> Optional[str]:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


class _MethodScanner:
    def __init__(
        self,
        path: str,
        cls_name: str,
        guards: Dict[str, str],
        suppressed: Dict[int, str],
        holds: Dict[int, Tuple[str, ...]],
    ) -> None:
        self.path = path
        self.cls_name = cls_name
        self.guards = guards
        self.lock_names = set(guards.values())
        self.suppressed = suppressed
        self.holds = holds
        self.findings: List[Finding] = []
        self._escaped: set = set()  # nodes already reported as LCK002

    # -- entry ----------------------------------------------------------
    def scan(self, fn: ast.AST) -> None:
        assert isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef))
        self.symbol = f"{self.cls_name}.{fn.name}"
        self.check_reads = fn.name != "__init__"
        held: Set[str] = set()
        for ln in range(fn.lineno, fn.body[0].lineno + 1):
            held.update(self.holds.get(ln, ()))
        for stmt in fn.body:
            self._visit(stmt, held)

    # -- recursion ------------------------------------------------------
    def _visit(self, node: ast.AST, held: Set[str]) -> None:
        if isinstance(node, (ast.With, ast.AsyncWith)):
            acquired: Set[str] = set()
            for item in node.items:
                attr = _self_attr(item.context_expr)
                if attr is not None and (attr in self.lock_names or attr.endswith("lock")):
                    acquired.add(attr)
                else:
                    self._visit(item.context_expr, held)
                if item.optional_vars is not None:
                    self._visit(item.optional_vars, held)
            for stmt in node.body:
                self._visit(stmt, held | acquired)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # nested helper: inherits the lexical held-set plus its own
            # holds() declaration (documented limitation for escaping closures)
            inner = set(held)
            for ln in range(node.lineno, node.body[0].lineno + 1):
                inner.update(self.holds.get(ln, ()))
            for stmt in node.body:
                self._visit(stmt, inner)
            return
        if isinstance(node, ast.Call):
            self._check_escape(node, held)
        attr = _self_attr(node)
        if (
            attr is not None
            and self.check_reads
            and attr in self.guards
            and id(node) not in self._escaped
        ):
            lock = self.guards[attr]
            if lock not in held and not is_suppressed(
                self.suppressed, node.lineno, getattr(node, "end_lineno", None)
            ):
                self.findings.append(
                    Finding(
                        code="LCK001",
                        path=self.path,
                        line=node.lineno,
                        symbol=self.symbol,
                        detail=attr,
                        message=(
                            f"access to self.{attr} (guarded by {lock}) "
                            f"without holding {lock}"
                        ),
                        hint=hint_for("LCK001"),
                    )
                )
        for child in ast.iter_child_nodes(node):
            self._visit(child, held)

    # -- cross-thread escape -------------------------------------------
    def _check_escape(self, call: ast.Call, held: Set[str]) -> None:
        func = call.func
        is_thread = (isinstance(func, ast.Name) and func.id in _THREAD_CTORS) or (
            isinstance(func, ast.Attribute) and func.attr in _THREAD_CTORS
        )
        is_submit = isinstance(func, ast.Attribute) and func.attr in _SUBMIT_METHODS
        if not (is_thread or is_submit):
            return
        payload = list(call.args) + [kw.value for kw in call.keywords]
        for arg in payload:
            for sub in ast.walk(arg):
                attr = _self_attr(sub)
                if attr is None or attr not in self.guards:
                    continue
                self._escaped.add(id(sub))
                if is_suppressed(
                    self.suppressed, sub.lineno, getattr(sub, "end_lineno", None)
                ) or is_suppressed(
                    self.suppressed, call.lineno, getattr(call, "end_lineno", None)
                ):
                    continue
                self.findings.append(
                    Finding(
                        code="LCK002",
                        path=self.path,
                        line=sub.lineno,
                        symbol=self.symbol,
                        detail=attr,
                        message=(
                            f"self.{attr} (guarded by {self.guards[attr]}) handed to "
                            "another thread — the receiver runs outside the lock"
                        ),
                        hint=hint_for("LCK002"),
                    )
                )


def check_source(source: str, path: str) -> List[Finding]:
    """Run the lock-discipline pass over one module's source."""
    tree = ast.parse(source, filename=path)
    suppressed, holds = parse_annotations(source)
    findings: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        guards = extract_guards(node)
        if not guards:
            continue
        scanner = _MethodScanner(path, node.name, guards, suppressed, holds)
        for stmt in node.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scanner.scan(stmt)
        findings.extend(scanner.findings)
    return findings


def check_file(filename: str, relpath: Optional[str] = None) -> List[Finding]:
    with open(filename, "r", encoding="utf-8") as f:
        source = f.read()
    return check_source(source, relpath or filename)
