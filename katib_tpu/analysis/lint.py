"""``katib-tpu lint`` driver: run the checkers, ratchet against a baseline.

The baseline file (``artifacts/lint/baseline.json``) holds fingerprints
of *accepted* findings.  ``run_lint`` fails only on findings whose
fingerprint is not in the baseline, so existing debt is ratcheted down
(a fixed finding's stale fingerprint is reported and pruned by
``--update-baseline``), never flag-dayed — and never silently grown.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

from . import jaxcheck, lockcheck
from .findings import Finding

# lock-discipline pass: any module may declare guards; scan the package.
DEFAULT_LOCK_PATHS = ("katib_tpu",)
# JAX-hazard pass: the dispatch-sensitive layers named by the discipline
# (parallel, nas/darts+enas, ops, trial/model code, the runner).
DEFAULT_JAX_PATHS = (
    "katib_tpu/parallel",
    "katib_tpu/nas",
    "katib_tpu/ops",
    "katib_tpu/models",
    "katib_tpu/runner",
)
# timing-boundary rule (JAX105) only applies to benchmark entry points.
DEFAULT_TIMING_FILES = ("bench.py",)

BASELINE_DEFAULT = os.path.join("artifacts", "lint", "baseline.json")


@dataclass
class LintReport:
    findings: List[Finding] = field(default_factory=list)
    new: List[Finding] = field(default_factory=list)
    baselined: List[Finding] = field(default_factory=list)
    stale_baseline: List[str] = field(default_factory=list)
    files_scanned: int = 0

    @property
    def exit_code(self) -> int:
        return 1 if self.new else 0

    def to_json(self) -> dict:
        return {
            "files_scanned": self.files_scanned,
            "new": [f.__dict__ for f in self.new],
            "baselined": [f.__dict__ for f in self.baselined],
            "stale_baseline": list(self.stale_baseline),
        }


def _iter_py(root: str, rel: str) -> List[str]:
    """Repo-relative .py paths under *rel* (a file or a directory)."""
    full = os.path.join(root, rel)
    if os.path.isfile(full):
        return [rel] if rel.endswith(".py") else []
    out: List[str] = []
    for dirpath, dirnames, filenames in os.walk(full):
        dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                out.append(
                    os.path.relpath(os.path.join(dirpath, fn), root).replace(os.sep, "/")
                )
    return out


def collect_findings(
    root: str = ".",
    lock_paths: Sequence[str] = DEFAULT_LOCK_PATHS,
    jax_paths: Sequence[str] = DEFAULT_JAX_PATHS,
    timing_files: Sequence[str] = DEFAULT_TIMING_FILES,
) -> tuple:
    """Run both AST passes; returns (findings, files_scanned)."""
    findings: List[Finding] = []
    seen_files = set()

    lock_files = []
    for rel in lock_paths:
        lock_files.extend(_iter_py(root, rel))
    for rel in lock_files:
        seen_files.add(rel)
        findings.extend(lockcheck.check_file(os.path.join(root, rel), rel))

    jax_files = []
    for rel in jax_paths:
        jax_files.extend(_iter_py(root, rel))
    for rel in jax_files:
        seen_files.add(rel)
        findings.extend(jaxcheck.check_file(os.path.join(root, rel), rel))

    for rel in timing_files:
        if os.path.isfile(os.path.join(root, rel)):
            seen_files.add(rel)
            findings.extend(
                jaxcheck.check_file(os.path.join(root, rel), rel, timing=True)
            )

    findings.sort(key=lambda f: (f.path, f.line, f.code))
    return findings, len(seen_files)


def load_baseline(path: Optional[str]) -> List[str]:
    if not path or not os.path.isfile(path):
        return []
    with open(path, "r", encoding="utf-8") as f:
        doc = json.load(f)
    return list(doc.get("findings", []))


def write_baseline(path: str, findings: Sequence[Finding]) -> None:
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    doc = {
        "version": 1,
        "comment": (
            "Accepted lint debt, by fingerprint (code:path:symbol:detail). "
            "The ratchet: katib-tpu lint fails on findings NOT in this list. "
            "Only shrink it; grow it only with a reviewed justification."
        ),
        "findings": sorted(f.fingerprint for f in findings),
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=2)
        f.write("\n")


def run_lint(
    root: str = ".",
    baseline_path: Optional[str] = None,
    lock_paths: Sequence[str] = DEFAULT_LOCK_PATHS,
    jax_paths: Sequence[str] = DEFAULT_JAX_PATHS,
    timing_files: Sequence[str] = DEFAULT_TIMING_FILES,
) -> LintReport:
    findings, nfiles = collect_findings(root, lock_paths, jax_paths, timing_files)
    accepted = set(load_baseline(baseline_path))
    report = LintReport(findings=findings, files_scanned=nfiles)
    found_fps: Dict[str, bool] = {}
    for f in findings:
        found_fps[f.fingerprint] = True
        if f.fingerprint in accepted:
            report.baselined.append(f)
        else:
            report.new.append(f)
    report.stale_baseline = sorted(fp for fp in accepted if fp not in found_fps)
    return report
