"""Finding record shared by the AST checkers and the lint driver."""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class Finding:
    """One lint finding.

    ``fingerprint`` deliberately excludes the line number so baseline
    entries survive unrelated edits; ``detail`` disambiguates multiple
    findings of the same code inside one symbol (usually the attribute
    or callee name involved).
    """

    code: str
    path: str  # repo-relative, forward slashes
    line: int
    symbol: str  # Class.method or function the finding is in
    detail: str  # attribute / callee the finding is about
    message: str
    hint: str = ""

    @property
    def fingerprint(self) -> str:
        return f"{self.code}:{self.path}:{self.symbol}:{self.detail}"

    def render(self) -> str:
        out = f"{self.path}:{self.line}: {self.code} [{self.symbol}] {self.message}"
        if self.hint:
            out += f"\n    hint: {self.hint}"
        return out


# hazard-code registry: code -> (title, default fix hint) -----------------
CODES = {
    "LCK001": (
        "unguarded access to a guarded attribute",
        "wrap the access in `with self.<lock>:`, annotate the def with "
        "`# lint: holds(<lock>)` if every caller holds it, or suppress with "
        "`# lint: unguarded-ok(<reason>)`",
    ),
    "LCK002": (
        "guarded attribute escapes to another thread",
        "pass an immutable snapshot (or the lock itself) into the thread/executor "
        "instead of the guarded object",
    ),
    "JAX101": (
        "host sync inside a hot (scan/jit-loop) body",
        "keep the body device-pure; fetch results once after the loop "
        "(`float()`/`.item()`/`np.asarray` force a device round-trip per step)",
    ),
    "JAX102": (
        "jax.jit constructed inside a loop body",
        "hoist the jit() call out of the loop (each call builds a fresh cache entry "
        "and retraces)",
    ),
    "JAX103": (
        "non-hashable operand passed at a static_argnums position",
        "pass a hashable value (tuple, int, frozen dataclass) — lists/dicts/sets "
        "raise or silently retrace per call",
    ),
    "JAX104": (
        "donated buffer reused after donate_argnums call",
        "rebind the name from the call's result; the donated input buffer is "
        "invalidated by XLA and reads return garbage on TPU",
    ),
    "JAX105": (
        "timing boundary without a device sync",
        "call jax.block_until_ready(...) (or force a host fetch) before stopping "
        "the timer; otherwise the number measures dispatch, not device time",
    ),
}


def hint_for(code: str) -> str:
    return CODES.get(code, ("", ""))[1]
