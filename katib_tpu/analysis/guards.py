"""The ``guarded_by`` annotation convention for lock discipline.

A class declares which lock guards which attributes with a single class
attribute the AST checker can read without importing the module::

    class AsyncLoops:
        _GUARDS = guarded_by(
            _queue_lock=("_ready", "_packing", "_pack_ts"),
            _futures_lock=("futures", "_fut_meta"),
        )

The checker (:mod:`~katib_tpu.analysis.lockcheck`) then flags every read
or write of a guarded attribute outside a lexical ``with self.<lock>:``
scope.  Two comment annotations refine it:

- ``# lint: unguarded-ok(<reason>)`` on the flagged line suppresses the
  finding (any lint code, not just lock codes); the reason is mandatory.
- ``# lint: holds(_lock_a[, _lock_b])`` on a ``def`` line declares that
  every caller enters the function with those locks held (the
  "called under X lock" helper pattern).

At runtime ``guarded_by`` returns the ``{attr: lock}`` mapping, so the
declaration doubles as machine-readable documentation.
"""

from __future__ import annotations

import re
from typing import Dict, Iterable, Tuple, Union

AttrSpec = Union[str, Iterable[str]]

# comment grammar shared by the checkers --------------------------------
_SUPPRESS_RE = re.compile(r"#\s*lint:\s*unguarded-ok\(([^)]+)\)")
_HOLDS_RE = re.compile(r"#\s*lint:\s*holds\(([^)]+)\)")


def guarded_by(**locks: AttrSpec) -> Dict[str, str]:
    """Map each named attribute to the lock that guards it.

    Keyword names are lock attribute names (``_queue_lock``); values are
    an attribute name or an iterable of attribute names.  An attribute
    may be guarded by exactly one lock.
    """
    mapping: Dict[str, str] = {}
    for lock, attrs in locks.items():
        if isinstance(attrs, str):
            attrs = (attrs,)
        attrs = tuple(attrs)
        if not attrs:
            raise ValueError(
                f"guarded_by({lock}=...): a lock must guard at least one attribute"
            )
        for attr in attrs:
            if not isinstance(attr, str) or not attr:
                raise TypeError(f"guarded_by({lock}=...): attribute names must be non-empty strings")
            if attr in mapping and mapping[attr] != lock:
                raise ValueError(
                    f"attribute {attr!r} declared guarded by both {mapping[attr]!r} and {lock!r}"
                )
            mapping[attr] = lock
    return mapping


def parse_annotations(source: str) -> Tuple[Dict[int, str], Dict[int, Tuple[str, ...]]]:
    """Extract lint comment annotations from *source*.

    Returns ``(suppressed, holds)`` where ``suppressed`` maps a 1-based
    line number to the suppression reason and ``holds`` maps a ``def``
    line number to the tuple of lock names the caller is declared to
    hold.
    """
    suppressed: Dict[int, str] = {}
    holds: Dict[int, Tuple[str, ...]] = {}
    for lineno, line in enumerate(source.splitlines(), start=1):
        m = _SUPPRESS_RE.search(line)
        if m and m.group(1).strip():
            suppressed[lineno] = m.group(1).strip()
        m = _HOLDS_RE.search(line)
        if m:
            names = tuple(n.strip() for n in m.group(1).split(",") if n.strip())
            if names:
                holds[lineno] = names
    return suppressed, holds


def is_suppressed(suppressed: Dict[int, str], lineno: int, end_lineno: int = None) -> bool:
    """True when any line of the node's span carries a suppression."""
    end = end_lineno if end_lineno is not None else lineno
    return any(ln in suppressed for ln in range(lineno, end + 1))
