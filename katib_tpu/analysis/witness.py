"""Runtime lock-order witness (``KATIB_LOCK_WITNESS=1``).

The engine's locks are created through :func:`make_lock`.  By default
that returns a plain ``threading.Lock`` — zero overhead, the witness is
compiled out.  With ``KATIB_LOCK_WITNESS=1`` in the environment at lock
creation time it returns a :class:`WitnessLock` instead, which records
the process-wide lock-acquisition graph: an edge ``A -> B`` means some
thread acquired ``B`` while holding ``A``.  Acquiring a lock that would
close a cycle in that graph is a *potential lock-order inversion* — two
threads interleaving those paths can deadlock — and the witness turns it
into a hard failure (:class:`LockOrderInversion`) at the acquisition
site, before the lock is taken.

Nodes are lock *roles* (the name passed to ``make_lock``), not
instances: lock-order discipline is a property of roles ("async.state
before async.queue before async.futures"), and per-instance locks of the
same role (every ``_Metric._lock``) share one node.  Consequences:

- acquiring a role already held anywhere on the thread's stack records
  no edge (instance-level nesting within a role is indistinguishable
  from re-acquisition, so it cannot be ordered);
- the witness therefore does not detect single-role self-deadlock.

The chaos soak prints :func:`witness_summary` and fails on any recorded
inversion (``orchestrator/soak.py``); tests exercise the cycle detector
directly (``tests/test_lint.py``).
"""

from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional, Tuple

ENV_VAR = "KATIB_LOCK_WITNESS"


class LockOrderInversion(AssertionError):
    """Acquiring this lock would close a cycle in the acquisition graph."""


def witness_enabled() -> bool:
    return os.environ.get(ENV_VAR, "").strip() not in ("", "0", "false", "no")


class _Graph:
    """Process-global acquisition graph.  All mutation under one mutex."""

    def __init__(self) -> None:
        self._mu = threading.Lock()
        # role -> {successor role -> acquisition count}
        self.edges: Dict[str, Dict[str, int]] = {}
        self.acquires: Dict[str, int] = {}
        self.inversions: List[Tuple[str, ...]] = []

    def note_acquire(self, name: str) -> None:
        with self._mu:
            self.acquires[name] = self.acquires.get(name, 0) + 1

    def note_edge(self, held: str, acquiring: str) -> Optional[Tuple[str, ...]]:
        """Record ``held -> acquiring``; return the cycle path if one forms."""
        with self._mu:
            cycle = self._path(acquiring, held)
            succ = self.edges.setdefault(held, {})
            succ[acquiring] = succ.get(acquiring, 0) + 1
            if cycle is not None:
                path = tuple(cycle) + (acquiring,)
                self.inversions.append(path)
                return path
            return None

    def _path(self, src: str, dst: str) -> Optional[List[str]]:
        """DFS path src -> dst over recorded edges (None if unreachable)."""
        if src == dst:
            return [src]
        stack: List[Tuple[str, List[str]]] = [(src, [src])]
        seen = {src}
        while stack:
            node, path = stack.pop()
            for nxt in self.edges.get(node, ()):
                if nxt == dst:
                    return path + [nxt]
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + [nxt]))
        return None

    def cycles(self) -> List[Tuple[str, ...]]:
        """All inversions recorded at acquire time plus any residual graph
        cycle (belt and braces: the graph is checked even if an inversion
        exception was swallowed by a retry path)."""
        with self._mu:
            found = list(self.inversions)
            # iterative DFS cycle scan over the whole graph
            WHITE, GREY, BLACK = 0, 1, 2
            color = {n: WHITE for n in set(self.edges) | {v for s in self.edges.values() for v in s}}
            for root in list(color):
                if color[root] != WHITE:
                    continue
                stack: List[Tuple[str, List[str]]] = [(root, [root])]
                while stack:
                    node, path = stack.pop()
                    if node == "\x00pop":
                        color[path[-1]] = BLACK
                        continue
                    if color[node] == BLACK:
                        continue
                    color[node] = GREY
                    stack.append(("\x00pop", path))
                    for nxt in self.edges.get(node, ()):
                        if color.get(nxt, WHITE) == GREY and nxt in path:
                            cyc = tuple(path[path.index(nxt):]) + (nxt,)
                            if cyc not in found:
                                found.append(cyc)
                        elif color.get(nxt, WHITE) == WHITE:
                            stack.append((nxt, path + [nxt]))
            return found

    def snapshot(self) -> dict:
        with self._mu:
            return {
                "acquires": dict(self.acquires),
                "edges": [
                    (u, v, n)
                    for u, succ in sorted(self.edges.items())
                    for v, n in sorted(succ.items())
                ],
                "inversions": [list(p) for p in self.inversions],
            }

    def reset(self) -> None:
        with self._mu:
            self.edges.clear()
            self.acquires.clear()
            self.inversions.clear()


_GRAPH = _Graph()
_HELD = threading.local()


def _stack() -> List[str]:
    stack = getattr(_HELD, "stack", None)
    if stack is None:
        stack = _HELD.stack = []
    return stack


class WitnessLock:
    """Drop-in ``threading.Lock`` wrapper that witnesses acquisition order."""

    __slots__ = ("name", "_lk")

    def __init__(self, name: str, lk=None) -> None:
        self.name = name
        self._lk = lk if lk is not None else threading.Lock()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        stack = _stack()
        if stack and self.name not in stack:
            cycle = _GRAPH.note_edge(stack[-1], self.name)
            if cycle is not None:
                raise LockOrderInversion(
                    "lock-order inversion: acquiring %r while holding %r closes the cycle %s"
                    % (self.name, stack[-1], " -> ".join(cycle))
                )
        ok = self._lk.acquire(blocking, timeout)
        if ok:
            _GRAPH.note_acquire(self.name)
            stack.append(self.name)
        return ok

    def release(self) -> None:
        stack = _stack()
        # pop the most recent occurrence (release order may not be LIFO)
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] == self.name:
                del stack[i]
                break
        self._lk.release()

    def locked(self) -> bool:
        return self._lk.locked()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"<WitnessLock {self.name!r} {'locked' if self.locked() else 'unlocked'}>"


def make_lock(name: str, *, factory=threading.Lock):
    """Create the lock for role *name*.

    Plain ``factory()`` (default ``threading.Lock``) unless
    ``KATIB_LOCK_WITNESS=1`` was set when the lock is created — the
    witness is opt-in and carries zero cost when disabled.
    """
    if not witness_enabled():
        return factory()
    return WitnessLock(name, factory())


def witness_reset() -> None:
    """Clear the acquisition graph (tests / between soak rounds)."""
    _GRAPH.reset()


def witness_cycles() -> List[Tuple[str, ...]]:
    return _GRAPH.cycles()


def witness_summary() -> dict:
    """Graph snapshot: per-role acquire counts, edges, recorded inversions."""
    return _GRAPH.snapshot()


def format_summary() -> str:
    snap = _GRAPH.snapshot()
    lines = ["lock-order witness: acquisition graph"]
    if not snap["acquires"]:
        lines.append("  (no witnessed acquisitions — was KATIB_LOCK_WITNESS=1 set?)")
        return "\n".join(lines)
    for name, n in sorted(snap["acquires"].items()):
        lines.append(f"  {name}: {n} acquisitions")
    if snap["edges"]:
        lines.append("  observed order (held -> acquired):")
        for u, v, n in snap["edges"]:
            lines.append(f"    {u} -> {v}  (x{n})")
    cycles = _GRAPH.cycles()
    if cycles:
        lines.append("  INVERSIONS DETECTED:")
        for path in cycles:
            lines.append("    " + " -> ".join(path))
    else:
        lines.append("  no inversions: the observed order is acyclic")
    return "\n".join(lines)
