"""AST JAX-hazard checker.

Flags the dispatch-purity hazards that silently eat the fused-loop win
(ROADMAP item 1):

- **JAX101** host sync (`float()`/`.item()`/`np.asarray`/`device_get`)
  inside a hot body — a `lax.scan`/`fori_loop`/`while_loop` body
  function, or a loop inside a jit-decorated function.  Each one is a
  device round-trip per step.
- **JAX102** ``jax.jit``/``pjit`` constructed inside a ``for``/``while``
  body — a fresh cache entry and retrace per iteration.
- **JAX103** a non-hashable literal (list/dict/set/comprehension) passed
  at a ``static_argnums`` position of a jit-wrapped callable.
- **JAX104** a buffer reused after being donated: ``g = jax.jit(f,
  donate_argnums=(0,))``; ``out = g(x)``; any later read of ``x``
  before rebinding.  XLA invalidates the input buffer — reads return
  garbage on TPU and only *happen* to work on CPU.
- **JAX105** (bench files only) a ``time.perf_counter()`` delta whose
  timed region contains real work but no device sync
  (``block_until_ready`` / host fetch) — the number measures dispatch,
  not device time.  See the 93x-inflation note in ``bench.py``.

All checks are intraprocedural and name-based (no imports, no type
inference); ``# lint: unguarded-ok(<reason>)`` suppresses any finding on
its line.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Tuple

from .findings import Finding, hint_for
from .guards import is_suppressed, parse_annotations

_JIT_NAMES = {"jit", "pjit"}
_SCAN_TAILS = {"scan"}  # lax.scan / jax.lax.scan / bare scan
_NP_MODULES = {"np", "numpy", "onp"}
_HOST_SYNC_ATTRS = {"item", "tolist"}
_SYNC_CALL_MARKERS = ("block_until_ready", "device_get", "barrier")
_TRIVIAL_CALLS = {
    "perf_counter", "monotonic", "time", "sleep", "print", "len", "range",
    "enumerate", "zip", "min", "max", "sorted", "abs", "round", "isinstance",
    "getattr", "setattr", "str", "repr", "format", "append", "extend", "join",
    "items", "keys", "values", "get", "pop", "list", "dict", "tuple", "set",
    "sum", "int", "bool", "strip", "split", "write", "flush", "debug", "info",
    "warning", "error",
}


def _callee_tail(func: ast.AST) -> Optional[str]:
    """Last dotted component of a call target (``jax.lax.scan`` -> ``scan``)."""
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


def _is_jit_call(call: ast.Call) -> bool:
    return _callee_tail(call.func) in _JIT_NAMES


def _int_tuple(node: ast.AST) -> Tuple[int, ...]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, int):
                out.append(elt.value)
        return tuple(out)
    return ()


def _jit_info(call: ast.Call) -> Dict[str, Tuple[int, ...]]:
    info = {"static": (), "donate": ()}
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            info["static"] = _int_tuple(kw.value)
        elif kw.arg == "donate_argnums":
            info["donate"] = _int_tuple(kw.value)
    return info


def _walk_scope(stmts: List[ast.stmt]) -> Iterator[ast.AST]:
    """Walk statements without descending into nested def/class/lambda."""
    stack: List[ast.AST] = list(stmts)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef, ast.Lambda)
        ):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _scopes(tree: ast.Module) -> List[Tuple[str, ast.AST, List[ast.stmt]]]:
    """Every (symbol, node, body) scope: the module plus each function."""
    out: List[Tuple[str, ast.AST, List[ast.stmt]]] = [("<module>", tree, tree.body)]

    def rec(node: ast.AST, prefix: str) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                name = f"{prefix}.{child.name}" if prefix else child.name
                out.append((name, child, child.body))
                rec(child, name)
            elif isinstance(child, ast.ClassDef):
                rec(child, f"{prefix}.{child.name}" if prefix else child.name)
            else:
                rec(child, prefix)

    rec(tree, "")
    return out


class _Checker:
    def __init__(self, source: str, path: str, timing: bool) -> None:
        self.tree = ast.parse(source, filename=path)
        self.path = path
        self.timing = timing
        self.suppressed, _ = parse_annotations(source)
        self.findings: List[Finding] = []
        self.scopes = _scopes(self.tree)
        self.defs_by_name: Dict[str, List[ast.AST]] = {}
        self.symbol_of: Dict[int, str] = {}
        for sym, node, _body in self.scopes:
            self.symbol_of[id(node)] = sym
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.defs_by_name.setdefault(node.name, []).append(node)
        # name -> static/donate positions, from `g = jax.jit(f, ...)` anywhere
        self.jits: Dict[str, Dict[str, Tuple[int, ...]]] = {}
        for node in ast.walk(self.tree):
            if (
                isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)
                and _is_jit_call(node.value)
            ):
                info = _jit_info(node.value)
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        self.jits[t.id] = info

    # ------------------------------------------------------------------
    def run(self) -> List[Finding]:
        self._check_host_sync()
        self._check_jit_in_loop()
        self._check_static_args()
        self._check_donation()
        if self.timing:
            self._check_timing()
        # hot regions can nest (a loop inside a loop inside a jitted fn):
        # keep one finding per (code, line, detail)
        unique: Dict[Tuple[str, int, str], Finding] = {}
        for f in self.findings:
            unique.setdefault((f.code, f.line, f.detail), f)
        return list(unique.values())

    def _emit(self, code: str, node: ast.AST, symbol: str, detail: str, message: str) -> None:
        if is_suppressed(self.suppressed, node.lineno, getattr(node, "end_lineno", None)):
            return
        self.findings.append(
            Finding(
                code=code,
                path=self.path,
                line=node.lineno,
                symbol=symbol,
                detail=detail,
                message=message,
                hint=hint_for(code),
            )
        )

    # -- JAX101 ---------------------------------------------------------
    def _hot_bodies(self) -> List[Tuple[str, List[ast.stmt], str]]:
        """(symbol, stmts, why) regions where a host sync is a hazard."""
        hot: List[Tuple[str, List[ast.stmt], str]] = []
        seen: set = set()

        def mark(fn_node: ast.AST, why: str) -> None:
            if id(fn_node) in seen:
                return
            seen.add(id(fn_node))
            if isinstance(fn_node, ast.Lambda):
                hot.append(("<lambda>", [ast.Expr(value=fn_node.body)], why))
            else:
                sym = self.symbol_of.get(id(fn_node), getattr(fn_node, "name", "?"))
                hot.append((sym, fn_node.body, why))

        def mark_arg(arg: ast.AST, why: str) -> None:
            if isinstance(arg, ast.Lambda):
                mark(arg, why)
            elif isinstance(arg, ast.Name):
                for fn_node in self.defs_by_name.get(arg.id, ()):
                    mark(fn_node, why)

        for node in ast.walk(self.tree):
            if isinstance(node, ast.Call):
                tail = _callee_tail(node.func)
                if tail in _SCAN_TAILS and node.args:
                    mark_arg(node.args[0], "lax.scan body")
                elif tail == "fori_loop" and len(node.args) >= 3:
                    mark_arg(node.args[2], "fori_loop body")
                elif tail == "while_loop" and len(node.args) >= 2:
                    mark_arg(node.args[0], "while_loop cond")
                    mark_arg(node.args[1], "while_loop body")
        # loops inside jit-decorated functions
        for sym, node, body in self.scopes:
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if not any(self._is_jit_decorator(d) for d in node.decorator_list):
                continue
            for sub in _walk_scope(body):
                if isinstance(sub, (ast.For, ast.While)):
                    hot.append((sym, sub.body + sub.orelse, "loop in jitted fn"))
        return hot

    @staticmethod
    def _is_jit_decorator(dec: ast.AST) -> bool:
        if _callee_tail(dec) in _JIT_NAMES:
            return True
        if isinstance(dec, ast.Call):
            if _callee_tail(dec.func) in _JIT_NAMES:
                return True
            if _callee_tail(dec.func) == "partial" and dec.args:
                return _callee_tail(dec.args[0]) in _JIT_NAMES
        return False

    def _check_host_sync(self) -> None:
        for sym, stmts, why in self._hot_bodies():
            for node in _walk_scope(stmts):
                if not isinstance(node, ast.Call):
                    continue
                reason = self._host_sync_kind(node)
                if reason:
                    self._emit(
                        "JAX101", node, sym, reason,
                        f"{reason} inside a {why} forces a device round-trip per step",
                    )

    @staticmethod
    def _host_sync_kind(call: ast.Call) -> Optional[str]:
        func = call.func
        if isinstance(func, ast.Name) and func.id in ("float", "int", "bool"):
            if call.args and not isinstance(call.args[0], ast.Constant):
                return f"{func.id}()"
        if isinstance(func, ast.Attribute):
            if func.attr in _HOST_SYNC_ATTRS:
                return f".{func.attr}()"
            if func.attr in ("asarray", "array") and isinstance(func.value, ast.Name):
                if func.value.id in _NP_MODULES:
                    return f"{func.value.id}.{func.attr}()"
            if func.attr == "device_get":
                return "device_get()"
        return None

    # -- JAX102 ---------------------------------------------------------
    def _check_jit_in_loop(self) -> None:
        for sym, _node, body in self.scopes:
            for sub in _walk_scope(body):
                if not isinstance(sub, (ast.For, ast.While)):
                    continue
                for inner in _walk_scope(sub.body + sub.orelse):
                    if isinstance(inner, ast.Call) and _is_jit_call(inner):
                        self._emit(
                            "JAX102", inner, sym, _callee_tail(inner.func) or "jit",
                            "jit() constructed inside a loop body retraces every iteration",
                        )

    # -- JAX103 ---------------------------------------------------------
    _NONHASHABLE = (
        ast.List, ast.Dict, ast.Set, ast.ListComp, ast.DictComp, ast.SetComp,
        ast.GeneratorExp,
    )

    def _check_static_args(self) -> None:
        for sym, _node, body in self.scopes:
            for sub in _walk_scope(body):
                if not isinstance(sub, ast.Call):
                    continue
                static: Tuple[int, ...] = ()
                callee = "?"
                if isinstance(sub.func, ast.Name) and sub.func.id in self.jits:
                    static = self.jits[sub.func.id]["static"]
                    callee = sub.func.id
                elif isinstance(sub.func, ast.Call) and _is_jit_call(sub.func):
                    static = _jit_info(sub.func)["static"]
                    callee = "jit(...)"
                for idx in static:
                    if idx < len(sub.args) and isinstance(sub.args[idx], self._NONHASHABLE):
                        self._emit(
                            "JAX103", sub.args[idx], sym, f"{callee}[{idx}]",
                            f"non-hashable literal at static_argnums position {idx} "
                            f"of {callee}",
                        )

    # -- JAX104 ---------------------------------------------------------
    def _check_donation(self) -> None:
        donators = {n: i["donate"] for n, i in self.jits.items() if i["donate"]}
        if not donators:
            return
        for sym, _node, body in self.scopes:
            self._scan_donation(body, donators, sym)

    def _scan_donation(self, stmts, donators, sym) -> None:
        dead: Dict[str, int] = {}

        def revive(target: ast.AST) -> None:
            if isinstance(target, ast.Name):
                dead.pop(target.id, None)
            elif isinstance(target, (ast.Tuple, ast.List)):
                for elt in target.elts:
                    revive(elt)
            elif isinstance(target, ast.Starred):
                revive(target.value)

        def expr(node: ast.AST) -> None:
            if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
                if node.id in dead:
                    self._emit(
                        "JAX104", node, sym, node.id,
                        f"{node.id!r} read after being donated on line {dead[node.id]}",
                    )
                    dead.pop(node.id, None)
                return
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef, ast.Lambda)):
                return
            if isinstance(node, ast.Call):
                expr(node.func)
                for a in node.args:
                    expr(a)
                for kw in node.keywords:
                    expr(kw.value)
                if isinstance(node.func, ast.Name) and node.func.id in donators:
                    for idx in donators[node.func.id]:
                        if idx < len(node.args) and isinstance(node.args[idx], ast.Name):
                            dead[node.args[idx].id] = node.lineno
                return
            for child in ast.iter_child_nodes(node):
                expr(child)

        def stmt(node: ast.stmt) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                return  # nested scopes are scanned on their own
            if isinstance(node, ast.Assign):
                expr(node.value)
                for t in node.targets:
                    revive(t)
            elif isinstance(node, ast.AugAssign):
                expr(node.value)
                expr(node.target)
                revive(node.target)
            elif isinstance(node, ast.AnnAssign):
                if node.value is not None:
                    expr(node.value)
                revive(node.target)
            elif isinstance(node, ast.For):
                expr(node.iter)
                revive(node.target)
                for s in node.body + node.orelse:
                    stmt(s)
            elif isinstance(node, (ast.While, ast.If)):
                expr(node.test)
                for s in node.body + node.orelse:
                    stmt(s)
            elif isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    expr(item.context_expr)
                    if item.optional_vars is not None:
                        revive(item.optional_vars)
                for s in node.body:
                    stmt(s)
            elif isinstance(node, ast.Try):
                for s in node.body + node.orelse + node.finalbody:
                    stmt(s)
                for handler in node.handlers:
                    for s in handler.body:
                        stmt(s)
            else:
                for child in ast.iter_child_nodes(node):
                    expr(child)

        for s in stmts:
            stmt(s)

    # -- JAX105 ---------------------------------------------------------
    def _check_timing(self) -> None:
        for sym, _node, body in self.scopes:
            starts: List[Tuple[str, int]] = []  # (timer name, line)
            stops: List[Tuple[str, int, ast.AST]] = []
            calls: List[Tuple[int, str]] = []  # (line, kind)
            for node in _walk_scope(body):
                if isinstance(node, ast.Assign) and self._is_clock_call(node.value):
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            starts.append((t.id, node.lineno))
                elif (
                    isinstance(node, ast.BinOp)
                    and isinstance(node.op, ast.Sub)
                    and self._is_clock_call(node.left)
                    and isinstance(node.right, ast.Name)
                ):
                    stops.append((node.right.id, node.lineno, node))
                if isinstance(node, ast.Call):
                    calls.append((node.lineno, self._call_kind(node)))
            for timer, stop_line, stop_node in stops:
                cands = [ln for (t, ln) in starts if t == timer and ln < stop_line]
                if not cands:
                    continue
                start_line = max(cands)
                region = [
                    kind for (ln, kind) in calls if start_line < ln <= stop_line
                ]
                if "work" in region and "sync" not in region:
                    self._emit(
                        "JAX105", stop_node, sym, timer,
                        f"timer {timer!r} stopped without a device sync in the "
                        f"timed region (started line {start_line})",
                    )

    @staticmethod
    def _is_clock_call(node: ast.AST) -> bool:
        return (
            isinstance(node, ast.Call)
            and _callee_tail(node.func) in ("perf_counter", "monotonic")
        )

    def _call_kind(self, call: ast.Call) -> str:
        tail = _callee_tail(call.func) or ""
        low = tail.lower()
        if any(marker in low for marker in _SYNC_CALL_MARKERS):
            return "sync"
        if self._host_sync_kind(call):
            return "sync"  # a host fetch forces completion too
        if tail in _TRIVIAL_CALLS or tail in ("perf_counter", "monotonic"):
            return "trivial"
        return "work"


def check_source(source: str, path: str, timing: bool = False) -> List[Finding]:
    return _Checker(source, path, timing).run()


def check_file(filename: str, relpath: Optional[str] = None, timing: bool = False) -> List[Finding]:
    with open(filename, "r", encoding="utf-8") as f:
        source = f.read()
    return check_source(source, relpath or filename, timing)
