"""Static analysis and runtime concurrency witnesses for katib-tpu.

Three tools live here, surfaced through ``katib-tpu lint``:

- :mod:`~katib_tpu.analysis.lockcheck` — AST lock-discipline checker over
  classes that declare ``_GUARDS = guarded_by(...)``.
- :mod:`~katib_tpu.analysis.jaxcheck` — AST JAX-hazard checker (host syncs
  in hot loops, jit-in-loop, static_argnums, donation reuse, unsynced
  bench timing).
- :mod:`~katib_tpu.analysis.witness` — runtime lock-order witness
  (``KATIB_LOCK_WITNESS=1``) recording the process-wide lock-acquisition
  graph and turning lock-order inversions into hard failures.

This ``__init__`` stays import-light (stdlib only): production modules
import ``guarded_by``/``make_lock`` from here at module-import time.
"""

from .guards import guarded_by
from .witness import (
    LockOrderInversion,
    make_lock,
    witness_enabled,
    witness_reset,
    witness_summary,
)

__all__ = [
    "guarded_by",
    "make_lock",
    "witness_enabled",
    "witness_reset",
    "witness_summary",
    "LockOrderInversion",
]
