"""Deterministic virtual clock: a run-token scheduler over real threads.

The orchestrator stack is genuinely multi-threaded (suggest / schedule /
harvest loops, a trial pool, a watchdog, a supervisor).  Rather than
reimplement it as coroutines — which would stop exercising the real code —
the simulator keeps the real threads and serializes them: at most ONE
managed thread runs at any moment (it holds the *run token*); every other
managed thread is parked inside a clock call.  Parking registers a waiter
``(seq, predicate, deadline)``; when the token is released the dispatcher
grants the lowest-seq waiter whose predicate holds, and when nothing is
runnable it advances virtual time to the earliest armed deadline.  Because
every scheduling decision happens at a clock call under one lock, with
ticket numbers assigned only by the token holder, the interleaving — and
therefore the journal — is a pure function of the seed.

Three mechanisms close the classic determinism holes:

* **Arrival handshake** — ``spawn``/``submit`` assign the new thread's
  ticket while the caller still holds the token, then block the caller (in
  real time) until the new thread has parked.  A set of threads "starting
  concurrently" therefore joins the waiter list in ticket order, never in
  OS scheduling order.
* **Depart barrier** — a pool task's wrapper releases the token *before*
  ``ThreadPoolExecutor`` resolves its Future, so the dispatcher holds all
  grants until the Future's done-callback clears the barrier.  The next
  token holder consequently sees ``f.done()`` deterministically.
* **Virtual liveness** — threads created through ``spawn`` report
  ``is_alive()`` from a flag flipped in the wrapper's ``finally``, not from
  OS thread state, so the supervisor's crashed/stalled classification is a
  function of virtual time only.
"""

from __future__ import annotations

import threading
import time as _real_time
from collections import namedtuple
from typing import Any, Callable, Iterable

import concurrent.futures as cf

from katib_tpu.analysis import make_lock

# Virtual wall-clock epoch: journal `ts` fields become epoch + virtual
# offset, so same-seed runs produce byte-identical journals regardless of
# when they execute.
VIRTUAL_EPOCH = 1_700_000_000.0

# If no waiter has been granted for this much REAL time the simulation is
# wedged outside the clock (a real deadlock, not a virtual one) — every
# parked thread raises rather than hanging CI.
_WALL_STALL_SECONDS = 60.0
_HANDSHAKE_SECONDS = 60.0

DoneAndNotDoneFutures = namedtuple("DoneAndNotDoneFutures", ["done", "not_done"])


class VirtualDeadlock(RuntimeError):
    """All managed threads parked, no predicate true, no deadline armed."""


class _Waiter:
    __slots__ = ("seq", "predicate", "deadline", "event", "woke", "granted", "name")

    def __init__(self, seq, predicate, deadline, name):
        self.seq = seq
        self.predicate = predicate
        self.deadline = deadline
        self.event = threading.Event()
        self.woke = False
        self.granted = False
        self.name = name


class _VThread(threading.Thread):
    """Thread whose liveness is a virtual-time fact, not an OS fact."""

    def __init__(self, *args: Any, **kwargs: Any) -> None:
        super().__init__(*args, **kwargs)
        self._v_started = False
        self._v_departed = False

    def start(self) -> None:
        self._v_started = True
        super().start()

    def is_alive(self) -> bool:
        return self._v_started and not self._v_departed


class VirtualClock:
    """Drop-in for the ambient clock that makes time a simulation variable."""

    def __init__(
        self,
        *,
        epoch: float = VIRTUAL_EPOCH,
        max_virtual_seconds: float | None = None,
    ) -> None:
        self._lock = make_lock("sim.clock")
        self._now = 0.0
        self._epoch = epoch
        self._seq = 0
        self._waiters: list[_Waiter] = []
        self._running: int | None = None
        self._barrier: cf.Future | None = None
        self._last_grant_wall = _real_time.monotonic()
        self._max_virtual = max_virtual_seconds
        self._deadlocked: str | None = None

    # ------------------------------------------------------------------ reads

    def monotonic(self) -> float:
        return self._now

    def perf_counter(self) -> float:
        return self._now

    def time(self) -> float:
        return self._epoch + self._now

    # ------------------------------------------------------------- scheduling

    def _next_seq_locked(self) -> int:
        self._seq += 1
        return self._seq

    def _park(
        self,
        predicate: Callable[[], bool] | None,
        deadline: float | None,
        name: str = "",
    ) -> bool:
        with self._lock:
            if self._deadlocked:
                raise VirtualDeadlock(self._deadlocked)
            w = _Waiter(self._next_seq_locked(), predicate, deadline, name)
            self._waiters.append(w)
            self._running = None
            self._dispatch_locked()
        while not w.event.wait(10.0):
            with self._lock:
                if self._deadlocked:
                    raise VirtualDeadlock(self._deadlocked)
                stalled = (
                    _real_time.monotonic() - self._last_grant_wall
                    > _WALL_STALL_SECONDS
                )
            if stalled and not w.event.is_set():
                raise RuntimeError(
                    f"virtual clock wedged: no grant for {_WALL_STALL_SECONDS}s "
                    f"of real time while {name or 'waiter'} was parked "
                    "(a thread is blocked outside the clock seam)"
                )
        if not w.granted:
            raise VirtualDeadlock(self._deadlocked or "woken without a grant")
        return w.woke

    def _dispatch_locked(self) -> None:
        """Grant the next waiter, advancing virtual time if needed."""
        if self._running is not None or self._barrier is not None:
            return
        while True:
            if self._deadlocked:
                return
            runnable = None
            for w in sorted(self._waiters, key=lambda w: w.seq):
                if w.predicate is not None and w.predicate():
                    runnable = w
                    w.woke = True
                    break
                if w.deadline is not None and w.deadline <= self._now:
                    runnable = w
                    w.woke = False
                    break
            if runnable is not None:
                self._grant_locked(runnable)
                return
            if not self._waiters:
                return
            deadlines = [w.deadline for w in self._waiters if w.deadline is not None]
            if not deadlines:
                self._deadlocked = (
                    "all managed threads parked with no armed deadline: "
                    + ", ".join(w.name or f"seq{w.seq}" for w in self._waiters)
                )
                for w in self._waiters:
                    w.event.set()
                return
            self._now = max(self._now, min(deadlines))
            if self._max_virtual is not None and self._now > self._max_virtual:
                self._deadlocked = (
                    f"virtual time exceeded cap {self._max_virtual}s "
                    "(runaway schedule)"
                )
                for w in self._waiters:
                    w.event.set()
                return

    def _grant_locked(self, w: _Waiter) -> None:
        self._waiters.remove(w)
        self._running = -1  # token now conceptually held by the woken thread
        self._last_grant_wall = _real_time.monotonic()
        w.granted = True
        w.event.set()

    def _release(self) -> None:
        with self._lock:
            self._running = None
            self._dispatch_locked()

    # ------------------------------------------------------------ clock calls

    def sleep(self, seconds: float) -> None:
        self._park(None, self._now + max(0.0, seconds), name="sleep")

    def wait(self, event: threading.Event, timeout: float | None = None) -> bool:
        if event.is_set():
            return True
        deadline = None if timeout is None else self._now + max(0.0, timeout)
        return self._park(event.is_set, deadline, name="event-wait")

    def wait_until(
        self, predicate: Callable[[], bool], timeout: float | None = None
    ) -> bool:
        if predicate():
            return True
        deadline = None if timeout is None else self._now + max(0.0, timeout)
        return self._park(predicate, deadline, name="predicate-wait")

    def join_thread(
        self, thread: threading.Thread, timeout: float | None = None
    ) -> bool:
        if isinstance(thread, _VThread):
            pred = lambda: thread._v_departed  # noqa: E731
        else:
            pred = lambda: not thread.is_alive()  # noqa: E731
        if pred():
            return True
        deadline = None if timeout is None else self._now + max(0.0, timeout)
        return self._park(pred, deadline, name=f"join:{thread.name}")

    def wait_futures(
        self, futures: Iterable[cf.Future], timeout: float | None = None
    ) -> Any:
        futs = list(futures)
        pred = lambda: all(f.done() for f in futs)  # noqa: E731
        if futs and not pred():
            deadline = None if timeout is None else self._now + max(0.0, timeout)
            self._park(pred, deadline, name="futures-wait")
        done = {f for f in futs if f.done()}
        return DoneAndNotDoneFutures(done, {f for f in futs if f not in done})

    # -------------------------------------------------------- thread creation

    def spawn(
        self,
        target: Callable[[], Any],
        *,
        name: str | None = None,
        daemon: bool = True,
    ) -> threading.Thread:
        with self._lock:
            ticket = self._next_seq_locked()
        parked = threading.Event()
        holder: list[_VThread] = []

        def _run() -> None:
            self._check_in(ticket, parked, name or "thread")
            try:
                target()
            finally:
                holder[0]._v_departed = True
                self._release()

        t = _VThread(target=_run, name=name, daemon=daemon)
        holder.append(t)
        t.start()
        self._await_handshake(parked, name or "thread")
        return t

    def submit(
        self, pool: cf.Executor, fn: Callable[..., Any], /, *args: Any, **kwargs: Any
    ) -> cf.Future:
        with self._lock:
            ticket = self._next_seq_locked()
        parked = threading.Event()
        cell: list[cf.Future | None] = [None]

        def _wrapped(*a: Any, **k: Any) -> Any:
            self._check_in(ticket, parked, "pool-task")
            try:
                return fn(*a, **k)
            finally:
                self._depart_with_barrier(cell[0])

        fut = pool.submit(_wrapped, *args, **kwargs)
        cell[0] = fut
        fut.add_done_callback(self._barrier_cleared)
        self._await_handshake(parked, "pool-task")
        return fut

    def _check_in(self, ticket: int, parked: threading.Event, name: str) -> None:
        """New thread/task: park at its pre-assigned ticket, tell the spawner."""
        with self._lock:
            if self._deadlocked:
                parked.set()
                raise VirtualDeadlock(self._deadlocked)
            w = _Waiter(ticket, lambda: True, None, name)
            self._waiters.append(w)
            parked.set()
            self._dispatch_locked()
        while not w.event.wait(10.0):
            with self._lock:
                if self._deadlocked:
                    raise VirtualDeadlock(self._deadlocked)
        if not w.granted:
            raise VirtualDeadlock(self._deadlocked or "woken without a grant")

    def _await_handshake(self, parked: threading.Event, name: str) -> None:
        if not parked.wait(_HANDSHAKE_SECONDS):
            raise RuntimeError(
                f"virtual clock: spawned {name} never parked "
                f"within {_HANDSHAKE_SECONDS}s of real time "
                "(thread pool saturated beyond its accounting?)"
            )

    def _depart_with_barrier(self, fut: cf.Future | None) -> None:
        with self._lock:
            self._running = None
            if fut is not None and not fut.done():
                # Hold all grants until the executor resolves the Future so
                # the next token holder sees f.done() deterministically.
                self._barrier = fut
                return
            self._dispatch_locked()

    def _barrier_cleared(self, fut: cf.Future) -> None:
        with self._lock:
            if self._barrier is fut:
                self._barrier = None
                self._dispatch_locked()

    # ------------------------------------------------------------------- root

    def start_root(self) -> None:
        """The calling (real) thread becomes the first token holder."""
        with self._lock:
            self._running = -1

    def finish_root(self) -> None:
        """Release the root token; remaining parked threads self-drain."""
        self._release()

    def __enter__(self) -> "VirtualClock":
        self.start_root()
        return self

    def __exit__(self, *exc: Any) -> None:
        self.finish_root()
