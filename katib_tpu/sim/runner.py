"""Scenario runner: wire the virtual clock + modeled executor around the
REAL orchestrator stack and gate the outcome on the invariant checker.

``run_scenario`` is the whole simulator in one call:

1. install a :class:`VirtualClock` as the ambient clock and gate journal
   fsync off (virtual runs are about schedules, not disk durability);
2. build a real :class:`ExperimentSpec` (white-box, async engine on) and a
   real :class:`Orchestrator` whose only substitutions are the modeled
   trial/cohort executors, a seeded trial-name source, and a
   latency-wrapped — but real — suggester;
3. spawn a clock-managed fault-driver thread that walks the scenario's
   fault schedule in virtual time through the production
   :class:`FaultInjector` seams (plus ``orch.drain()`` / ``orch.stop()``);
4. run the experiment, then replay the journal through
   :mod:`katib_tpu.sim.invariants` and return a deterministic verdict.

Crash scenarios are two-phase: a child process (this module run with
``python -m katib_tpu.sim.runner``) arms ``KATIB_CRASH_AT`` and dies at a
registered persistence site; the parent resumes the same workdir and the
invariant gate runs over the combined journal.
"""

from __future__ import annotations

import dataclasses
import json
import os
import random
import shutil
import subprocess
import sys
import tempfile
import threading
import time as _real_time

from katib_tpu.core.types import (
    AlgorithmSpec,
    ExperimentSpec,
    FeasibleSpace,
    ObjectiveSpec,
    ObjectiveType,
    ParameterSpec,
    ParameterType,
    ResumePolicy,
)
from katib_tpu.orchestrator import journal as journal_mod
from katib_tpu.orchestrator.orchestrator import Orchestrator
from katib_tpu.store.base import MemoryObservationStore
from katib_tpu.suggest.base import make_suggester
from katib_tpu.utils import faults
from katib_tpu.utils import tracing as tracing_mod
from katib_tpu.utils.clock import get_clock, set_clock

from katib_tpu.sim.clock import VirtualClock
from katib_tpu.sim.executor import LatencySuggester, ModeledExecutor, _stream
from katib_tpu.sim.invariants import check_invariants, journal_digest
from katib_tpu.sim.scenario import Scenario, load_scenario, scenario_to_dict

#: the child half of a two-phase crash scenario sets this so it does not
#: recurse into spawning another child
_CHILD_ENV = "KATIB_SIM_CHILD"


def _sim_train_fn(ctx):  # pragma: no cover - never dispatched
    raise RuntimeError(
        "simulator: the modeled executor must intercept trial dispatch"
    )


def _token_hex_factory(seed: int):
    """Seeded stand-in for ``secrets.token_hex`` so trial names — which key
    the journal — are a function of the scenario seed."""
    rng = _stream(seed, "token-hex")

    def token_hex(nbytes: int = 4) -> str:
        return f"{rng.getrandbits(8 * nbytes):0{2 * nbytes}x}"

    return token_hex


def _build_spec(sc: Scenario) -> ExperimentSpec:
    params = [
        ParameterSpec(
            "lr", ParameterType.DOUBLE, FeasibleSpace(min=1e-4, max=1.0)
        ),
        ParameterSpec(
            "momentum", ParameterType.DOUBLE, FeasibleSpace(min=0.0, max=0.99)
        ),
        ParameterSpec(
            "arch",
            ParameterType.CATEGORICAL,
            FeasibleSpace(list=["mlp", "cnn", "gru", "moe"]),
        ),
    ]
    spec = ExperimentSpec(
        name=f"sim-{sc.name}",
        objective=ObjectiveSpec(
            type=ObjectiveType.MAXIMIZE, objective_metric_name="score"
        ),
        algorithm=AlgorithmSpec(name=sc.algorithm, settings={"seed": str(sc.seed)}),
        parameters=params,
        parallel_trial_count=sc.parallel,
        max_trial_count=sc.trials,
        train_fn=_sim_train_fn,
        async_orch=True,
        prewarm=False,  # its worker thread lives outside the clock seam
        max_retries=2,
        retry_backoff_seconds=0.25,
        drain_grace_seconds=10.0,
    )
    if sc.crash is not None:
        # the parent phase resumes the child's workdir, suggester state
        # included — exactly what LongRunning is for
        spec = dataclasses.replace(spec, resume_policy=ResumePolicy.LONG_RUNNING)
    if sc.spec:
        overrides = dict(sc.spec)
        if isinstance(overrides.get("resume_policy"), str):
            overrides["resume_policy"] = ResumePolicy(overrides["resume_policy"])
        spec = dataclasses.replace(spec, **overrides)
    return spec


def _fault_schedule(sc: Scenario, orch: Orchestrator, inj: faults.FaultInjector):
    """Expand the scenario's fault list (plus clear_after events) into a
    time-sorted list of (virtual_time, description, thunk)."""
    out: list[tuple[float, str, object]] = []

    def add(t, desc, fn):
        out.append((float(t), desc, fn))

    for f in sc.faults:
        if f.action == "kill_loop":
            loop = f.loop or "suggest"
            add(f.at, f"kill_loop:{loop}", lambda loop=loop: inj.kill_loop_now(loop))
        elif f.action == "stall_suggester":
            s = f.seconds or 10.0
            add(f.at, f"stall_suggester:{s}", lambda s=s: inj.stall_suggester_now(s))
        elif f.action == "wedge_device":
            add(f.at, f"wedge_device:{f.device}",
                lambda d=f.device: inj.wedge_device(d))
            if f.clear_after is not None:
                add(f.at + f.clear_after, f"unwedge_device:{f.device}",
                    lambda d=f.device: inj.unwedge_device(d))
        elif f.action == "drop_slice":
            devs = list(sc.slices.slice_devices(f.slice))
            add(f.at, f"drop_slice:{f.slice}",
                lambda devs=devs: [inj.wedge_device(d) for d in devs])
            if f.clear_after is not None:
                add(f.at + f.clear_after, f"restore_slice:{f.slice}",
                    lambda devs=devs: [inj.unwedge_device(d) for d in devs])
        elif f.action == "flake":
            kind = faults.FailureKind(f.kind)
            add(f.at, f"flake:{f.rate}",
                lambda r=f.rate, k=kind: inj.flake(r, k))
            if f.clear_after is not None:
                add(f.at + f.clear_after, "flake:clear", lambda: inj.flake(0.0))
        elif f.action == "drain":
            add(f.at, "drain", orch.drain)
        elif f.action == "stop":
            add(f.at, "stop", orch.stop)
        else:
            raise ValueError(f"unknown fault action {f.action!r}")
    out.sort(key=lambda e: e[0])
    return out


def _drive_faults(schedule, halt: threading.Event) -> None:
    clock = get_clock()
    for at, _desc, fn in schedule:
        delta = at - clock.monotonic()
        if delta > 0 and clock.wait(halt, delta):
            return
        if halt.is_set():
            return
        fn()


def _run_phase(
    sc: Scenario, workdir: str, *, resume: bool, crashed: bool
) -> dict:
    """One in-process simulated run (everything except the crash child)."""
    spec = _build_spec(sc)
    injector = faults.FaultInjector(rng=_stream(sc.seed, "injector"))
    executor = ModeledExecutor(sc, injector)
    clock = VirtualClock(max_virtual_seconds=sc.virtual_cap())
    # each compaction serializes the full experiment state (O(trials)), so
    # the auto cadence keeps total compaction work O(trials): a handful of
    # snapshots over the run, not one per fixed batch
    snapshot_every = (
        sc.snapshot_every
        if sc.snapshot_every is not None
        else max(64, sc.trials // 4)
    )
    orch = Orchestrator(
        store=MemoryObservationStore(),
        workdir=workdir,
        poll_interval=sc.poll_interval,
        fault_injector=injector,
        preflight=False,
        run_trial_fn=executor.run_trial,
        run_cohort_fn=executor.run_cohort,
        token_hex=_token_hex_factory(sc.seed),
        journal_snapshot_every=snapshot_every,
        status_publish_interval=sc.status_publish_interval,
        suggester_fn=lambda s: LatencySuggester(make_suggester(s), sc),
    )
    halt = threading.Event()
    prev_clock = set_clock(clock)
    # fsync and span tracing are real-time I/O with no virtual-time meaning;
    # both gates are saved/restored so the ambient process is untouched
    prev_sync = os.environ.get(journal_mod.SYNC_ENV)
    os.environ[journal_mod.SYNC_ENV] = "0"
    prev_trace = os.environ.get(tracing_mod.TRACE_ENV)
    os.environ[tracing_mod.TRACE_ENV] = "0"
    wall0 = _real_time.monotonic()
    error = None
    exp = None
    try:
        with clock:
            schedule = _fault_schedule(sc, orch, injector)
            driver = None
            if schedule:
                driver = clock.spawn(
                    lambda: _drive_faults(schedule, halt),
                    name="sim-fault-driver",
                )
            try:
                exp = orch.run(spec, resume=resume)
            finally:
                halt.set()
                if driver is not None:
                    clock.join_thread(driver)
        virtual_seconds = clock.monotonic()
    except Exception as e:  # noqa: BLE001 - verdictized, not swallowed
        error = f"{type(e).__name__}: {e}"
        virtual_seconds = clock.monotonic()
    finally:
        set_clock(prev_clock)
        if prev_sync is None:
            os.environ.pop(journal_mod.SYNC_ENV, None)
        else:
            os.environ[journal_mod.SYNC_ENV] = prev_sync
        if prev_trace is None:
            os.environ.pop(tracing_mod.TRACE_ENV, None)
        else:
            os.environ[tracing_mod.TRACE_ENV] = prev_trace
    wall_seconds = _real_time.monotonic() - wall0

    if exp is not None:
        violations = check_invariants(
            sc, sc.seed, exp, orch, workdir, crashed=crashed
        )
    else:
        violations = [f"run crashed in-process: {error}"]
    stats = getattr(orch, "async_stats", None) or {}
    return {
        "scenario": sc.name,
        "seed": sc.seed,
        "experiment": spec.name,
        "condition": exp.condition.value if exp is not None else "Error",
        "trials": len(exp.trials) if exp is not None else 0,
        "settled": stats.get("trials_settled"),
        "occupancy": stats.get("sustained_occupancy"),
        "loop_restarts": stats.get("loop_restarts") or {},
        "fallback": stats.get("fallback"),
        "virtual_seconds": round(virtual_seconds, 3),
        "wall_seconds": round(wall_seconds, 3),
        "journal_sha256": journal_digest(workdir, spec.name),
        "violations": violations,
        "verdict": "PASS" if not violations else "FAIL",
    }


def _run_crash(sc: Scenario, workdir: str) -> dict:
    """Two-phase crash scenario: child dies at the armed persistence site,
    parent resumes the same workdir, invariants run over the whole story."""
    crash = sc.crash
    scenario_path = os.path.join(workdir, "_scenario.yaml")
    with open(scenario_path, "w", encoding="utf-8") as f:
        import yaml

        f.write(yaml.safe_dump(scenario_to_dict(sc), sort_keys=False))
    pkg_root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    env = dict(os.environ)
    env[_CHILD_ENV] = "1"
    env[faults.CRASH_AT_ENV] = f"{crash.at}:{crash.hit}"
    env[faults.CRASH_MODE_ENV] = crash.mode
    env["PYTHONPATH"] = pkg_root + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [
            sys.executable, "-m", "katib_tpu.sim.runner", scenario_path,
            "--seed", str(sc.seed), "--workdir", workdir,
        ],
        env=env,
        capture_output=True,
        text=True,
        timeout=600,
    )
    pre: list[str] = []
    # "exit" mode calls os._exit(137); "kill" mode raises SIGKILL, which
    # subprocess reports as returncode -9
    expected = {137} if crash.mode == "exit" else {-9, 137}
    if proc.returncode not in expected:
        pre.append(
            f"crash: child exited {proc.returncode} (expected "
            f"{sorted(expected)} from {crash.at}:{crash.hit}); "
            f"stderr tail: {proc.stderr[-400:]!r}"
        )
    verdict = _run_phase(sc, workdir, resume=True, crashed=True)
    verdict["crash"] = {
        "site": crash.at,
        "hit": crash.hit,
        "mode": crash.mode,
        "child_exit": proc.returncode,
    }
    if pre:
        verdict["violations"] = pre + verdict["violations"]
        verdict["verdict"] = "FAIL"
    return verdict


def run_scenario(
    scenario: Scenario, seed: int | None = None, workdir: str | None = None
) -> dict:
    """Run one scenario to a deterministic verdict dict.

    ``seed`` overrides the scenario's committed seed; ``workdir`` pins the
    experiment directory (same seed + same workdir → byte-identical
    journal).  A temporary workdir is created — and removed on a PASS —
    when none is given.
    """
    sc = (
        scenario
        if seed is None or seed == scenario.seed
        else dataclasses.replace(scenario, seed=seed)
    )
    owns_workdir = workdir is None
    if owns_workdir:
        workdir = tempfile.mkdtemp(prefix=f"katib-sim-{sc.name}-")
    try:
        if sc.crash is not None and os.environ.get(_CHILD_ENV) != "1":
            verdict = _run_crash(sc, workdir)
        else:
            verdict = _run_phase(sc, workdir, resume=False, crashed=False)
    except BaseException:
        owns_workdir = False  # keep the evidence
        raise
    finally:
        if owns_workdir and os.path.isdir(workdir):
            shutil.rmtree(workdir, ignore_errors=True)
    verdict["workdir"] = None if owns_workdir else workdir
    return verdict


def main(argv: list[str] | None = None) -> int:
    import argparse

    p = argparse.ArgumentParser(
        prog="python -m katib_tpu.sim.runner",
        description="Run one simulator scenario to a verdict.",
    )
    p.add_argument("scenario", help="scenario YAML path")
    p.add_argument("--seed", type=int, default=None)
    p.add_argument("--workdir", default=None)
    p.add_argument("--json", action="store_true", dest="as_json")
    args = p.parse_args(argv)
    verdict = run_scenario(
        load_scenario(args.scenario), seed=args.seed, workdir=args.workdir
    )
    if args.as_json:
        print(json.dumps(verdict, indent=2, sort_keys=True))
    else:
        print(
            f"{verdict['verdict']}: {verdict['scenario']} seed={verdict['seed']} "
            f"trials={verdict['trials']} virtual={verdict['virtual_seconds']}s "
            f"wall={verdict['wall_seconds']}s"
        )
        for v in verdict["violations"]:
            print(f"  violation: {v}")
    return 0 if verdict["verdict"] == "PASS" else 1


if __name__ == "__main__":
    sys.exit(main())
