"""Scenario spec for the virtual-time simulator.

A scenario is a small YAML document describing one simulated experiment:
trial count, parallelism, the suggester + its latency model, modeled trial
duration distributions (seeded from committed bench numbers,
``artifacts/orchestrator/*.json``), a simulated slice topology, a fault
schedule in virtual time, and the invariant expectations the run must meet.

Example::

    name: mixed-faults
    trials: 20000
    parallel: 32
    seed: 7
    poll_interval: 0.25
    suggester:
      algorithm: random
      latency: {distribution: lognormal, mean: 0.5, sigma: 0.25}
    durations:
      distribution: lognormal
      mean: 0.2
      sigma: 0.3
      straggler_rate: 0.01
      straggler_factor: 8.0
    slices: {count: 4, devices_per_slice: 8}
    faults:
      - {at: 30.0, action: kill_loop, loop: suggest}
      - {at: 60.0, action: drop_slice, slice: 2, clear_after: 30.0}
      - {at: 95.0, action: stall_suggester, seconds: 12.0}
    expect:
      restarts: true
      occupancy_min: 0.5

Everything has a default; ``katib-tpu sim scenario.yaml --seed N`` overrides
the seed from the CLI.
"""

from __future__ import annotations

import dataclasses
import math
from dataclasses import dataclass, field


@dataclass
class LatencyModel:
    """A seeded duration distribution (seconds)."""

    distribution: str = "constant"  # constant | uniform | lognormal
    mean: float = 0.0
    sigma: float = 0.0  # lognormal shape / uniform half-width
    min: float = 0.0
    max: float = math.inf

    def draw(self, rng) -> float:
        if self.distribution == "constant" or self.mean <= 0.0:
            d = self.mean
        elif self.distribution == "uniform":
            d = rng.uniform(
                max(0.0, self.mean - self.sigma), self.mean + self.sigma
            )
        elif self.distribution == "lognormal":
            # parameterized by the distribution MEAN (matches the committed
            # bench numbers), not the underlying mu
            mu = math.log(self.mean) - 0.5 * self.sigma**2
            d = rng.lognormvariate(mu, self.sigma)
        else:
            raise ValueError(f"unknown distribution {self.distribution!r}")
        return min(max(d, self.min), self.max)


@dataclass
class DurationModel(LatencyModel):
    """Trial execution time + heavy-tail straggler model."""

    distribution: str = "lognormal"
    mean: float = 0.2  # async_occupancy.json train block
    sigma: float = 0.3
    straggler_rate: float = 0.0
    straggler_factor: float = 8.0

    def draw(self, rng) -> float:
        d = super().draw(rng)
        if self.straggler_rate > 0.0 and rng.random() < self.straggler_rate:
            d *= self.straggler_factor
        return d


@dataclass
class SliceTopology:
    count: int = 1
    devices_per_slice: int = 8

    @property
    def total_devices(self) -> int:
        return self.count * self.devices_per_slice

    def slice_devices(self, slice_id: int) -> range:
        d = self.devices_per_slice
        return range(slice_id * d, (slice_id + 1) * d)


@dataclass
class FaultEvent:
    """One scheduled fault in virtual time.

    Actions: ``kill_loop`` (loop=suggest|schedule|harvest),
    ``stall_suggester`` (seconds), ``wedge_device`` (device),
    ``drop_slice`` (slice), ``flake`` (rate, kind), ``drain``, ``stop``.
    ``clear_after`` un-wedges a device/slice that much later.
    """

    at: float
    action: str
    loop: str = ""
    seconds: float = 0.0
    device: int = -1
    slice: int = -1
    rate: float = 0.0
    kind: str = "Transient"
    clear_after: float | None = None


@dataclass
class Expectations:
    """What the invariant gate tolerates for this scenario."""

    restarts: bool = False  # loop restarts are an expected outcome
    fallback: bool = False  # sync-fallback is an expected outcome
    failed: bool = False  # a FAILED experiment verdict is expected
    occupancy_min: float = 0.0  # sustained-occupancy floor (0 = skip)


@dataclass
class CrashSpec:
    """Two-phase crash-kill scenario: a child process dies at a PR 10 crash
    point (``utils.faults.CRASH_POINTS``), the parent resumes the same
    workdir and the invariant gate runs over the combined journal."""

    at: str = "journal.append"
    hit: int = 1
    mode: str = "exit"  # exit | kill


@dataclass
class Scenario:
    name: str = "scenario"
    trials: int = 1000
    parallel: int = 16
    seed: int = 0
    poll_interval: float = 0.25
    algorithm: str = "random"
    suggest_latency: LatencyModel = field(
        default_factory=lambda: LatencyModel(
            distribution="lognormal", mean=0.5, sigma=0.25
        )
    )
    durations: DurationModel = field(default_factory=DurationModel)
    slices: SliceTopology = field(default_factory=SliceTopology)
    faults: list[FaultEvent] = field(default_factory=list)
    expect: Expectations = field(default_factory=Expectations)
    crash: CrashSpec | None = None
    # ExperimentSpec passthrough overrides (max_retries, cohort_width, ...)
    spec: dict = field(default_factory=dict)
    # journal compaction cadence in the simulated run (None = auto: big
    # enough that compaction stays O(trials))
    snapshot_every: int | None = None
    # status.json republish throttle (virtual seconds)
    status_publish_interval: float = 10.0
    # hard virtual-time cap; None = auto from the workload size
    max_virtual_seconds: float | None = None
    # metric noise in the modeled objective
    metric_noise: float = 0.05

    def virtual_cap(self) -> float:
        if self.max_virtual_seconds is not None:
            return self.max_virtual_seconds
        # generous: all trials serially at mean duration + suggester time,
        # plus a flat allowance for fault recovery windows
        serial = self.trials * (
            self.durations.mean + self.suggest_latency.mean
        )
        return max(600.0, 20.0 * serial / max(1, self.parallel) + 600.0)


def _build(cls, data: dict, where: str):
    fields = {f.name: f for f in dataclasses.fields(cls)}
    unknown = set(data) - set(fields)
    if unknown:
        raise ValueError(
            f"{where}: unknown key(s) {sorted(unknown)} "
            f"(known: {sorted(fields)})"
        )
    return cls(**data)


def scenario_from_dict(data: dict) -> Scenario:
    """Validate + build a Scenario from parsed YAML/JSON."""
    data = dict(data or {})
    out: dict = {}
    for key in (
        "name", "trials", "parallel", "seed", "poll_interval", "algorithm",
        "spec", "snapshot_every", "status_publish_interval",
        "max_virtual_seconds", "metric_noise",
    ):
        if key in data:
            out[key] = data.pop(key)
    sug = data.pop("suggester", None)
    if sug:
        if "algorithm" in sug:
            out["algorithm"] = sug["algorithm"]
        if "latency" in sug:
            out["suggest_latency"] = _build(
                LatencyModel, sug["latency"], "suggester.latency"
            )
    if "durations" in data:
        out["durations"] = _build(DurationModel, data.pop("durations"), "durations")
    if "slices" in data:
        out["slices"] = _build(SliceTopology, data.pop("slices"), "slices")
    if "expect" in data:
        out["expect"] = _build(Expectations, data.pop("expect"), "expect")
    if "crash" in data:
        out["crash"] = _build(CrashSpec, data.pop("crash"), "crash")
    if "faults" in data:
        out["faults"] = [
            _build(FaultEvent, f, f"faults[{i}]")
            for i, f in enumerate(data.pop("faults"))
        ]
    if data:
        raise ValueError(
            f"scenario: unknown top-level key(s) {sorted(data)}"
        )
    return _build(Scenario, out, "scenario")


def scenario_to_dict(sc: Scenario) -> dict:
    """Inverse of :func:`scenario_from_dict` (used to hand a scenario to the
    crash-phase child process): round-trips through the loader."""
    return {
        "name": sc.name,
        "trials": sc.trials,
        "parallel": sc.parallel,
        "seed": sc.seed,
        "poll_interval": sc.poll_interval,
        "suggester": {
            "algorithm": sc.algorithm,
            "latency": dataclasses.asdict(sc.suggest_latency),
        },
        "durations": dataclasses.asdict(sc.durations),
        "slices": dataclasses.asdict(sc.slices),
        "faults": [dataclasses.asdict(f) for f in sc.faults],
        "expect": dataclasses.asdict(sc.expect),
        **({"crash": dataclasses.asdict(sc.crash)} if sc.crash else {}),
        "spec": dict(sc.spec),
        "snapshot_every": sc.snapshot_every,
        "status_publish_interval": sc.status_publish_interval,
        "max_virtual_seconds": sc.max_virtual_seconds,
        "metric_noise": sc.metric_noise,
    }


def load_scenario(path: str) -> Scenario:
    """Load a scenario YAML (or JSON — YAML is a superset) file."""
    import yaml

    with open(path, encoding="utf-8") as f:
        doc = yaml.safe_load(f) or {}
    if not isinstance(doc, dict):
        raise ValueError(f"{path}: scenario document must be a mapping")
    sc = scenario_from_dict(doc)
    if sc.name == "scenario":
        import os

        sc.name = os.path.splitext(os.path.basename(path))[0]
    return sc
