"""Invariant gate: replay the journal of a simulated run and assert the
orchestrator's safety/liveness contracts held.

The checks are deliberately phrased over the *durable* record (journal
replay + fsck) rather than in-memory state, then cross-checked against
memory — the same evidence an operator has after a real incident:

* exactly-once settlement — replay drops zero duplicate ``(trial, epoch)``
  settle records, and the journal parses clean (no torn tail, no bad
  records) on a non-crash run;
* no starvation — every trial the suggester proposed reached a terminal
  condition (DRAINED tolerated only when the scenario drains/stops);
* memory/journal agreement — the in-memory experiment and the replayed
  state agree on every trial's terminal condition;
* retry-budget monotonicity — no trial exceeds ``max_retries``;
* supervisor restart budgets — per-loop restarts stay within
  ``loop_restart_budget``, and restarts/fallback/failure only appear when
  the scenario expects them;
* occupancy recovery — sustained occupancy ends at/above the scenario
  floor despite the fault schedule;
* artifact integrity — ``katib-tpu fsck`` (read-only) passes over the
  experiment directory.
"""

from __future__ import annotations

import hashlib
import json
import os

from katib_tpu.orchestrator.fsck import fsck_experiment
from katib_tpu.orchestrator.journal import (
    journal_path,
    list_snapshots,
    replay_journal,
)

from katib_tpu.sim.scenario import Scenario

_TERMINAL = {
    "Succeeded",
    "Killed",
    "Failed",
    "EarlyStopped",
    "MetricsUnavailable",
}


def journal_digest(workdir: str, exp_name: str) -> str:
    """sha256 over the durable record — journal suffix AND snapshots (the
    journal truncates at compaction, so the snapshot chain is part of the
    story) — with the absolute workdir normalized out, so same-seed runs in
    different directories produce the same digest."""
    exp_dir = os.path.join(workdir, exp_name)
    parts: list[tuple[str, str]] = []
    jpath = journal_path(workdir, exp_name)
    if os.path.exists(jpath):
        parts.append(("journal", jpath))
    for seq, path in sorted(list_snapshots(exp_dir)):
        parts.append((f"snapshot-{seq}", path))
    if not parts:
        return ""
    anchor = os.path.abspath(workdir).encode()
    h = hashlib.sha256()
    for tag, path in parts:
        with open(path, "rb") as f:
            raw = f.read()
        if tag.startswith("snapshot"):
            # a snapshot's crc field covers the UN-normalized state (it
            # embeds absolute checkpoint paths), so hashing the raw bytes
            # would make same-seed runs in different workdirs diverge on
            # the crc alone; hash the canonical crc-less re-serialization
            try:
                doc = json.loads(raw)
                doc.pop("crc", None)
                raw = json.dumps(doc, sort_keys=True, default=str).encode()
            except ValueError:
                pass  # torn snapshot: hash as-is, fsck will flag it
        h.update(tag.encode() + b"\0")
        h.update(raw.replace(anchor, b"<WORKDIR>"))
        h.update(b"\0")
    return h.hexdigest()


def check_invariants(
    scenario: Scenario,
    seed: int,
    exp,
    orch,
    workdir: str,
    *,
    crashed: bool = False,
) -> list[str]:
    """Returns a list of violation strings (empty = all invariants held)."""
    v: list[str] = []
    ends_early = any(f.action in ("drain", "stop") for f in scenario.faults)
    spec = exp.spec
    stats_map = getattr(orch, "async_stats", None) or {}

    # -- the durable record -------------------------------------------------
    state, rstats = replay_journal(workdir, exp.name)
    if state is None:
        return [f"journal: no replayable state for {exp.name!r}"]
    if rstats.duplicates:
        v.append(
            f"exactly-once: replay dropped {rstats.duplicates} duplicate "
            "settle record(s)"
        )
    if not crashed and (rstats.bad_records or rstats.torn_bytes):
        v.append(
            f"journal hygiene: {rstats.bad_records} bad record(s), "
            f"{rstats.torn_bytes} torn byte(s) on a run that never crashed"
        )
    jtrials: dict = state.get("trials") or {}

    # -- no starvation ------------------------------------------------------
    nonterminal = {
        name: (t.get("condition") or "?")
        for name, t in jtrials.items()
        if (t.get("condition") or "?") not in _TERMINAL
    }
    if ends_early:
        # a drained/stopped run legitimately parks in-flight work as
        # Drained and leaves proposed-but-never-started trials Pending
        nonterminal = {
            n: c
            for n, c in nonterminal.items()
            if c not in ("Drained", "Pending")
        }
    if nonterminal:
        sample = sorted(nonterminal.items())[:5]
        v.append(
            f"starvation: {len(nonterminal)} proposed trial(s) never "
            f"settled, e.g. {sample}"
        )

    # -- memory / journal agreement ----------------------------------------
    mismatched = 0
    example = ""
    for name, trial in exp.trials.items():
        jt = jtrials.get(name)
        if jt is None:
            mismatched += 1
            example = example or f"{name}: in memory, absent from journal"
            continue
        if trial.condition.value in _TERMINAL and (
            jt.get("condition") != trial.condition.value
        ):
            mismatched += 1
            example = example or (
                f"{name}: memory={trial.condition.value} "
                f"journal={jt.get('condition')}"
            )
    if mismatched:
        v.append(
            f"memory/journal divergence on {mismatched} trial(s) ({example})"
        )

    # -- retry-budget monotonicity -----------------------------------------
    max_retries = int(getattr(spec, "max_retries", 0) or 0)
    over = {
        name: int(t.get("retry_count") or 0)
        for name, t in jtrials.items()
        if int(t.get("retry_count") or 0) > max_retries
    }
    if over:
        sample = sorted(over.items())[:5]
        v.append(
            f"retry budget: {len(over)} trial(s) above max_retries="
            f"{max_retries}, e.g. {sample}"
        )

    # -- trial-count budget -------------------------------------------------
    budget = int(getattr(spec, "max_trial_count", 0) or 0)
    if budget and len(jtrials) > budget:
        v.append(
            f"budget: journal holds {len(jtrials)} trials > "
            f"max_trial_count={budget}"
        )

    # -- supervisor restart budgets ----------------------------------------
    restarts = stats_map.get("loop_restarts") or {}
    budget_r = int(getattr(spec, "loop_restart_budget", 0) or 0)
    for loop, n in sorted(restarts.items()):
        if budget_r and int(n) > budget_r:
            v.append(
                f"supervisor: loop {loop!r} restarted {n}x > "
                f"loop_restart_budget={budget_r}"
            )
    total_restarts = sum(int(n) for n in restarts.values())
    if total_restarts and not scenario.expect.restarts:
        v.append(
            f"supervisor: {total_restarts} unexpected loop restart(s) "
            f"({dict(restarts)})"
        )
    fallback = stats_map.get("fallback")
    if fallback and not scenario.expect.fallback:
        v.append(f"supervisor: unexpected sync fallback ({fallback})")

    # -- experiment verdict -------------------------------------------------
    cond = exp.condition.value
    stopped = any(f.action == "stop" for f in scenario.faults)
    if cond == "Failed" and not scenario.expect.failed and not stopped:
        # a scheduled `stop` is an operator abort — the orchestrator
        # surfaces it as Failed("experiment stopped"), which is the
        # expected outcome, not a violation
        v.append(f"experiment Failed unexpectedly: {exp.message}")
    if not exp.condition.is_terminal() and not ends_early:
        v.append(f"experiment ended non-terminal: {cond}")

    # -- occupancy recovery -------------------------------------------------
    floor = scenario.expect.occupancy_min
    occ = stats_map.get("sustained_occupancy")
    if floor > 0.0:
        if occ is None:
            v.append("occupancy: floor set but async stats recorded none")
        elif float(occ) < floor:
            v.append(
                f"occupancy: sustained {float(occ):.3f} < floor {floor}"
            )

    # -- artifact integrity -------------------------------------------------
    report = fsck_experiment(os.path.join(workdir, exp.name), repair=False)
    if not report.ok():
        for p in report.problems:
            v.append(f"fsck: {p}")
    return v
