"""Modeled trial executor + suggester latency for the simulator.

This is the ONLY scheduler-facing piece the simulator replaces: instead of
compiling and stepping a real program, a dispatched trial draws its
execution time from the scenario's seeded duration model, waits it out in
*virtual* time (responsive to stop/drain, exactly like the real runner),
consults the real :class:`~katib_tpu.utils.faults.FaultInjector` seams
(``on_trial_attempt``, ``on_cohort_execute``, ``is_device_wedged``) so
injected faults take the production classification/retry paths, and settles
with a deterministic modeled metric.  Every duration/metric draw is keyed by
``(scenario seed, trial name, attempt)`` so the schedule — and therefore the
journal — is a pure function of the seed regardless of dispatch order.
"""

from __future__ import annotations

import hashlib
import random
import threading

from katib_tpu.core.types import TrialCondition
from katib_tpu.runner.trial_runner import TrialResult
from katib_tpu.utils import faults
from katib_tpu.utils.clock import get_clock

from katib_tpu.sim.scenario import Scenario


def _stream(*key: object) -> random.Random:
    """An independent seeded RNG for one (seed, trial, attempt, ...) key."""
    h = hashlib.sha256(":".join(str(k) for k in key).encode()).digest()
    return random.Random(int.from_bytes(h[:8], "big"))


def _wait_virtual(clock, events: list[threading.Event], seconds: float) -> bool:
    """Wait ``seconds`` of clock time; True if any event fired first.
    Uses the virtual clock's predicate wait when available; falls back to a
    chunked poll under a real clock (tests at tiny scale)."""
    live = [e for e in events if e is not None]
    wait_until = getattr(clock, "wait_until", None)
    if wait_until is not None:
        return wait_until(lambda: any(e.is_set() for e in live), seconds)
    deadline = clock.monotonic() + seconds
    while clock.monotonic() < deadline:
        if any(e.is_set() for e in live):
            return True
        clock.sleep(min(0.02, seconds))
    return any(e.is_set() for e in live)


class ModeledExecutor:
    """Callable seams for ``Orchestrator(run_trial_fn=..., run_cohort_fn=...)``."""

    def __init__(self, scenario: Scenario, injector: faults.FaultInjector):
        self.sc = scenario
        self.injector = injector

    # -- device placement ---------------------------------------------------

    def _device_of(self, trial_name: str, attempt: int) -> int:
        """Deterministic placement: each attempt lands on a (re)drawn device
        so a retry after a device fault can escape the wedged slice — the
        stand-in for the allocator leasing a different sub-mesh."""
        rng = _stream(self.sc.seed, "placement", trial_name, attempt)
        return rng.randrange(self.sc.slices.total_devices)

    # -- the run_trial seam -------------------------------------------------

    def run_trial(
        self,
        trial,
        store,
        objective,
        mesh=None,
        stop_event=None,
        injector=None,
        watchdog=None,
        drain_event=None,
    ) -> TrialResult:
        clock = get_clock()
        inj = injector or self.injector
        try:
            # the production seam: may raise InjectedFault (flake /
            # fail_trial arms) which classifies exactly like a real failure
            inj.on_trial_attempt(trial)
        except faults.InjectedFault as e:
            return TrialResult(
                TrialCondition.FAILED, str(e), faults.classify_exception(e)
            )
        attempt = inj.attempts_of(trial.name)
        rng = _stream(self.sc.seed, "trial", trial.name, attempt)
        device = self._device_of(trial.name, attempt)
        if inj.is_device_wedged(device):
            return TrialResult(
                TrialCondition.FAILED,
                f"injected device fault: dispatch to wedged device {device}",
                faults.FailureKind.DEVICE,
            )
        duration = self.sc.durations.draw(rng)
        if _wait_virtual(clock, [stop_event, drain_event], duration):
            if drain_event is not None and drain_event.is_set():
                return TrialResult(
                    TrialCondition.DRAINED,
                    "drain requested: checkpointed at a step boundary",
                )
            return TrialResult(TrialCondition.KILLED, "stop requested")
        if inj.is_device_wedged(device):
            # the wedge landed mid-flight: the program dies under the trial
            return TrialResult(
                TrialCondition.FAILED,
                f"injected device fault: device {device} wedged during step",
                faults.FailureKind.DEVICE,
            )
        self._settle_metrics(trial, store, objective, rng)
        return TrialResult(TrialCondition.SUCCEEDED)

    # -- the run_cohort seam ------------------------------------------------

    def run_cohort(
        self,
        trials,
        store,
        objective,
        mesh=None,
        stop_event=None,
        injector=None,
        watchdog=None,
        drain_event=None,
        buckets=True,
    ) -> dict:
        clock = get_clock()
        inj = injector or self.injector
        results: dict[str, TrialResult] = {}
        attempts: dict[str, int] = {}
        for t in trials:
            try:
                inj.on_trial_attempt(t)
            except faults.InjectedFault as e:
                results[t.name] = TrialResult(
                    TrialCondition.FAILED, str(e), faults.classify_exception(e)
                )
            attempts[t.name] = inj.attempts_of(t.name)
        members = [t for t in trials if t.name not in results]
        if not members:
            return results
        # one vectorized program on one sub-mesh: placement keyed by the
        # first member, the whole cohort shares it
        lead = members[0]
        device = self._device_of(lead.name, attempts[lead.name])
        slice_id = device // self.sc.slices.devices_per_slice
        device_ids = list(self.sc.slices.slice_devices(slice_id))
        try:
            # the production cohort seam: wedged device in the mesh -> one
            # DEVICE fault for the whole group (elastic degradation path)
            inj.on_cohort_execute(members, device_ids)
        except faults.InjectedFault as e:
            kind = faults.classify_exception(e)
            for t in members:
                results[t.name] = TrialResult(TrialCondition.FAILED, str(e), kind)
            return results
        duration = max(
            self.sc.durations.draw(
                _stream(self.sc.seed, "trial", t.name, attempts[t.name])
            )
            for t in members
        )
        if _wait_virtual(clock, [stop_event, drain_event], duration):
            drained = drain_event is not None and drain_event.is_set()
            for t in members:
                results[t.name] = (
                    TrialResult(
                        TrialCondition.DRAINED,
                        "drain requested: checkpointed at a step boundary",
                    )
                    if drained
                    else TrialResult(TrialCondition.KILLED, "stop requested")
                )
            return results
        hit = sorted(
            d for d in device_ids if inj.is_device_wedged(d)
        )
        if hit:
            for t in members:
                results[t.name] = TrialResult(
                    TrialCondition.FAILED,
                    f"injected device fault: wedged device(s) {hit} under cohort",
                    faults.FailureKind.DEVICE,
                )
            return results
        for t in members:
            rng = _stream(self.sc.seed, "trial", t.name, attempts[t.name])
            self.sc.durations.draw(rng)  # keep stream position == singleton path
            self._settle_metrics(t, store, objective, rng)
            results[t.name] = TrialResult(TrialCondition.SUCCEEDED)
        return results

    # -- modeled objective --------------------------------------------------

    def _settle_metrics(self, trial, store, objective, rng: random.Random) -> None:
        """A deterministic objective surface + seeded noise, reported through
        the store (the harvest loop builds the reduced Observation from
        ``store.observation_for`` — a trial with no reported points would
        settle METRICS_UNAVAILABLE).  Numeric params contribute a smooth
        bowl; categorical params a per-(name, value) hashed unit draw —
        enough structure that update_optimal behaves like a real sweep.
        No builtin ``hash()``: that is salted per-process and would break
        cross-process determinism."""
        parts = []
        for a in trial.spec.assignments:
            try:
                x = float(a.value)
            except (TypeError, ValueError):
                parts.append(
                    _stream(self.sc.seed, "cat", a.name, str(a.value)).random()
                )
            else:
                parts.append(1.0 / (1.0 + abs(x)))
        score = sum(parts) / len(parts) if parts else 0.5
        value = max(0.0, score + rng.gauss(0.0, self.sc.metric_noise))
        store.report_point(trial.name, objective.objective_metric_name, value)


class LatencySuggester:
    """Wraps the real suggester: every ``get_suggestions`` call first sleeps
    a seeded draw from the scenario's suggester latency model — the 0.5 s
    suggester of ``async_occupancy.json``, made reproducible."""

    def __init__(self, inner, scenario: Scenario):
        self._inner = inner
        self._sc = scenario
        self._calls = 0

    def __getattr__(self, name):
        return getattr(self._inner, name)

    @property
    def adaptive(self):
        return self._inner.adaptive

    def get_suggestions(self, experiment, count):
        self._calls += 1
        d = self._sc.suggest_latency.draw(
            _stream(self._sc.seed, "suggest", self._calls)
        )
        if d > 0.0:
            get_clock().sleep(d)
        return self._inner.get_suggestions(experiment, count)
