"""Virtual-time scale simulator: a discrete-event twin of the orchestrator.

The package substitutes exactly two things in a real experiment run: the
ambient clock (``katib_tpu.utils.clock``) becomes a :class:`VirtualClock`
that advances to the next armed timer instead of sleeping, and the trial
dispatch seam (``Orchestrator(run_trial_fn=...)``) becomes a modeled
executor whose durations are drawn (seeded) from committed bench
distributions.  Everything else — orchestrator, async loops, supervisor,
journal, suggester, fault injector — is the real production code.
"""

from katib_tpu.sim.clock import VirtualClock
from katib_tpu.sim.scenario import Scenario, load_scenario
from katib_tpu.sim.runner import run_scenario

__all__ = ["VirtualClock", "Scenario", "load_scenario", "run_scenario"]
