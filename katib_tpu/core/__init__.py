from katib_tpu.core.types import *  # noqa: F401,F403
from katib_tpu.core.validation import ValidationError, validate_experiment  # noqa: F401
