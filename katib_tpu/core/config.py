"""Typed framework configuration — the KatibConfig equivalent.

The reference loads a single ``KatibConfig`` object (apiVersion
``config.kubeflow.org/v1beta1``) with an ``init`` section of controller flags
and a ``runtime`` registry mapping algorithm names to suggestion-service
images/resources (``pkg/apis/config/v1beta1/types.go:27-120``, loader
``pkg/util/v1beta1/katibconfig/config.go:60``, scheme defaulting
``defaults.go:76+``).  The TPU-native config keeps the same two-section
shape but registers *in-process* runtime facts instead of container images:

- ``init``    — orchestrator flags (workdir, poll interval, default trial
  parallelism, profiler toggles) — the analog of ``ControllerConfig``.
- ``runtime`` — per-algorithm default settings and per-trial mesh shapes
  (the analog of per-algorithm image/resource registration), plus
  metrics-collector defaults per kind.
- ``store``   — observation-store backend selection (memory / sqlite /
  native / remote), the analog of the DB-manager connection config
  (``pkg/db/v1beta1/common/const.go`` env overrides).

Loading merges, in order: built-in defaults → YAML file → environment
variables (``KATIB_TPU_*``, the analog of ``consts/const.go:156-166``).
Unknown keys are rejected — parity with the reference's typed decode.
"""

from __future__ import annotations

import dataclasses
import os
from dataclasses import dataclass, field
from typing import Any, Mapping

import yaml

from katib_tpu.core.types import ExperimentSpec


class ConfigError(ValueError):
    pass


def _check_keys(section: str, data: Mapping[str, Any], allowed: tuple[str, ...]) -> None:
    unknown = set(data) - set(allowed)
    if unknown:
        raise ConfigError(
            f"unknown {section} config keys: {sorted(unknown)} (allowed: {sorted(allowed)})"
        )


@dataclass
class InitConfig:
    """Orchestrator flags (reference ``ControllerConfig``, ``types.go:35-57``)."""

    workdir: str = "katib_runs"
    poll_interval: float = 0.02
    # default for ExperimentSpec.parallel_trial_count when unset (reference
    # default 3, ``experiment_defaults.go:35``)
    parallel_trial_count: int = 3
    # per-trial JAX profiler traces under <workdir>/<exp>/<trial>/profile
    # (the reference has no tracing at all — SURVEY.md §5 gap)
    enable_profiler: bool = False
    # default mesh axes for trial execution, e.g. {"data": 4, "model": 2};
    # empty = single-device / caller-provided mesh
    mesh_axes: dict[str, int] = field(default_factory=dict)

    @classmethod
    def from_dict(cls, data: Mapping[str, Any]) -> "InitConfig":
        _check_keys("init", data, tuple(f.name for f in dataclasses.fields(cls)))
        return cls(**data)


@dataclass
class AlgorithmRuntimeConfig:
    """Per-algorithm registration (the analog of the reference's
    ``SuggestionConfig`` image/resources/PVC entry, ``types.go:77-96``)."""

    # defaults merged under the experiment's own algorithm settings
    settings: dict[str, str] = field(default_factory=dict)
    # mesh override for trials of this algorithm (DARTS wants the whole
    # slice; random-search trials can share chips)
    mesh_axes: dict[str, int] = field(default_factory=dict)
    # persistent state dir — the FromVolume-resume analog of the reference's
    # suggestion PVC (``composer.go:296``); suggester checkpoints live here
    persistent_dir: str | None = None

    @classmethod
    def from_dict(cls, data: Mapping[str, Any]) -> "AlgorithmRuntimeConfig":
        _check_keys("runtime.algorithms", data, tuple(f.name for f in dataclasses.fields(cls)))
        out = cls(**data)
        out.settings = {k: str(v) for k, v in out.settings.items()}
        return out


@dataclass
class CollectorRuntimeConfig:
    """Per-kind metrics-collector defaults (reference
    ``MetricsCollectorConfig``, ``types.go:98-108``)."""

    filter: str | None = None
    path: str | None = None

    @classmethod
    def from_dict(cls, data: Mapping[str, Any]) -> "CollectorRuntimeConfig":
        _check_keys("runtime.metrics_collectors", data, tuple(f.name for f in dataclasses.fields(cls)))
        return cls(**data)


@dataclass
class RuntimeConfig:
    algorithms: dict[str, AlgorithmRuntimeConfig] = field(default_factory=dict)
    early_stopping: dict[str, dict[str, str]] = field(default_factory=dict)
    metrics_collectors: dict[str, CollectorRuntimeConfig] = field(default_factory=dict)

    @classmethod
    def from_dict(cls, data: Mapping[str, Any]) -> "RuntimeConfig":
        _check_keys("runtime", data, ("algorithms", "early_stopping", "metrics_collectors"))
        return cls(
            algorithms={
                name: AlgorithmRuntimeConfig.from_dict(v or {})
                for name, v in (data.get("algorithms") or {}).items()
            },
            early_stopping={
                name: {k: str(v) for k, v in (v or {}).items()}
                for name, v in (data.get("early_stopping") or {}).items()
            },
            metrics_collectors={
                kind: CollectorRuntimeConfig.from_dict(v or {})
                for kind, v in (data.get("metrics_collectors") or {}).items()
            },
        )


@dataclass
class StoreConfig:
    """Observation-store backend selection (the DB-manager connection analog)."""

    backend: str = "memory"  # memory | sqlite | native | remote | mysql | postgres
    path: str = "katib_observations.db"  # sqlite file
    host: str = "127.0.0.1"  # remote db-manager
    port: int = 6789
    # external-SQL backends (reference MySQL/Postgres DB-manager,
    # ``mysql/init.go:35``): ``user:password@host:port/dbname``
    dsn: str = ""

    _BACKENDS = ("memory", "sqlite", "native", "remote", "mysql", "postgres")

    @classmethod
    def from_dict(cls, data: Mapping[str, Any]) -> "StoreConfig":
        _check_keys("store", data, ("backend", "path", "host", "port", "dsn"))
        out = cls(**data)
        if out.backend not in cls._BACKENDS:
            raise ConfigError(
                f"store.backend {out.backend!r} not in {cls._BACKENDS}"
            )
        return out

    def make_store(self):
        if self.backend == "memory":
            from katib_tpu.store.base import MemoryObservationStore

            return MemoryObservationStore()
        if self.backend == "sqlite":
            from katib_tpu.store.sqlite import SqliteObservationStore

            return SqliteObservationStore(self.path)
        if self.backend == "native":
            from katib_tpu.native import NativeObservationStore, native_available

            if not native_available():
                from katib_tpu.store.base import MemoryObservationStore

                return MemoryObservationStore()
            return NativeObservationStore()
        if self.backend in ("mysql", "postgres"):
            return self._make_dbapi_store()
        from katib_tpu.native.dbmanager import RemoteObservationStore

        return RemoteObservationStore(self.host, self.port)

    def _make_dbapi_store(self):
        """External-SQL store over the reference's observation_logs schema
        (``store/dbapi.py``).  Drivers are imported lazily — whichever of
        the usual DB-API modules is installed is used."""
        from katib_tpu.store.dbapi import DbapiObservationStore

        user, password, host, port, dbname = _parse_dsn(
            self.dsn, default_port=3306 if self.backend == "mysql" else 5432
        )
        candidates = (
            ("pymysql", "MySQLdb")
            if self.backend == "mysql"
            else ("psycopg2", "pg8000")
        )
        # database=, not dbname=: every candidate accepts database= (psycopg2
        # takes both spellings; pg8000's connect() only knows database=)
        kwargs = dict(
            user=user, password=password, host=host, port=port, database=dbname
        )
        import importlib

        last_err: Exception | None = None
        for mod_name in candidates:
            try:
                mod = importlib.import_module(mod_name)
            except ImportError as e:
                last_err = e
                continue
            return DbapiObservationStore(
                lambda: mod.connect(**kwargs), dialect=self.backend
            )
        raise ConfigError(
            f"store.backend {self.backend!r} needs one of {candidates} "
            f"installed (none importable: {last_err})"
        )


def _parse_dsn(
    dsn: str, default_port: int
) -> tuple[str, str, str, int, str]:
    """``user[:password]@host[:port]/dbname`` -> components (the shape of
    the reference's env-assembled MySQL DSN, ``mysql/mysql.go:40-55``)."""
    cred, _, rest = dsn.rpartition("@")
    user, _, password = cred.partition(":")
    hostport, _, dbname = rest.partition("/")
    host, _, port_s = hostport.partition(":")
    try:
        port = int(port_s) if port_s else default_port
    except ValueError:
        raise ConfigError(f"store.dsn has non-numeric port: {dsn!r}") from None
    if not host or not dbname:
        raise ConfigError(
            f"store.dsn must look like user:password@host:port/dbname, got {dsn!r}"
        )
    return user, password, host, port, dbname


# env-var overrides, the analog of ``consts/const.go:156-166`` /
# ``pkg/db/v1beta1/common/const.go``
_ENV_OVERRIDES = (
    ("KATIB_TPU_WORKDIR", ("init", "workdir"), str),
    ("KATIB_TPU_STORE_BACKEND", ("store", "backend"), str),
    ("KATIB_TPU_STORE_PATH", ("store", "path"), str),
    ("KATIB_TPU_DB_HOST", ("store", "host"), str),
    ("KATIB_TPU_DB_PORT", ("store", "port"), int),
    ("KATIB_TPU_DB_DSN", ("store", "dsn"), str),
)


@dataclass
class KatibConfig:
    init: InitConfig = field(default_factory=InitConfig)
    runtime: RuntimeConfig = field(default_factory=RuntimeConfig)
    store: StoreConfig = field(default_factory=StoreConfig)

    # -- loading ------------------------------------------------------------

    @classmethod
    def from_dict(cls, data: Mapping[str, Any]) -> "KatibConfig":
        _check_keys("top-level", data, ("apiVersion", "kind", "init", "runtime", "store"))
        api = data.get("apiVersion")
        if api is not None and api != "config.katib-tpu.dev/v1":
            raise ConfigError(f"unsupported apiVersion {api!r}")
        return cls(
            init=InitConfig.from_dict(data.get("init") or {}),
            runtime=RuntimeConfig.from_dict(data.get("runtime") or {}),
            store=StoreConfig.from_dict(data.get("store") or {}),
        )

    @classmethod
    def load(cls, path: str | None = None, env: Mapping[str, str] | None = None) -> "KatibConfig":
        """Defaults → YAML file (if given) → ``KATIB_TPU_*`` env overrides."""
        data: dict[str, Any] = {}
        if path is not None:
            with open(path) as f:
                loaded = yaml.safe_load(f) or {}
            if not isinstance(loaded, dict):
                raise ConfigError(f"config file {path} must be a mapping")
            data = loaded
        cfg = cls.from_dict(data)
        env = os.environ if env is None else env
        for var, (section, key), cast in _ENV_OVERRIDES:
            if var in env:
                try:
                    value = cast(env[var])
                except ValueError as e:
                    raise ConfigError(f"bad env override {var}={env[var]!r}") from e
                setattr(getattr(cfg, section), key, value)
        if cfg.store.backend not in StoreConfig._BACKENDS:
            raise ConfigError(
                f"store.backend {cfg.store.backend!r} not in {StoreConfig._BACKENDS}"
            )
        return cfg

    # -- application --------------------------------------------------------

    def apply_to(self, spec: ExperimentSpec) -> ExperimentSpec:
        """Merge registered runtime defaults into an experiment spec: config
        algorithm settings sit under the experiment's own (the reference
        merges service defaults the same way — e.g. DARTS
        ``service.py:118-135``), and collector filter/path fill unset fields."""
        spec = dataclasses.replace(spec) if dataclasses.is_dataclass(spec) else spec
        algo_cfg = self.runtime.algorithms.get(spec.algorithm.name)
        if algo_cfg and algo_cfg.settings:
            merged = {**algo_cfg.settings, **dict(spec.algorithm.settings)}
            spec.algorithm = dataclasses.replace(spec.algorithm, settings=merged)
        if spec.early_stopping is not None:
            es_cfg = self.runtime.early_stopping.get(spec.early_stopping.name)
            if es_cfg:
                merged = {**es_cfg, **dict(spec.early_stopping.settings)}
                spec.early_stopping = dataclasses.replace(
                    spec.early_stopping, settings=merged
                )
        mc = spec.metrics_collector
        mc_cfg = self.runtime.metrics_collectors.get(mc.kind.value)
        if mc_cfg:
            spec.metrics_collector = dataclasses.replace(
                mc,
                filter=mc.filter or mc_cfg.filter,
                path=mc.path or mc_cfg.path,
            )
        return spec

    def mesh_axes_for(self, algorithm: str) -> dict[str, int]:
        algo_cfg = self.runtime.algorithms.get(algorithm)
        if algo_cfg and algo_cfg.mesh_axes:
            return dict(algo_cfg.mesh_axes)
        return dict(self.init.mesh_axes)

    def make_orchestrator(self, **overrides):
        """Build an Orchestrator wired from this config (store backend,
        workdir, poll interval); ``overrides`` win."""
        from katib_tpu.orchestrator.orchestrator import Orchestrator

        kwargs: dict[str, Any] = dict(
            store=self.store.make_store(),
            workdir=self.init.workdir,
            poll_interval=self.init.poll_interval,
            config=self,
        )
        kwargs.update(overrides)
        return Orchestrator(**kwargs)
