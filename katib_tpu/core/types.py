"""Core domain types for the TPU-native AutoML framework.

These are the framework's equivalent of the reference's CRD type layer (L0):

- Parameter / feasible-space model  -> reference ``pkg/apis/controller/experiments/v1beta1/experiment_types.go:196-215``
- Objective & metric strategies     -> reference ``pkg/apis/controller/common/v1beta1/common_types.go:94-160``
- Algorithm / early-stopping specs  -> reference ``common_types.go:24-66``
- Trial assignments & observations  -> reference ``pkg/apis/controller/trials/v1beta1/trial_types.go:27-126``,
                                       ``pkg/apis/controller/suggestions/v1beta1/suggestion_types.go:77``

The design is deliberately *not* a CRD translation: there is no Kubernetes, no
unstructured YAML round-tripping, no status-condition churn over an API server.
Experiments, trials and suggestions are plain Python objects owned by an
in-process orchestrator; trials are (by default) white-box JAX functions rather
than opaque containers, which collapses the reference's webhook/sidecar
machinery into direct function calls.
"""

from __future__ import annotations

import dataclasses
import enum
import math
import time
from dataclasses import dataclass, field

from katib_tpu.utils.clock import get_clock
from typing import Any, Callable, Iterable, Iterator, Mapping, Sequence

__all__ = [
    "ParameterType",
    "Distribution",
    "FeasibleSpace",
    "ParameterSpec",
    "ParameterAssignment",
    "ObjectiveType",
    "MetricStrategyType",
    "MetricStrategy",
    "ObjectiveSpec",
    "AlgorithmSpec",
    "EarlyStoppingSpec",
    "ComparisonOp",
    "EarlyStoppingRule",
    "MetricsCollectorKind",
    "MetricsCollectorSpec",
    "GraphConfig",
    "NasOperation",
    "NasConfig",
    "ResumePolicy",
    "TrialCondition",
    "Metric",
    "MetricLog",
    "Observation",
    "TrialAssignmentSet",
    "TrialSpec",
    "Trial",
    "ExperimentCondition",
    "ExperimentSpec",
    "Experiment",
    "OptimalTrial",
]


# ---------------------------------------------------------------------------
# Parameters & search space
# ---------------------------------------------------------------------------


class ParameterType(str, enum.Enum):
    """Parameter kinds (reference ``experiment_types.go:196-204``)."""

    DOUBLE = "double"
    INT = "int"
    DISCRETE = "discrete"
    CATEGORICAL = "categorical"


class Distribution(str, enum.Enum):
    """Sampling distribution hints (reference ``experiment_types.go:225-231``)."""

    UNIFORM = "uniform"
    LOG_UNIFORM = "logUniform"
    NORMAL = "normal"
    LOG_NORMAL = "logNormal"


@dataclass(frozen=True)
class FeasibleSpace:
    """Feasible region of one parameter (reference ``experiment_types.go:209-215``).

    ``min``/``max``/``step`` apply to double/int parameters; ``list`` applies to
    discrete/categorical.  Values are kept in native Python types rather than the
    reference's all-strings encoding.
    """

    min: float | None = None
    max: float | None = None
    list: tuple[Any, ...] | None = None
    step: float | None = None
    distribution: Distribution = Distribution.UNIFORM

    def __post_init__(self) -> None:
        if self.list is not None and not isinstance(self.list, tuple):
            object.__setattr__(self, "list", tuple(self.list))

    def width(self) -> float:
        if self.min is None or self.max is None:
            raise ValueError("width() requires min/max bounds")
        return float(self.max) - float(self.min)

    def is_log_scaled(self) -> bool:
        return self.distribution in (Distribution.LOG_UNIFORM, Distribution.LOG_NORMAL)


@dataclass(frozen=True)
class ParameterSpec:
    """One tunable parameter (reference ``experiment_types.go:196-207``)."""

    name: str
    type: ParameterType
    feasible: FeasibleSpace

    def __post_init__(self) -> None:
        t, f = self.type, self.feasible
        if t in (ParameterType.DOUBLE, ParameterType.INT):
            if f.min is None or f.max is None:
                raise ValueError(f"parameter {self.name!r}: {t.value} requires min and max")
            if f.max < f.min:
                raise ValueError(f"parameter {self.name!r}: max < min")
            if f.is_log_scaled() and f.min <= 0:
                raise ValueError(f"parameter {self.name!r}: log distribution requires min > 0")
        else:
            if not f.list:
                raise ValueError(f"parameter {self.name!r}: {t.value} requires a non-empty list")

    # -- value helpers -----------------------------------------------------

    def cast(self, value: Any) -> Any:
        """Coerce a raw value into this parameter's native type."""
        if self.type is ParameterType.DOUBLE:
            return float(value)
        if self.type is ParameterType.INT:
            return int(round(float(value)))
        if self.type is ParameterType.DISCRETE:
            # discrete values are numeric; match against the list
            v = float(value)
            for item in self.feasible.list or ():
                if math.isclose(float(item), v, rel_tol=1e-12, abs_tol=1e-12):
                    return item
            return v
        return value

    def grid_values(self, max_points: int = 25) -> list[Any]:
        """Enumerate candidate grid values (used by grid search & validation)."""
        f = self.feasible
        if self.type in (ParameterType.DISCRETE, ParameterType.CATEGORICAL):
            return [self.cast(v) for v in f.list or ()]
        if self.type is ParameterType.INT:
            step = int(f.step or 1)
            return [int(v) for v in range(int(f.min), int(f.max) + 1, max(step, 1))]
        # double: need an explicit step, otherwise linspace over max_points
        if f.step:
            n = int(math.floor((f.max - f.min) / f.step + 1e-9)) + 1
            return [float(f.min) + i * float(f.step) for i in range(n)]
        n = max_points
        return [float(f.min) + (f.max - f.min) * i / (n - 1) for i in range(n)]

    def contains(self, value: Any) -> bool:
        try:
            v = self.cast(value)
        except (TypeError, ValueError):
            return False
        f = self.feasible
        if self.type in (ParameterType.DOUBLE, ParameterType.INT):
            return f.min - 1e-12 <= float(v) <= f.max + 1e-12
        if self.type is ParameterType.DISCRETE:
            return any(math.isclose(float(x), float(v), rel_tol=1e-12) for x in f.list)
        return v in f.list


@dataclass(frozen=True)
class ParameterAssignment:
    """A concrete (name, value) binding (reference ``common_types.go:178-185``)."""

    name: str
    value: Any

    def as_tuple(self) -> tuple[str, Any]:
        return (self.name, self.value)


def assignments_to_dict(assignments: Sequence[ParameterAssignment]) -> dict[str, Any]:
    return {a.name: a.value for a in assignments}


# ---------------------------------------------------------------------------
# Objective & metrics
# ---------------------------------------------------------------------------


class ObjectiveType(str, enum.Enum):
    """minimize/maximize (reference ``common_types.go:84-91``)."""

    MINIMIZE = "minimize"
    MAXIMIZE = "maximize"

    def better(self, a: float, b: float) -> bool:
        """True if ``a`` is strictly better than ``b`` under this objective."""
        return a < b if self is ObjectiveType.MINIMIZE else a > b

    def best(self, values: Sequence[float]) -> float:
        return min(values) if self is ObjectiveType.MINIMIZE else max(values)


class MetricStrategyType(str, enum.Enum):
    """How to reduce a metric's log to one value (reference ``common_types.go:129-136``)."""

    MIN = "min"
    MAX = "max"
    LATEST = "latest"

    def reduce(self, values: Sequence[float]) -> float:
        if not values:
            raise ValueError("cannot reduce empty metric log")
        if self is MetricStrategyType.MIN:
            return min(values)
        if self is MetricStrategyType.MAX:
            return max(values)
        return values[-1]


@dataclass(frozen=True)
class MetricStrategy:
    """Per-metric extraction strategy (reference ``common_types.go:138-144``)."""

    name: str
    value: MetricStrategyType


@dataclass(frozen=True)
class ObjectiveSpec:
    """Optimization objective (reference ``common_types.go:94-127``).

    ``goal`` stops the experiment early when reached.  ``metric_strategies``
    default to max for maximize / min for minimize on the objective metric and
    latest for additional metrics, matching the reference's defaulting
    (``experiment_defaults.go:55-88``).
    """

    type: ObjectiveType
    objective_metric_name: str
    goal: float | None = None
    additional_metric_names: tuple[str, ...] = ()
    metric_strategies: tuple[MetricStrategy, ...] = ()

    def __post_init__(self) -> None:
        if not isinstance(self.additional_metric_names, tuple):
            object.__setattr__(self, "additional_metric_names", tuple(self.additional_metric_names))
        if not isinstance(self.metric_strategies, tuple):
            object.__setattr__(self, "metric_strategies", tuple(self.metric_strategies))

    def all_metric_names(self) -> tuple[str, ...]:
        return (self.objective_metric_name, *self.additional_metric_names)

    def strategy_for(self, metric_name: str) -> MetricStrategyType:
        for s in self.metric_strategies:
            if s.name == metric_name:
                return s.value
        if metric_name == self.objective_metric_name:
            return (
                MetricStrategyType.MIN
                if self.type is ObjectiveType.MINIMIZE
                else MetricStrategyType.MAX
            )
        return MetricStrategyType.LATEST

    def is_goal_reached(self, value: float) -> bool:
        if self.goal is None:
            return False
        if self.type is ObjectiveType.MINIMIZE:
            return value <= self.goal
        return value >= self.goal


# ---------------------------------------------------------------------------
# Algorithm / early-stopping specs
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class AlgorithmSpec:
    """Suggestion algorithm + settings (reference ``common_types.go:24-40``).

    Settings are a plain mapping; Hyperband mutates them between rounds (the
    reference round-trips the mutation through ``Suggestion.Status.AlgorithmSettings``,
    ``suggestionclient.go:194-196`` — here the orchestrator owns the mutable copy).
    """

    name: str
    settings: Mapping[str, str] = field(default_factory=dict)

    def setting(self, key: str, default: str | None = None) -> str | None:
        return self.settings.get(key, default)


@dataclass(frozen=True)
class EarlyStoppingSpec:
    """Early-stopping algorithm + settings (reference ``common_types.go:42-58``)."""

    name: str
    settings: Mapping[str, str] = field(default_factory=dict)


class ComparisonOp(str, enum.Enum):
    """Rule comparison (reference ``api.proto`` ComparisonType / ``common_types.go:160-176``)."""

    EQUAL = "equal"
    LESS = "less"
    GREATER = "greater"

    def holds(self, observed: float, threshold: float) -> bool:
        if self is ComparisonOp.LESS:
            return observed < threshold
        if self is ComparisonOp.GREATER:
            return observed > threshold
        return math.isclose(observed, threshold, rel_tol=1e-9, abs_tol=1e-12)


@dataclass(frozen=True)
class EarlyStoppingRule:
    """One stop rule attached to a trial (reference ``common_types.go:160-176``).

    ``start_step``: the rule only fires once the metric has been reported at
    least ``start_step`` times (reference ``file-metricscollector/main.go:332-361``).
    """

    name: str
    value: float
    comparison: ComparisonOp
    start_step: int = 0

    def describe(self) -> str:
        return f"rule {self.name} {self.comparison.value} {self.value}"


# ---------------------------------------------------------------------------
# Metrics collection
# ---------------------------------------------------------------------------


class MetricsCollectorKind(str, enum.Enum):
    """Collector kinds (reference ``common_types.go:205-227``).

    ``PUSH`` is the TPU-native default: white-box trials report metrics through
    a direct in-process callback, eliminating the reference's sidecar scraping.
    The file/stdout kinds remain for black-box subprocess trials.
    """

    PUSH = "Push"
    STDOUT = "StdOut"
    FILE = "File"
    JSONL = "JsonLines"
    # TensorBoard event files written by the trial (reference
    # TensorFlowEvent collector, ``common_types.go:212-215``); parsed after
    # the trial exits by ``runner/tfevent.py`` — no TF dependency.
    TFEVENT = "TensorFlowEvent"
    # Scrape the trial's Prometheus exposition endpoint while it runs
    # (reference Prometheus collector kind, ``common_types.go:216-219``).
    PROMETHEUS = "Prometheus"
    NONE = "None"


@dataclass(frozen=True)
class MetricsCollectorSpec:
    """Metrics collection config (reference ``common_types.go:230-260``)."""

    kind: MetricsCollectorKind = MetricsCollectorKind.PUSH
    # For FILE/JSONL collectors: path the black-box trial writes to.
    # For PROMETHEUS: the HTTP path of the exposition endpoint (default
    # ``/metrics``, reference ``common_types.go:47``).
    path: str | None = None
    # Metric line filter, default matches the reference's TEXT format regex
    # ``([\w|-]+)\s*=\s*([+-]?\d...)`` (``pkg/metricscollector/v1beta1/common/const.go``).
    filter: str | None = None
    # PROMETHEUS only: port the trial listens on and scrape cadence.
    port: int | None = None
    scrape_interval: float = 1.0


# ---------------------------------------------------------------------------
# NAS config
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class GraphConfig:
    """NAS macro-graph bounds (reference ``experiment_types.go:308-315``)."""

    num_layers: int = 8
    input_sizes: tuple[int, ...] = ()
    output_sizes: tuple[int, ...] = ()


@dataclass(frozen=True)
class NasOperation:
    """One NAS primitive with its own sub-search-space (reference ``experiment_types.go:317-320``)."""

    operation_type: str
    parameters: tuple[ParameterSpec, ...] = ()


@dataclass(frozen=True)
class NasConfig:
    """NAS search configuration (reference ``experiment_types.go:304-306``)."""

    graph_config: GraphConfig = field(default_factory=GraphConfig)
    operations: tuple[NasOperation, ...] = ()


# ---------------------------------------------------------------------------
# Trials
# ---------------------------------------------------------------------------


class ResumePolicy(str, enum.Enum):
    """Experiment resume semantics (reference ``experiment_types.go:181-191``)."""

    NEVER = "Never"
    LONG_RUNNING = "LongRunning"
    FROM_VOLUME = "FromVolume"


class TrialCondition(str, enum.Enum):
    """Trial lifecycle states (reference ``trial_types.go:118-126``)."""

    CREATED = "Created"
    PENDING = "Pending"
    RUNNING = "Running"
    SUCCEEDED = "Succeeded"
    KILLED = "Killed"
    FAILED = "Failed"
    EARLY_STOPPED = "EarlyStopped"
    METRICS_UNAVAILABLE = "MetricsUnavailable"
    # checkpoint-and-exit during an orchestrator drain (preemption SIGTERM):
    # deliberately NON-terminal — a resumed run resubmits the trial under the
    # same name/checkpoint dir and it continues from its last saved step, and
    # the max_trial_count budget is never charged for a preempted slot
    DRAINED = "Drained"

    def is_terminal(self) -> bool:
        return self in (
            TrialCondition.SUCCEEDED,
            TrialCondition.KILLED,
            TrialCondition.FAILED,
            TrialCondition.EARLY_STOPPED,
            TrialCondition.METRICS_UNAVAILABLE,
        )

    def is_completed_ok(self) -> bool:
        """Counts toward the suggestion-request budget (reference
        ``experiment_controller.go:449-461`` counts succeeded + early-stopped)."""
        return self in (TrialCondition.SUCCEEDED, TrialCondition.EARLY_STOPPED)


@dataclass(frozen=True)
class Metric:
    """One reduced metric (reference ``common_types.go:187-195``)."""

    name: str
    value: float
    min: float = math.nan
    max: float = math.nan
    latest: float = math.nan


@dataclass(frozen=True)
class MetricLog:
    """One raw reported point (reference ``api.proto`` MetricLog)."""

    metric_name: str
    value: float
    timestamp: float = 0.0
    step: int = -1


@dataclass
class Observation:
    """Reduced view of a trial's metric logs (reference ``common_types.go:196-203``)."""

    metrics: list[Metric] = field(default_factory=list)

    def get(self, name: str) -> Metric | None:
        for m in self.metrics:
            if m.name == name:
                return m
        return None


@dataclass
class TrialAssignmentSet:
    """A suggester's proposal for one trial (reference ``suggestion_types.go:77-96``).

    ``labels`` carry algorithm lineage (PBT generation/parent), mirroring the
    reference's suggestion-label propagation (``pbt/service.py:183-187``).
    """

    assignments: list[ParameterAssignment]
    name: str | None = None
    early_stopping_rules: list[EarlyStoppingRule] = field(default_factory=list)
    labels: dict[str, str] = field(default_factory=dict)

    def as_dict(self) -> dict[str, Any]:
        return assignments_to_dict(self.assignments)


@dataclass
class TrialSpec:
    """What to run for one trial (reference ``trial_types.go:27-80``).

    Instead of an unstructured Kubernetes ``RunSpec``, a trial either calls a
    white-box Python/JAX ``train_fn(ctx)`` or launches a black-box subprocess
    command (argv with ``${trialParameters.X}`` placeholders, parity with the
    reference's template substitution ``manifest/generator.go:79-99``).
    """

    assignments: list[ParameterAssignment] = field(default_factory=list)
    early_stopping_rules: list[EarlyStoppingRule] = field(default_factory=list)
    labels: dict[str, str] = field(default_factory=dict)
    # Exactly one of train_fn / command should be set.
    train_fn: Callable[..., Any] | None = None
    command: list[str] | None = None
    metrics_collector: MetricsCollectorSpec = field(default_factory=MetricsCollectorSpec)
    # retain trial artifacts (checkpoints, logs) after completion
    retain: bool = False
    # wall-clock deadline for one trial run; None = unbounded (the reference
    # bounds every e2e experiment at 40 min, ``run-e2e-experiment.py:11`` —
    # here the bound is enforced per trial so a hung trial can't pin a slot)
    max_runtime_seconds: float | None = None
    # bounded re-runs when the trial succeeds but never reported the
    # objective metric (the reference requeues metrics-not-reported trials,
    # ``trial_controller.go:182-185``); 0 = classify immediately
    metrics_retries: int = 0
    # bounded re-runs after a TRANSIENT failure (preemption,
    # RESOURCE_EXHAUSTED, OSError family, retryable exit code — see
    # utils/faults.py); retries reuse the trial's name and checkpoint dir so
    # a checkpoint-aware train_fn resumes mid-trial.  Permanent failures
    # (ValueError/assertion/shape errors) never retry.  0 = classify the
    # first failure immediately
    max_retries: int = 0
    # first-retry delay for the shared exponential backoff (doubles per
    # attempt, jittered, capped at ~30s, stop-event responsive)
    retry_backoff_seconds: float = 1.0
    # hang watchdog: fail the trial FailureKind.HANG when no progress
    # (report() call / cohort step / black-box metric activity) lands for
    # this long (utils/watchdog.py).  Unlike max_runtime_seconds — which is
    # only polled at reporting points for white-box trials — the watchdog's
    # monitor thread interrupts a train_fn wedged BETWEEN reports (stuck
    # compile, deadlocked collective).  None = disabled.
    progress_deadline_seconds: float | None = None
    # compile watchdog: budget for jit compile + FIRST dispatch (trace to
    # first ctx.report()).  The progress watchdog only arms per-step cadence;
    # a 470s live compile (BENCH_r05) is indistinguishable from a wedge
    # without a separate budget.  Overruns classify as the retryable
    # FailureKind.COMPILE_HANG.  None = disabled.
    compile_deadline_seconds: float | None = None

    def params(self) -> dict[str, Any]:
        return assignments_to_dict(self.assignments)


@dataclass
class Trial:
    """A trial instance + status (reference ``trial_types.go`` + status)."""

    name: str
    spec: TrialSpec
    experiment_name: str = ""
    condition: TrialCondition = TrialCondition.CREATED
    observation: Observation | None = None
    message: str = ""
    start_time: float = 0.0
    completion_time: float = 0.0
    checkpoint_dir: str | None = None
    # transient-failure retries consumed so far — journaled to status.json so
    # a resume-after-crash continues with the budget already spent rather
    # than resetting it (budget math still counts the trial once)
    retry_count: int = 0
    # FailureKind value ("Transient"/"Permanent") of the most recent failed
    # attempt, None while no attempt has failed (or after a later success)
    failure_kind: str | None = None

    def params(self) -> dict[str, Any]:
        return self.spec.params()

    @property
    def labels(self) -> dict[str, str]:
        return self.spec.labels

    def objective_value(self, objective: ObjectiveSpec) -> float | None:
        if self.observation is None:
            return None
        m = self.observation.get(objective.objective_metric_name)
        return None if m is None else m.value


# ---------------------------------------------------------------------------
# Experiments
# ---------------------------------------------------------------------------


class ExperimentCondition(str, enum.Enum):
    """Experiment lifecycle (reference ``experiment_types.go:136-160``)."""

    CREATED = "Created"
    RUNNING = "Running"
    RESTARTING = "Restarting"
    GOAL_REACHED = "GoalReached"
    MAX_TRIALS_REACHED = "MaxTrialsReached"
    SUCCEEDED = "Succeeded"
    FAILED = "Failed"

    def is_terminal(self) -> bool:
        return self in (
            ExperimentCondition.SUCCEEDED,
            ExperimentCondition.FAILED,
            ExperimentCondition.GOAL_REACHED,
            ExperimentCondition.MAX_TRIALS_REACHED,
        )


@dataclass
class ExperimentSpec:
    """Experiment definition (reference ``experiment_types.go:27-80``)."""

    name: str
    objective: ObjectiveSpec
    algorithm: AlgorithmSpec
    parameters: list[ParameterSpec] = field(default_factory=list)
    nas_config: NasConfig | None = None
    early_stopping: EarlyStoppingSpec | None = None
    # Budget knobs (reference ``experiment_types.go:41-53``; defaults
    # ``experiment_defaults.go:31-44``).
    parallel_trial_count: int = 3
    max_trial_count: int | None = None
    # None = unlimited (reference: nil MaxFailedTrialCount never fails the
    # experiment, ``status_util.go:204-205``)
    max_failed_trial_count: int | None = None
    resume_policy: ResumePolicy = ResumePolicy.NEVER
    metrics_collector: MetricsCollectorSpec = field(default_factory=MetricsCollectorSpec)
    # White-box trial entry point: fn(ctx) -> None, metrics via ctx.report(...).
    train_fn: Callable[..., Any] | None = None
    # Black-box alternative: argv template with ${trialParameters.X} placeholders.
    command: list[str] | None = None
    # Keep trial artifacts (checkpoint steps) after successful completion
    # (reference ``trialTemplate.retain``, ``trial_types.go:57``).
    retain: bool = False
    # Per-trial wall-clock deadline + metrics-unavailable retry budget,
    # propagated into every TrialSpec (see TrialSpec for reference parity).
    max_trial_runtime_seconds: float | None = None
    metrics_retries: int = 0
    # Transient-failure retry budget + backoff base, propagated into every
    # TrialSpec (see TrialSpec / utils.faults for the taxonomy).
    max_retries: int = 0
    retry_backoff_seconds: float = 1.0
    # Suggester circuit breaker: this many CONSECUTIVE get_suggestions
    # exceptions fail the experiment with the last traceback; fewer are
    # counted (katib_suggester_errors_total) and retried after a cooldown
    # while in-flight trials keep running.
    suggester_max_errors: int = 5
    # Vectorized trial cohorts: up to this many compatible pending trials
    # (same cohortKey — same model, shapes, step count) execute as ONE
    # vmapped jitted program sharing a single compiled executable
    # (runner/cohort.py).  1 = disabled; requires a cohort-capable train_fn
    # (see runner.cohort.attach_cohort_fn).
    cohort_width: int = 1
    # Default cohort key stamped on every trial when cohort_width > 1;
    # proposals may override per trial via the COHORT_KEY_LABEL label
    # (PBT generations, Hyperband rungs).  None = only labeled proposals
    # group into cohorts.
    cohort_key: str | None = None
    # Cohort shape bucketing: pad each cohort's member axis up to the next
    # power of two (x trial-axis multiple) instead of the exact width, so
    # heterogeneous cohort sizes collapse onto a handful of cached
    # executables — ghost members make the extra rows free
    # (katib_tpu/compile/buckets.py).  Only affects orchestrator-driven
    # cohorts; the direct run_cohort API defaults to exact padding.
    cohort_buckets: bool = True
    # Background compile prewarm: while trials run, a best-effort daemon
    # worker compiles upcoming groups' programs (via the train_fn's prewarm
    # twin, see compile.prewarm.attach_prewarm_fn) into the jit + persistent
    # caches so their first step deserializes instead of recompiling.
    # No-op for train_fns without a prewarm twin; never fails a trial.
    prewarm: bool = True
    # Persistent XLA compilation-cache directory wired at run() start
    # (jax_compilation_cache_dir); None falls back to the
    # KATIB_COMPILE_CACHE env var, empty/unset disables.
    compile_cache: str | None = None
    # Shared artifact tier: a fleet-shared directory of serialized AOT
    # executables (compile/artifacts.py).  With it wired, the prewarm
    # worker publishes what it compiles and the dispatch path fetches
    # before tracing, so a brand-new host's first step is warm.  None
    # falls back to KATIB_ARTIFACT_DIR; empty/unset disables the tier
    # (the local <compile_cache>/artifacts tier still works).
    artifact_dir: str | None = None
    # Hang watchdog: classify a trial FailureKind.HANG (and interrupt it)
    # when no progress signal lands for this long — propagated into every
    # TrialSpec (see TrialSpec.progress_deadline_seconds).  None = disabled.
    progress_deadline_seconds: float | None = None
    # Graceful-drain window after SIGTERM/SIGINT on `katib-tpu run`: running
    # trials get this long to checkpoint-and-exit at a step boundary before
    # being hard-killed (still journaled Drained, so resume re-runs them).
    drain_grace_seconds: float = 30.0
    # Compile watchdog: fail a trial FailureKind.COMPILE_HANG (retryable)
    # when its jit compile + first dispatch exceed this budget — propagated
    # into every TrialSpec (see TrialSpec.compile_deadline_seconds).
    # None = disabled.
    compile_deadline_seconds: float | None = None
    # Async orchestrator (podracer-style decoupled suggest/schedule/harvest
    # loops, orchestrator/async_loops.py): None decides from the
    # KATIB_ASYNC_ORCH env var (default ON; "0" keeps the legacy
    # synchronous propose->execute->harvest loop for one release).
    async_orch: bool | None = None
    # Async suggest loop: how many proposed-but-undispatched trials to keep
    # journaled and ready ahead of the scheduler, so suggester latency hides
    # behind training instead of idling the mesh.  None = auto
    # (4 x max(parallel_trial_count, effective cohort width)).
    suggest_lookahead: int | None = None
    # Async schedule loop backpressure: dispatch new work while measured
    # device occupancy (busy executor slots / parallel_trial_count) is below
    # this target; 1.0 keeps every slot busy with one unit queued behind it,
    # lower values deliberately throttle (e.g. leave headroom for a
    # co-tenant experiment).
    occupancy_target: float = 1.0
    # Async cohort packing: a partially-filled shape bucket flushes after
    # waiting this long for more compatible ready trials (and immediately
    # when the remaining max_trial_count budget can never fill it) instead
    # of waiting indefinitely for a full-width group.
    cohort_fill_deadline_seconds: float = 2.0
    # Loop supervision (orchestrator/supervisor.py): a live async loop whose
    # progress watermark has not advanced for this long — while upstream work
    # was available — is classified STALLED and restarted from journal state.
    loop_stall_deadline_seconds: float = 60.0
    # Per-loop restart budget: after this many restarts of any single loop
    # the supervisor stops healing and degrades to the synchronous path
    # (KATIB_ASYNC_ORCH=0 semantics) instead of dying. 0 = never restart,
    # fall back on the first crash/stall.
    loop_restart_budget: int = 3
    # On-device PBT escape hatch (pbt-ondevice algorithm, parallel/pbt.py):
    # None defers to the algorithm's `on_device` setting (default ON);
    # False forces the host checkpoint-exchange path, True forces the
    # fused on-device generation loop.  KATIB_PBT_ONDEVICE env wins over
    # both (operator kill switch without editing specs).
    pbt_ondevice: bool | None = None
    # Speculative straggler re-dispatch: when a member runs past
    # straggler_factor x the median settle time it is re-submitted as a
    # singleton; first settle wins (exactly-once journal keying), the rival
    # is cancelled/ignored. Off by default — it burns a slot per straggler.
    speculative_redispatch: bool = False
    straggler_factor: float = 4.0

    def parameter(self, name: str) -> ParameterSpec:
        for p in self.parameters:
            if p.name == name:
                return p
        raise KeyError(name)

    def search_space_size(self) -> float:
        """Cardinality of the fully-discrete space, inf if any double lacks a step."""
        size = 1.0
        for p in self.parameters:
            if p.type is ParameterType.DOUBLE and not p.feasible.step:
                return math.inf
            size *= len(p.grid_values())
        return size


@dataclass
class OptimalTrial:
    """Best-so-far tracking (reference ``experiment/util/status_util.go``)."""

    trial_name: str
    objective_value: float
    assignments: list[ParameterAssignment]
    observation: Observation


# Trial label naming the device count the trial's sub-mesh lease should
# span.  Lives here (jax-free module) so the producers (suggesters) and the
# consumer (orchestrator + ElasticSliceAllocator) share one definition
# without dragging jax into metadata-only import paths.
DEVICES_LABEL = "katib-tpu/devices"

# Trial label naming the vectorized-cohort compatibility class: trials whose
# specs carry the same value (same model, shapes, step count) may be batched
# into one vmapped program up to ExperimentSpec.cohort_width.  Jax-free for
# the same reason as DEVICES_LABEL — suggesters stamp it, the orchestrator
# groups on it, runner/cohort.py executes the group.
COHORT_KEY_LABEL = "katib-tpu/cohort-key"


@dataclass
class Experiment:
    """Experiment instance + live status (spec + the reference's ExperimentStatus,
    ``experiment_types.go:83-134``)."""

    spec: ExperimentSpec
    condition: ExperimentCondition = ExperimentCondition.CREATED
    trials: dict[str, Trial] = field(default_factory=dict)
    optimal: OptimalTrial | None = None
    start_time: float = field(default_factory=lambda: get_clock().time())
    completion_time: float = 0.0
    message: str = ""
    # Mutable algorithm settings (Hyperband state lives here; reference
    # round-trips it via Suggestion.Status.AlgorithmSettings).
    algorithm_settings: dict[str, str] = field(default_factory=dict)
    # best-objective@wallclock: one row per improvement of the optimal
    # trial ({time, elapsed_s, objective_value, trial_name}) — the BASELINE
    # driver metric, journaled with the status so every experiment carries
    # its own convergence curve
    optimal_history: list[dict] = field(default_factory=list)

    def __post_init__(self) -> None:
        if not self.algorithm_settings:
            self.algorithm_settings = dict(self.spec.algorithm.settings)

    @property
    def name(self) -> str:
        return self.spec.name

    # -- status accounting (reference ``experiment/util/status_util.go``) ---

    def trials_by_condition(self, cond: TrialCondition) -> list[Trial]:
        return [t for t in self.trials.values() if t.condition is cond]

    @property
    def succeeded_count(self) -> int:
        return len(self.trials_by_condition(TrialCondition.SUCCEEDED))

    @property
    def failed_count(self) -> int:
        return len(self.trials_by_condition(TrialCondition.FAILED))

    @property
    def early_stopped_count(self) -> int:
        return len(self.trials_by_condition(TrialCondition.EARLY_STOPPED))

    @property
    def metrics_unavailable_count(self) -> int:
        return len(self.trials_by_condition(TrialCondition.METRICS_UNAVAILABLE))

    @property
    def running_count(self) -> int:
        return sum(1 for t in self.trials.values() if not t.condition.is_terminal())

    @property
    def completed_count(self) -> int:
        return sum(1 for t in self.trials.values() if t.condition.is_completed_ok())

    def iter_completed(self) -> Iterator[Trial]:
        return (t for t in self.trials.values() if t.condition.is_completed_ok())

    def update_optimal(self, settled: Iterable[Trial] | None = None) -> None:
        """Recompute the best trial (reference ``status_util.go`` optimal-trial agg).

        ``settled`` narrows the aggregation to just-settled trials, folded
        into the standing ``optimal`` instead of rescanning every completed
        trial — the harvest path settles in small batches, so the full scan
        made settlement quadratic in trial count (dominant at simulator /
        large-sweep scale).  A completed trial's objective value is frozen
        at settlement, so folding each exactly once is equivalent to the
        full recompute.  With no argument the full scan runs (resume paths,
        terminal verdicts, anything that mutated history wholesale).
        """
        obj = self.spec.objective
        if settled is None:
            best: OptimalTrial | None = None
            pool: Iterable[Trial] = self.iter_completed()
        else:
            best = self.optimal
            pool = (t for t in settled if t.condition.is_completed_ok())
        for t in pool:
            v = t.objective_value(obj)
            if v is None or math.isnan(v):
                continue
            if best is None or obj.type.better(v, best.objective_value):
                best = OptimalTrial(
                    trial_name=t.name,
                    objective_value=v,
                    assignments=list(t.spec.assignments),
                    observation=t.observation or Observation(),
                )
        self.optimal = best
        if best is not None:
            last = self.optimal_history[-1] if self.optimal_history else None
            if (
                last is None
                or last["objective_value"] != best.objective_value
                or last["trial_name"] != best.trial_name
            ):
                now = get_clock().time()
                # a recompute AFTER completion (e.g. resuming an old journal
                # that predates the curve) must not charge process downtime
                # to the curve: the run's own clock ends at completion_time
                clock = now
                if self.completion_time and self.condition.is_terminal():
                    clock = min(now, self.completion_time)
                self.optimal_history.append(
                    {
                        "time": now,
                        "elapsed_s": round(max(clock - self.start_time, 0.0), 3),
                        "objective_value": best.objective_value,
                        "trial_name": best.trial_name,
                    }
                )


def clone_with(obj: Any, **changes: Any) -> Any:
    """dataclasses.replace that tolerates frozen types."""
    return dataclasses.replace(obj, **changes)
