"""Experiment validation — the in-process equivalent of the reference's
validating admission webhook (``pkg/webhook/v1beta1/experiment/validator/validator.go:67``).

Because there is no API server, validation runs synchronously when an
experiment is submitted to the orchestrator; errors raise ``ValidationError``
with all findings aggregated (matching the webhook's multi-error reporting).
"""

from __future__ import annotations

import math
import re

from katib_tpu.core.types import (
    Experiment,
    ExperimentSpec,
    MetricsCollectorKind,
    ObjectiveSpec,
    ParameterSpec,
    ParameterType,
)


class ValidationError(ValueError):
    def __init__(self, errors: list[str]):
        self.errors = errors
        super().__init__("; ".join(errors))


# Algorithms that require a fully enumerable search space
# (reference grid validation in optuna base_service / webhook).
_GRID_ALGORITHMS = {"grid"}

# Algorithms that ignore `parameters` and use nas_config instead
# (reference ``validator.go`` NAS branch).
_NAS_ALGORITHMS = {"darts", "enas"}


def validate_objective(obj: ObjectiveSpec | None, errors: list[str]) -> None:
    """Reference ``validator.go:105-135``."""
    if obj is None:
        errors.append("objective is required")
        return
    if not obj.objective_metric_name:
        errors.append("objective.objective_metric_name is required")
    if obj.objective_metric_name in obj.additional_metric_names:
        errors.append("objective metric must not repeat in additional_metric_names")
    known = set(obj.all_metric_names())
    for s in obj.metric_strategies:
        if s.name not in known:
            errors.append(f"metric strategy for unknown metric {s.name!r}")


def validate_parameters(params: list[ParameterSpec], errors: list[str]) -> None:
    """Reference ``validator.go:137-200`` (parameter-space checks).

    Structural invariants (bounds, list presence) are enforced by
    ``ParameterSpec.__post_init__``; this layer checks cross-parameter rules.
    """
    seen: set[str] = set()
    for p in params:
        if p.name in seen:
            errors.append(f"duplicate parameter name {p.name!r}")
        seen.add(p.name)
        if p.type is ParameterType.DOUBLE and p.feasible.step is not None and p.feasible.step <= 0:
            errors.append(f"parameter {p.name!r}: step must be positive")


def validate_command_template(spec: ExperimentSpec, errors: list[str]) -> None:
    """Dry-run render of the black-box command template — the analog of the
    webhook's trial-template render check (``validator.go:254``): every
    ``${trialParameters.X}`` placeholder must name a declared parameter."""
    if not spec.command:
        return
    declared = {p.name for p in spec.parameters}
    for arg in spec.command:
        for pname in re.findall(r"\$\{trialParameters\.([^}]+)\}", arg):
            if pname not in declared:
                errors.append(
                    f"command references undeclared parameter {pname!r} "
                    f"(placeholder ${{trialParameters.{pname}}})"
                )


def validate_experiment(spec: ExperimentSpec) -> None:
    """Full validation; raises ``ValidationError`` with every finding."""
    errors: list[str] = []

    if not spec.name:
        errors.append("experiment name is required")
    else:
        # the name becomes a workdir path component (status journal,
        # checkpoint dirs) and may arrive from a URL/YAML; refuse anything
        # that escapes the workdir (the reference gets this for free from
        # K8s DNS-1123 object-name rules)
        from katib_tpu.utils.names import is_safe_path_component

        if not is_safe_path_component(spec.name):
            errors.append(f"experiment name {spec.name!r} must not contain path separators")
    validate_objective(spec.objective, errors)

    if not spec.algorithm or not spec.algorithm.name:
        errors.append("algorithm.name is required")
    algo = spec.algorithm.name if spec.algorithm else ""

    if algo in _NAS_ALGORITHMS:
        if spec.nas_config is None:
            errors.append(f"algorithm {algo!r} requires nas_config")
        elif not spec.nas_config.operations:
            errors.append("nas_config.operations must be non-empty")
    else:
        if not spec.parameters:
            errors.append("parameters must be non-empty for non-NAS algorithms")
        validate_parameters(spec.parameters, errors)

    if algo in _GRID_ALGORITHMS and spec.parameters:
        if math.isinf(spec.search_space_size()):
            errors.append(
                "grid search requires a finite space: every double parameter needs a step"
            )

    if spec.parallel_trial_count < 1:
        errors.append("parallel_trial_count must be >= 1")
    if spec.max_trial_count is not None and spec.max_trial_count < 1:
        errors.append("max_trial_count must be >= 1")
    if spec.max_failed_trial_count is not None and spec.max_failed_trial_count < 0:
        errors.append("max_failed_trial_count must be >= 0")
    if spec.metrics_retries < 0:
        errors.append("metrics_retries must be >= 0")
    if spec.max_retries < 0:
        errors.append("max_retries must be >= 0")
    if spec.retry_backoff_seconds < 0:
        errors.append("retry_backoff_seconds must be >= 0")
    if spec.suggester_max_errors < 1:
        errors.append("suggester_max_errors must be >= 1")
    if spec.cohort_width < 1:
        errors.append("cohort_width must be >= 1")
    if spec.suggest_lookahead is not None and spec.suggest_lookahead < 1:
        errors.append("suggest_lookahead must be >= 1")
    if not (0.0 < spec.occupancy_target <= 1.0):
        errors.append("occupancy_target must be in (0, 1]")
    if spec.cohort_fill_deadline_seconds < 0:
        errors.append("cohort_fill_deadline_seconds must be >= 0")
    if spec.loop_stall_deadline_seconds <= 0:
        errors.append("loop_stall_deadline_seconds must be > 0")
    if spec.loop_restart_budget < 0:
        errors.append("loop_restart_budget must be >= 0")
    if spec.straggler_factor <= 1.0:
        errors.append("straggler_factor must be > 1")
    if spec.cohort_width > 1 and spec.command is not None:
        # cohorts vectorize a white-box JAX program; a subprocess argv has
        # no train step to vmap
        errors.append("cohort_width > 1 applies to white-box train_fn trials only")

    if spec.train_fn is not None and spec.command is not None:
        errors.append("specify exactly one of train_fn or command, not both")
    if spec.train_fn is None and spec.command is None:
        errors.append("one of train_fn or command is required")
    if spec.command is not None and spec.metrics_collector.kind is MetricsCollectorKind.PUSH:
        errors.append(
            "black-box command trials need a file/stdout metrics collector, not Push"
        )
    if spec.metrics_collector.kind in (
        MetricsCollectorKind.FILE,
        MetricsCollectorKind.JSONL,
        MetricsCollectorKind.TFEVENT,
    ) and not spec.metrics_collector.path:
        errors.append(
            f"metrics collector kind {spec.metrics_collector.kind.value} requires a path"
        )
    if (
        spec.early_stopping is not None
        and spec.metrics_collector.kind is MetricsCollectorKind.TFEVENT
    ):
        # event files are parsed once after exit, so rules could never fire
        # mid-run (the reference only wires early stopping into the
        # line-based file collector, ``file-metricscollector/main.go:332``)
        errors.append(
            "early stopping requires a line-based metrics collector "
            "(StdOut/File/JsonLines/Push), not TensorFlowEvent"
        )
    validate_command_template(spec, errors)

    if errors:
        raise ValidationError(errors)


def validate_and_wrap(spec: ExperimentSpec) -> Experiment:
    validate_experiment(spec)
    return Experiment(spec=spec)
