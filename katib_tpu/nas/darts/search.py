"""DARTS search driver: the white-box trial workload.

Parity with the reference trial image's epoch loop
(``examples/v1beta1/trial-images/darts-cnn-cifar10/run_trial.py:148-233``):
split train data 50/50 into w-set and alpha-set, run bilevel steps per batch,
validate each epoch, print the best genotype at the end.  Here the "print
Best-Genotype= line for the sidecar regex" becomes: report accuracy through
the trial context and write ``genotype.json`` to the trial checkpoint dir.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from katib_tpu import costmodel
from katib_tpu.models.data import Dataset, batches, load_named_dataset
from katib_tpu.nas.darts.architect import (
    DartsHyper,
    SearchState,
    init_search_state,
    make_search_step,
)
from katib_tpu.nas.darts.model import (
    Alphas,
    DartsNetwork,
    extract_genotype,
    init_alphas,
)
from katib_tpu.nas.darts.ops import DEFAULT_PRIMITIVES
from katib_tpu.parallel.mesh import needs_safe_conv, replicate, shard_batch
from katib_tpu.parallel.train import accuracy, cross_entropy_loss, make_eval_step
from katib_tpu.utils import observability as obs
from katib_tpu.utils import tracing
from katib_tpu.utils.booleans import parse_bool

_SEARCH_META = "search_meta.json"


class StepLoopUnavailable(RuntimeError):
    """An explicitly-requested device-resident step loop cannot engage.

    Raised instead of silently running the slow host-driven path: a silent
    fallback once burned a TPU window on the wrong program shape.  The
    message enumerates exactly why the loop is inert so the trial settles
    with an actionable reason."""

# resolved ONCE at import: run() used to re-read the env on every call, so
# two searches in one process could silently run with different unrolls if
# the harness mutated the env between them; the A/B harness sets the env
# before spawning the child, which this still honors
_DEFAULT_SCAN_UNROLL = int(os.environ.get("KATIB_SCAN_UNROLL", "1"))


def _persistent_cache_dir() -> str:
    """The wired XLA persistent-cache dir ("" when disabled) — stamped on
    first-step spans so a cache hit is visible as compile-time collapse."""
    try:
        import jax

        return str(getattr(jax.config, "jax_compilation_cache_dir", None) or "")
    except Exception:
        return ""


def _record_first_step(compile_s: float, execute_s: float, workload: str) -> None:
    """First-step latency split: under async dispatch the first jitted call
    blocks on trace+compile, fetching its result blocks on execution.  With
    the persistent compilation cache wired (KATIB_COMPILE_CACHE), a cache
    hit shows up here as the compile phase collapsing to deserialize time.

    Warm/cold labeling goes through the shape registry with a coarse
    per-workload signature — classify + record only, NO hit/miss counters:
    orchestrator-driven darts trials already count once at the runner's
    first-step seam, and a double bump would overstate the hit rate."""
    from katib_tpu import costmodel
    from katib_tpu.compile.registry import REGISTRY, CompileSignature

    cache = "unknown"
    try:
        sig = CompileSignature(program=f"darts:{workload}")
        cache = REGISTRY.classify(sig)
        REGISTRY.record(sig, source="darts", compile_seconds=compile_s)
        # the search observes its step/window program into the ambient
        # slot right before calling here — persist the XLA cost next to
        # the darts signature
        active = costmodel.active_cost()
        if active is not None:
            REGISTRY.record_cost(sig, active[0].as_dict())
    except Exception:
        pass  # classification is telemetry, never a search failure
    obs.trial_first_step_seconds.set(
        compile_s, phase="compile", cache=cache, workload=workload
    )
    obs.trial_first_step_seconds.set(
        execute_s, phase="execute", cache=cache, workload=workload
    )
    tracing.record_span(
        "first_step",
        compile_s + execute_s,
        workload=workload,
        compile_s=round(compile_s, 4),
        execute_s=round(execute_s, 4),
        cache=cache,
        persistent_cache=_persistent_cache_dir(),
    )


def _draw_epoch_indices(seed: int, epoch: int, n_w: int, n_a: int, n_used: int):
    """Per-epoch batch permutations, one stream per (seed, epoch): w's draw
    first, then a's.  Shared by the scan and device-resident step-loop
    paths; the host-streamed path draws the same order lazily inside
    ``batches()`` (equality is pinned by the parity tests, not by sharing
    this function) — batch composition equality across paths is
    load-bearing for resume and for reproducibility."""
    erng = np.random.default_rng([seed, epoch])
    return erng.permutation(n_w)[:n_used], erng.permutation(n_a)[:n_used]


def _read_search_meta(checkpoint_dir: str) -> dict | None:
    try:
        with open(os.path.join(checkpoint_dir, _SEARCH_META)) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


def _write_search_meta(checkpoint_dir: str, meta: dict) -> None:
    path = os.path.join(checkpoint_dir, _SEARCH_META)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(meta, f)
    os.replace(tmp, path)


def run_darts_search(
    dataset: Dataset,
    *,
    primitives=DEFAULT_PRIMITIVES,
    num_layers: int = 8,
    init_channels: int = 16,
    n_nodes: int = 4,
    stem_multiplier: int = 3,
    num_epochs: int = 10,
    batch_size: int = 128,
    hyper: DartsHyper | None = None,
    mesh=None,
    seed: int = 0,
    report=None,
    native_prefetch: bool | None = None,
    checkpoint_dir: str | None = None,
    remat: bool = True,
    remat_policy: str | None = None,
    device_data: bool | None = None,
    step_loop: bool | None = None,
    step_loop_window: int | None = None,
    fused: bool = False,
    scan_unroll: int | None = None,
    augment_fn=None,
    search_augment: bool | None = None,
) -> dict[str, Any]:
    """Run the bilevel architecture search; returns genotype + final metrics.

    ``checkpoint_dir``: when set, the search state (weights, alphas,
    optimizer, velocity) is snapshotted through Orbax after every epoch and
    the search resumes from the latest snapshot on restart — a long run on
    a preemptible/flaky chip loses at most one epoch (the reference trial
    image restarts its 50-epoch search from scratch, ``run_trial.py:148``).

    ``device_data``: ship the training splits to device memory ONCE and run
    each epoch as a single ``lax.scan`` whose body gathers its batch
    on-device from per-epoch permutation indices.  Per step the host then
    sends two index vectors (~KB) instead of two image batches (~MB), and
    per epoch there is ONE dispatch instead of one per step — on a
    relay-tunneled chip the per-step transfer+dispatch was measured at
    ~0.73 s against a 5.8 ms compute step (artifacts/flagship/run_log.json
    vs bench_tpu.json).  CIFAR-scale splits are a few hundred MB, far under
    v5e HBM.  Default (``None``): enabled for single-device runs (the mesh
    path keeps explicit per-batch ``shard_batch`` placement); overridable
    via ``KATIB_DEVICE_DATA``.  Batch composition per epoch is IDENTICAL to
    the host-streamed path (same ``default_rng([seed, epoch])`` permutation
    draw order), so resume and reproducibility semantics do not change.

    ``step_loop`` / ``step_loop_window``: the DEFAULT execution path folds
    ``step_loop_window`` bilevel steps into one ``lax.scan``-driven device
    dispatch over the device-resident splits (window default: the whole
    epoch, i.e. one dispatch per epoch).  ``KATIB_STEP_LOOP=0`` (or
    ``step_loop=False``) restores eager stepping — one dispatch per step,
    the program to reach for when the epoch-scale compile is the
    bottleneck.  An EXPLICIT ``step_loop=True`` / ``KATIB_STEP_LOOP=1``
    that cannot engage raises :class:`StepLoopUnavailable` instead of
    silently running the slow path.  Batch composition, augmentation
    keying, and resume semantics are identical across all paths.
    """
    net = DartsNetwork(
        primitives=tuple(primitives),
        init_channels=init_channels,
        num_layers=num_layers,
        n_nodes=n_nodes,
        num_classes=dataset.num_classes,
        stem_multiplier=stem_multiplier,
        # remat trades recompute for HBM; at CIFAR shapes a single v5e
        # fits the supernet without it, and the bilevel step does 5
        # gradient passes — skipping recompute is a real speedup when
        # memory allows (remat=False); remat_policy="dots" keeps
        # conv/matmul outputs and recomputes only elementwise work —
        # the batch-scaling configuration (model.py DartsNetwork)
        remat=remat,
        remat_policy=remat_policy,
        # model-axis meshes need the partitioner-safe conv forms
        # (ops/depthwise.py module doc)
        safe_conv=needs_safe_conv(mesh),
        # fused mixed-op evaluation plan (nas/darts/fused.py): fewer,
        # bigger dispatches for the small-op-bound supernet
        fused_convs=fused,
    )
    rng = np.random.default_rng(seed)
    key = jax.random.PRNGKey(seed)
    k_init, k_alpha = jax.random.split(key)

    # 50/50 split: w trains on one half, alpha on the other (run_trial.py:98-111)
    n = len(dataset.x_train)
    perm = rng.permutation(n)
    half = n // 2
    w_idx, a_idx = perm[:half], perm[half:]
    x_w, y_w = dataset.x_train[w_idx], dataset.y_train[w_idx]
    x_a, y_a = dataset.x_train[a_idx], dataset.y_train[a_idx]

    sample = jnp.zeros((1, *dataset.input_shape), jnp.float32)
    alphas = init_alphas(n_nodes, len(primitives), k_alpha)
    weights = net.init(k_init, sample, alphas)

    steps_per_epoch = max(1, half // batch_size)
    if hyper is None:
        hyper = DartsHyper()
    hyper = hyper._replace(total_steps=max(1, steps_per_epoch * num_epochs))

    def loss_fn(w, a, batch):
        x, y = batch
        return cross_entropy_loss(net.apply(w, x, a), y)

    def metric_fn(carry, batch):
        w, a = carry
        x, y = batch
        logits = net.apply(w, x, a)
        return {"accuracy": accuracy(logits, y), "loss": cross_entropy_loss(logits, y)}

    search_step = make_search_step(loss_fn, hyper, mesh)
    evaluate = jax.jit(metric_fn) if mesh is None else make_eval_step(metric_fn, mesh)

    state = init_search_state(weights, alphas, hyper)
    if mesh is not None:
        state = replicate(state, mesh)

    ckpt = None
    start_epoch = 0
    resumed_history: list[dict] = []
    resumed_best = 0.0
    resumed_elapsed = 0.0
    if checkpoint_dir is not None:
        from katib_tpu.utils.checkpoint import TrialCheckpointer

        ckpt = TrialCheckpointer(checkpoint_dir, max_to_keep=2)
        latest = ckpt.latest_step()
        if latest is not None:
            state, _ = ckpt.restore(template=jax.device_get(state), step=latest)
            start_epoch = latest  # step index == epochs completed
            if mesh is not None:
                state = replicate(state, mesh)
            # sidecar carries what the pytree can't: the metric history and
            # wallclock base, so a resumed run reports the FULL search (not
            # just the post-restart epochs)
            meta = _read_search_meta(checkpoint_dir)
            if meta is not None and meta.get("epochs_completed") == latest:
                resumed_history = [
                    h for h in meta.get("history", ()) if h["epoch"] < latest
                ]
                resumed_best = float(meta.get("best_accuracy", 0.0))
                resumed_elapsed = float(meta.get("elapsed_s", 0.0))

    # an EXPLICIT native-prefetch request (argument or env) outranks the
    # implicit device_data default — otherwise run_darts_search(...,
    # native_prefetch=True) would silently run the scan path instead of
    # the C++ loader the caller asked for
    prefetch_requested = native_prefetch is True or parse_bool(
        os.environ.get("KATIB_NATIVE_LOADER")
    )
    # the windowed device-resident step loop is the DEFAULT path; an
    # explicit request (param or env) that cannot engage must raise
    # (StepLoopUnavailable) rather than warn-and-run-slow
    env_sl = os.environ.get("KATIB_STEP_LOOP")
    step_loop_explicit = step_loop is True or (
        env_sl is not None and parse_bool(env_sl)
    )
    if step_loop is None:
        step_loop = parse_bool(env_sl, default=True)
    if device_data is None:
        env = os.environ.get("KATIB_DEVICE_DATA")
        # mesh runs keep device-resident splits only under the step loop
        # (replicated placement + in-scan sharding constraints); the eager
        # mesh path keeps its explicit per-batch shard_batch placement
        device_data = (
            not prefetch_requested and (mesh is None or step_loop)
            if env is None
            else parse_bool(env)
        )
    # Search-phase train-time augmentation (reference trains the search on
    # transformed CIFAR — crop+flip, run_trial.py:98-111 via
    # utils.get_dataset; cutout is augment-phase only).  Opt in with the
    # augment_fn parameter, or KATIB_SEARCH_AUG=1 for the default
    # crop+flip.  Applied to the w-split batch in BOTH epoch paths (scan
    # and streamed/mesh), keyed off SearchState.step so the stream is
    # reproducible from the seed and survives resume.  Default-off: it
    # changes the compiled epoch program, so the flagship's terminal-cache
    # and resume compatibility within a round are preserved.
    if search_augment is None:
        search_augment = parse_bool(os.environ.get("KATIB_SEARCH_AUG"))
    if augment_fn is None and search_augment:
        from katib_tpu.models.augmentation import random_crop_flip

        augment_fn = random_crop_flip
    aug_key = jax.random.PRNGKey(seed + 0x5EED)
    aug_step = (
        jax.jit(lambda k, xb: augment_fn(k, xb)) if augment_fn is not None else None
    )

    # scan_steps is the true per-epoch step count (steps_per_epoch above is
    # clamped to >=1 for the lr schedule even when the split is smaller
    # than one batch — the streamed path then just yields zero batches)
    scan_steps = len(x_w) // batch_size

    # step-loop engagement gate.  An explicit request that cannot engage
    # RAISES — a silent fallback once burned a TPU window on the wrong
    # program shape (the epoch-scale compile it was set to avoid); a
    # default-on loop that cannot engage quietly runs the eager path.
    if step_loop and (not device_data or scan_steps < 1):
        reasons = []
        if prefetch_requested:
            reasons.append(
                "native prefetch was requested (it disables the "
                "device-resident data default)"
            )
        env_dd = os.environ.get("KATIB_DEVICE_DATA")
        if env_dd is not None and not parse_bool(env_dd):
            reasons.append("KATIB_DEVICE_DATA=0 disables the device-data path")
        elif not device_data and not reasons:
            reasons.append("device_data=False was passed")
        if scan_steps < 1:
            reasons.append("the train split is smaller than one batch")
        if step_loop_explicit:
            raise StepLoopUnavailable(
                "the device-resident step loop was explicitly requested "
                "(step_loop/KATIB_STEP_LOOP) but cannot engage: "
                + ("; ".join(reasons) or "device_data resolved to False")
            )
        step_loop = False

    # scan window: param > KATIB_STEP_LOOP_WINDOW > whole epoch (one
    # dispatch per epoch, the maximum fold and the throughput default)
    if step_loop_window is None:
        env_w = os.environ.get("KATIB_STEP_LOOP_WINDOW", "").strip()
        step_loop_window = int(env_w) if env_w else None
    if step_loop_window is not None and step_loop_window < 1:
        raise ValueError(
            f"step_loop_window must be a positive step count, got {step_loop_window}"
        )
    window = (
        scan_steps
        if step_loop_window is None
        else max(1, min(step_loop_window, scan_steps))
    )

    # unroll>1 inlines that many bilevel steps per XLA While-loop
    # iteration — the microbench found a fixed ~1.35-1.5 ms
    # per-scan-iteration floor (artifacts/flagship/op_microbench.json),
    # and unrolling amortizes it at the cost of a proportionally
    # bigger program (longer compile, more code HBM).  Default 1;
    # KATIB_SCAN_UNROLL overrides for the A/B harness (resolved once
    # at module import, not per run).
    if scan_unroll is None:
        scan_unroll = _DEFAULT_SCAN_UNROLL

    gather_batches = None
    window_fn = None
    if step_loop:
        # THE default path: splits live in HBM (replicated over the mesh
        # when one is set) for the whole search, and every dispatch is one
        # jitted lax.scan over [window, batch] permutation indices with
        # on-device gather — per dispatch the host sends two small index
        # arrays instead of `window` image batches
        raw_step = make_search_step(loss_fn, hyper, mesh, jit=False)
        if mesh is None:
            constrain = None
            xw_d, yw_d, xa_d, ya_d = (
                jax.device_put(a) for a in (x_w, y_w, x_a, y_a)
            )
        else:
            from jax.sharding import NamedSharding, PartitionSpec

            from katib_tpu.parallel.mesh import DATA_AXIS, replicated

            rep = replicated(mesh)
            batch_sharding = NamedSharding(mesh, PartitionSpec(DATA_AXIS))

            def constrain(t):
                # pin gathered batches to the data axis so the partitioner
                # runs the in-scan step exactly like the eager path's
                # explicit shard_batch placement
                return jax.lax.with_sharding_constraint(t, batch_sharding)

            xw_d, yw_d, xa_d, ya_d = (
                jax.device_put(a, rep) for a in (x_w, y_w, x_a, y_a)
            )

        def _window(state, xw, yw, xa, ya, w_ix, a_ix):
            def body(s, ix):
                wi, ai = ix
                xb, yb = xw[wi], yw[wi]
                vx, vy = xa[ai], ya[ai]
                if constrain is not None:
                    xb, yb, vx, vy = (constrain(t) for t in (xb, yb, vx, vy))
                if augment_fn is not None:
                    xb = augment_fn(jax.random.fold_in(aug_key, s.step), xb)
                s, m = raw_step(s, (xb, yb), (vx, vy))
                return s, m["train_loss"]

            return jax.lax.scan(
                body, state, (w_ix, a_ix), unroll=max(1, scan_unroll)
            )

        # donate the carried state: the bilevel step holds two full
        # weight copies already — double-buffering a third across the
        # window call would waste HBM
        if mesh is None:
            window_fn = jax.jit(_window, donate_argnums=(0,))
        else:
            window_fn = jax.jit(
                _window,
                in_shardings=(rep,) * 7,
                out_shardings=(rep, rep),
                donate_argnums=(0,),
            )
    elif device_data and mesh is None and scan_steps >= 1:
        # eager stepping over device-resident splits (KATIB_STEP_LOOP=0):
        # one async dispatch per step plus a tiny on-device gather, the
        # separately jitted search_step as the only compiled program — the
        # mode to reach for when the pool's compile path is the bottleneck
        # (a terminal-side epoch-program compile was measured at ~8 min
        # against the single step's seconds).  Dispatches stay async
        # (losses fetched once per epoch); batch composition and
        # augmentation keying are identical to the windowed path.
        xw_d, yw_d, xa_d, ya_d = (
            jax.device_put(a) for a in (x_w, y_w, x_a, y_a)
        )
        gather_batches = jax.jit(
            lambda xw, yw, xa, ya, wi, ai: (
                (xw[wi], yw[wi]),
                (xa[ai], ya[ai]),
            )
        )
    # window-size gauge: 0 when the step loop is not engaged, so a low-MFU
    # run is diagnosable from /api/status alone
    obs.step_loop_window.set(
        float(window) if window_fn is not None else 0.0, workload="darts"
    )

    # optional native prefetch: C++ worker threads gather the next shuffled
    # batch while the device runs the current bilevel step (enable with
    # native_prefetch=True or KATIB_NATIVE_LOADER=1; falls back silently
    # when the native runtime isn't built).  Moot under device_data — there
    # is no host-side batch gather left to overlap.
    if native_prefetch is None:
        native_prefetch = os.environ.get("KATIB_NATIVE_LOADER", "") not in ("", "0")
    native_loaders = None
    loader_cache_dir = None
    if native_prefetch and not device_data:
        from katib_tpu.native import native_available

        if native_available():
            import tempfile

            from katib_tpu.native import NativeBatchLoader

            loader_cache_dir = tempfile.mkdtemp(prefix="darts-loader-")
            # equal record counts keep the two epoch streams in lockstep
            # (the a-half can be 1 longer when n is odd; an extra sample
            # would desync the C loaders' positional epoch boundaries)
            n_sync = len(x_w)
            built: list = []
            try:
                for xs_, ys_, sd, name in (
                    (x_w, y_w, seed, "w.bin"),
                    (x_a[:n_sync], y_a[:n_sync], seed + 1, "a.bin"),
                ):
                    built.append(
                        NativeBatchLoader(
                            xs_, ys_, batch=batch_size, seed=sd,
                            cache_path=os.path.join(loader_cache_dir, name),
                            # resumed runs consume epoch k's shuffle, same
                            # invariant as the Python batches() path below
                            start_epoch=start_epoch,
                        )
                    )
                native_loaders = tuple(built)
            except (RuntimeError, OSError) as e:
                # prefetch is an optimization — a loader that can't start
                # (batch > n, disk full, ...) falls back to the Python
                # stream instead of failing the search
                import shutil
                import warnings

                for dl in built:
                    dl.close()
                shutil.rmtree(loader_cache_dir, ignore_errors=True)
                loader_cache_dir = None
                warnings.warn(
                    f"native prefetch unavailable ({e}); using Python batches",
                    RuntimeWarning,
                    stacklevel=2,
                )

    best_acc = resumed_best
    history = list(resumed_history)
    # the eval batch is constant across epochs — place it once instead of
    # re-shipping ~MBs over the (possibly tunneled) host->device link per
    # epoch
    ne = min(len(dataset.x_test), 1024)
    eval_batch = (dataset.x_test[:ne], dataset.y_test[:ne])
    eval_batch = (
        shard_batch(eval_batch, mesh)
        if mesh is not None
        else jax.device_put(eval_batch)
    )
    # time base continues across restarts so elapsed_s stays monotonic
    t0 = time.perf_counter() - resumed_elapsed
    trace_epochs = parse_bool(os.environ.get("KATIB_EPOCH_TRACE"))
    # roofline: the XLA cost of this search's compiled step/window program,
    # observed once on the start epoch and re-published against each
    # epoch's measured step time (darts.epoch span attrs + MFU gauges)
    cost_rec = None

    def _trace(tag: str, since: float) -> float:
        now = time.perf_counter()
        if trace_epochs:
            print(f"epoch-trace: {tag} {now - since:.2f}s", flush=True)
        return now

    try:
        for epoch in range(start_epoch, num_epochs):
            t_mark = time.perf_counter()
            t_epoch = t_mark
            if window_fn is not None:
                n_used = scan_steps * batch_size
                w_ix, a_ix = _draw_epoch_indices(
                    seed, epoch, len(x_w), len(x_a), n_used
                )
                w_ix = w_ix.reshape(scan_steps, batch_size)
                a_ix = a_ix.reshape(scan_steps, batch_size)
                t_dispatch = time.perf_counter()
                loss_parts = []
                dispatches = 0
                pos = 0
                first_avals = None
                first_window = 0
                while pos < scan_steps:
                    k = min(window, scan_steps - pos)
                    w_j = jnp.asarray(w_ix[pos : pos + k], jnp.int32)
                    a_j = jnp.asarray(a_ix[pos : pos + k], jnp.int32)
                    if epoch == start_epoch and pos == 0:
                        # shape-only avals (window_fn donates the state, so
                        # the live operands can't be reused after the call)
                        first_avals = jax.tree.map(
                            lambda t: jax.ShapeDtypeStruct(t.shape, t.dtype),
                            (state, xw_d, yw_d, xa_d, ya_d, w_j, a_j),
                        )
                        first_window = k
                    # full windows all reuse one executable; the remainder
                    # chunk (at most one per epoch) gets its own trace
                    state, losses = window_fn(
                        state, xw_d, yw_d, xa_d, ya_d, w_j, a_j
                    )
                    loss_parts.append(losses)
                    dispatches += 1
                    pos += k
                dispatch_s = time.perf_counter() - t_dispatch
                steps = scan_steps
                t_mark = _trace("scan-dispatch", t_mark)
                t_fetch = time.perf_counter()
                # dispatches stay async; ONE device->host transfer per epoch
                train_loss = float(
                    np.sum(np.concatenate(jax.device_get(loss_parts)))
                )
                fetch_s = time.perf_counter() - t_fetch
                t_mark = _trace("loss-fetch", t_mark)
                if epoch == start_epoch:
                    if first_avals is not None:
                        # per-run program (fresh jit per search): no memo
                        # label, trace-only extraction off the timed path
                        cost_rec = costmodel.observe_program(
                            None,
                            window_fn,
                            first_avals,
                            program="darts:darts-scan",
                            steps=first_window,
                            per_report=dispatches,
                        )
                    # windowed scan: the first dispatch blocks on
                    # trace+compile, the loss fetch blocks on execution
                    _record_first_step(dispatch_s, fetch_s, "darts-scan")
            else:
                # one shared per-step loop body for every host-driven epoch
                # path; only the batch source differs (review: the augment
                # keying and async loss handling must not live in two
                # hand-synced copies)
                if gather_batches is not None:
                    # device-resident step loop: batches gathered on-device
                    # from the scan path's exact permutation draws
                    n_used = scan_steps * batch_size
                    w_ix, a_ix = _draw_epoch_indices(
                        seed, epoch, len(x_w), len(x_a), n_used
                    )
                    w_ix = w_ix.reshape(scan_steps, batch_size)
                    a_ix = a_ix.reshape(scan_steps, batch_size)
                    pair_stream = (
                        gather_batches(
                            xw_d,
                            yw_d,
                            xa_d,
                            ya_d,
                            jnp.asarray(w_ix[i], jnp.int32),
                            jnp.asarray(a_ix[i], jnp.int32),
                        )
                        for i in range(scan_steps)
                    )
                elif native_loaders is not None:
                    pair_stream = zip(
                        native_loaders[0].epoch(), native_loaders[1].epoch()
                    )
                else:
                    # per-epoch stream keyed on (seed, epoch): a run resumed
                    # at epoch k shuffles exactly like the uninterrupted run
                    # would have — a shared sequential rng would replay
                    # epoch 0's order after every restart
                    erng = np.random.default_rng([seed, epoch])
                    pair_stream = zip(
                        batches(x_w, y_w, batch_size, erng),
                        batches(x_a, y_a, batch_size, erng),
                    )
                # keep per-step losses as device futures: float()-ing inside
                # the loop would block the host on every step and serialize
                # the async dispatch pipeline (one device round-trip per
                # step — on a tunneled chip that is the dominant cost); one
                # transfer per epoch instead
                step_losses = []
                # first-step split (start epoch only): one extra host sync
                # on step 0, the remaining steps keep the async pipeline
                first_pending = epoch == start_epoch
                for wb, ab in pair_stream:
                    if mesh is not None:
                        wb, ab = shard_batch(wb, mesh), shard_batch(ab, mesh)
                    if aug_step is not None:
                        # after sharding (partitions along batch) and keyed
                        # off the SAME SearchState.step the scan path folds
                        wb = (
                            aug_step(
                                jax.random.fold_in(aug_key, state.step), wb[0]
                            ),
                            wb[1],
                        )
                    if first_pending:
                        first_pending = False
                        first_avals = jax.tree.map(
                            lambda t: jax.ShapeDtypeStruct(t.shape, t.dtype),
                            (state, wb, ab),
                        )
                        t_first = time.perf_counter()
                        state, metrics = search_step(state, wb, ab)
                        compile_s = time.perf_counter() - t_first
                        t_first = time.perf_counter()
                        jax.block_until_ready(metrics["train_loss"])
                        cost_rec = costmodel.observe_program(
                            None,
                            search_step,
                            first_avals,
                            program="darts:darts",
                            steps=1,
                            per_report=max(1, scan_steps),
                        )
                        _record_first_step(
                            compile_s, time.perf_counter() - t_first, "darts"
                        )
                    else:
                        state, metrics = search_step(state, wb, ab)
                    step_losses.append(metrics["train_loss"])
                steps = len(step_losses)
                dispatches = steps  # eager: one dispatch per step
                t_mark = _trace("step-dispatch", t_mark)
                train_loss = (
                    float(np.sum(jax.device_get(step_losses))) if steps else 0.0
                )
                t_mark = _trace("loss-fetch", t_mark)

            em = evaluate((state.weights, state.alphas), eval_batch)
            val_acc = float(em["accuracy"])
            t_mark = _trace("eval", t_mark)
            best_acc = max(best_acc, val_acc)
            # per-epoch telemetry: step-time distribution, throughput gauge,
            # HBM gauges, and one "darts.epoch" span in the trace journal
            epoch_s = time.perf_counter() - t_epoch
            obs.trial_step_seconds.observe(epoch_s / max(steps, 1), workload="darts")
            images_per_s = (steps * batch_size) / epoch_s if epoch_s > 0 else 0.0
            obs.trial_images_per_second.set(images_per_s, workload="darts")
            obs.record_device_memory()
            # steps-per-dispatch is THE dispatch-overhead diagnostic: 1.0
            # means every step pays a host round-trip (eager), `window`
            # means the scan loop is folding that many steps per dispatch
            spd = steps / dispatches if dispatches else 0.0
            obs.steps_per_dispatch.set(spd, workload="darts")
            # roofline gauges against this epoch's measured per-step time
            # (includes eval, so MFU reads slightly conservative)
            cost_attrs = (
                costmodel.publish_dispatch(
                    cost_rec, epoch_s / max(steps, 1), workload="darts"
                )
                if cost_rec is not None
                else {}
            )
            tracing.record_span(
                "darts.epoch",
                epoch_s,
                epoch=epoch,
                steps=steps,
                images_per_s=round(images_per_s, 1),
                val_accuracy=round(val_acc, 4),
                step_loop=window_fn is not None,
                step_loop_window=window if window_fn is not None else 0,
                device_data=bool(window_fn is not None or gather_batches is not None),
                steps_per_dispatch=round(spd, 2),
                **cost_attrs,
            )
            history.append(
                {
                    "epoch": epoch,
                    "val_accuracy": val_acc,
                    "train_loss": train_loss / max(steps, 1),
                    # best-objective@wallclock is the BASELINE driver metric;
                    # every row carries elapsed seconds so the curve is
                    # plottable
                    "elapsed_s": round(time.perf_counter() - t0, 3),
                    "best_accuracy": best_acc,
                }
            )
            if ckpt is not None:
                # step index = epochs completed; restore resumes at epoch
                # `latest` with at most one epoch of lost work
                host_state = jax.device_get(state)
                t_mark = _trace("state-download", t_mark)
                ckpt.save(host_state, epoch + 1)
                t_mark = _trace("ckpt-save", t_mark)
                _write_search_meta(
                    checkpoint_dir,
                    {
                        "epochs_completed": epoch + 1,
                        "best_accuracy": best_acc,
                        "history": history,
                        "elapsed_s": round(time.perf_counter() - t0, 3),
                    },
                )
            if report is not None:
                cont = report(
                    epoch=epoch, accuracy=val_acc, loss=train_loss / max(steps, 1)
                )
                if cont is False:
                    break
    finally:
        # an exception mid-epoch must not leak C++ worker threads, the
        # mmap, or a dataset-sized temp dir
        if native_loaders is not None:
            import shutil

            for dl in native_loaders:
                dl.close()
            shutil.rmtree(loader_cache_dir, ignore_errors=True)

    genotype = extract_genotype(
        jax.device_get(state.alphas), primitives, n_nodes=n_nodes
    )
    return {
        "genotype": genotype,
        "best_accuracy": best_acc,
        "history": history,
        "alphas": jax.device_get(state.alphas),
    }


def darts_trial(ctx) -> None:
    """White-box DARTS trial (reference workload ``run_trial.py`` main).

    Consumes the three parameters the DARTS suggester emits
    (``darts/service.py:49-99``): ``algorithm-settings`` (JSON dict),
    ``search-space`` (JSON list of primitives), ``num-layers``.
    """
    settings = json.loads(ctx.params.get("algorithm-settings", "{}"))
    primitives = tuple(json.loads(ctx.params.get("search-space", "null")) or DEFAULT_PRIMITIVES)
    num_layers = int(ctx.params.get("num-layers", 8))

    # same dataset knob as the ENAS trial (models/data.py dispatch)
    n_train = settings.get("n_train")
    n_test = settings.get("n_test")
    dataset = load_named_dataset(
        str(settings.get("dataset", "cifar10")),
        int(n_train) if n_train is not None else None,
        int(n_test) if n_test is not None else None,
    )
    # DartsHyper's field defaults are the single source of truth; settings
    # override field-by-field (total_steps is derived from the schedule)
    overrides = {}
    for name in DartsHyper._fields:
        if name == "total_steps" or name not in settings:
            continue
        raw = settings[name]
        # bool fields (unrolled / paired_hessian / debug_alpha_grad) parse
        # as booleans, keyed off the field default's type so a new flag
        # cannot silently float()-coerce; a null/absent-ish value falls
        # back to the FIELD's default, not a blanket True
        default = DartsHyper._field_defaults.get(name)
        if isinstance(default, bool):
            overrides[name] = parse_bool(raw, default=default)
        else:
            overrides[name] = float(raw)
    hyper = DartsHyper(**overrides)

    stopped = [False]

    def report(epoch, accuracy, loss):
        cont = ctx.report(step=epoch, accuracy=accuracy, loss=loss)
        if not cont:
            stopped[0] = True
        return cont

    init_channels = int(settings.get("init_channels", 16))
    batch_size = int(settings.get("batch_size", 128))
    stem_multiplier = int(settings.get("stem_multiplier", 3))
    num_epochs = int(settings.get("num_epochs", 10))
    # step-loop knobs: the Katib-style camelCase spelling (stepLoopWindow,
    # the ISSUE/CR surface) and the snake_case used by every other setting
    # both resolve; absent -> None -> run_darts_search's env/default chain
    raw_window = settings.get("step_loop_window", settings.get("stepLoopWindow"))
    result = run_darts_search(
        dataset,
        primitives=primitives,
        num_layers=num_layers,
        init_channels=init_channels,
        n_nodes=int(settings.get("num_nodes", 4)),
        stem_multiplier=stem_multiplier,
        num_epochs=num_epochs,
        batch_size=batch_size,
        hyper=hyper,
        mesh=ctx.mesh,
        report=report,
        # algorithm setting "fused": the fused mixed-op evaluation plan
        # (nas/darts/fused.py) — a Katib-style CR can request it
        fused=parse_bool(settings.get("fused")),
        # device-resident step-loop knobs (the default path; setting
        # step_loop=false pins eager stepping, an explicit true raises
        # StepLoopUnavailable when the loop cannot engage)
        step_loop=(
            parse_bool(settings["step_loop"])
            if "step_loop" in settings
            else None
        ),
        step_loop_window=int(raw_window) if raw_window is not None else None,
        # remat knobs ride the same spec surface as the batch-scaling
        # harness (model.py DartsNetwork): remat=false skips recompute
        # when HBM allows, remat_policy="dots" keeps matmul outputs
        remat=parse_bool(settings.get("remat"), default=True),
        remat_policy=(
            str(settings["remat_policy"])
            if settings.get("remat_policy") not in (None, "")
            else None
        ),
        # algorithm setting "search_augment": the reference's crop+flip
        # search transforms (run_trial.py:98-111); the fn selection lives
        # in run_darts_search so the env path and this one cannot diverge
        # (absent setting -> None -> the env fallback still applies)
        search_augment=(
            parse_bool(settings["search_augment"])
            if "search_augment" in settings
            else None
        ),
        # per-epoch snapshots under the trial's checkpoint dir: a preempted
        # trial re-runs from its last completed epoch, not from scratch
        checkpoint_dir=(
            os.path.join(ctx.checkpoint_dir, "search")
            if ctx.checkpoint_dir
            else None
        ),
    )
    # the reference prints Best-Genotype= for the stdout scraper; we persist
    # the discrete architecture alongside the trial instead
    out_dir = ctx.ensure_checkpoint_dir()
    with open(os.path.join(out_dir, "genotype.json"), "w") as f:
        json.dump(
            {
                "normal": result["genotype"].normal,
                "reduce": result["genotype"].reduce,
                "best_accuracy": result["best_accuracy"],
            },
            f,
            indent=2,
        )

    # optional augment phase: train the discovered genotype as a fixed
    # network and report its accuracy as a trial metric (setting
    # ``augment_epochs`` > 0 turns it on; the reference has no equivalent —
    # its trial ends at the printed genotype)
    aug_epochs = int(settings.get("augment_epochs", 0))
    if aug_epochs > 0 and not stopped[0] and not ctx.should_stop():
        # an early-stopped search must not burn an augment budget the
        # orchestrator already decided to reclaim; likewise a drain signal
        # landing between the last search epoch and this phase boundary —
        # the genotype is already persisted, so exiting here loses nothing
        from katib_tpu.nas.darts.augment import train_genotype

        acc = train_genotype(
            result["genotype"],
            dataset,
            init_channels=init_channels,
            num_layers=num_layers,
            stem_multiplier=stem_multiplier,
            lr=float(settings.get("augment_lr", 0.025)),
            epochs=aug_epochs,
            batch_size=batch_size,
            mesh=ctx.mesh,
        )
        # step continues past the search epochs so the metric time-series
        # stays monotonic (reporting at aug_epochs would rewind into the
        # search's step range)
        ctx.report(step=num_epochs + aug_epochs, augment_accuracy=float(acc))
