"""DARTS primitive operations as flax modules.

Parity with the reference trial image's op set
(``examples/v1beta1/trial-images/darts-cnn-cifar10/operations.py:18-31``):
none / avg_pooling_3x3 / max_pooling_3x3 / skip_connection /
separable_convolution_{3x3,5x5} / dilated_convolution_{3x3,5x5}.

TPU-first choices:
- NHWC layout (the TPU-native conv layout; the reference is NCHW CUDA);
- bfloat16 compute, float32 normalization statistics;
- stateless batch normalization: DARTS search always runs BN in training mode
  with ``affine=False`` (running stats are never consumed during search), so
  normalizing with the current batch's statistics is functionally equivalent
  and keeps the whole supernet a pure function — no mutable collections to
  thread through the bilevel derivatives;
- the mixed op computes every primitive and contracts with softmax weights in
  one pass — on TPU through the fused Pallas kernel in
  ``katib_tpu/ops/mixed_op.py`` (one read of the stacked activations), on
  other backends through the reference einsum (``KATIB_PALLAS_MIXED_OP``
  selects; the kernel module doc has the mode table).
"""

from __future__ import annotations

from typing import Callable, Sequence

import flax.linen as nn
import jax.numpy as jnp

from katib_tpu.ops.depthwise import DepthwiseConv, PointwiseConv
from katib_tpu.ops.mixed_op import mixed_op_sum

DEFAULT_PRIMITIVES = (
    "none",
    "max_pooling_3x3",
    "avg_pooling_3x3",
    "skip_connection",
    "separable_convolution_3x3",
    "separable_convolution_5x5",
    "dilated_convolution_3x3",
    "dilated_convolution_5x5",
)


def batch_norm(x: jnp.ndarray, eps: float = 1e-5) -> jnp.ndarray:
    """Training-mode BN over (N, H, W), no affine, stateless (see module doc)."""
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=(0, 1, 2), keepdims=True)
    var = jnp.var(x32, axis=(0, 1, 2), keepdims=True)
    return ((x32 - mean) * jnp.sqrt(1.0 / (var + eps))).astype(x.dtype)


class ReluConvBn(nn.Module):
    channels: int
    kernel: int = 1
    stride: int = 1
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        x = nn.relu(x)
        if self.kernel == 1:
            # the cell-preprocessing case; also safe under nn.vmap
            x = PointwiseConv(
                self.channels, stride=self.stride, dtype=self.dtype
            )(x)
        else:
            x = nn.Conv(
                self.channels,
                (self.kernel, self.kernel),
                strides=(self.stride, self.stride),
                padding="SAME",
                use_bias=False,
                dtype=self.dtype,
            )(x)
        return batch_norm(x)


class SepConv(nn.Module):
    """Depthwise-separable conv applied twice (reference SepConv stacks two)."""

    channels: int
    kernel: int
    stride: int
    dtype: jnp.dtype = jnp.bfloat16
    safe: bool = False

    @nn.compact
    def __call__(self, x):
        for i, stride in enumerate((self.stride, 1)):
            x = nn.relu(x)
            # shift-MAC depthwise, not nn.Conv(feature_group_count=C): the
            # SPMD partitioner corrupts grouped-conv filter gradients on
            # meshes with a model axis (ops/depthwise.py module doc)
            x = DepthwiseConv(
                kernel=self.kernel, stride=stride, dtype=self.dtype,
                safe=self.safe,
            )(x)
            # einsum pointwise: a vmapped nn.Conv batches into the grouped
            # form the partitioner corrupts (ops/depthwise.py module doc)
            x = PointwiseConv(self.channels, dtype=self.dtype)(x)
            x = batch_norm(x)
        return x


class DilConv(nn.Module):
    """Dilated depthwise-separable conv (3x3 d2 -> rf 5x5; 5x5 d2 -> rf 9x9)."""

    channels: int
    kernel: int
    stride: int
    dilation: int = 2
    dtype: jnp.dtype = jnp.bfloat16
    safe: bool = False

    @nn.compact
    def __call__(self, x):
        x = nn.relu(x)
        # shift-MAC depthwise (see SepConv / ops/depthwise.py)
        x = DepthwiseConv(
            kernel=self.kernel,
            stride=self.stride,
            dilation=self.dilation,
            dtype=self.dtype,
            safe=self.safe,
        )(x)
        x = PointwiseConv(self.channels, dtype=self.dtype)(x)
        return batch_norm(x)


class FactorizedReduce(nn.Module):
    """Stride-2 spatial reduction preserving information via two offset 1x1
    convs (reference ``operations.py`` FactorizedReduce)."""

    channels: int
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        x = nn.relu(x)
        a = PointwiseConv(self.channels // 2, stride=2, dtype=self.dtype)(x)
        b = PointwiseConv(self.channels // 2, stride=2, dtype=self.dtype)(
            x[:, 1:, 1:, :]
        )
        # pad b back to a's spatial shape (off-by-one from the shifted slice)
        pad_h = a.shape[1] - b.shape[1]
        pad_w = a.shape[2] - b.shape[2]
        b = jnp.pad(b, ((0, 0), (0, pad_h), (0, pad_w), (0, 0)))
        return batch_norm(jnp.concatenate([a, b], axis=-1))


class Pool(nn.Module):
    kind: str  # "avg" | "max"
    stride: int

    @nn.compact
    def __call__(self, x):
        window = (3, 3)
        strides = (self.stride, self.stride)
        if self.kind == "avg":
            out = nn.avg_pool(x, window, strides=strides, padding="SAME")
        else:
            out = nn.max_pool(x, window, strides=strides, padding="SAME")
        return batch_norm(out)


class Zero(nn.Module):
    stride: int

    @nn.compact
    def __call__(self, x):
        if self.stride == 1:
            return jnp.zeros_like(x)
        return jnp.zeros_like(x[:, :: self.stride, :: self.stride, :])


class SkipConnect(nn.Module):
    channels: int
    stride: int
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        if self.stride == 1:
            return x
        return FactorizedReduce(self.channels, dtype=self.dtype)(x)


def build_op(
    name: str, channels: int, stride: int, dtype=jnp.bfloat16, safe: bool = False
) -> nn.Module:
    """Primitive factory (reference ``OPS`` table, ``operations.py:18``).

    ``safe`` selects the partitioner-safe depthwise formulation for meshes
    with a model axis (ops/depthwise.py module doc)."""
    table: dict[str, Callable[[], nn.Module]] = {
        "none": lambda: Zero(stride),
        "avg_pooling_3x3": lambda: Pool("avg", stride),
        "max_pooling_3x3": lambda: Pool("max", stride),
        "skip_connection": lambda: SkipConnect(channels, stride, dtype=dtype),
        "separable_convolution_3x3": lambda: SepConv(
            channels, 3, stride, dtype=dtype, safe=safe),
        "separable_convolution_5x5": lambda: SepConv(
            channels, 5, stride, dtype=dtype, safe=safe),
        "dilated_convolution_3x3": lambda: DilConv(
            channels, 3, stride, dtype=dtype, safe=safe),
        "dilated_convolution_5x5": lambda: DilConv(
            channels, 5, stride, dtype=dtype, safe=safe),
    }
    if name not in table:
        raise ValueError(f"unknown primitive {name!r}; known: {sorted(table)}")
    return table[name]()


class MixedOp(nn.Module):
    """Continuous relaxation of one edge: softmax-weighted sum of primitives.

    ``fused=True`` evaluates the four depthwise-separable primitives
    through :class:`~katib_tpu.nas.darts.fused.FusedSepDil` (2 masked
    depthwise + 2 batched-pointwise dispatches instead of 6+6) — same
    math, different evaluation plan (``nas/darts/fused.py`` module doc).
    """

    primitives: Sequence[str]
    channels: int
    stride: int
    dtype: jnp.dtype = jnp.bfloat16
    safe: bool = False
    fused: bool = False

    @nn.compact
    def __call__(self, x, weights):
        # weights: (n_ops,) softmax over this edge's alphas
        fused_outs: dict = {}
        if self.fused:
            from katib_tpu.nas.darts.fused import FUSED_PRIMITIVES, FusedSepDil

            if set(FUSED_PRIMITIVES) <= set(self.primitives):
                fused_outs = FusedSepDil(
                    self.channels, self.stride, dtype=self.dtype, safe=self.safe
                )(x)
        outs = [
            fused_outs[p]
            if p in fused_outs
            else build_op(p, self.channels, self.stride, self.dtype, safe=self.safe)(x)
            for p in self.primitives
        ]
        stacked = jnp.stack(outs, axis=0)  # (n_ops, N, H, W, C)
        # fused weighting+accumulation (ops/mixed_op.py): Pallas on TPU,
        # the reference einsum elsewhere — KATIB_PALLAS_MIXED_OP overrides
        return mixed_op_sum(weights, stacked)
