"""DARTS bilevel optimization — the architect, as one jitted step.

Parity with the reference architect
(``examples/v1beta1/trial-images/darts-cnn-cifar10/architect.py``):

- virtual step     w' = w - xi * (momentum*v + grad_w L_train + wd*w)   (:30)
- val grads        d_alpha, d_w' of L_val(w', alpha)                    (:79-88)
- Hessian-vector   finite difference: (grad_a L_train(w+eps*d_w') -
                   grad_a L_train(w-eps*d_w')) / (2 eps), eps=0.01/||d_w'||  (:98-135)
- update           alpha_grad = d_alpha - xi * hessian                 (:67)

The reference materializes a second torch model and mutates it in-place; in
JAX the virtual weights are just another pytree, the whole computation is one
pure function, and XLA fuses the three backward passes.  Weight step (SGD +
momentum + cosine lr + grad clip, ``run_trial.py:113-141,193-205``) and alpha
step (Adam) live in the same jit so a full search step is a single device
program — no host round-trips inside the epoch loop.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
import optax

from katib_tpu.nas.darts.model import Alphas

tmap = jax.tree_util.tree_map


class SearchState(NamedTuple):
    step: jnp.ndarray
    weights: Any
    alphas: Alphas
    a_opt: Any
    velocity: Any  # momentum buffer mirror for the virtual step


class DartsHyper(NamedTuple):
    """Search hyperparameters (reference defaults ``darts/service.py:118-135``)."""

    w_lr: float = 0.025
    w_lr_min: float = 0.001
    w_momentum: float = 0.9
    w_weight_decay: float = 3e-4
    w_grad_clip: float = 5.0
    alpha_lr: float = 3e-4
    alpha_weight_decay: float = 1e-3
    total_steps: int = 1000  # for the cosine schedule
    unrolled: bool = True  # second-order (hessian correction) on/off
    # evaluate the two finite-difference passes (grad_a at w+eps*d and
    # w-eps*d) as ONE vmapped pass over a stacked weight pytree instead of
    # two sequential passes.  Same math (parity-gated in tests); the step
    # drops from 5 sequential network passes to 4 — a designed attack on
    # the measured overhead-bound profile (0.56% MFU, op_microbench.json)
    # where arithmetic inside a pass is nearly free but passes are not.
    # Off by default until the on-chip A/B decides.
    paired_hessian: bool = False
    # expose the raw second-order alpha gradient in the step metrics —
    # parity gates compare IT rather than the post-Adam alphas (Adam's
    # sign-like first step turns sub-noise gradient elements into full
    # ±alpha_lr divergences, so updated alphas are ill-conditioned
    # evidence).  Off by default: it adds an alpha-sized tensor per step.
    debug_alpha_grad: bool = False


def make_search_step(
    loss_fn: Callable[[Any, Alphas, Any], jnp.ndarray],
    hyper: DartsHyper,
    mesh=None,
    jit: bool = True,
) -> Callable:
    """Build ``search_step(state, train_batch, val_batch) -> (state, metrics)``.

    ``loss_fn(weights, alphas, batch) -> scalar`` is the supernet loss.
    ``jit=False`` returns the raw (untraced) step for callers that inline it
    into a larger jitted program — the windowed ``lax.scan`` step loop in
    ``search.py`` wraps N steps in ONE jit and must not nest a sharded jit
    inside its scan body.
    """
    a_tx = optax.chain(
        optax.add_decayed_weights(hyper.alpha_weight_decay),
        optax.adam(hyper.alpha_lr, b1=0.5, b2=0.999),
    )

    def cosine_lr(step):
        t = jnp.minimum(step.astype(jnp.float32) / hyper.total_steps, 1.0)
        return hyper.w_lr_min + 0.5 * (hyper.w_lr - hyper.w_lr_min) * (
            1.0 + jnp.cos(jnp.pi * t)
        )

    def clip(grads):
        from katib_tpu.parallel.train import clip_by_global_norm

        return clip_by_global_norm(grads, hyper.w_grad_clip)

    grad_w = jax.grad(loss_fn, argnums=0)
    grad_a = jax.grad(loss_fn, argnums=1)
    val_grads = jax.value_and_grad(loss_fn, argnums=(0, 1))

    def alpha_grad_unrolled(state: SearchState, lr, train_batch, val_batch):
        """Second-order alpha gradient (architect.py:30-135)."""
        w, a = state.weights, state.alphas
        # virtual step with decoupled weight decay + momentum lookahead
        gw = grad_w(w, a, train_batch)
        w_virtual = tmap(
            lambda p, g, v: p
            - lr * (hyper.w_momentum * v + g + hyper.w_weight_decay * p),
            w,
            gw,
            state.velocity,
        )
        # gradients at the virtual point
        val_loss, (dw, da) = val_grads(w_virtual, a, val_batch)
        # finite-difference Hessian-vector product
        dw_norm = optax.global_norm(dw)
        eps = 0.01 / (dw_norm + 1e-12)
        if hyper.paired_hessian:
            # one vmapped pass over stacked (w+, w-) — see DartsHyper
            w_pm = tmap(
                lambda p, d: jnp.stack([p + eps * d, p - eps * d]), w, dw
            )
            da_pm = jax.vmap(grad_a, in_axes=(0, None, None))(
                w_pm, a, train_batch
            )
            da_pos = tmap(lambda t: t[0], da_pm)
            da_neg = tmap(lambda t: t[1], da_pm)
        else:
            w_pos = tmap(lambda p, d: p + eps * d, w, dw)
            w_neg = tmap(lambda p, d: p - eps * d, w, dw)
            da_pos = grad_a(w_pos, a, train_batch)
            da_neg = grad_a(w_neg, a, train_batch)
        hessian = tmap(lambda p, n: (p - n) / (2.0 * eps), da_pos, da_neg)
        alpha_grad = tmap(lambda d, h: d - lr * h, da, hessian)
        return alpha_grad, val_loss

    def alpha_grad_first_order(state: SearchState, lr, train_batch, val_batch):
        val_loss, (_, da) = val_grads(state.weights, state.alphas, val_batch)
        return da, val_loss

    alpha_grad_fn = alpha_grad_unrolled if hyper.unrolled else alpha_grad_first_order

    def search_step(state: SearchState, train_batch, val_batch):
        lr = cosine_lr(state.step)

        # 1) architecture update
        a_grad, val_loss = alpha_grad_fn(state, lr, train_batch, val_batch)
        a_updates, a_opt = a_tx.update(a_grad, state.a_opt, state.alphas)
        alphas = optax.apply_updates(state.alphas, a_updates)

        # 2) weight update at the NEW alphas (reference run_trial.py:193-205:
        #    alpha step happens before the weight step each batch)
        train_loss, gw = jax.value_and_grad(loss_fn)(state.weights, alphas, train_batch)
        gw = tmap(lambda g, p: g + hyper.w_weight_decay * p, gw, state.weights)
        gw, gnorm = clip(gw)
        velocity = tmap(
            lambda v, g: hyper.w_momentum * v + g, state.velocity, gw
        )
        weights = tmap(lambda p, v: p - lr * v, state.weights, velocity)

        new_state = SearchState(
            step=state.step + 1,
            weights=weights,
            alphas=alphas,
            a_opt=a_opt,
            velocity=velocity,
        )
        metrics = {
            "train_loss": train_loss,
            "val_loss": val_loss,
            "w_lr": lr,
            "grad_norm": gnorm,
        }
        if hyper.debug_alpha_grad:
            metrics["alpha_grad"] = a_grad
        return new_state, metrics

    if not jit:
        return search_step

    if mesh is None:
        return jax.jit(search_step, donate_argnums=(0,))

    from jax.sharding import NamedSharding, PartitionSpec

    from katib_tpu.parallel.mesh import DATA_AXIS, replicated

    state_sharding = replicated(mesh)
    batch_sharding = NamedSharding(mesh, PartitionSpec(DATA_AXIS))
    return jax.jit(
        search_step,
        in_shardings=(state_sharding, batch_sharding, batch_sharding),
        out_shardings=(state_sharding, state_sharding),
        donate_argnums=(0,),
    )


def init_search_state(
    weights: Any, alphas: Alphas, hyper: DartsHyper
) -> SearchState:
    a_tx = optax.chain(
        optax.add_decayed_weights(hyper.alpha_weight_decay),
        optax.adam(hyper.alpha_lr, b1=0.5, b2=0.999),
    )
    return SearchState(
        step=jnp.zeros((), jnp.int32),
        weights=weights,
        alphas=alphas,
        a_opt=a_tx.init(alphas),
        velocity=tmap(jnp.zeros_like, weights),
    )
