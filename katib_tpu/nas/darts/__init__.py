from katib_tpu.nas.darts.architect import DartsHyper, make_search_step  # noqa: F401
from katib_tpu.nas.darts.augment import (  # noqa: F401
    GenotypeNetwork,
    train_genotype,
)
from katib_tpu.nas.darts.model import (  # noqa: F401
    Alphas,
    DartsNetwork,
    Genotype,
    extract_genotype,
    init_alphas,
)
from katib_tpu.nas.darts.search import darts_trial, run_darts_search  # noqa: F401
from katib_tpu.nas.darts.service import DartsSuggester  # noqa: F401
