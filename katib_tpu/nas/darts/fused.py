"""Fused evaluation of the four depthwise-separable DARTS primitives.

The reference primitive set (``darts-cnn-cifar10/operations.py:18-31``)
contains four conv primitives — separable 3x3/5x5 (two depthwise-separable
reps each) and dilated 3x3/5x5 (one rep, dilation 2).  Evaluated naively,
one mixed op dispatches 6 depthwise convs + 6 pointwise convs + 6 batch
norms, every one of them tiny at search width (16-64 channels on 32x32):
the on-chip profile of the bilevel step is per-op overhead and tile
padding, not math (0.56% MFU measured, ``docs/performance.md``).

The fused form exploits that all four branches consume the SAME input and
that every branch's tap pattern embeds in a 9x9 window:

==========================  =======  ========  =========================
branch                      kernel   dilation  taps inside the 9x9 grid
==========================  =======  ========  =========================
separable_convolution_3x3   3x3      1         rows/cols {3,4,5}
separable_convolution_5x5   5x5      1         rows/cols {2..6}
dilated_convolution_3x3     3x3      2         rows/cols {2,4,6}
dilated_convolution_5x5     5x5      2         rows/cols {0,2,4,6,8}
==========================  =======  ========  =========================

Stage A runs all four first reps as ONE depthwise conv with channel
multiplier 4 (kernel ``(9,9,1,4C)``, ``feature_group_count=C``), each
branch's natural parameters scattered into its masked positions, followed
by ONE grouped pointwise as a batched einsum (``(4,C,C)`` weights — a
single batched matmul instead of four C x C slivers) and a per-branch BN.
Stage B applies the separable branches' second rep the same way: one
masked 5x5 depthwise over the two branches' 2C channels (multiplier 1,
``feature_group_count=2C``) + a ``(2,C,C)`` batched pointwise + BN.  Net:
2 depthwise + 2 batched-matmul pointwise + 2 BN dispatches instead of
6 + 6 + 6, and the input is read from HBM once instead of four times.

Exactness (pinned by ``tests/test_fused_ops.py``): with SAME padding the
masked window reproduces each branch's own padding arithmetic — for
stride s and centered masks, output o reads input ``o*s - pad_lo + tap``,
and the 9x9 pad ((3,4) at stride 2 on even sizes; (4,4) at stride 1)
lands every branch on exactly the offsets its natural SAME-padded conv
reads.  The parameters ARE the unmerged parameters (same ``(k,k,1,C)``
shapes, same lecun-normal fan-in), so the fusion is a pure
evaluation-plan change, not a model change.

``safe=True`` (meshes with a model axis, where XLA's SPMD partitioner
miscompiles grouped-conv filter gradients — ``ops/depthwise.py`` module
doc) computes the same masked convs as shift-MACs over each branch's
active taps only: elementwise ops, partitioner-safe, numerically the
masked dense conv by construction.
"""

from __future__ import annotations

from typing import Dict

import flax.linen as nn
import jax.numpy as jnp

# (name, kernel, dilation, has_second_rep) in fixed branch order
BRANCH_SPECS = (
    ("separable_convolution_3x3", 3, 1, True),
    ("separable_convolution_5x5", 5, 1, True),
    ("dilated_convolution_3x3", 3, 2, False),
    ("dilated_convolution_5x5", 5, 2, False),
)
FUSED_PRIMITIVES = tuple(s[0] for s in BRANCH_SPECS)


def _taps(kernel: int, dilation: int, window: int) -> list[int]:
    """Row/col offsets of a centered k x k (dilation d) kernel inside the
    fused window."""
    extent = (kernel - 1) * dilation + 1
    base = (window - extent) // 2
    return [base + i * dilation for i in range(kernel)]


def _same_pads(size: int, stride: int, extent: int) -> tuple[int, int]:
    """XLA SAME padding: lo = total // 2 (stride-2/even-size gives (3,4)
    for the 9-extent window, matching each branch's natural pads)."""
    out = -(-size // stride)
    total = max((out - 1) * stride + extent - size, 0)
    return total // 2, total - total // 2


class _MaskedDepthwise(nn.Module):
    """Masked-window depthwise conv evaluating B branches in one dispatch.

    ``specs``: ((param_name, kernel, dilation), ...), one branch per spec;
    parameters keep the unmerged ``(k, k, 1, C)`` shape and lecun-normal
    fan-in so checkpoints round-trip with the per-branch form.

    ``shared_input=True``: input (N, H, W, C); every branch convolves the
    same C channels (channel multiplier B).  ``shared_input=False``: input
    (N, H, W, B, C); branch b convolves only its own slice (multiplier 1
    over the flattened B*C channels).  Output is (N, H', W', B, C) either
    way.
    """

    specs: tuple  # ((name, kernel, dilation), ...)
    window: int
    stride: int = 1
    shared_input: bool = True
    dtype: jnp.dtype = jnp.bfloat16
    safe: bool = False

    @nn.compact
    def __call__(self, x):
        import jax

        nb = len(self.specs)
        c = x.shape[-1]
        kerns = [
            (
                self.param(
                    name, nn.initializers.lecun_normal(), (k, k, 1, c), jnp.float32
                ).astype(self.dtype),
                k,
                d,
            )
            for name, k, d in self.specs
        ]
        win, s = self.window, self.stride
        if not self.safe:
            if self.shared_input:
                # kernel axis-3 = flatten of (C, B): grouped-conv group c
                # (input channel c) yields output channels [c*B, (c+1)*B)
                merged = jnp.zeros((win, win, c, nb), self.dtype)
                for b, (kern, k, d) in enumerate(kerns):
                    taps = _taps(k, d, win)
                    for i, ti in enumerate(taps):
                        for j, tj in enumerate(taps):
                            merged = merged.at[ti, tj, :, b].set(kern[i, j, 0])
                merged = merged.reshape(win, win, 1, c * nb)
                out = jax.lax.conv_general_dilated(
                    x.astype(self.dtype),
                    merged,
                    window_strides=(s, s),
                    padding="SAME",
                    dimension_numbers=("NHWC", "HWIO", "NHWC"),
                    feature_group_count=c,
                )
                out = out.reshape(*out.shape[:3], c, nb)
                return jnp.moveaxis(out, -1, -2)  # (N, H', W', B, C)
            # branch-sliced input: flatten (B, C) b-major; group b*C+ch is
            # branch b's channel ch with branch b's masked kernel
            n, h, w = x.shape[0], x.shape[1], x.shape[2]
            merged = jnp.zeros((win, win, nb, c), self.dtype)
            for b, (kern, k, d) in enumerate(kerns):
                taps = _taps(k, d, win)
                for i, ti in enumerate(taps):
                    for j, tj in enumerate(taps):
                        merged = merged.at[ti, tj, b, :].set(kern[i, j, 0])
            merged = merged.reshape(win, win, 1, nb * c)
            out = jax.lax.conv_general_dilated(
                x.astype(self.dtype).reshape(n, h, w, nb * c),
                merged,
                window_strides=(s, s),
                padding="SAME",
                dimension_numbers=("NHWC", "HWIO", "NHWC"),
                feature_group_count=nb * c,
            )
            return out.reshape(*out.shape[:3], nb, c)
        # ---- shift-MAC form: each branch's ACTIVE taps only (union cost
        # equals the unmerged safe path; pad/slice work is shared)
        h_dim, w_dim = (1, 2)
        h, w = x.shape[h_dim], x.shape[w_dim]
        pad_h = _same_pads(h, s, win)
        pad_w = _same_pads(w, s, win)
        pad_cfg = [(0, 0)] * x.ndim
        pad_cfg[h_dim], pad_cfg[w_dim] = pad_h, pad_w
        xp = jnp.pad(x.astype(self.dtype), pad_cfg)
        out_h, out_w = -(-h // s), -(-w // s)
        branch_outs = []
        for b, (kern, k, d) in enumerate(kerns):
            taps = _taps(k, d, win)
            src = xp if self.shared_input else xp[:, :, :, b, :]
            acc = None
            for i, ti in enumerate(taps):
                for j, tj in enumerate(taps):
                    tap = src[
                        :,
                        ti : ti + (out_h - 1) * s + 1 : s,
                        tj : tj + (out_w - 1) * s + 1 : s,
                        :,
                    ]
                    term = tap * kern[i, j, 0]
                    acc = term if acc is None else acc + term
            branch_outs.append(acc)
        return jnp.stack(branch_outs, axis=-2)  # (N, H', W', B, C)


def _grouped_pointwise(module: nn.Module, name: str, y, features: int, dtype):
    """Per-branch 1x1 convs as ONE batched einsum: (N,H,W,B,C) x (B,C,F).

    Parameter ``(B, C, F)`` stacks the unmerged ``(C, F)`` pointwise
    kernels branch-major; lecun-normal fan-in stays C per branch."""
    nb, c = y.shape[-2], y.shape[-1]
    # batch_axis=0: fan-in must stay C (the unmerged per-branch fan-in),
    # not B*C
    kern = module.param(
        name,
        nn.initializers.lecun_normal(batch_axis=0),
        (nb, c, features),
        jnp.float32,
    )
    return jnp.einsum("nhwbc,bcf->nhwbf", y.astype(dtype), kern.astype(dtype))


def _branch_norm(y: jnp.ndarray, eps: float = 1e-5) -> jnp.ndarray:
    """Training-mode BN per (branch, channel) — identical statistics to the
    unmerged per-branch ``ops.batch_norm`` (mean/var over N,H,W)."""
    y32 = y.astype(jnp.float32)
    mean = jnp.mean(y32, axis=(0, 1, 2), keepdims=True)
    var = jnp.var(y32, axis=(0, 1, 2), keepdims=True)
    return ((y32 - mean) * jnp.sqrt(1.0 / (var + eps))).astype(y.dtype)


class FusedSepDil(nn.Module):
    """All four depthwise-separable primitives of one mixed op, fused.

    Returns ``{primitive_name: (N, H', W', C)}`` — numerically identical
    (up to dtype rounding) to running ``SepConv``/``DilConv`` separately
    on the same parameters (``tests/test_fused_ops.py`` embeds unmerged
    kernels into the masked form and pins equality).
    """

    channels: int
    stride: int
    dtype: jnp.dtype = jnp.bfloat16
    safe: bool = False

    @nn.compact
    def __call__(self, x) -> Dict[str, jnp.ndarray]:
        c = self.channels
        x = nn.relu(x)
        # ---- stage A: all four first reps, one masked 9x9 multiplier-4 dw
        y = _MaskedDepthwise(
            specs=tuple((f"dw_{n}_0", k, d) for n, k, d, _ in BRANCH_SPECS),
            window=9,
            stride=self.stride,
            shared_input=True,
            dtype=self.dtype,
            safe=self.safe,
        )(x)
        y = _grouped_pointwise(self, "pw_0", y, c, self.dtype)
        y = _branch_norm(y)

        # dilated branches are complete after one rep
        out_dil3 = y[..., 2, :]
        out_dil5 = y[..., 3, :]

        # ---- stage B: separable branches' second rep (stride 1)
        z = nn.relu(y[..., :2, :])
        z = _MaskedDepthwise(
            specs=tuple(
                (f"dw_{n}_1", k, d) for n, k, d, second in BRANCH_SPECS if second
            ),
            window=5,
            stride=1,
            shared_input=False,
            dtype=self.dtype,
            safe=self.safe,
        )(z)
        z = _grouped_pointwise(self, "pw_1", z, c, self.dtype)
        z = _branch_norm(z)

        return {
            "separable_convolution_3x3": z[..., 0, :],
            "separable_convolution_5x5": z[..., 1, :],
            "dilated_convolution_3x3": out_dil3,
            "dilated_convolution_5x5": out_dil5,
        }
