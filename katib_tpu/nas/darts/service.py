"""DARTS suggester — config-only service.

Parity with the reference (``pkg/suggestion/v1beta1/nas/darts/service.py``):
all search happens inside the single trial; the suggester's job is to convert
the NAS operations into a primitive list (``get_search_space`` :102), merge
algorithm settings over defaults (:118-135), validate them (:162), and emit
exactly ONE trial carrying three string parameters: ``algorithm-settings``,
``search-space``, ``num-layers`` (:49-99).
"""

from __future__ import annotations

import json

from katib_tpu.core.types import (
    Experiment,
    ExperimentSpec,
    ParameterAssignment,
    TrialAssignmentSet,
)
from katib_tpu.suggest.base import (
    SearchExhausted,
    Suggester,
    SuggesterError,
    register,
)

from katib_tpu.nas.darts.architect import DartsHyper

DEFAULT_SETTINGS: dict[str, object] = {
    # reference defaults ``darts/service.py:118-135``; the optimizer-side
    # values come from DartsHyper so the trial and service can't drift
    "num_epochs": 50,
    **{
        k: v
        for k, v in DartsHyper._field_defaults.items()
        if k not in ("total_steps", "unrolled")
    },
    "batch_size": 128,
    "init_channels": 16,
    "num_nodes": 4,
    "stem_multiplier": 3,
}

_POSITIVE_INT = {
    "num_epochs", "batch_size", "init_channels", "num_nodes",
    "stem_multiplier", "n_train", "n_test",
    # scan-window of the device-resident step loop (search.py); the
    # camelCase spelling is the Katib-style CR surface, the snake_case
    # the internal one — both validate the same way
    "step_loop_window", "stepLoopWindow",
}
# augment_epochs may be 0 (off, the default); validated separately below
_NON_NEGATIVE_INT = {"augment_epochs"}
_POSITIVE_FLOAT = {
    "w_lr",
    "w_lr_min",
    "w_momentum",
    "w_weight_decay",
    "w_grad_clip",
    "alpha_lr",
    "alpha_weight_decay",
    "augment_lr",
}


def search_space_from_nas_config(nas_config) -> list[str]:
    """Operations -> primitive names (reference ``get_search_space`` :102:
    ``<operation_type>_<k>x<k>`` per filter size; skip_connection bare)."""
    primitives: list[str] = []
    for op in nas_config.operations:
        if op.operation_type == "skip_connection":
            primitives.append("skip_connection")
            continue
        sizes = []
        for p in op.parameters:
            if p.name == "filter_size" and p.feasible.list:
                sizes = list(p.feasible.list)
        if not sizes:
            raise SuggesterError(
                f"operation {op.operation_type!r} needs a filter_size categorical parameter"
            )
        for k in sizes:
            primitives.append(f"{op.operation_type}_{k}x{k}")
    return primitives


@register("darts")
class DartsSuggester(Suggester):
    @classmethod
    def validate(cls, spec: ExperimentSpec) -> None:
        if spec.nas_config is None or not spec.nas_config.operations:
            raise SuggesterError("darts requires nas_config with operations")
        search_space_from_nas_config(spec.nas_config)
        for name, raw in spec.algorithm.settings.items():
            if name in _POSITIVE_INT or name in _NON_NEGATIVE_INT:
                try:
                    v = int(raw)
                except (TypeError, ValueError):
                    raise SuggesterError(f"{name} must be an integer") from None
                if v <= 0 and name in _POSITIVE_INT:
                    raise SuggesterError(f"{name} must be > 0")
                if v < 0:
                    raise SuggesterError(f"{name} must be >= 0")
            elif name in _POSITIVE_FLOAT:
                try:
                    v = float(raw)
                except (TypeError, ValueError):
                    raise SuggesterError(f"{name} must be a number") from None
                if v < 0:
                    raise SuggesterError(f"{name} must be >= 0")
            elif name == "dataset":
                from katib_tpu.models.data import NAMED_DATASETS

                if str(raw) not in NAMED_DATASETS:
                    # a typo must fail at submission, not after the search
                    raise SuggesterError(
                        f"dataset must be one of {NAMED_DATASETS}, got {raw!r}"
                    )

    def merged_settings(self) -> dict:
        merged = dict(DEFAULT_SETTINGS)
        for k, v in self.spec.algorithm.settings.items():
            merged[k] = v
        return merged

    def get_suggestions(
        self, experiment: Experiment, count: int
    ) -> list[TrialAssignmentSet]:
        if experiment.trials:
            # one search trial per experiment (reference emits exactly one,
            # ``service.py:49``: "DARTS algorithm uses only one trial")
            raise SearchExhausted("darts runs exactly one search trial")
        primitives = search_space_from_nas_config(self.spec.nas_config)
        num_layers = self.spec.nas_config.graph_config.num_layers
        return [
            TrialAssignmentSet(
                assignments=[
                    ParameterAssignment(
                        "algorithm-settings", json.dumps(self.merged_settings())
                    ),
                    ParameterAssignment("search-space", json.dumps(primitives)),
                    ParameterAssignment("num-layers", str(num_layers)),
                ],
                labels={"nas": "darts"},
            )
        ]
