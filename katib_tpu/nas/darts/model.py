"""DARTS supernet: cells of mixed ops with architecture parameters.

Parity with the reference trial image's supernet
(``examples/v1beta1/trial-images/darts-cnn-cifar10/model.py``: ``Cell`` :21,
``NetworkCNN`` :74, genotype extraction :187), restructured for JAX:

- architecture parameters (alphas) are NOT flax parameters of the network —
  they are an explicit pytree passed to ``apply``.  The bilevel optimization
  differentiates w and alpha independently, so keeping them as separate
  arguments gives ``jax.grad(..., argnums=...)`` directly instead of
  surgically splitting a parameter dict;
- cells are optionally wrapped in ``jax.checkpoint`` (remat) so the supernet
  (every primitive evaluated on every edge) fits HBM at CIFAR scale — the
  reference needs two full model copies for its virtual step, and so do we.
"""

from __future__ import annotations

from typing import NamedTuple, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

from katib_tpu.nas.darts.ops import (
    DEFAULT_PRIMITIVES,
    FactorizedReduce,
    MixedOp,
    ReluConvBn,
    batch_norm,
)


class Alphas(NamedTuple):
    """Architecture parameters: one row of op-logits per edge."""

    normal: jnp.ndarray  # (n_edges, n_ops)
    reduce: jnp.ndarray  # (n_edges, n_ops)


def n_edges(n_nodes: int) -> int:
    # node j has j+2 incoming edges (from 2 cell inputs + prior nodes)
    return sum(j + 2 for j in range(n_nodes))


def init_alphas(
    n_nodes: int, n_ops: int, rng: jax.Array, scale: float = 1e-3
) -> Alphas:
    k = n_edges(n_nodes)
    r1, r2 = jax.random.split(rng)
    return Alphas(
        normal=scale * jax.random.normal(r1, (k, n_ops), jnp.float32),
        reduce=scale * jax.random.normal(r2, (k, n_ops), jnp.float32),
    )


class Cell(nn.Module):
    """One DARTS cell (reference ``model.py:21``): nodes connected by mixed
    ops; output = channel-concat of the intermediate nodes.

    Edges are evaluated through ``nn.vmap`` groups — all of a node's incoming
    edges with the same stride share ONE traced MixedOp with stacked
    parameters.  Identical math to per-edge modules (vmapped batch-norm
    statistics are per-edge), but the XLA graph carries one mixed-op trace
    per group instead of one per edge: the bilevel DARTS step at reference
    scale (8 cells x 14 edges x 8 primitives, x4 passes) is otherwise tens
    of thousands of convolutions and multi-minute (CPU: unbounded) compiles.
    """

    primitives: Sequence[str]
    channels: int
    n_nodes: int = 4
    reduction: bool = False
    reduction_prev: bool = False
    dtype: jnp.dtype = jnp.bfloat16
    # partitioner-safe conv forms for meshes with a model axis
    # (ops/depthwise.py module doc)
    safe_conv: bool = False
    # fused evaluation of the 4 depthwise-separable primitives
    # (nas/darts/fused.py module doc)
    fused_convs: bool = False

    @nn.compact
    def __call__(self, s0, s1, weights):
        # weights: (n_edges, n_ops) softmaxed alphas for this cell type
        if self.reduction_prev:
            s0 = FactorizedReduce(self.channels, dtype=self.dtype)(s0)
        else:
            s0 = ReluConvBn(self.channels, dtype=self.dtype)(s0)
        s1 = ReluConvBn(self.channels, dtype=self.dtype)(s1)

        VmappedMixedOp = nn.vmap(
            MixedOp,
            variable_axes={"params": 0},
            split_rngs={"params": True},
            in_axes=(0, 0),
            out_axes=0,
        )

        def edge_group(states_group, w_rows, stride):
            # [k, N, H, W, C] states + [k, n_ops] weight rows -> [k, N, H', W', C]
            return VmappedMixedOp(
                self.primitives, self.channels, stride, dtype=self.dtype,
                safe=self.safe_conv, fused=self.fused_convs,
            )(jnp.stack(states_group), w_rows)

        states = [s0, s1]
        offset = 0
        for node in range(self.n_nodes):
            k = len(states)
            w_rows = weights[offset : offset + k]
            if self.reduction:
                # cell inputs reduce spatially (stride 2); intermediate
                # states are already reduced (stride 1)
                total = edge_group(states[:2], w_rows[:2], 2).sum(axis=0)
                if k > 2:
                    total = total + edge_group(states[2:], w_rows[2:], 1).sum(axis=0)
            else:
                total = edge_group(states, w_rows, 1).sum(axis=0)
            offset += k
            states.append(total)
        return jnp.concatenate(states[2:], axis=-1)


def run_macro(
    x,
    make_cell,
    *,
    init_channels: int,
    num_layers: int,
    num_classes: int,
    stem_multiplier: int,
    dtype,
):
    """The shared macro-skeleton (reference ``model.py:74`` NetworkCNN):
    stem conv + BN, cells with channel-doubling reductions at 1/3 and 2/3
    depth, global average pool, float32 classifier head.

    ``make_cell(channels, reduction, reduction_prev) -> fn(s0, s1)``
    supplies the per-layer cell — the supernet's mixed-op :class:`Cell` or
    the augment phase's discrete ``GenotypeCell`` — so the two networks can
    never drift apart in macro-architecture (must be called inside an
    ``nn.compact`` ``__call__``; flax tracks the submodules it builds)."""
    c_cur = init_channels * stem_multiplier
    x = nn.Conv(c_cur, (3, 3), padding="SAME", use_bias=False, dtype=dtype)(
        x.astype(dtype)
    )
    s0 = s1 = batch_norm(x)

    c = init_channels
    reduction_prev = False
    reduction_layers = {num_layers // 3, 2 * num_layers // 3}
    for layer in range(num_layers):
        reduction = layer in reduction_layers and num_layers > 2
        if reduction:
            c *= 2
        s0, s1 = s1, make_cell(c, reduction, reduction_prev)(s0, s1)
        reduction_prev = reduction

    out = jnp.mean(s1, axis=(1, 2))  # global average pool
    return nn.Dense(num_classes, dtype=jnp.float32)(out.astype(jnp.float32))


class DartsNetwork(nn.Module):
    """Supernet (reference ``model.py:74`` NetworkCNN): the shared macro
    skeleton with mixed-op cells."""

    primitives: Sequence[str] = DEFAULT_PRIMITIVES
    init_channels: int = 16
    num_layers: int = 8
    n_nodes: int = 4
    num_classes: int = 10
    stem_multiplier: int = 3
    remat: bool = True
    # rematerialisation policy: None = recompute everything (max memory
    # saving, measured ~1.8x per-image cost on the bilevel step); "dots" =
    # jax.checkpoint_policies.dots_with_no_batch_dims_saveable — keep
    # matmul/conv outputs resident and recompute only the cheap
    # elementwise/BN work, trading a little HBM for most of full remat's
    # recompute cost.  The knob exists because the no-remat bilevel step
    # tops out at batch ~64 on a 16 GiB v5e (12.1 GiB measured by the AOT
    # block) and full remat erases the batch-scaling win it enables.
    remat_policy: str | None = None
    dtype: jnp.dtype = jnp.bfloat16
    # select partitioner-safe conv forms; REQUIRED when training over a
    # mesh with a model axis > 1 (ops/depthwise.py module doc)
    safe_conv: bool = False
    # fused evaluation of the 4 depthwise-separable primitives: 2 masked
    # depthwise + 2 batched-pointwise dispatches per mixed op instead of
    # 6+6 (nas/darts/fused.py); changes the parameter-tree layout, so it
    # is a per-network choice, not a runtime toggle
    fused_convs: bool = False

    @nn.compact
    def __call__(self, x, alphas: Alphas):
        w_normal = jax.nn.softmax(alphas.normal.astype(jnp.float32), axis=-1)
        w_reduce = jax.nn.softmax(alphas.reduce.astype(jnp.float32), axis=-1)
        # validate the policy even with remat off, so a typo'd policy fails
        # now rather than when remat is later re-enabled
        policies = {
            None: None,
            "dots": jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
        }
        try:
            policy = policies[self.remat_policy]
        except KeyError:
            raise ValueError(
                f"unknown remat_policy {self.remat_policy!r}; "
                f"expected one of {sorted(k for k in policies if k)} or None"
            ) from None
        if self.remat:
            cell_cls = (
                nn.remat(Cell, policy=policy) if policy is not None else nn.remat(Cell)
            )
        else:
            cell_cls = Cell

        def make_cell(c, reduction, reduction_prev):
            cell = cell_cls(
                primitives=self.primitives,
                channels=c,
                n_nodes=self.n_nodes,
                reduction=reduction,
                reduction_prev=reduction_prev,
                dtype=self.dtype,
                safe_conv=self.safe_conv,
                fused_convs=self.fused_convs,
            )
            weights = w_reduce if reduction else w_normal
            return lambda s0, s1: cell(s0, s1, weights)

        return run_macro(
            x,
            make_cell,
            init_channels=self.init_channels,
            num_layers=self.num_layers,
            num_classes=self.num_classes,
            stem_multiplier=self.stem_multiplier,
            dtype=self.dtype,
        )


# ---------------------------------------------------------------------------
# Genotype extraction (reference ``model.py:187``)
# ---------------------------------------------------------------------------


class Genotype(NamedTuple):
    normal: list
    reduce: list

    def render(self) -> str:
        return f"Genotype(normal={self.normal}, reduce={self.reduce})"


def extract_genotype(
    alphas: Alphas, primitives: Sequence[str], n_nodes: int = 4
) -> Genotype:
    """Discretize: per node keep the top-2 incoming edges ranked by their
    strongest non-'none' op weight; each kept edge uses that op."""
    import numpy as np

    def parse(matrix) -> list:
        weights = np.asarray(jax.nn.softmax(jnp.asarray(matrix, jnp.float32), axis=-1))
        try:
            none_idx = list(primitives).index("none")
        except ValueError:
            none_idx = None
        gene = []
        offset = 0
        for node in range(n_nodes):
            k = node + 2
            edges = weights[offset : offset + k]
            scores = []
            for e in range(k):
                row = edges[e].copy()
                if none_idx is not None:
                    row[none_idx] = -np.inf
                best_op = int(np.argmax(row))
                scores.append((float(row[best_op]), e, best_op))
            scores.sort(reverse=True)
            gene.append(
                [(primitives[op], edge) for _, edge, op in sorted(scores[:2], key=lambda t: t[1])]
            )
            offset += k
        return gene

    return Genotype(normal=parse(alphas.normal), reduce=parse(alphas.reduce))
