"""DARTS augment phase: train the discovered genotype as a fixed network.

The reference trial image stops at printing ``Best-Genotype=...``
(``darts-cnn-cifar10/run_trial.py:231-233``) — the genotype is the
experiment's product, and actually *using* it is left to the user.  This
module closes that loop: ``GenotypeNetwork`` materializes a discrete cell
network from a :class:`~katib_tpu.nas.darts.model.Genotype` (each node =
sum of its two kept ops, no mixed-op softmax), and ``train_genotype`` runs
standard supervised training on it — the DARTS paper's "augment" stage,
sized for whatever dataset the search ran on.

The discrete network reuses the same primitive factory as the supernet
(``ops.build_op``), so a genotype searched here trains on exactly the op
implementations that were scored during search.
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp

from katib_tpu.nas.darts.model import Genotype, run_macro
from katib_tpu.nas.darts.ops import (
    FactorizedReduce,
    ReluConvBn,
    build_op,
)


class GenotypeCell(nn.Module):
    """One discrete cell: per node, the genotype's two kept ``(op, src)``
    edges are applied and summed; the cell output concatenates the
    intermediate nodes (reference cell layout, ``model.py:21``, with the
    mixed op replaced by the chosen primitive)."""

    gene: Sequence[Sequence[tuple]]  # per node: [(op_name, src_state), ...]
    channels: int
    reduction: bool = False
    reduction_prev: bool = False
    dtype: jnp.dtype = jnp.bfloat16
    safe_conv: bool = False  # ops/depthwise.py module doc

    @nn.compact
    def __call__(self, s0, s1):
        if self.reduction_prev:
            s0 = FactorizedReduce(self.channels, dtype=self.dtype)(s0)
        else:
            s0 = ReluConvBn(self.channels, dtype=self.dtype)(s0)
        s1 = ReluConvBn(self.channels, dtype=self.dtype)(s1)

        states = [s0, s1]
        for node in self.gene:
            total = None
            for op_name, src in node:
                # cell inputs shrink spatially in reduction cells; states
                # computed inside the cell are already reduced
                stride = 2 if self.reduction and src < 2 else 1
                out = build_op(
                    op_name, self.channels, stride, self.dtype,
                    safe=self.safe_conv,
                )(states[src])
                total = out if total is None else total + out
            states.append(total)
        return jnp.concatenate(states[2:], axis=-1)


class GenotypeNetwork(nn.Module):
    """Discrete-architecture classifier: stem + genotype cells with
    reductions at 1/3 and 2/3 depth — the same macro-layout the supernet
    searched (``model.py:74``)."""

    genotype: Genotype
    init_channels: int = 16
    num_layers: int = 8
    num_classes: int = 10
    stem_multiplier: int = 3
    dtype: jnp.dtype = jnp.bfloat16
    safe_conv: bool = False  # ops/depthwise.py module doc

    @nn.compact
    def __call__(self, x):
        def make_cell(c, reduction, reduction_prev):
            gene = self.genotype.reduce if reduction else self.genotype.normal
            return GenotypeCell(
                gene=tuple(tuple(tuple(e) for e in node) for node in gene),
                channels=c,
                reduction=reduction,
                reduction_prev=reduction_prev,
                dtype=self.dtype,
                safe_conv=self.safe_conv,
            )

        return run_macro(
            x,
            make_cell,
            init_channels=self.init_channels,
            num_layers=self.num_layers,
            num_classes=self.num_classes,
            stem_multiplier=self.stem_multiplier,
            dtype=self.dtype,
        )


def train_genotype(
    genotype: Genotype,
    dataset,
    *,
    init_channels: int = 16,
    num_layers: int = 8,
    stem_multiplier: int = 3,
    lr: float = 0.025,
    epochs: int = 10,
    batch_size: int = 96,
    mesh=None,
    report=None,
    data_augment: bool = False,
) -> float:
    """Train the discrete network; returns final held-out accuracy.

    ``data_augment``: apply the reference trial image's CIFAR train-time
    pipeline (RandomCrop(pad 4) + flip + Cutout(16),
    ``darts-cnn-cifar10/utils.py:15-30``) as device-side batch transforms
    (``models/augmentation.py``) — the transforms the paper's ~97% augment
    protocol depends on.  Off by default so throughput artifacts stay
    comparable across rounds; the accuracy-focused runs opt in."""
    from katib_tpu.models.mnist import train_classifier

    from katib_tpu.parallel.mesh import needs_safe_conv

    net = GenotypeNetwork(
        genotype=genotype,
        init_channels=init_channels,
        num_layers=num_layers,
        num_classes=dataset.num_classes,
        stem_multiplier=stem_multiplier,
        safe_conv=needs_safe_conv(mesh),
    )
    augment_fn = None
    if data_augment:
        from katib_tpu.models.augmentation import cifar_train_augment

        augment_fn = cifar_train_augment
    return train_classifier(
        net,
        dataset,
        lr=lr,
        epochs=epochs,
        batch_size=batch_size,
        optimizer="momentum",
        mesh=mesh,
        report=report,
        augment_fn=augment_fn,
    )
