"""ENAS child-training trial workload.

Parity with the reference trial image
(``examples/v1beta1/trial-images/enas-cnn-cifar10/RunTrial.py:52-100``): build
the CNN from the ``architecture``/``nn_config`` parameters, train for N
epochs, report ``Validation-Accuracy`` per epoch — here via the trial
context instead of stdout lines, on a JAX mesh instead of MirroredStrategy.
"""

from __future__ import annotations

import json
import time

from katib_tpu.models.data import load_named_dataset
from katib_tpu.models.mnist import train_classifier
from katib_tpu.nas.enas.child import child_from_arc
from katib_tpu.nas.enas.controller import arc_from_json


def enas_trial(ctx) -> None:
    arch = json.loads(ctx.params["architecture"])
    nn_config = json.loads(ctx.params["nn_config"])
    num_layers = int(nn_config["num_layers"])
    operations = nn_config.get("operations")

    from katib_tpu.parallel.mesh import needs_safe_conv

    arc = arc_from_json(arch, num_layers)
    kwargs = {"operations": tuple(operations)} if operations else {}
    model = child_from_arc(
        arc,
        channels=int(ctx.params.get("channels", 24)),
        num_classes=int(ctx.params.get("num_classes", 10)),
        # model-axis meshes need the partitioner-safe depthwise form
        # (ops/depthwise.py module doc)
        safe_conv=needs_safe_conv(ctx.mesh),
        **kwargs,
    )
    n_train = ctx.params.get("n_train")
    n_test = ctx.params.get("n_test")
    dataset = load_named_dataset(
        str(ctx.params.get("dataset", "cifar10")),
        int(n_train) if n_train is not None else None,
        int(n_test) if n_test is not None else None,
    )

    # per-epoch telemetry rides the report callback: the interval between
    # calls is one training epoch (train_classifier reports once per epoch)
    from katib_tpu.utils import observability as obs
    from katib_tpu.utils import tracing

    epochs = int(ctx.params.get("num_epochs", 3))
    batch_size = int(ctx.params.get("batch_size", 128))
    last_report = [time.perf_counter()]

    def report(epoch, accuracy, loss):
        now = time.perf_counter()
        epoch_s, last_report[0] = now - last_report[0], now
        steps = max(len(dataset.x_train) // batch_size, 1)
        obs.trial_step_seconds.observe(epoch_s / steps, workload="enas")
        images_per_s = (steps * batch_size) / epoch_s if epoch_s > 0 else 0.0
        obs.trial_images_per_second.set(images_per_s, workload="enas")
        obs.record_device_memory()
        tracing.record_span(
            "enas.epoch",
            epoch_s,
            trial=ctx.trial_name,
            epoch=epoch,
            images_per_s=round(images_per_s, 1),
            accuracy=round(float(accuracy), 4),
        )
        return ctx.report(step=epoch, accuracy=accuracy, loss=loss)

    # opt-in ENAS weight sharing (the paper's core efficiency idea, which
    # the reference never implements): children overlay the experiment's
    # shared parameter pool before training and publish back afterwards
    from katib_tpu.utils.booleans import parse_bool

    init_transform = on_finish = None
    share = parse_bool(ctx.params.get("weight_sharing"))
    if share and ctx.checkpoint_dir:
        import os

        from katib_tpu.nas.enas.shared import (
            load_pool,
            overlay_matching,
            publish_pool,
        )

        pool_dir = os.path.join(
            os.path.dirname(ctx.checkpoint_dir), "enas-shared"
        )
        pool = load_pool(pool_dir)

        def init_transform(params, _pool=pool):
            if _pool is None:
                return params
            merged, _ = overlay_matching(params, _pool)
            return merged

        def on_finish(params):
            publish_pool(pool_dir, params)

    train_classifier(
        model,
        dataset,
        lr=float(ctx.params.get("lr", 0.05)),
        epochs=int(ctx.params.get("num_epochs", 3)),
        batch_size=int(ctx.params.get("batch_size", 128)),
        optimizer="momentum",
        mesh=ctx.mesh,
        report=report,
        init_transform=init_transform,
        on_finish=on_finish,
    )
