"""ENAS child-training trial workload.

Parity with the reference trial image
(``examples/v1beta1/trial-images/enas-cnn-cifar10/RunTrial.py:52-100``): build
the CNN from the ``architecture``/``nn_config`` parameters, train for N
epochs, report ``Validation-Accuracy`` per epoch — here via the trial
context instead of stdout lines, on a JAX mesh instead of MirroredStrategy.
"""

from __future__ import annotations

import json

from katib_tpu.models.data import load_cifar10, load_digits_real
from katib_tpu.models.mnist import train_classifier
from katib_tpu.nas.enas.child import child_from_arc
from katib_tpu.nas.enas.controller import arc_from_json


def enas_trial(ctx) -> None:
    arch = json.loads(ctx.params["architecture"])
    nn_config = json.loads(ctx.params["nn_config"])
    num_layers = int(nn_config["num_layers"])
    operations = nn_config.get("operations")

    arc = arc_from_json(arch, num_layers)
    kwargs = {"operations": tuple(operations)} if operations else {}
    model = child_from_arc(
        arc,
        channels=int(ctx.params.get("channels", 24)),
        num_classes=int(ctx.params.get("num_classes", 10)),
        **kwargs,
    )
    # "digits" = the bundled REAL dataset (UCI handwritten digits); default
    # stays the CIFAR-10 loader (real npz when KATIB_DATA_DIR provides it,
    # structured synthetic fallback otherwise)
    ds_name = ctx.params.get("dataset", "cifar10")
    if ds_name == "digits":
        # digits has 1797 samples total — CIFAR-scale defaults would clamp
        # the test split to 1 sample and make accuracy a coin flip
        n_train = int(ctx.params.get("n_train", 1400))
        n_test = int(ctx.params.get("n_test", 397))
        dataset = load_digits_real(n_train, n_test)
    elif ds_name == "cifar10":
        n_train = int(ctx.params.get("n_train", 8192))
        n_test = int(ctx.params.get("n_test", 2048))
        dataset = load_cifar10(n_train, n_test)
    else:
        raise ValueError(
            f"unknown dataset {ds_name!r} (expected 'cifar10' or 'digits')"
        )

    def report(epoch, accuracy, loss):
        return ctx.report(step=epoch, accuracy=accuracy, loss=loss)

    # opt-in ENAS weight sharing (the paper's core efficiency idea, which
    # the reference never implements): children overlay the experiment's
    # shared parameter pool before training and publish back afterwards
    from katib_tpu.utils.booleans import parse_bool

    init_transform = on_finish = None
    share = parse_bool(ctx.params.get("weight_sharing"))
    if share and ctx.checkpoint_dir:
        import os

        from katib_tpu.nas.enas.shared import (
            load_pool,
            overlay_matching,
            publish_pool,
        )

        pool_dir = os.path.join(
            os.path.dirname(ctx.checkpoint_dir), "enas-shared"
        )
        pool = load_pool(pool_dir)

        def init_transform(params, _pool=pool):
            if _pool is None:
                return params
            merged, _ = overlay_matching(params, _pool)
            return merged

        def on_finish(params):
            publish_pool(pool_dir, params)

    train_classifier(
        model,
        dataset,
        lr=float(ctx.params.get("lr", 0.05)),
        epochs=int(ctx.params.get("num_epochs", 3)),
        batch_size=int(ctx.params.get("batch_size", 128)),
        optimizer="momentum",
        mesh=ctx.mesh,
        report=report,
        init_transform=init_transform,
        on_finish=on_finish,
    )
