from katib_tpu.nas.enas.child import DEFAULT_OPERATIONS, EnasChild, child_from_arc  # noqa: F401
from katib_tpu.nas.enas.controller import (  # noqa: F401
    Arc,
    ControllerConfig,
    arc_from_json,
    arc_to_json,
    make_reinforce,
    sample_arc,
)
from katib_tpu.nas.enas.service import EnasSuggester  # noqa: F401
from katib_tpu.nas.enas.trial import enas_trial  # noqa: F401
