"""ENAS child network: builds a CNN from a sampled architecture.

Parity with the reference's Keras model constructor
(``examples/v1beta1/trial-images/enas-cnn-cifar10/ModelConstructor.py`` +
``op_library.py``): one operation per layer (conv 3x3/5x5, separable conv,
avg/max pool) plus skip connections that concatenate earlier layer outputs.
The reference trains it with ``tf.distribute.MirroredStrategy`` over local
GPUs (``RunTrial.py:54-62``); here the training loop is the shared
mesh-sharded classifier trainer.
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp
import numpy as np

from katib_tpu.nas.enas.controller import Arc

# operation vocabulary (op_library.py); index = controller's op id
DEFAULT_OPERATIONS = (
    "convolution_3x3",
    "convolution_5x5",
    "separable_convolution_3x3",
    "separable_convolution_5x5",
    "avg_pooling_3x3",
    "max_pooling_3x3",
)


class _Op(nn.Module):
    name_: str
    channels: int
    dtype: jnp.dtype = jnp.bfloat16
    safe_conv: bool = False  # ops/depthwise.py module doc

    @nn.compact
    def __call__(self, x):
        n = self.name_
        if n.startswith("convolution"):
            k = int(n.split("_")[-1][0])
            x = nn.Conv(self.channels, (k, k), padding="SAME", dtype=self.dtype)(x)
            x = nn.relu(x)
        elif n.startswith("separable_convolution"):
            from katib_tpu.ops.depthwise import DepthwiseConv

            k = int(n.split("_")[-1][0])
            # safe=True switches to the shift-MAC depthwise for meshes with
            # a model axis, where the grouped form's filter gradient is
            # miscompiled (ops/depthwise.py module doc)
            x = DepthwiseConv(kernel=k, dtype=self.dtype, safe=self.safe_conv)(x)
            x = nn.Conv(self.channels, (1, 1), dtype=self.dtype)(x)
            x = nn.relu(x)
        elif n.startswith("avg_pooling"):
            x = nn.avg_pool(x, (3, 3), strides=(1, 1), padding="SAME")
            x = nn.Conv(self.channels, (1, 1), dtype=self.dtype)(x)
        elif n.startswith("max_pooling"):
            x = nn.max_pool(x, (3, 3), strides=(1, 1), padding="SAME")
            x = nn.Conv(self.channels, (1, 1), dtype=self.dtype)(x)
        else:
            raise ValueError(f"unknown ENAS operation {n!r}")
        return x


class EnasChild(nn.Module):
    """CNN instantiated from a controller arc (static: the arc is hashable
    config, so each sampled architecture compiles once)."""

    arc_ops: tuple  # per-layer op indices
    arc_skips: tuple  # per-layer tuple of 0/1 for earlier layers
    operations: Sequence[str] = DEFAULT_OPERATIONS
    channels: int = 32
    num_classes: int = 10
    pool_every: int = 3
    dtype: jnp.dtype = jnp.bfloat16
    safe_conv: bool = False  # ops/depthwise.py module doc

    @nn.compact
    def __call__(self, x):
        x = x.astype(self.dtype)
        x = nn.Conv(self.channels, (3, 3), padding="SAME", dtype=self.dtype)(x)
        outputs = []
        for layer, op_idx in enumerate(self.arc_ops):
            inp = x
            skips = self.arc_skips[layer]
            used = [outputs[j] for j, s in enumerate(skips) if s]
            if used:
                inp = jnp.concatenate([inp, *used], axis=-1)
            # op-qualified module name: weight-sharing pools key parameters
            # by flax path, and e.g. avg/max pooling have identically-shaped
            # 1x1 projections — the op name in the path keeps each op's
            # weights separate per layer (the ENAS paper's per-op pool)
            x = _Op(
                self.operations[op_idx],
                self.channels,
                dtype=self.dtype,
                safe_conv=self.safe_conv,
                name=f"op{layer}_{self.operations[op_idx]}",
            )(inp)
            outputs.append(x)
            if (layer + 1) % self.pool_every == 0:
                x = nn.max_pool(x, (2, 2), strides=(2, 2))
                # downsample stored outputs so later skip concats still align
                outputs = [
                    nn.max_pool(o, (2, 2), strides=(2, 2)) for o in outputs
                ]
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes, dtype=jnp.float32)(x.astype(jnp.float32))


def child_from_arc(
    arc: Arc,
    operations: Sequence[str] = DEFAULT_OPERATIONS,
    channels: int = 32,
    num_classes: int = 10,
    safe_conv: bool = False,
) -> EnasChild:
    ops = tuple(int(o) for o in np.asarray(arc.ops))
    skips = tuple(
        tuple(int(s) for s in np.asarray(arc.skips)[layer, :layer])
        for layer in range(len(ops))
    )
    return EnasChild(
        arc_ops=ops,
        arc_skips=skips,
        operations=tuple(operations),
        channels=channels,
        num_classes=num_classes,
        safe_conv=safe_conv,
    )
