"""ENAS weight sharing: children inherit a shared parameter pool.

The ENAS paper's core efficiency idea (Pham et al. 2018, §2) is that child
models SHARE weights — a sampled architecture trains the shared pool, and
the next child starts from it instead of from scratch.  The reference
never implements this: its child trainer builds a fresh Keras model per
trial (``enas-cnn-cifar10/RunTrial.py:52``), so every trial pays full
training cost and the controller's reward signal is noisy early-training
accuracy.  Here sharing is an opt-in trial parameter (``weight_sharing``)
that makes each child overlay the pool's parameters before training and
publish its trained parameters back afterwards.

Sharing is **by module path + shape**: a child's parameter is inherited
when the pool has a leaf at the same flax path with the same shape/dtype.
Layer ``i``'s op module is named ``op{i}_{op_name}`` (child.py), so the
pool holds separate weights per (layer, op) — the ENAS paper's per-op
pool — and a skip-dependent input-width mismatch simply re-initializes
that leaf.  Write-back is last-writer-wins
under a process-wide lock — trials run as threads of one orchestrator, so
the lock is sufficient, and ENAS's shared pool is explicitly a lossy
communal resource (the paper updates it concurrently from sampled archs).
"""

from __future__ import annotations

import threading
from typing import Any

from flax import traverse_util

from katib_tpu.utils.checkpoint import TrialCheckpointer

_LOCK = threading.Lock()


def overlay_matching(params: Any, shared: Any) -> tuple[Any, int]:
    """Replace every leaf of ``params`` whose path + shape + dtype match a
    leaf of ``shared``; returns ``(new_params, n_inherited)``."""
    flat_p = traverse_util.flatten_dict(params)
    flat_s = traverse_util.flatten_dict(shared)
    n = 0
    for key, value in flat_p.items():
        cand = flat_s.get(key)
        if (
            cand is not None
            and getattr(cand, "shape", None) == getattr(value, "shape", ())
            and getattr(cand, "dtype", None) == getattr(value, "dtype", None)
        ):
            flat_p[key] = cand
            n += 1
    return traverse_util.unflatten_dict(flat_p), n


def load_pool(directory: str) -> Any | None:
    """Latest shared-pool pytree, or None when no pool exists yet."""
    with _LOCK:
        ckpt = TrialCheckpointer(directory, max_to_keep=2)
        restored = ckpt.restore()
        return None if restored is None else restored[0]


def publish_pool(directory: str, params: Any) -> None:
    """Publish trained parameters as the new pool version (last-writer-wins)."""
    import jax

    with _LOCK:
        ckpt = TrialCheckpointer(directory, max_to_keep=2)
        latest = ckpt.latest_step()
        ckpt.save(jax.device_get(params), 1 if latest is None else latest + 1)
