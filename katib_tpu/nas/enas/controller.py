"""ENAS controller: LSTM architecture sampler + REINFORCE trainer in JAX.

Parity with the reference's TF1-graph controller
(``pkg/suggestion/v1beta1/nas/enas/Controller.py``): a single-cell LSTM
(hidden 64) samples one operation per layer and, from layer 1 on, an
attention-scored binary skip decision to every earlier layer
(``_build_sampler`` :81-198); REINFORCE with entropy bonus, EMA baseline and
a KL skip-rate penalty trains it on child validation accuracy
(``build_trainer`` :198-257).

JAX redesign: the controller is a pure function of (params, rng) —
sampling returns the arc plus its log-prob/entropy/skip stats, the REINFORCE
update is ``jax.grad`` of log_prob * advantage re-evaluated on the stored
arc, and the whole train step is jitted.  No TF session, no
``ctrl_cache/`` checkpoint files — params are a pytree the service persists
with everything else.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
import optax


class ControllerParams(NamedTuple):
    w_lstm: jnp.ndarray  # (2H, 4H)
    g_emb: jnp.ndarray  # (1, H)
    w_emb: jnp.ndarray  # (num_ops, H)
    w_soft: jnp.ndarray  # (H, num_ops)
    attn_w1: jnp.ndarray  # (H, H)
    attn_w2: jnp.ndarray  # (H, H)
    attn_v: jnp.ndarray  # (H, 1)


class ControllerConfig(NamedTuple):
    """Defaults mirror ``AlgorithmSettings.py`` (hidden 64, temp 5.0, ...)."""

    num_layers: int = 8
    num_operations: int = 6
    hidden_size: int = 64
    temperature: float | None = 5.0
    tanh_const: float | None = 2.25
    entropy_weight: float | None = 1e-5
    baseline_decay: float = 0.999
    learning_rate: float = 5e-5
    skip_target: float = 0.4
    skip_weight: float | None = 0.8


class Arc(NamedTuple):
    ops: jnp.ndarray  # (num_layers,) int32
    skips: jnp.ndarray  # (num_layers, num_layers) lower-triangular 0/1


def init_controller(cfg: ControllerConfig, key: jax.Array) -> ControllerParams:
    h = cfg.hidden_size
    ks = jax.random.split(key, 7)
    u = lambda k, shape: jax.random.uniform(k, shape, jnp.float32, -0.01, 0.01)
    return ControllerParams(
        w_lstm=u(ks[0], (2 * h, 4 * h)),
        g_emb=u(ks[1], (1, h)),
        w_emb=u(ks[2], (cfg.num_operations, h)),
        w_soft=u(ks[3], (h, cfg.num_operations)),
        attn_w1=u(ks[4], (h, h)),
        attn_w2=u(ks[5], (h, h)),
        attn_v=u(ks[6], (h, 1)),
    )


def _lstm(x, c, h, w):
    ifog = jnp.concatenate([x, h], axis=1) @ w
    i, f, o, g = jnp.split(ifog, 4, axis=1)
    c2 = jax.nn.sigmoid(i) * jnp.tanh(g) + jax.nn.sigmoid(f) * c
    return c2, jax.nn.sigmoid(o) * jnp.tanh(c2)


def _shape_logits(logits, cfg: ControllerConfig):
    if cfg.temperature is not None:
        logits = logits / cfg.temperature
    if cfg.tanh_const is not None:
        logits = cfg.tanh_const * jnp.tanh(logits)
    return logits


def _trace(params: ControllerParams, cfg: ControllerConfig, arc: Arc, key=None):
    """Run the controller over a (given or sampled) arc, accumulating
    log-probs, entropies and skip penalties.

    When ``key`` is provided the arc argument is ignored per-step and actions
    are sampled; either way the returned quantities are differentiable wrt
    params for the supplied/sampled actions (the REINFORCE trick: re-evaluate
    log p(arc) on the stored arc).
    """
    h_size = cfg.hidden_size
    c = jnp.zeros((1, h_size))
    h = jnp.zeros((1, h_size))
    inputs = params.g_emb
    skip_targets = jnp.array([1.0 - cfg.skip_target, cfg.skip_target])

    ops: list = []
    skips: list = []
    log_prob = 0.0
    entropy = 0.0
    skip_penalty = 0.0
    skip_count = 0.0
    all_h: list = []
    all_hw: list = []
    keys = (
        jax.random.split(key, 2 * cfg.num_layers) if key is not None else [None] * (2 * cfg.num_layers)
    )

    for layer in range(cfg.num_layers):
        c, h = _lstm(inputs, c, h, params.w_lstm)
        logits = _shape_logits(h @ params.w_soft, cfg)  # (1, num_ops)
        if key is not None:
            op = jax.random.categorical(keys[2 * layer], logits[0])
        else:
            op = arc.ops[layer]
        logp = jax.nn.log_softmax(logits[0])[op]
        log_prob = log_prob + logp
        entropy = entropy + jax.lax.stop_gradient(-logp * jnp.exp(logp))
        ops.append(op)
        inputs = params.w_emb[op][None, :]

        c, h = _lstm(inputs, c, h, params.w_lstm)
        if layer > 0:
            prev_h = jnp.concatenate(all_h, axis=0)  # (layer, H)
            prev_hw = jnp.concatenate(all_hw, axis=0)  # (layer, H)
            query = jnp.tanh(h @ params.attn_w2 + prev_hw) @ params.attn_v  # (layer, 1)
            sk_logits = _shape_logits(
                jnp.concatenate([-query, query], axis=1), cfg
            )  # (layer, 2)
            if key is not None:
                sk = jax.random.categorical(keys[2 * layer + 1], sk_logits, axis=-1)
            else:
                sk = arc.skips[layer, :layer]
            sk = sk.astype(jnp.int32)
            logp_all = jax.nn.log_softmax(sk_logits, axis=-1)
            logp_sk = jnp.take_along_axis(logp_all, sk[:, None], axis=1).sum()
            log_prob = log_prob + logp_sk
            entropy = entropy + jax.lax.stop_gradient(-logp_sk * jnp.exp(logp_sk))
            # KL(skip distribution || target rate) penalty (Controller.py:156-159)
            skip_prob = jax.nn.sigmoid(sk_logits)
            kl = (skip_prob * jnp.log(skip_prob / skip_targets)).sum()
            skip_penalty = skip_penalty + kl
            skf = sk.astype(jnp.float32)
            skip_count = skip_count + skf.sum()
            inputs = (skf[None, :] @ prev_h) / (1.0 + skf.sum())
            row = jnp.zeros((cfg.num_layers,), jnp.int32).at[:layer].set(sk)
        else:
            inputs = params.g_emb
            row = jnp.zeros((cfg.num_layers,), jnp.int32)
        skips.append(row)
        all_h.append(h)
        all_hw.append(h @ params.attn_w1)

    out_arc = Arc(ops=jnp.stack(ops).astype(jnp.int32), skips=jnp.stack(skips))
    stats = {
        "log_prob": log_prob,
        "entropy": entropy,
        "skip_penalty": skip_penalty / max(cfg.num_layers - 1, 1),
        "skip_count": skip_count,
    }
    return out_arc, stats


def sample_arc(params: ControllerParams, cfg: ControllerConfig, key: jax.Array):
    dummy = Arc(
        ops=jnp.zeros((cfg.num_layers,), jnp.int32),
        skips=jnp.zeros((cfg.num_layers, cfg.num_layers), jnp.int32),
    )
    return _trace(params, cfg, dummy, key=key)


class ReinforceState(NamedTuple):
    params: ControllerParams
    opt_state: optax.OptState
    baseline: jnp.ndarray
    step: jnp.ndarray


def make_reinforce(cfg: ControllerConfig):
    """Build (init, train_step, sample) for controller REINFORCE training."""
    tx = optax.adam(cfg.learning_rate)

    def init(key: jax.Array) -> ReinforceState:
        params = init_controller(cfg, key)
        return ReinforceState(
            params=params,
            opt_state=tx.init(params),
            baseline=jnp.zeros(()),
            step=jnp.zeros((), jnp.int32),
        )

    @jax.jit
    def train_step(state: ReinforceState, arc: Arc, reward: jnp.ndarray):
        """One REINFORCE step on a sampled arc with observed reward
        (``build_trainer``: reward += entropy bonus; EMA baseline; loss =
        log_prob * (reward - baseline) + skip_weight * skip_penalty)."""

        def loss_fn(params):
            _, stats = _trace(params, cfg, arc)
            r = reward
            if cfg.entropy_weight is not None:
                r = r + cfg.entropy_weight * stats["entropy"]
            baseline = state.baseline - (1.0 - cfg.baseline_decay) * (
                state.baseline - r
            )
            # REINFORCE under gradient DESCENT: loss = -log p * advantage.
            # (The reference's ``sample_log_probs`` are TF cross-entropies,
            # i.e. already -log p, so its ``loss = log_probs * advantage``
            # carries the same sign, Controller.py:133,219.)
            loss = -stats["log_prob"] * jax.lax.stop_gradient(r - baseline)
            if cfg.skip_weight is not None:
                loss = loss + cfg.skip_weight * stats["skip_penalty"]
            return loss, baseline

        (loss, baseline), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            state.params
        )
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        return (
            ReinforceState(params, opt_state, baseline, state.step + 1),
            {"loss": loss, "baseline": baseline},
        )

    sample = jax.jit(lambda params, key: sample_arc(params, cfg, key))
    return init, train_step, sample


def arc_to_json(arc: Arc) -> list:
    """Serialize for the trial parameter (reference passes the architecture
    as nested lists in the ``architecture`` parameter)."""
    ops = np.asarray(arc.ops).tolist()
    skips = np.asarray(arc.skips)
    out = []
    for layer, op in enumerate(ops):
        out.append([int(op)] + [int(s) for s in skips[layer, :layer]])
    return out


def arc_from_json(data: list, num_layers: int) -> Arc:
    ops = np.zeros((num_layers,), np.int32)
    skips = np.zeros((num_layers, num_layers), np.int32)
    for layer, row in enumerate(data):
        ops[layer] = row[0]
        for j, s in enumerate(row[1:]):
            skips[layer, j] = s
    return Arc(ops=jnp.asarray(ops), skips=jnp.asarray(skips))
