"""ENAS suggester — stateful RL controller service.

Parity with the reference (``pkg/suggestion/v1beta1/nas/enas/service.py``):
round 1 emits randomly-initialized-controller samples; every later round
computes the mean validation accuracy of the completed trials
(``GetEvaluationResult`` :400, sign-flipped for minimize), trains the
controller ``controller_train_steps`` REINFORCE steps — each step samples a
fresh arc and applies the round reward (:311-330) — then samples the next
round's architectures.  Each trial carries two string parameters,
``architecture`` (nested list: per layer [op_id, skip...]) and ``nn_config``
(network shape + op vocabulary), exactly like the reference's trial inputs.

The reference's TF Saver ``ctrl_cache/`` checkpoint (:278) is unnecessary:
controller state is a JAX pytree living in the suggester; `state_dict()` /
`load_state_dict()` expose it for orchestrator-level persistence.
"""

from __future__ import annotations

import json

import jax
import numpy as np

from katib_tpu.core.types import (
    Experiment,
    ExperimentSpec,
    ParameterAssignment,
    TrialAssignmentSet,
)
from katib_tpu.nas.enas.child import DEFAULT_OPERATIONS
from katib_tpu.nas.enas.controller import (
    ControllerConfig,
    arc_to_json,
    make_reinforce,
)
from katib_tpu.suggest.base import (
    Suggester,
    SuggesterError,
    SuggestionsNotReady,
    register,
)

ROUND_LABEL = "enas-round"

_SETTING_TYPES = {
    "controller_hidden_size": int,
    "controller_temperature": float,
    "controller_tanh_const": float,
    "controller_entropy_weight": float,
    "controller_baseline_decay": float,
    "controller_learning_rate": float,
    "controller_skip_target": float,
    "controller_skip_weight": float,
    "controller_train_steps": int,
}

# settings that accept the reference's "None" sentinel to disable the feature
# (``enas/AlgorithmSettings.py`` checkNumericAndNone list)
_NULLABLE_SETTINGS = {
    "controller_temperature",
    "controller_tanh_const",
    "controller_entropy_weight",
    "controller_skip_weight",
}


def _operations_from_nas_config(nas_config) -> list[str]:
    ops: list[str] = []
    for op in nas_config.operations:
        sizes = []
        for p in op.parameters:
            if p.name == "filter_size" and p.feasible.list:
                sizes = list(p.feasible.list)
        if sizes:
            ops.extend(f"{op.operation_type}_{k}x{k}" for k in sizes)
        else:
            ops.append(op.operation_type)
    return ops


@register("enas")
class EnasSuggester(Suggester):
    @classmethod
    def validate(cls, spec: ExperimentSpec) -> None:
        if spec.nas_config is None or not spec.nas_config.operations:
            raise SuggesterError("enas requires nas_config with operations")
        s = spec.algorithm.settings
        for name, caster in _SETTING_TYPES.items():
            if name not in s:
                continue
            if s[name] == "None":
                if name not in _NULLABLE_SETTINGS:
                    raise SuggesterError(f"{name} does not accept None")
                continue
            try:
                caster(s[name])
            except (TypeError, ValueError):
                raise SuggesterError(f"{name} must be {caster.__name__}") from None
        if "controller_baseline_decay" in s and not (
            0.0 <= float(s["controller_baseline_decay"]) <= 1.0
        ):
            raise SuggesterError("controller_baseline_decay must be in [0, 1]")

    def __init__(self, spec: ExperimentSpec):
        super().__init__(spec)
        s = dict(spec.algorithm.settings)

        def get(name, default, caster):
            raw = s.get(name)
            if raw is None:
                return default
            if raw == "None":
                return None
            return caster(raw)

        self.operations = (
            _operations_from_nas_config(spec.nas_config)
            if spec.nas_config
            else list(DEFAULT_OPERATIONS)
        )
        self.num_layers = spec.nas_config.graph_config.num_layers if spec.nas_config else 8
        self.cfg = ControllerConfig(
            num_layers=self.num_layers,
            num_operations=len(self.operations),
            hidden_size=get("controller_hidden_size", 64, int),
            temperature=get("controller_temperature", 5.0, float),
            tanh_const=get("controller_tanh_const", 2.25, float),
            entropy_weight=get("controller_entropy_weight", 1e-5, float),
            baseline_decay=get("controller_baseline_decay", 0.999, float),
            learning_rate=get("controller_learning_rate", 5e-5, float),
            skip_target=get("controller_skip_target", 0.4, float),
            skip_weight=get("controller_skip_weight", 0.8, float),
        )
        self.train_steps = get("controller_train_steps", 50, int)
        init, self._train_step, self._sample = make_reinforce(self.cfg)
        self._key = jax.random.PRNGKey(self.seed())
        self.state = init(self._next_key())
        self.round = 0
        self._trained_rounds: set[int] = set()

    def _next_key(self):
        self._key, sub = jax.random.split(self._key)
        return sub

    # -- persistence hooks --------------------------------------------------

    def state_dict(self) -> dict:
        return {
            "round": self.round,
            "trained_rounds": sorted(self._trained_rounds),
            "controller": jax.device_get(self.state),
        }

    def load_state_dict(self, data: dict) -> None:
        self.round = data["round"]
        self._trained_rounds = set(data["trained_rounds"])
        self.state = jax.tree_util.tree_map(lambda x: x, data["controller"])

    # -- main ---------------------------------------------------------------

    def _round_trials(self, experiment: Experiment, rnd: int):
        return [
            t
            for t in experiment.trials.values()
            if t.labels.get(ROUND_LABEL) == str(rnd)
        ]

    def _mean_reward(self, trials) -> float | None:
        """Reference ``GetEvaluationResult``: mean objective of the round's
        completed trials, sign-flipped for minimize."""
        obj = self.spec.objective
        sign = 1.0 if obj.type.value == "maximize" else -1.0
        vals = [
            t.objective_value(obj)
            for t in trials
            if t.condition.is_completed_ok() and t.objective_value(obj) is not None
        ]
        if not vals:
            return None
        return sign * float(np.mean(vals))

    def get_suggestions(
        self, experiment: Experiment, count: int
    ) -> list[TrialAssignmentSet]:
        prev = self._round_trials(experiment, self.round - 1) if self.round else []
        if prev:
            if any(not t.condition.is_terminal() for t in prev):
                raise SuggestionsNotReady(
                    f"enas round {self.round - 1} still has trials running"
                )
            if (self.round - 1) not in self._trained_rounds:
                reward = self._mean_reward(prev)
                if reward is not None:
                    from katib_tpu.utils import tracing

                    with tracing.span(
                        "enas.controller_train",
                        round=self.round - 1,
                        steps=self.train_steps,
                    ):
                        for _ in range(self.train_steps):
                            arc, _ = self._sample(
                                self.state.params, self._next_key()
                            )
                            self.state, _ = self._train_step(
                                self.state, arc, np.float32(reward)
                            )
                self._trained_rounds.add(self.round - 1)

        nn_config = json.dumps(
            {
                "num_layers": self.num_layers,
                "operations": self.operations,
            }
        )
        out = []
        for _ in range(count):
            arc, _ = self._sample(self.state.params, self._next_key())
            out.append(
                TrialAssignmentSet(
                    assignments=[
                        ParameterAssignment("architecture", json.dumps(arc_to_json(arc))),
                        ParameterAssignment("nn_config", nn_config),
                    ],
                    labels={ROUND_LABEL: str(self.round)},
                )
            )
        self.round += 1
        return out
