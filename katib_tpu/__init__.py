"""katib_tpu — a TPU-native AutoML framework.

Hyperparameter tuning (random/grid/TPE/multivariate-TPE/GP-BO/CMA-ES/Sobol/
Hyperband), population-based training, early stopping, and neural architecture
search (DARTS, ENAS), built for JAX/XLA on Cloud TPU.  Capability parity with
kubeflow/katib (see SURVEY.md), redesigned: trials are white-box JAX functions
on TPU meshes, metrics stream in-process, checkpoints are Orbax pytrees.
"""

__version__ = "0.1.0"

from katib_tpu.core import types as types  # noqa: F401
