"""Roofline cost model — per-program XLA cost records and live MFU telemetry.

``bench.py`` has always known how expensive the flagship program is
(``compiled.cost_analysis()``), but that accounting was trapped in the
benchmark: ordinary trials, cohorts, and the dashboard ran blind to
utilization.  This package makes the roofline a first-class observability
layer:

- :mod:`peaks` — per-device-kind peak flops / HBM bandwidth tables (the
  MFU denominator), with ``KATIB_PEAK_FLOPS`` / ``KATIB_PEAK_BW`` env
  overrides for hardware the table doesn't know.
- :mod:`record` — :class:`CostRecord`: flops, bytes accessed, peak HBM,
  arithmetic intensity, roofline floors and memory/compute-bound
  classification for one compiled program; extraction helpers for
  ``Lowered`` / ``Compiled`` objects and live jitted functions.
- :mod:`live` — the ambient per-thread cost slot: model code that owns
  the jitted objects observes its program once
  (:func:`live.observe_program`); the runner/cohort heartbeat seams read
  the slot and publish ``katib_dispatch_mfu`` /
  ``katib_arithmetic_intensity`` / ``katib_roofline_headroom`` against
  measured step time (:func:`live.publish_dispatch`).
- :mod:`aot` — the deviceless TPU-topology AOT compile path shared with
  ``bench.py`` (cost analysis without a device grant — works on CPU
  hosts and wedged pools).
- :mod:`profiler` — on-demand ``jax.profiler`` capture with an
  in-process registry of trace directories (``/api/status`` and the
  ``katib-tpu profile --list`` verb read it).

Cost records persist at the ``CompileSignature`` seam: the shape
registry (``katib_tpu/compile/registry.py``) merges each program's cost
into its signature row in ``shape_registry.jsonl``, so ``katib-tpu
cost`` can print the roofline table of a sweep that ran yesterday.
Everything here is best-effort telemetry — an extraction failure must
never fail a trial.
"""

from __future__ import annotations

from katib_tpu.costmodel.live import (
    active_cost,
    clear_active,
    observe_program,
    publish_dispatch,
    set_active_cost,
    span_attrs,
)
from katib_tpu.costmodel.peaks import (
    DevicePeaks,
    detect_device_kind,
    normalize_device_kind,
    peaks_for,
)
from katib_tpu.costmodel.record import (
    CostRecord,
    cost_of_compiled,
    cost_of_lowered,
    extract_cost,
)

__all__ = [
    "CostRecord",
    "DevicePeaks",
    "active_cost",
    "clear_active",
    "cost_of_compiled",
    "cost_of_lowered",
    "detect_device_kind",
    "extract_cost",
    "normalize_device_kind",
    "observe_program",
    "peaks_for",
    "publish_dispatch",
    "set_active_cost",
    "span_attrs",
]
