"""Live roofline telemetry — the ambient cost slot and the MFU gauges.

The runner's heartbeat seams (``runner/trial_runner.py`` ``_beat``,
``runner/cohort.py`` ``_beat``, the DARTS epoch block) know *when* work
happened but never hold the jitted objects; model code holds the jitted
objects but doesn't own the clocks.  The bridge is the same ambient
per-thread pattern ``utils/tracing.py`` uses for tracers:

- model code observes its program once per trial
  (:func:`observe_program` — memoized, one extra trace, no compile) and
  the record lands in this thread's slot;
- the heartbeat reads :func:`active_cost`, divides by the measured
  report interval, and publishes :func:`publish_dispatch`'s gauges —
  ``katib_dispatch_mfu``, ``katib_arithmetic_intensity``,
  ``katib_roofline_headroom`` — plus span attrs for the trial/cohort/
  darts.epoch spans.

``per_report`` is the model's declaration of granularity: how many
dispatches of the observed program one ``ctx.report`` interval covers
(1 for a scan-epoch program reporting per epoch; the per-epoch batch
count for a streamed per-batch step).  Everything is best-effort — a
failed observation leaves the slot empty and the heartbeat publishes
nothing.
"""

from __future__ import annotations

import threading
from typing import Any

from katib_tpu.analysis import make_lock
from katib_tpu.costmodel.peaks import DevicePeaks, peaks_for
from katib_tpu.costmodel.record import CostRecord, extract_cost
from katib_tpu.utils import observability as obs

# label -> CostRecord | None (None pins a failed extraction so a sweep
# doesn't re-trace a program that cannot be costed, once per trial)
_MEMO: dict[Any, CostRecord | None] = {}
_MEMO_MAX = 128
_MEMO_LOCK = make_lock("costmodel.memo")

_tls = threading.local()


def observe_program(
    label: Any,
    fn: Any,
    args: tuple,
    *,
    program: str = "?",
    steps: int = 1,
    per_report: int = 1,
    dtype: str = "bf16",
) -> CostRecord | None:
    """Extract (memoized by ``label``) the cost of jitted ``fn`` at
    ``args`` and arm this thread's active-cost slot with it.

    ``label`` should be process-stable for one compiled program (e.g.
    the model/optimizer/mesh tuple the jit-step caches key by) so
    concurrent sweep trials sharing one executable trace it once.
    ``None`` or unhashable labels skip the memo (per-run programs like a
    DARTS search's window fn).  Never raises.
    """
    try:
        try:
            hashable = label is not None and (hash(label) or True)
        except TypeError:
            hashable = False
        rec = None
        hit = False
        if hashable:
            with _MEMO_LOCK:
                if label in _MEMO:
                    rec, hit = _MEMO[label], True
        if not hit:
            # a fetched artifact carries its cost record in the envelope —
            # adopt it and skip the extra trace (the whole point of the
            # ride-along: fetched programs publish MFU without re-tracing)
            rec = _artifact_cost(fn, args, program)
            if rec is None:
                rec = extract_cost(
                    fn, args, program=program, steps=steps, dtype=dtype
                )
            if hashable:
                with _MEMO_LOCK:
                    _MEMO[label] = rec
                    while len(_MEMO) > _MEMO_MAX:
                        _MEMO.pop(next(iter(_MEMO)))
        if rec is not None:
            set_active_cost(rec, per_report=per_report)
        # mirror the (fn, args, cost) into the artifact offer slot: the
        # prewarm worker publishes what its twin observed, and this call
        # is the one place twins hand over exactly that pair
        try:
            from katib_tpu.compile import artifacts

            artifacts.note_observed(
                fn,
                args,
                program=program,
                cost=rec.as_dict() if rec is not None else None,
            )
        except Exception:
            pass
        return rec
    except Exception:
        return None


def _artifact_cost(fn: Any, args: tuple, program: str) -> CostRecord | None:
    """The cost record riding with a loaded artifact matching this
    program at these avals, or None (then the caller traces)."""
    try:
        from katib_tpu.compile.artifacts import ARTIFACTS

        cost = ARTIFACTS.cost_for(program, args)
        return CostRecord.from_dict(cost) if cost else None
    except Exception:
        return None


def set_active_cost(rec: CostRecord, per_report: int = 1) -> None:
    """Arm the calling thread's slot directly (models with their own
    cost accounting, tests)."""
    _tls.cost = rec
    _tls.per_report = max(1, int(per_report))


def active_cost() -> tuple[CostRecord, int] | None:
    """This thread's (record, per_report), or None when nothing observed."""
    rec = getattr(_tls, "cost", None)
    if rec is None:
        return None
    return rec, getattr(_tls, "per_report", 1)


def clear_active() -> None:
    """Disarm the slot (trial start: executor threads are reused, and a
    stale record from the previous trial must not leak into this one)."""
    _tls.cost = None
    _tls.per_report = 1
    _tls.attrs = {}


def span_attrs() -> dict:
    """Cost attrs of this thread's most recent publication — stamped on
    trial/cohort spans by whoever owns the span."""
    return dict(getattr(_tls, "attrs", {}) or {})


# backwards-friendly alias used by the package __init__
take_span_attrs = span_attrs


def publish_dispatch(
    rec: CostRecord,
    step_secs: float,
    *,
    workload: str,
    peaks: DevicePeaks | None = None,
) -> dict:
    """Publish the roofline gauges for one measured per-step time and
    return the span attrs (also retained for :func:`span_attrs`).

    - ``katib_dispatch_mfu`` — measured flops/s over peak flops
    - ``katib_arithmetic_intensity`` — flops per byte accessed
    - ``katib_roofline_headroom`` — measured step time over the binding
      roofline floor (1.0 = running at the roofline; 10 = 10x off it)
    """
    try:
        if step_secs <= 0 or not rec.flops:
            return {}
        pk = peaks or peaks_for()
        roof = rec.roofline(pk)
        mfu = rec.mfu(step_secs, pk)
        floor = roof["floor_step_secs"]
        headroom = step_secs / floor if floor else 0.0
        obs.dispatch_mfu.set(
            mfu, workload=workload, device_kind=pk.device_kind, dtype=rec.dtype
        )
        obs.arithmetic_intensity.set(
            roof["arithmetic_intensity"], workload=workload
        )
        obs.roofline_headroom.set(
            headroom, workload=workload, bound=roof["bound"]
        )
        attrs = {
            "mfu": round(mfu, 6),
            "arithmetic_intensity": round(roof["arithmetic_intensity"], 2),
            "roofline": roof["bound"],
            "roofline_headroom": round(headroom, 1),
        }
        _tls.attrs = attrs
        return dict(attrs)
    except Exception:
        return {}  # gauges are telemetry, never a trial failure
