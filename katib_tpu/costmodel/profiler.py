"""On-demand ``jax.profiler`` capture + the profile-directory registry.

``enable_profiler`` runs used to leave their trace directories invisible
after the capture: the orchestrator wrote
``<workdir>/<exp>/<trial>/profile`` and nothing ever listed it.  This
module makes captures discoverable three ways:

- in-process: :func:`register_profile` records every capture; the UI
  backend serves :func:`list_profiles` under ``/api/status``;
- trace journal: the orchestrator wraps profiled attempts in a
  ``profile.capture`` span carrying ``trace_dir``, so ``katib-tpu
  profile --list`` (and ``trace summary``) see past runs from any
  process;
- filesystem: :func:`scan_profiles` globs ``<workdir>/*/*/profile`` as
  the fallback for journals that predate the span.

:func:`capture` is the one capture wrapper (the profiler is a
process-global singleton — callers serialize; the orchestrator already
holds ``_profile_lock`` around it).
"""

from __future__ import annotations

import glob
import os
import time
from contextlib import contextmanager
from typing import Iterator

from katib_tpu.analysis import make_lock

_PROFILES: list[dict] = []
_PROFILES_MAX = 64
_PROFILES_LOCK = make_lock("costmodel.profiles")

PROFILE_SPAN = "profile.capture"


def register_profile(
    trace_dir: str, *, trial: str | None = None, experiment: str | None = None
) -> dict:
    """Record one capture in the in-process registry (served by
    ``/api/status``); returns the entry."""
    entry = {
        "trace_dir": str(trace_dir),
        "trial": trial,
        "experiment": experiment,
        "wall": round(time.time(), 3),
    }
    with _PROFILES_LOCK:
        _PROFILES.append(entry)
        del _PROFILES[:-_PROFILES_MAX]
    return dict(entry)


def list_profiles() -> list[dict]:
    with _PROFILES_LOCK:
        return [dict(e) for e in _PROFILES]


def reset() -> None:
    """Forget registered captures (tests)."""
    with _PROFILES_LOCK:
        _PROFILES.clear()


@contextmanager
def capture(
    trace_dir: str, *, trial: str | None = None, experiment: str | None = None
) -> Iterator[str]:
    """``jax.profiler.trace`` into ``trace_dir``, registered on entry and
    bracketed by a ``profile.capture`` span so the directory is linked
    from both ``/api/status`` and the trace journal.  The jax profiler is
    a process-global singleton — do not nest captures."""
    import jax

    from katib_tpu.utils import tracing

    os.makedirs(trace_dir, exist_ok=True)
    register_profile(trace_dir, trial=trial, experiment=experiment)
    with tracing.span(PROFILE_SPAN, trial=trial, trace_dir=trace_dir):
        with jax.profiler.trace(trace_dir):
            yield trace_dir


def scan_profiles(workdir: str) -> list[dict]:
    """Offline discovery: profile directories under
    ``<workdir>/<experiment>/<trial>/profile`` plus any ``trace_dir``
    recorded on ``profile.capture`` spans in the experiments' journals."""
    from katib_tpu.utils import tracing

    found: dict[str, dict] = {}
    for d in sorted(glob.glob(os.path.join(workdir, "*", "*", "profile"))):
        if not os.path.isdir(d):
            continue
        rel = os.path.relpath(d, workdir).split(os.sep)
        found[os.path.abspath(d)] = {
            "trace_dir": d,
            "experiment": rel[0] if len(rel) > 2 else None,
            "trial": rel[1] if len(rel) > 2 else None,
            "source": "filesystem",
        }
    for journal in sorted(glob.glob(os.path.join(workdir, "*", tracing.TRACE_FILE))):
        exp = os.path.basename(os.path.dirname(journal))
        for rec in tracing.read_journal(journal):
            if rec.get("name") != PROFILE_SPAN:
                continue
            args = rec.get("args", {}) or {}
            d = args.get("trace_dir")
            if not d:
                continue
            entry = found.setdefault(
                os.path.abspath(str(d)),
                {"trace_dir": str(d), "experiment": exp, "source": "journal"},
            )
            if args.get("trial"):
                entry["trial"] = args.get("trial")
            if rec.get("wall") is not None:
                entry["wall"] = rec.get("wall")
    return sorted(found.values(), key=lambda e: str(e.get("trace_dir")))
