"""CostRecord — XLA-derived cost of one compiled program, plus extraction.

A :class:`CostRecord` is what the shape registry persists next to each
compile signature and what the live gauges divide by measured step time:
flops and bytes accessed for ONE dispatch of the program, the peak-HBM
footprint when a ``Compiled`` object was available, and ``steps`` — how
many training steps that dispatch folds (a ``lax.scan`` epoch program
carries the whole epoch's flops; per-step math divides by ``steps``).

Extraction is tolerant by design: ``cost_analysis`` availability varies
by backend and jax version, and a program we cannot cost must train
exactly as if this module didn't exist — every helper returns ``None``
on failure instead of raising.

``bytes accessed`` is XLA's PRE-FUSION figure (every op's operands and
results), which overstates real HBM traffic — the bandwidth floor it
produces is a lower bound on step time and the derived ``max_mfu`` an
upper bound on what the program can reach (same caveat ``bench.py``'s
AOT block always documented).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Mapping

from katib_tpu.costmodel.peaks import DevicePeaks, peaks_for


def _first_dict(cost: Any) -> Mapping[str, Any]:
    """``cost_analysis()`` returns a dict or a per-computation list of
    dicts depending on jax version/backend — normalize to one mapping."""
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else {}
    return cost if isinstance(cost, Mapping) else {}


@dataclass
class CostRecord:
    """Cost of one dispatch of one compiled program."""

    program: str = "?"
    flops: float = 0.0
    bytes_accessed: float = 0.0
    hbm_bytes: int = 0  # args+outputs+temps+code; 0 when unknown (no Compiled)
    steps: int = 1  # training steps folded into one dispatch of this program
    dtype: str = "bf16"

    @property
    def flops_per_step(self) -> float:
        return self.flops / max(self.steps, 1)

    @property
    def bytes_per_step(self) -> float:
        return self.bytes_accessed / max(self.steps, 1)

    @property
    def arithmetic_intensity(self) -> float:
        """flops per byte accessed (0 when bytes are unknown)."""
        return self.flops / self.bytes_accessed if self.bytes_accessed else 0.0

    def roofline(self, peaks: DevicePeaks | None = None) -> dict:
        """Roofline placement against ``peaks`` (detected when None).

        Returns the per-step compute floor (time at MFU=1), the
        pre-fusion bandwidth floor, the binding floor and its class
        (``compute-bound`` / ``memory-bound``), and ``max_mfu`` — the
        utilization ceiling the binding floor allows (1.0 when compute
        bound, ``intensity/ridge`` when memory bound)."""
        pk = peaks or peaks_for()
        peak = pk.peak_flops(self.dtype)
        compute_floor = self.flops_per_step / peak if peak else 0.0
        bw_floor = (
            self.bytes_per_step / pk.hbm_bandwidth if pk.hbm_bandwidth else 0.0
        )
        floor = max(compute_floor, bw_floor)
        bound = "compute-bound" if compute_floor >= bw_floor else "memory-bound"
        max_mfu = compute_floor / floor if floor else 0.0
        return {
            "device_kind": pk.device_kind,
            "compute_floor_step_secs": compute_floor,
            "prefusion_bw_step_secs": bw_floor,
            "floor_step_secs": floor,
            "bound": bound,
            "arithmetic_intensity": self.arithmetic_intensity,
            "ridge_intensity": pk.ridge_intensity,
            "max_mfu": max_mfu,
        }

    def mfu(self, step_secs: float, peaks: DevicePeaks | None = None) -> float:
        """Model-flops utilization at a measured per-step time."""
        if step_secs <= 0 or not self.flops:
            return 0.0
        pk = peaks or peaks_for()
        peak = pk.peak_flops(self.dtype)
        return (self.flops_per_step / step_secs) / peak if peak else 0.0

    def as_dict(self) -> dict:
        return {
            "program": self.program,
            "flops": self.flops,
            "bytes_accessed": self.bytes_accessed,
            "hbm_bytes": self.hbm_bytes,
            "steps": self.steps,
            "dtype": self.dtype,
        }

    @classmethod
    def from_dict(cls, d: Mapping[str, Any]) -> "CostRecord":
        try:
            return cls(
                program=str(d.get("program", "?")),
                flops=float(d.get("flops", 0.0)),
                bytes_accessed=float(d.get("bytes_accessed", 0.0)),
                hbm_bytes=int(d.get("hbm_bytes", 0)),
                steps=max(1, int(d.get("steps", 1))),
                dtype=str(d.get("dtype", "bf16")),
            )
        except (TypeError, ValueError):
            return cls()


def cost_of_lowered(
    lowered: Any, *, program: str = "?", steps: int = 1, dtype: str = "bf16"
) -> CostRecord | None:
    """Cost from a ``jax.stages.Lowered`` — trace-time only, no XLA
    compile behind it (HBM footprint stays 0: that needs a Compiled)."""
    try:
        cost = _first_dict(lowered.cost_analysis())
        return CostRecord(
            program=program,
            flops=float(cost.get("flops", 0.0)),
            bytes_accessed=float(cost.get("bytes accessed", 0.0)),
            steps=max(1, int(steps)),
            dtype=dtype,
        )
    except Exception:
        return None


def cost_of_compiled(
    compiled: Any, *, program: str = "?", steps: int = 1, dtype: str = "bf16"
) -> CostRecord | None:
    """Cost from a ``jax.stages.Compiled`` — adds the peak-HBM footprint
    (argument + output + temp + generated-code bytes) to the flop/byte
    counts."""
    try:
        cost = _first_dict(compiled.cost_analysis())
        rec = CostRecord(
            program=program,
            flops=float(cost.get("flops", 0.0)),
            bytes_accessed=float(cost.get("bytes accessed", 0.0)),
            steps=max(1, int(steps)),
            dtype=dtype,
        )
    except Exception:
        return None
    try:
        mem = compiled.memory_analysis()
        rec.hbm_bytes = int(
            mem.argument_size_in_bytes
            + mem.output_size_in_bytes
            + mem.temp_size_in_bytes
            + mem.generated_code_size_in_bytes
        )
    except Exception:
        pass  # memory analysis is optional; the flop counts stand alone
    return rec


def extract_cost(
    fn: Any,
    args: tuple = (),
    *,
    program: str = "?",
    steps: int = 1,
    dtype: str = "bf16",
) -> CostRecord | None:
    """Cost of a jitted function at ``args`` avals — one extra trace via
    ``fn.lower(*args)``, no compile (``args`` may be concrete arrays or
    ``jax.ShapeDtypeStruct``s; donated operands are fine, lowering reads
    shapes only)."""
    try:
        lowered = fn.lower(*args)
    except Exception:
        return None
    return cost_of_lowered(lowered, program=program, steps=steps, dtype=dtype)
