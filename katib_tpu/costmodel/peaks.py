"""Per-device-kind peak flops / HBM bandwidth tables — the MFU denominator.

``bench.py`` used to hardcode a single v5e datasheet entry; moving the
table here makes MFU meaningful on v5p/v4/v3 and on CPU dev boxes, and
gives operators an escape hatch for hardware the table doesn't know:

- ``KATIB_PEAK_FLOPS`` — peak dense flops/s per chip (every dtype)
- ``KATIB_PEAK_BW``    — peak HBM bandwidth, bytes/s

Datasheet sources: TPU v5e/v5p/v4/v3 public specs (per-chip dense
matmul peak; f32 at half the bf16 rate on generations without an f32
MXU path).  The ``cpu`` entry is a deliberately round nominal figure so
development runs publish *non-null* gauges — CPU MFU is an ordering
signal, not an absolute one.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field


@dataclass(frozen=True)
class DevicePeaks:
    """Peak throughput of one device kind (per chip)."""

    device_kind: str
    flops: dict[str, float] = field(default_factory=dict)  # dtype -> flops/s
    hbm_bandwidth: float = 0.0  # bytes/s
    hbm_bytes: int = 0

    def peak_flops(self, dtype: str = "bf16") -> float:
        """Peak for ``dtype``, falling back bf16 -> best known (a missing
        dtype must yield a denominator, not a KeyError mid-trial)."""
        v = self.flops.get(dtype)
        if v is None:
            v = self.flops.get("bf16")
        if v is None and self.flops:
            v = max(self.flops.values())
        return float(v or 0.0)

    @property
    def ridge_intensity(self) -> float:
        """Arithmetic intensity (flops/byte) where the compute and
        bandwidth roofs cross — programs below it are memory-bound."""
        if not self.hbm_bandwidth:
            return 0.0
        return self.peak_flops() / self.hbm_bandwidth


PEAKS: dict[str, DevicePeaks] = {
    "v5e": DevicePeaks(
        "v5e",
        {"bf16": 197e12, "f32": 98.5e12, "int8": 394e12},
        hbm_bandwidth=819e9,
        hbm_bytes=16 * 1024**3,
    ),
    "v5p": DevicePeaks(
        "v5p",
        {"bf16": 459e12, "f32": 229.5e12, "int8": 918e12},
        hbm_bandwidth=2765e9,
        hbm_bytes=95 * 1024**3,
    ),
    "v4": DevicePeaks(
        "v4",
        {"bf16": 275e12, "f32": 137.5e12},
        hbm_bandwidth=1228e9,
        hbm_bytes=32 * 1024**3,
    ),
    "v3": DevicePeaks(
        "v3",
        {"bf16": 123e12, "f32": 61.5e12},
        hbm_bandwidth=900e9,
        hbm_bytes=32 * 1024**3,
    ),
    # nominal dev-box figure: keeps CPU runs publishing non-null MFU
    # gauges; treat CPU MFU as relative, not absolute
    "cpu": DevicePeaks(
        "cpu",
        {"bf16": 2e11, "f32": 2e11},
        hbm_bandwidth=5e10,
        hbm_bytes=16 * 1024**3,
    ),
}

_DEFAULT_KIND = "v5e"  # the pool this repo targets; unknown TPUs assume it


def normalize_device_kind(kind: str | None) -> str:
    """Fold a raw ``Device.device_kind`` / platform string onto a table
    key: ``"TPU v5 lite"`` -> ``v5e``, ``"TPU v4"`` -> ``v4``, anything
    CPU-ish -> ``cpu``, unknown TPU kinds -> the default generation."""
    if not kind:
        return _DEFAULT_KIND
    k = str(kind).strip().lower()
    if "cpu" in k:
        return "cpu"
    if "v5 lite" in k or "v5lite" in k or "v5e" in k:
        return "v5e"
    if "v5p" in k or k == "tpu v5" or k == "v5":
        return "v5p"
    if "v4" in k:
        return "v4"
    if "v3" in k:
        return "v3"
    return k if k in PEAKS else _DEFAULT_KIND


def detect_device_kind() -> str:
    """Best-effort device kind of the live backend.  ``PALLAS_AXON_TPU_GEN``
    wins (the axon relay's devices self-report generically); falls back
    to ``jax.devices()[0]`` and, with no backend at all, ``cpu``."""
    env = os.environ.get("PALLAS_AXON_TPU_GEN")
    if env:
        return normalize_device_kind(env)
    try:
        import jax

        d = jax.devices()[0]
        if d.platform != "tpu":
            return normalize_device_kind(d.platform)
        return normalize_device_kind(getattr(d, "device_kind", None))
    except Exception:
        return "cpu"


def _env_float(name: str) -> float | None:
    raw = os.environ.get(name)
    if not raw:
        return None
    try:
        return float(raw)
    except ValueError:
        return None


def peaks_for(device_kind: str | None = None) -> DevicePeaks:
    """The peaks entry for ``device_kind`` (detected when None), with the
    ``KATIB_PEAK_FLOPS`` / ``KATIB_PEAK_BW`` env overrides applied."""
    kind = (
        normalize_device_kind(device_kind)
        if device_kind is not None
        else detect_device_kind()
    )
    entry = PEAKS.get(kind, PEAKS[_DEFAULT_KIND])
    flops_ov = _env_float("KATIB_PEAK_FLOPS")
    bw_ov = _env_float("KATIB_PEAK_BW")
    if flops_ov is None and bw_ov is None:
        return entry
    flops = (
        {k: flops_ov for k in (entry.flops or {"bf16": 0.0})}
        if flops_ov is not None
        else dict(entry.flops)
    )
    return DevicePeaks(
        device_kind=entry.device_kind,
        flops=flops,
        hbm_bandwidth=bw_ov if bw_ov is not None else entry.hbm_bandwidth,
        hbm_bytes=entry.hbm_bytes,
    )
