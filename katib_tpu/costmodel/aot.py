"""Deviceless TPU-topology AOT compile — cost analysis without a device.

The pip ``libtpu`` can compile a program *client-side* against a TPU
topology description (``jax.experimental.topologies``): no device grant,
no runtime — which means the cost/HBM analysis works from a CPU host and
even while the pool is wedged.  ``bench.py``'s AOT child pioneered the
path; it lives here so the bench and the ``katib-tpu cost`` verb share
one implementation.
"""

from __future__ import annotations

import time
from typing import Any

from katib_tpu.costmodel.record import CostRecord, cost_of_compiled

DEFAULT_TOPOLOGY = "v5e:1x1x1"


def topology_device(topology_name: str = DEFAULT_TOPOLOGY) -> Any:
    """First device of a deviceless TPU topology description.  Raises on
    hosts without a TPU-target compiler — callers gate on that."""
    from jax.experimental import topologies

    topo = topologies.get_topology_desc(
        platform="tpu",
        topology_name=topology_name,
        chips_per_host_bounds=(1, 1, 1),
        num_slices=1,
    )
    return topo.devices[0]


def aot_compile(fn: Any, args: tuple, device: Any) -> tuple[Any, float]:
    """Jit-compile ``fn`` at ``args`` avals for ``device`` (deviceless
    target ok).  Returns ``(compiled, compile_seconds)``; ``args`` may be
    concrete arrays or pytrees thereof — they are reduced to
    single-device-sharded avals before lowering."""
    import jax
    from jax.sharding import SingleDeviceSharding

    def place(a):
        return jax.ShapeDtypeStruct(
            a.shape, a.dtype, sharding=SingleDeviceSharding(device)
        )

    avals = jax.tree.map(place, tuple(args))
    t0 = time.perf_counter()
    compiled = jax.jit(fn).lower(*avals).compile()
    secs = time.perf_counter() - t0  # lint: unguarded-ok(deviceless AOT: client-side compile is synchronous host work)
    return compiled, secs


def aot_cost(
    fn: Any,
    args: tuple,
    *,
    program: str = "?",
    steps: int = 1,
    dtype: str = "bf16",
    topology_name: str = DEFAULT_TOPOLOGY,
) -> CostRecord | None:
    """One-call deviceless cost extraction: topology -> AOT compile ->
    :class:`CostRecord` (None when no TPU-target compiler is present)."""
    try:
        dev = topology_device(topology_name)
        compiled, _ = aot_compile(fn, args, dev)
    except Exception:
        return None
    return cost_of_compiled(compiled, program=program, steps=steps, dtype=dtype)
