import sys

from katib_tpu.cli import main

sys.exit(main())
