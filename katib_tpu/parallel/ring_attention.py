"""Sequence/context parallelism: ring attention and all-to-all (Ulysses).

The reference has no long-context machinery at all (SURVEY.md §5 "absent");
this module makes the reserved ``seq`` mesh axis real so HP/NAS search over
long-context transformer trials can shard the sequence dimension across
chips instead of replicating O(S) activations.

Two strategies, both over ``jax.shard_map`` on a named mesh axis:

- **ring**: K/V chunks rotate around the ring via ``ppermute`` while every
  device keeps its resident Q chunk; partial attention outputs merge through
  the streaming-softmax identity using the per-row logsumexp emitted by the
  inner kernel (``katib_tpu.ops.flash_attention``).  Communication rides
  neighbour ICI links and overlaps with the block matmuls.
- **ulysses**: two ``all_to_all``s re-shard [heads ↔ sequence] so each
  device runs dense attention for H/size heads over the full sequence.
  Cheaper collectives on small meshes; requires heads % axis_size == 0.

Causality is decided at chunk granularity: a device's Q chunk attends fully
to earlier chunks, causally to its own, and skips later ones (the skip
branch contributes logsumexp=-1e30, an exact no-op in the merge — and
``lax.switch`` means the skipped matmuls are never executed).
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from katib_tpu.ops.flash_attention import (
    _MASK_VALUE,
    flash_attention_with_lse,
    reference_attention_with_lse,
)
from katib_tpu.parallel.mesh import DATA_AXIS, SEQ_AXIS

InnerAttention = Callable[..., tuple[jax.Array, jax.Array]]


def _shard_map(fn, *, mesh, in_specs, out_specs):
    """``jax.shard_map`` where available (jax >= 0.6), else the
    ``jax.experimental`` spelling older runtimes ship (the ``check_vma``
    replication check is ``check_rep`` there; disabled either way — the
    ring's ppermute carry confuses it)."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=False,
        )
    from jax.experimental.shard_map import shard_map

    return shard_map(
        fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=False,
    )


def default_inner(block_q: int = 128, block_k: int = 128) -> InnerAttention:
    """Per-chunk attention kernel: Pallas flash on TPU, dense jnp elsewhere
    (interpret-mode Pallas inside shard_map is correct but far too slow for
    the 8-device CPU test mesh)."""
    if jax.default_backend() == "tpu":
        # positional call: custom_vjp functions reject keyword arguments
        return lambda q, k, v, causal: flash_attention_with_lse(
            q, k, v, causal, None, block_q, block_k, None
        )
    return reference_attention_with_lse


def ring_attention_local(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str = SEQ_AXIS,
    axis_size: int,
    causal: bool = True,
    inner: InnerAttention | None = None,
) -> jax.Array:
    """Ring attention over local chunks — call inside ``shard_map`` with
    q/k/v of shape [batch, heads, seq_local, head_dim], sequence dimension
    sharded on ``axis_name`` in contiguous chunks."""
    if inner is None:
        inner = default_inner()
    my = jax.lax.axis_index(axis_name)
    b, h, s_local, d = q.shape
    perm = [(r, (r + 1) % axis_size) for r in range(axis_size)]

    def chunk_full(kv):
        kc, vc = kv
        return inner(q, kc, vc, False)

    def chunk_diag(kv):
        kc, vc = kv
        return inner(q, kc, vc, True)

    def chunk_skip(kv):
        return (
            jnp.zeros((b, h, s_local, d), q.dtype),
            jnp.full((b, h, s_local), _MASK_VALUE, jnp.float32),
        )

    def step(carry, t):
        o_acc, lse_acc, k_cur, v_cur = carry
        j = (my - t) % axis_size  # origin rank of the kv chunk we now hold
        if causal:
            branch = jnp.where(j < my, 0, jnp.where(j == my, 1, 2))
            o_i, lse_i = jax.lax.switch(
                branch, [chunk_full, chunk_diag, chunk_skip], (k_cur, v_cur)
            )
        else:
            o_i, lse_i = chunk_full((k_cur, v_cur))
        lse_new = jnp.logaddexp(lse_acc, lse_i)
        w_acc = jnp.exp(lse_acc - lse_new)[..., None]
        w_i = jnp.exp(lse_i - lse_new)[..., None]
        # the accumulator stays float32 across the whole ring: casting back
        # to bf16 every step would round-trip the output axis_size times
        o_new = o_acc * w_acc + o_i.astype(jnp.float32) * w_i
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return (o_new, lse_new, k_nxt, v_nxt), None

    o0 = jnp.zeros((b, h, s_local, d), jnp.float32)
    lse0 = jnp.full((b, h, s_local), _MASK_VALUE, jnp.float32)
    (o, _, _, _), _ = jax.lax.scan(
        step, (o0, lse0, k, v), jnp.arange(axis_size)
    )
    return o.astype(q.dtype)


def ulysses_attention_local(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str = SEQ_AXIS,
    axis_size: int,
    causal: bool = True,
    inner: InnerAttention | None = None,
) -> jax.Array:
    """All-to-all (DeepSpeed-Ulysses style) sequence parallelism: re-shard
    [B, H, S/n, D] → [B, H/n, S, D], attend over the full sequence, shard
    back.  Heads must divide by the axis size."""
    if inner is None:
        inner = default_inner()
    h = q.shape[1]
    if h % axis_size:
        raise ValueError(
            f"heads ({h}) must be a multiple of the seq-axis size ({axis_size})"
        )

    def scatter_heads(x):
        return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2, tiled=True)

    qg, kg, vg = scatter_heads(q), scatter_heads(k), scatter_heads(v)
    o, _ = inner(qg, kg, vg, causal)
    return jax.lax.all_to_all(o, axis_name, split_axis=2, concat_axis=1, tiled=True)


def make_sequence_parallel_attention(
    mesh: Mesh,
    *,
    strategy: str = "ring",
    causal: bool = True,
    axis_name: str = SEQ_AXIS,
    inner: InnerAttention | None = None,
) -> Callable[[jax.Array, jax.Array, jax.Array], jax.Array]:
    """Build ``attn(q, k, v) -> o`` over global [B, H, S, D] arrays: batch
    sharded on the mesh's data axis, sequence on its seq axis.

    With a size-1 (or absent) seq axis this degenerates to plain single-chip
    flash attention — the same code path from one chip to a v5e-64 slice.
    """
    axis_size = mesh.shape.get(axis_name, 1)
    batch_axis = DATA_AXIS if DATA_AXIS in mesh.shape else None

    if axis_size == 1:
        def attn_single(q, k, v):
            inn = inner if inner is not None else default_inner()
            o, _ = inn(q, k, v, causal)
            return o

        return attn_single

    if strategy == "ring":
        local = functools.partial(
            ring_attention_local,
            axis_name=axis_name, axis_size=axis_size, causal=causal, inner=inner,
        )
    elif strategy == "ulysses":
        local = functools.partial(
            ulysses_attention_local,
            axis_name=axis_name, axis_size=axis_size, causal=causal, inner=inner,
        )
    else:
        raise ValueError(f"unknown sequence-parallel strategy {strategy!r}")

    spec = P(batch_axis, None, axis_name, None)

    def attn(q, k, v):
        return _shard_map(
            lambda a, b, c: local(a, b, c),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        )(q, k, v)

    return attn
