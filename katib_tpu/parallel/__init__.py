from katib_tpu.parallel.mesh import (  # noqa: F401
    DATA_AXIS,
    MODEL_AXIS,
    SEQ_AXIS,
    data_sharding,
    make_mesh,
    replicate,
    replicated,
    shard_batch,
)
from katib_tpu.parallel.train import (  # noqa: F401
    TrainState,
    accuracy,
    cross_entropy_loss,
    make_eval_step,
    make_train_step,
)
