from katib_tpu.parallel.mesh import (  # noqa: F401
    DATA_AXIS,
    MODEL_AXIS,
    SEQ_AXIS,
    data_sharding,
    make_mesh,
    replicate,
    replicated,
    shard_batch,
)
from katib_tpu.parallel.pbt import (  # noqa: F401
    HyperSpec,
    decode_member_hypers,
    encode_hypers,
    exploit_explore,
    make_pbt_generation_step,
    specs_from_json,
    specs_from_parameters,
    specs_to_json,
)
from katib_tpu.parallel.train import (  # noqa: F401
    TrainState,
    accuracy,
    cross_entropy_loss,
    make_eval_step,
    make_train_step,
)
