"""Multi-host initialization and TPU-slice scheduling for trials.

The reference's answer to distributed trials is to delegate to external K8s
operators (TFJob/PyTorchJob/MPIJob) and merely watch their status via GJSON
conditions (SURVEY.md §2.4, ``job_util.go:59``); its answer to trial
parallelism is ``parallelTrialCount`` pods.  TPU-native, both collapse into
this module:

- ``initialize_distributed`` brings up ``jax.distributed`` for one *slice
  process group* (coordinator + N hosts).  Inside the slice, collectives
  ride ICI; across slices, DCN — the sharding annotations ARE the
  communication backend, there is no NCCL/MPI equivalent to manage.
- ``SliceAllocator`` partitions the visible devices into fixed-size slice
  shares and leases one per trial, so ``parallelTrialCount`` concurrent
  trials each get a disjoint sub-mesh (the analog of the experiment
  controller's trial budget, ``experiment_controller.go:274-330``, with
  chips instead of pods as the scheduling unit).

Environment detection covers the standard TPU pod variables
(``COORDINATOR_ADDRESS``/``NUM_PROCESSES``/``PROCESS_ID``) and falls back
to single-process — so the same trial code runs on a laptop CPU, one v5e
chip, or a multi-host slice without changes.
"""

from __future__ import annotations

import os
import threading
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, Mapping, Sequence

from katib_tpu.parallel.mesh import DATA_AXIS, make_mesh

_INIT_LOCK = threading.Lock()
_INITIALIZED = False


def initialize_distributed(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
    local_device_ids: Sequence[int] | None = None,
) -> bool:
    """Idempotently bring up the JAX process group for this slice.

    Explicit args win; otherwise ``COORDINATOR_ADDRESS`` / ``NUM_PROCESSES``
    / ``PROCESS_ID`` env vars; otherwise (single-process, the common case on
    one chip or CPU) this is a no-op.  Returns True when a multi-process
    group was (or already is) initialized.
    """
    global _INITIALIZED
    with _INIT_LOCK:
        if _INITIALIZED:
            return True
        coordinator_address = coordinator_address or os.environ.get(
            "COORDINATOR_ADDRESS"
        )
        if num_processes is None and "NUM_PROCESSES" in os.environ:
            num_processes = int(os.environ["NUM_PROCESSES"])
        if process_id is None and "PROCESS_ID" in os.environ:
            process_id = int(os.environ["PROCESS_ID"])
        if coordinator_address is None or not num_processes or num_processes <= 1:
            return False
        import jax

        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
            local_device_ids=local_device_ids,
        )
        _INITIALIZED = True
        return True


# -- topology presets --------------------------------------------------------

#: chips per named TPU slice topology (v5e sizes from the BASELINE targets)
SLICE_TOPOLOGIES: dict[str, int] = {
    "v5e-1": 1,
    "v5e-4": 4,
    "v5e-8": 8,
    "v5e-16": 16,
    "v5e-32": 32,
    "v5e-64": 64,
    "v5e-128": 128,
    "v5e-256": 256,
}


def topology_size(topology: str) -> int:
    if topology not in SLICE_TOPOLOGIES:
        raise ValueError(
            f"unknown topology {topology!r}; known: {sorted(SLICE_TOPOLOGIES)}"
        )
    return SLICE_TOPOLOGIES[topology]


# -- per-trial slice leasing -------------------------------------------------

# one shared definition of the device-count trial label (re-exported here
# for locality with its consumer, defined jax-free in core.types)
from katib_tpu.core.types import DEVICES_LABEL  # noqa: F401



@dataclass
class SliceLease:
    """A leased share of the machine: build the trial's mesh from it."""

    index: int
    devices: tuple
    axes: Mapping[str, int]

    def mesh(self):
        return make_mesh(dict(self.axes), devices=self.devices)


def _default_devices(devices: Sequence[Any] | None) -> tuple:
    if devices is None:
        import jax

        devices = jax.devices()
    return tuple(devices)


class _MeshLeaseMixin:
    """Shared lease→mesh→release context manager for the allocators."""

    @contextmanager
    def slice_mesh(self, *args, **kwargs):
        """``with allocator.slice_mesh(...) as mesh:`` — lease, build,
        release; arguments pass through to ``lease``."""
        lease = self.lease(*args, **kwargs)
        try:
            yield lease.mesh()
        finally:
            self.release(lease)


class SliceAllocator(_MeshLeaseMixin):
    """Partition devices into equal slice shares; lease one per trial.

    ``axes`` is the per-trial mesh template (one axis may be -1 to absorb
    the share size), e.g. ``{"data": -1}`` or ``{"data": 2, "model": 2}``.
    ``lease()`` blocks until a share frees up — the orchestrator's thread
    pool naturally sizes the number of outstanding leases to
    ``parallel_trial_count``.
    """

    def __init__(
        self,
        slice_size: int,
        *,
        devices: Sequence[Any] | None = None,
        axes: Mapping[str, int] | None = None,
    ):
        devices = _default_devices(devices)
        if slice_size <= 0:
            raise ValueError("slice_size must be positive")
        if len(devices) < slice_size:
            raise ValueError(
                f"need at least {slice_size} devices, have {len(devices)}"
            )
        self.slice_size = slice_size
        self.axes = dict(axes) if axes else {DATA_AXIS: -1}
        n_slices = len(devices) // slice_size
        self._free: list[SliceLease] = [
            SliceLease(
                index=i,
                devices=tuple(devices[i * slice_size : (i + 1) * slice_size]),
                axes=self.axes,
            )
            for i in range(n_slices)
        ]
        self._cond = threading.Condition()
        self.n_slices = n_slices

    def available(self) -> int:
        with self._cond:
            return len(self._free)

    def lease(self, timeout: float | None = None) -> SliceLease:
        with self._cond:
            if not self._cond.wait_for(lambda: self._free, timeout=timeout):
                raise TimeoutError(
                    f"no free slice within {timeout}s ({self.n_slices} total)"
                )
            return self._free.pop()

    def release(self, lease: SliceLease) -> None:
        with self._cond:
            if any(l.index == lease.index for l in self._free):
                raise ValueError(f"slice {lease.index} is not leased")
            self._free.append(lease)
            self._cond.notify()



class ElasticSliceAllocator(_MeshLeaseMixin):
    """Variable-size device leasing: each trial asks for the number of chips
    it wants (``lease(n)``), the allocator grants n contiguous devices.

    This is the elasticity the reference cannot express (SURVEY §7 hard part
    (b)): Hyperband/PBT rungs can raise a trial's *device* budget between
    rungs the same way they raise epochs — promoted survivors get bigger
    sub-meshes, early rungs run many small ones.  Contiguity keeps a lease's
    collectives on neighboring chips (ICI locality on a real slice; on the
    virtual CPU mesh it is simply deterministic packing).

    Grants are FIFO-fair: a large request blocks later smaller ones instead
    of starving behind them (head-of-line semantics — the simple policy that
    guarantees progress for every size).
    """

    def __init__(self, devices: Sequence[Any] | None = None, *, axes=None):
        self._devices = _default_devices(devices)
        self.axes = dict(axes) if axes else {DATA_AXIS: -1}
        self._free = [True] * len(self._devices)
        self._cond = threading.Condition()
        self._queue: list[object] = []  # FIFO tickets
        # start-index -> the live lease object: release() checks identity so
        # a stale double release can never free a successor lease's devices
        self._live: dict[int, SliceLease] = {}

    @property
    def n_devices(self) -> int:
        return len(self._devices)

    def available(self) -> int:
        with self._cond:
            return sum(self._free)

    def pending(self) -> int:
        """Requests currently queued (waiting for a grant)."""
        with self._cond:
            return len(self._queue)

    def _find_run(self, n: int) -> int | None:
        """Lowest start index of n contiguous free devices, else None."""
        run = 0
        for i, free in enumerate(self._free):
            run = run + 1 if free else 0
            if run == n:
                return i - n + 1
        return None

    def lease(self, n_devices: int = 1, timeout: float | None = None) -> SliceLease:
        if not 1 <= n_devices <= len(self._devices):
            raise ValueError(
                f"n_devices must be in [1, {len(self._devices)}], got {n_devices}"
            )
        ticket = object()
        with self._cond:
            self._queue.append(ticket)
            try:
                def ready():
                    return (
                        self._queue[0] is ticket
                        and self._find_run(n_devices) is not None
                    )

                if not self._cond.wait_for(ready, timeout=timeout):
                    raise TimeoutError(
                        f"no {n_devices}-device run within {timeout}s "
                        f"({self.available()}/{len(self._devices)} free)"
                    )
                start = self._find_run(n_devices)
                assert start is not None
                for i in range(start, start + n_devices):
                    self._free[i] = False
                self._queue.pop(0)
                # the next waiter may also be satisfiable (e.g. it wants
                # fewer devices than remain free)
                self._cond.notify_all()
                lease = SliceLease(
                    index=start,
                    devices=self._devices[start : start + n_devices],
                    axes=self.axes,
                )
                self._live[start] = lease
                return lease
            except BaseException:
                if ticket in self._queue:
                    self._queue.remove(ticket)
                    self._cond.notify_all()
                raise

    def release(self, lease: SliceLease) -> None:
        with self._cond:
            # identity check first: a stale lease (double release, or a span
            # since re-leased to someone else) must never free devices
            if self._live.get(lease.index) is not lease:
                raise ValueError(
                    f"lease at device {lease.index} is not live (double "
                    "release, or its span was re-leased)"
                )
            del self._live[lease.index]
            for i in range(lease.index, lease.index + len(lease.devices)):
                self._free[i] = True
            self._cond.notify_all()

