"""On-device Population Based Training: exploit/explore as an array permutation.

The reference Katib's PBT moves checkpoints between pods with a directory
copy on a RWX PVC and reassigns hyperparameters through a host-side
controller round-trip per generation (``pbt/service.py:259-268``); our host
parity port (``suggest/pbt.py``) keeps that shape — one trial dispatch, one
Orbax save, one ``shutil.copytree`` per member per generation.  But the
cohort machinery (PRs 3-8) already holds the entire population as ONE
stacked ``[K, ...]`` pytree on device.  This module closes the loop the way
Podracer puts everything on the learner (arxiv 2104.06272): a full PBT
generation — train T steps, score, truncation-select, clone winners,
perturb hyperparameters — is one jitted dispatch with zero host transfers
inside it.  "Checkpoint exchange" becomes ``jnp.take`` over the member
axis (a collective permutation when the cohort is sharded over the
``trial`` mesh axis); hyperparameter perturbation rides a threaded
``jax.random`` key in-kernel.

Selection semantics mirror ``PbtSuggester`` (host reference):

- scores are scaled so higher is better; ``lo, hi`` are the
  ``(truncation, 1 - truncation)`` quantiles (``jnp.quantile`` matches
  ``np.quantile``'s linear interpolation, so device and host agree on the
  cut points bit-for-bit on equal inputs);
- the bottom quantile *exploits*: ``n_exploit = round_half_up(K * trunc)``
  members with score < lo (floored to 1 whenever anyone is below the
  quantile — the host's small-population floor fix) each clone a uniformly
  random winner (score >= hi): state AND hyperparameters;
- everyone else *explores*: each hyperparameter is perturbed x0.8/x1.2
  (clipped to bounds, rounded for ints, neighbor-stepped mod N for
  categorical/discrete) — or, with ``resample_probability`` set, is
  independently resampled from the prior with probability p and kept
  as-is otherwise, exactly the host ``_generate`` branch;
- ghost rows (mesh padding / shape buckets, rows ``[k:]``) never win,
  never exploit, and keep their hyperparameters, so bucketed cohorts share
  the same executable as exact-width ones;
- a member whose eval score goes non-finite ranks at the bottom and is
  overwritten by a winner on the next selection — divergence self-heals
  through the exploit path instead of freezing a lane.

Hyperparameters live as a ``{name: [P] float32}`` dict operand
(categorical/discrete carried in index space); the encode/decode helpers
below translate to/from native parameter dicts at generation boundaries
only.
"""

from __future__ import annotations

import json
import math
from dataclasses import dataclass
from typing import Any, Callable, Mapping, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from katib_tpu.parallel.mesh import replicated, trial_axis_size, trial_sharding

# stands in for -inf so quantile interpolation over a pool containing a
# diverged member stays finite (x * inf = nan would poison the cut points)
_NEG = -1e30


def _round_half_up(x: float) -> int:
    return int(math.floor(x + 0.5))


# -- search-space description (host <-> device boundary) ----------------------


@dataclass(frozen=True)
class HyperSpec:
    """Device-side view of one parameter: enough to perturb/resample it
    in-kernel and decode it back to a native value at the boundary.
    ``kind`` is the ParameterType value; categorical/discrete carry their
    value list for index-space decode."""

    name: str
    kind: str  # "double" | "int" | "discrete" | "categorical"
    lo: float = 0.0
    hi: float = 1.0
    log: bool = False
    values: tuple = ()

    @property
    def categorical(self) -> bool:
        return self.kind in ("discrete", "categorical")

    @property
    def n_choices(self) -> int:
        return len(self.values)


def specs_from_parameters(parameters: Sequence[Any]) -> tuple[HyperSpec, ...]:
    """Build the device-side space description from ``ParameterSpec``s."""
    out = []
    for p in parameters:
        kind = p.type.value
        f = p.feasible
        if kind in ("double", "int"):
            out.append(
                HyperSpec(
                    name=p.name,
                    kind=kind,
                    lo=float(f.min),
                    hi=float(f.max),
                    log=bool(f.is_log_scaled()),
                )
            )
        else:
            out.append(
                HyperSpec(name=p.name, kind=kind, values=tuple(f.list or ()))
            )
    return tuple(out)


def specs_to_json(specs: Sequence[HyperSpec]) -> str:
    return json.dumps(
        [
            {
                "name": s.name,
                "kind": s.kind,
                "lo": s.lo,
                "hi": s.hi,
                "log": s.log,
                "values": list(s.values),
            }
            for s in specs
        ]
    )


def specs_from_json(payload: str) -> tuple[HyperSpec, ...]:
    return tuple(
        HyperSpec(
            name=d["name"],
            kind=d["kind"],
            lo=float(d.get("lo", 0.0)),
            hi=float(d.get("hi", 1.0)),
            log=bool(d.get("log", False)),
            values=tuple(d.get("values", ())),
        )
        for d in json.loads(payload)
    )


def encode_hypers(
    specs: Sequence[HyperSpec],
    params_list: Sequence[Mapping[str, Any]],
    padded_size: int | None = None,
) -> dict[str, jnp.ndarray]:
    """Member parameter dicts -> ``{name: [P] float32}`` device operands.
    Categorical/discrete values are carried as their list index.  Ghost
    rows (``padded_size > len(params_list)``) repeat member 0."""
    k = len(params_list)
    p = padded_size if padded_size is not None else k
    out: dict[str, jnp.ndarray] = {}
    for s in specs:
        vals = []
        for i in range(p):
            # ghost rows repeat member 0 (inert but finite — same
            # convention as CohortContext.stacked)
            v = params_list[i if i < k else 0][s.name]
            if s.categorical:
                try:
                    vals.append(float(list(s.values).index(_cat_cast(s, v))))
                except ValueError:
                    vals.append(0.0)
            else:
                vals.append(float(v))
        out[s.name] = jnp.asarray(vals, dtype=jnp.float32)
    return out


def _cat_cast(s: HyperSpec, v: Any):
    """Match a raw value against the spec's value list the way
    ``ParameterSpec.cast`` does for discrete (numeric tolerance)."""
    if s.kind == "discrete":
        fv = float(v)
        for item in s.values:
            if math.isclose(float(item), fv, rel_tol=1e-12, abs_tol=1e-12):
                return item
        return v
    return v


def decode_member_hypers(
    specs: Sequence[HyperSpec], hypers: Mapping[str, Any], i: int
) -> dict[str, Any]:
    """Row ``i`` of the device hyper arrays -> a native parameter dict."""
    out: dict[str, Any] = {}
    for s in specs:
        v = float(jnp.asarray(hypers[s.name])[i])
        if s.categorical:
            out[s.name] = s.values[int(round(v)) % max(1, s.n_choices)]
        elif s.kind == "int":
            out[s.name] = int(round(v))
        else:
            out[s.name] = v
    return out


# -- the selection kernel -----------------------------------------------------


def exploit_explore(
    key: jax.Array,
    scores: jnp.ndarray,
    hypers: Mapping[str, jnp.ndarray],
    *,
    specs: Sequence[HyperSpec],
    k: int,
    truncation: float,
    resample_p: float | None = None,
):
    """One truncation-selection + perturbation step, fully on device.

    ``scores``: ``[P]`` (higher is better; rows ``[k:]`` are ghosts).
    ``hypers``: ``{name: [P]}`` (categorical in index space).

    Returns ``(parent, new_hypers, exploited, stats)``:
    ``parent[i]`` is the member whose state row ``i`` should take
    (``i`` itself for explorers/ghosts) — apply with
    ``jax.tree_util.tree_map(lambda x: jnp.take(x, parent, axis=0), states)``;
    ``exploited`` is the ``[P]`` bool exploit mask; ``stats`` carries the
    quantile cut points and winner mask for telemetry.

    Jit-safe with ``specs``/``k``/``truncation``/``resample_p`` static
    (close over them or mark them static).
    """
    p = scores.shape[0]
    if not 0 < k <= p:
        raise ValueError(f"k={k} out of range for padded size {p}")
    valid = jnp.arange(p) < k
    finite = jnp.isfinite(scores)
    s = jnp.where(valid & finite, scores, _NEG)

    # cut points over the k REAL members (static slice excludes ghosts);
    # linear-interpolation quantile, bit-identical to the host np.quantile
    pool = s[:k]
    lo = jnp.quantile(pool, truncation)
    hi = jnp.quantile(pool, 1.0 - truncation)

    below = valid & (s < lo)
    # host parity incl. the small-population fix: round half-up, floor of 1
    # whenever anyone actually fell below the quantile
    n_exploit = _round_half_up(k * truncation)
    n_exploit_dyn = jnp.where(
        below.any(), jnp.maximum(jnp.int32(n_exploit), 1), jnp.int32(n_exploit)
    )
    # rank ascending among valid members (ghosts pushed past the end) so
    # "the n_exploit members below lo" is deterministic: worst-first
    rank_key = jnp.where(valid, s, jnp.inf)
    order = jnp.argsort(rank_key)
    rank = jnp.argsort(order)
    exploited = below & (rank < n_exploit_dyn)

    winners = valid & finite & (s >= hi)
    any_winner = winners.any()
    exploited = exploited & any_winner

    key_sel, key_perturb = jax.random.split(key)
    logits = jnp.where(winners, 0.0, -jnp.inf)
    # ghosts draw too (vmapped over the full padded axis) but their rows
    # are discarded by the exploit mask — shapes stay bucket-stable
    member_keys = jax.random.split(key_sel, p)
    choice = jax.vmap(lambda mk: jax.random.categorical(mk, logits))(member_keys)
    self_idx = jnp.arange(p)
    parent = jnp.where(exploited, choice, self_idx)

    explore = valid & ~exploited
    new_hypers: dict[str, jnp.ndarray] = {}
    for j, spec in enumerate(specs):
        v = hypers[spec.name]
        kj = jax.random.fold_in(key_perturb, j)
        k_flip, k_draw = jax.random.split(kj)
        if resample_p is None:
            # perturb: x0.8 / x1.2 clipped (linear, like the host _perturb),
            # or +-1 neighbor step mod N in index space
            flip = jax.random.bernoulli(k_flip, 0.5, (p,))
            if spec.categorical:
                step = jnp.where(flip, -1.0, 1.0)
                perturbed = jnp.mod(jnp.round(v) + step, float(max(1, spec.n_choices)))
            else:
                factor = jnp.where(flip, 0.8, 1.2)
                perturbed = jnp.clip(v * factor, spec.lo, spec.hi)
                if spec.kind == "int":
                    perturbed = jnp.round(perturbed)
        else:
            # resample-with-probability-p: fresh prior draw or keep AS-IS
            # (the host branch never perturbs in this mode)
            take_new = jax.random.uniform(k_flip, (p,)) < resample_p
            u = jax.random.uniform(k_draw, (p,))
            if spec.categorical:
                drawn = jnp.floor(u * spec.n_choices)
                drawn = jnp.clip(drawn, 0, max(0, spec.n_choices - 1))
            elif spec.log:
                drawn = jnp.exp(
                    math.log(spec.lo) + u * (math.log(spec.hi) - math.log(spec.lo))
                )
            else:
                drawn = spec.lo + u * (spec.hi - spec.lo)
            if spec.kind == "int":
                drawn = jnp.round(drawn)
            perturbed = jnp.where(take_new, drawn, v)
        # exploiters inherit the winner's hyperparameters VERBATIM
        # (pre-perturb — standard PBT and the host's exploit branch)
        new_hypers[spec.name] = jnp.where(
            exploited,
            jnp.take(v, parent),
            jnp.where(explore, perturbed, v),
        ).astype(v.dtype)

    stats = {
        "lo": lo,
        "hi": hi,
        "n_exploit": n_exploit_dyn,
        "winners": winners,
    }
    return parent, new_hypers, exploited, stats


# -- the generation step ------------------------------------------------------


def make_pbt_generation_step(
    member_train_step: Callable,
    member_eval_fn: Callable,
    *,
    specs: Sequence[HyperSpec],
    k: int,
    truncation: float,
    resample_p: float | None = None,
    mesh: Any = None,
    donate: bool = True,
) -> Callable:
    """Build the fused generation step: T train steps x eval x selection x
    clone x perturb as ONE jitted program.

    ``member_train_step(state, hypers_row, batch) -> state`` is one member's
    SGD step (``hypers_row`` is ``{name: scalar}``); ``member_eval_fn(state,
    eval_batch) -> scalar`` scores one member (higher is better; apply the
    objective sign before calling).  Both are vmapped over the leading
    member axis.

    The returned ``gen_step(states, hypers, key, batch_idx, data,
    eval_batch)`` runs ``batch_idx.shape[0]`` training steps under
    ``lax.scan`` (per-step minibatches gathered ON DEVICE from the resident
    ``data`` by index — no host transfer inside the generation), evaluates,
    selects, permutes member states via ``jnp.take`` over the member axis,
    and perturbs hyperparameters with the threaded key.  Returns
    ``(states, hypers, key, scores, parent, exploited)``.  The carried
    population (``states``/``hypers``/``key``) is donated so G generations
    reuse the same device buffers as chunked dispatches of one cached
    executable.

    With a ``mesh`` carrying a ``trial`` axis, states/hypers shard their
    member dimension over it and the exploit ``take`` lowers to a
    collective permutation; everything else is replicated.
    """
    vstep = jax.vmap(member_train_step, in_axes=(0, 0, None))
    veval = jax.vmap(member_eval_fn, in_axes=(0, None))

    def gen_step(states, hypers, key, batch_idx, data, eval_batch):
        def body(carry, idx):
            st = carry
            batch = jax.tree_util.tree_map(
                lambda d: jnp.take(d, idx, axis=0), data
            )
            st = vstep(st, hypers, batch)
            return st, None

        states, _ = lax.scan(body, states, batch_idx)
        scores = veval(states, eval_batch)
        key, sel_key = jax.random.split(key)
        parent, new_hypers, exploited, _stats = exploit_explore(
            sel_key,
            scores,
            hypers,
            specs=specs,
            k=k,
            truncation=truncation,
            resample_p=resample_p,
        )
        states = jax.tree_util.tree_map(
            lambda x: jnp.take(x, parent, axis=0), states
        )
        return states, new_hypers, key, scores, parent, exploited

    donate_args = (0, 1, 2) if donate else ()
    if mesh is None or trial_axis_size(mesh) <= 1:
        return jax.jit(gen_step, donate_argnums=donate_args)
    member = trial_sharding(mesh)
    shared = replicated(mesh)
    return jax.jit(
        gen_step,
        in_shardings=(member, member, shared, shared, shared, shared),
        out_shardings=(member, member, shared, member, member, member),
        donate_argnums=donate_args,
    )
