"""Generic sharded training-step machinery.

The compute path the reference delegates to PyTorch-CUDA inside trial
containers (``darts-cnn-cifar10/run_trial.py:85-96``) is here a jitted,
mesh-sharded JAX function: parameters replicated (or model-sharded), batch
split over the ``data`` axis, gradient all-reduce inserted by GSPMD over ICI.
There is no NCCL analog to manage — sharding annotations ARE the
communication backend.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from katib_tpu.parallel.mesh import DATA_AXIS, replicated


class TrainState(NamedTuple):
    """Minimal train state (flax's TrainState without the apply_fn closure so
    it stays a plain pytree for checkpointing)."""

    step: jnp.ndarray
    params: Any
    opt_state: Any

    @classmethod
    def create(cls, params, tx: optax.GradientTransformation) -> "TrainState":
        return cls(
            step=jnp.zeros((), jnp.int32),
            params=params,
            opt_state=tx.init(params),
        )


def clip_by_global_norm(grads, max_norm: float):
    """Scale ``grads`` so their global norm is at most ``max_norm``; returns
    ``(clipped, norm)`` (the raw norm is a useful training metric)."""
    gnorm = optax.global_norm(grads)
    scale = jnp.minimum(1.0, max_norm / (gnorm + 1e-6))
    return jax.tree_util.tree_map(lambda g: g * scale, grads), gnorm


def make_train_step(
    loss_fn: Callable[..., jnp.ndarray],
    tx: optax.GradientTransformation,
    mesh: Mesh | None = None,
    donate: bool = True,
    grad_clip_norm: float | None = None,
) -> Callable:
    """Build ``step(state, batch) -> (state, metrics)``, jitted and sharded.

    ``loss_fn(params, batch) -> scalar loss`` (or ``(loss, aux)`` with
    ``has_aux`` inferred from the return).  With a mesh, params/opt-state are
    replicated and the batch is split on the data axis; XLA inserts the
    gradient all-reduce.
    """

    def step(state: TrainState, batch) -> tuple[TrainState, dict]:
        def wrapped(params):
            out = loss_fn(params, batch)
            if isinstance(out, tuple):
                return out
            return out, {}

        (loss, aux), grads = jax.value_and_grad(wrapped, has_aux=True)(state.params)
        if grad_clip_norm is not None:
            grads, _ = clip_by_global_norm(grads, grad_clip_norm)
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        metrics = {"loss": loss, **aux}
        return TrainState(state.step + 1, params, opt_state), metrics

    if mesh is None:
        return jax.jit(step, donate_argnums=(0,) if donate else ())

    state_sharding = replicated(mesh)
    batch_sharding = NamedSharding(mesh, PartitionSpec(DATA_AXIS))
    return jax.jit(
        step,
        in_shardings=(state_sharding, batch_sharding),
        out_shardings=(state_sharding, state_sharding),
        donate_argnums=(0,) if donate else (),
    )


def make_eval_step(
    metric_fn: Callable[..., dict],
    mesh: Mesh | None = None,
) -> Callable:
    """Build ``eval(params, batch) -> metrics`` jitted with batch sharding."""
    if mesh is None:
        return jax.jit(metric_fn)
    return jax.jit(
        metric_fn,
        in_shardings=(replicated(mesh), NamedSharding(mesh, PartitionSpec(DATA_AXIS))),
        out_shardings=replicated(mesh),
    )


def cross_entropy_loss(logits: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    logp = jax.nn.log_softmax(logits.astype(jnp.float32))
    return -jnp.mean(jnp.take_along_axis(logp, labels[:, None], axis=-1))


def accuracy(logits: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    return jnp.mean((jnp.argmax(logits, axis=-1) == labels).astype(jnp.float32))
