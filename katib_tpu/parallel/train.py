"""Generic sharded training-step machinery.

The compute path the reference delegates to PyTorch-CUDA inside trial
containers (``darts-cnn-cifar10/run_trial.py:85-96``) is here a jitted,
mesh-sharded JAX function: parameters replicated (or model-sharded), batch
split over the ``data`` axis, gradient all-reduce inserted by GSPMD over ICI.
There is no NCCL analog to manage — sharding annotations ARE the
communication backend.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from katib_tpu.parallel.mesh import (
    DATA_AXIS,
    replicated,
    trial_axis_size,
    trial_sharding,
)


class TrainState(NamedTuple):
    """Minimal train state (flax's TrainState without the apply_fn closure so
    it stays a plain pytree for checkpointing)."""

    step: jnp.ndarray
    params: Any
    opt_state: Any

    @classmethod
    def create(cls, params, tx: optax.GradientTransformation) -> "TrainState":
        return cls(
            step=jnp.zeros((), jnp.int32),
            params=params,
            opt_state=tx.init(params),
        )


def clip_by_global_norm(grads, max_norm: float):
    """Scale ``grads`` so their global norm is at most ``max_norm``; returns
    ``(clipped, norm)`` (the raw norm is a useful training metric)."""
    gnorm = optax.global_norm(grads)
    scale = jnp.minimum(1.0, max_norm / (gnorm + 1e-6))
    return jax.tree_util.tree_map(lambda g: g * scale, grads), gnorm


def make_train_step(
    loss_fn: Callable[..., jnp.ndarray],
    tx: optax.GradientTransformation,
    mesh: Mesh | None = None,
    donate: bool = True,
    grad_clip_norm: float | None = None,
) -> Callable:
    """Build ``step(state, batch) -> (state, metrics)``, jitted and sharded.

    ``loss_fn(params, batch) -> scalar loss`` (or ``(loss, aux)`` with
    ``has_aux`` inferred from the return).  With a mesh, params/opt-state are
    replicated and the batch is split on the data axis; XLA inserts the
    gradient all-reduce.
    """

    def step(state: TrainState, batch) -> tuple[TrainState, dict]:
        def wrapped(params):
            out = loss_fn(params, batch)
            if isinstance(out, tuple):
                return out
            return out, {}

        (loss, aux), grads = jax.value_and_grad(wrapped, has_aux=True)(state.params)
        if grad_clip_norm is not None:
            grads, _ = clip_by_global_norm(grads, grad_clip_norm)
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        metrics = {"loss": loss, **aux}
        return TrainState(state.step + 1, params, opt_state), metrics

    if mesh is None:
        return jax.jit(step, donate_argnums=(0,) if donate else ())

    state_sharding = replicated(mesh)
    batch_sharding = NamedSharding(mesh, PartitionSpec(DATA_AXIS))
    return jax.jit(
        step,
        in_shardings=(state_sharding, batch_sharding),
        out_shardings=(state_sharding, state_sharding),
        donate_argnums=(0,) if donate else (),
    )


# -- vectorized trial cohorts -------------------------------------------------


def stack_pytrees(trees):
    """Stack K structurally identical pytrees into one ``[K, ...]`` pytree
    (member k of the cohort lives at leading-axis row k)."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *trees)


def unstack_pytree(tree, k: int):
    """Inverse of :func:`stack_pytrees`: one ``[K, ...]`` pytree -> K pytrees."""
    return [jax.tree_util.tree_map(lambda x: x[i], tree) for i in range(k)]


class _TraceCounter:
    """Counts traces of the cohort step — the Python body runs once per jit
    trace, so tests can assert a K-member cohort compiles exactly one
    program instead of K."""

    def __init__(self) -> None:
        self.count = 0

    def bump(self) -> None:
        self.count += 1


cohort_trace_counter = _TraceCounter()


def make_cohort_train_step(
    loss_fn: Callable[..., jnp.ndarray],
    tx: optax.GradientTransformation,
    donate: bool = True,
    grad_clip_norm: float | None = None,
    mesh: Mesh | None = None,
) -> Callable:
    """Build ``step(states, batch) -> (states, metrics)`` over a whole cohort.

    ``states`` is a stacked ``[K, ...]`` TrainState pytree (one member per
    leading row); the batch is shared across members.  Per-member
    hyperparameters ride inside each member's opt_state as runtime values
    (``optax.inject_hyperparams``), so the K members — and every later
    cohort of the same shapes — share this single compiled executable; the
    carried state is donated so the device buffers are reused in place.

    With a ``mesh`` carrying a ``trial`` axis of size D, the stacked member
    dimension is split over it (batch replicated): D devices each step K/D
    members of ONE SPMD program, with no inter-chip collectives except the
    ``[K]`` metric gather at the host.  K must be a multiple of D
    (``padded_cohort_size``); donation and the per-member non-finite freeze
    are unchanged.  A mesh without a trial axis (or size 1) compiles the
    same program as no mesh at all.

    Divergence is contained per member: a row whose loss goes non-finite
    keeps its previous state (its metrics stay non-finite from then on), so
    one blown-up member never poisons the rest of the cohort.
    """

    def member_step(state: TrainState, batch) -> tuple[TrainState, dict]:
        def wrapped(params):
            out = loss_fn(params, batch)
            if isinstance(out, tuple):
                return out
            return out, {}

        (loss, aux), grads = jax.value_and_grad(wrapped, has_aux=True)(state.params)
        if grad_clip_norm is not None:
            grads, _ = clip_by_global_norm(grads, grad_clip_norm)
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        metrics = {"loss": loss, **aux}
        return TrainState(state.step + 1, params, opt_state), metrics

    vstep = jax.vmap(member_step, in_axes=(0, None))

    def step(states: TrainState, batch) -> tuple[TrainState, dict]:
        cohort_trace_counter.bump()
        new_states, metrics = vstep(states, batch)
        ok = jnp.isfinite(metrics["loss"])

        def pick(new, old):
            mask = ok.reshape(ok.shape + (1,) * (new.ndim - 1))
            return jnp.where(mask, new, old)

        return jax.tree_util.tree_map(pick, new_states, states), metrics

    if mesh is None or trial_axis_size(mesh) <= 1:
        return jax.jit(step, donate_argnums=(0,) if donate else ())

    member_sharding = trial_sharding(mesh)
    shared_sharding = replicated(mesh)
    return jax.jit(
        step,
        in_shardings=(member_sharding, shared_sharding),
        out_shardings=(member_sharding, member_sharding),
        donate_argnums=(0,) if donate else (),
    )


def make_cohort_eval_step(
    metric_fn: Callable[..., dict],
    mesh: Mesh | None = None,
) -> Callable:
    """Build ``eval(params, batch) -> metrics`` vmapped over stacked
    ``[K, ...]`` params with a shared batch; each returned metric is ``[K]``.
    With a trial-axis ``mesh`` the member dimension shards like the train
    step's (params split over ``trial``, batch replicated)."""
    veval = jax.vmap(metric_fn, in_axes=(0, None))
    if mesh is None or trial_axis_size(mesh) <= 1:
        return jax.jit(veval)
    member_sharding = trial_sharding(mesh)
    return jax.jit(
        veval,
        in_shardings=(member_sharding, replicated(mesh)),
        out_shardings=member_sharding,
    )


def make_eval_step(
    metric_fn: Callable[..., dict],
    mesh: Mesh | None = None,
) -> Callable:
    """Build ``eval(params, batch) -> metrics`` jitted with batch sharding."""
    if mesh is None:
        return jax.jit(metric_fn)
    return jax.jit(
        metric_fn,
        in_shardings=(replicated(mesh), NamedSharding(mesh, PartitionSpec(DATA_AXIS))),
        out_shardings=replicated(mesh),
    )


def cross_entropy_loss(logits: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    logp = jax.nn.log_softmax(logits.astype(jnp.float32))
    return -jnp.mean(jnp.take_along_axis(logp, labels[:, None], axis=-1))


def accuracy(logits: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    return jnp.mean((jnp.argmax(logits, axis=-1) == labels).astype(jnp.float32))
