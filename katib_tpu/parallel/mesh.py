"""Device-mesh construction and sharding helpers.

The reference has no tensor/data parallelism of its own — it delegates
distributed trials to external operators (TFJob/PyTorchJob, SURVEY.md §2.4).
Here parallelism is first-class: every trial trains on a ``jax.sharding.Mesh``
and the orchestrator decides how the chips are partitioned between trials.

Axis convention (reserved up front so HP/NAS search over large models can
shard without API changes):

- ``data``    — batch dimension (DP); gradients all-reduce over ICI
- ``model``   — tensor parallelism (TP) for wide layers
- ``seq``     — sequence/context parallelism (ring attention / Ulysses)
- ``trial``   — the cohort member axis: a vmap-batched ``[K, ...]`` trial
  cohort (``parallel/train.py:make_cohort_train_step``) shards its leading
  member dimension over this axis, so D chips each step K/D members of one
  SPMD program with no inter-chip collectives except the ``[K]`` metric
  gather (the Podracer recipe: many independent learners, one program)

A mesh with size-1 axes compiles to exactly the same XLA program as an
unsharded one, so single-chip trials use the same code path as v5e-64 runs.
"""

from __future__ import annotations

import math
from typing import Any, Mapping, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

DATA_AXIS = "data"
MODEL_AXIS = "model"
SEQ_AXIS = "seq"
TRIAL_AXIS = "trial"


def make_mesh(
    axis_sizes: Mapping[str, int] | None = None,
    devices: Sequence[Any] | None = None,
) -> Mesh:
    """Build a mesh over ``devices`` (default: all).

    ``axis_sizes`` maps axis name -> size; one axis may be -1 to absorb the
    remaining devices.  Default: a 1-D data mesh over every device, i.e. pure
    data parallelism.
    """
    devs = list(devices if devices is not None else jax.devices())
    n = len(devs)
    if axis_sizes is None:
        axis_sizes = {DATA_AXIS: n}
    names = tuple(axis_sizes)
    sizes = list(axis_sizes.values())
    if sizes.count(-1) > 1:
        raise ValueError("at most one axis may be -1")
    if -1 in sizes:
        known = math.prod(s for s in sizes if s != -1)
        if n % known:
            raise ValueError(f"{n} devices not divisible by {known}")
        sizes[sizes.index(-1)] = n // known
    if math.prod(sizes) != n:
        raise ValueError(f"mesh {dict(zip(names, sizes))} != {n} devices")
    grid = np.asarray(devs).reshape(sizes)
    return Mesh(grid, axis_names=names)


def data_sharding(mesh: Mesh, *, extra_dims: int = 1) -> NamedSharding:
    """Sharding for a batch: leading dim split over ``data`` (and ``seq`` if
    the mesh has one), remaining dims replicated."""
    spec = [DATA_AXIS] + [None] * extra_dims
    return NamedSharding(mesh, PartitionSpec(*spec))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


def shard_batch(batch, mesh: Mesh):
    """Place a pytree of arrays with leading batch dims onto the mesh's data
    axis.  Batch size must divide by the data-axis size (callers pad)."""

    def place(x):
        x = np.asarray(x) if not hasattr(x, "ndim") else x
        spec = PartitionSpec(DATA_AXIS, *([None] * (x.ndim - 1)))
        return jax.device_put(x, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map(place, batch)


def replicate(tree, mesh: Mesh):
    """Replicate a pytree (parameters, opt state) across the whole mesh."""
    sharding = replicated(mesh)
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, sharding), tree)


def local_mesh_size(mesh: Mesh, axis: str = DATA_AXIS) -> int:
    return mesh.shape[axis] if axis in mesh.shape else 1


# -- trial-parallel cohorts ---------------------------------------------------


def trial_axis_size(mesh: Mesh | None) -> int:
    """Devices on the cohort member axis (1 when absent / no mesh)."""
    if mesh is None:
        return 1
    return mesh.shape[TRIAL_AXIS] if TRIAL_AXIS in mesh.shape else 1


def trial_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for a stacked ``[K, ...]`` cohort pytree: leading member
    dimension split over ``trial``, everything else replicated."""
    return NamedSharding(mesh, PartitionSpec(TRIAL_AXIS))


def padded_cohort_size(k: int, mesh: Mesh | None) -> int:
    """``k`` rounded up to a multiple of the trial-axis size so every device
    carries the same member count (callers pad with inert ghost members)."""
    t = trial_axis_size(mesh)
    return -(-k // t) * t


def shard_members(tree, mesh: Mesh):
    """Place a stacked ``[K, ...]`` cohort pytree with its member axis split
    over ``trial`` (K must be a multiple of the trial-axis size — see
    :func:`padded_cohort_size`)."""
    sharding = trial_sharding(mesh)
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, sharding), tree)


def serial_mesh(mesh: Mesh | None) -> Mesh | None:
    """The mesh a SINGLETON trial should train on.  The ``trial`` axis
    partitions cohort members, not tensors — a trial-axis-only mesh has no
    data axis for ``shard_batch`` to split over, so serial paths (cohort
    fallback, transient-member rejoin, plain ``run_trial``) drop to the
    default single-device layout.  A mesh that also carries tensor axes is
    returned unchanged (the singleton replicates over ``trial`` too)."""
    if mesh is None:
        return None
    if set(mesh.shape) == {TRIAL_AXIS}:
        return None
    return mesh


def narrowed_trial_mesh(mesh: Mesh | None, survivors: Sequence[Any]) -> Mesh | None:
    """Rebuild ``mesh`` over the surviving devices after a device fault,
    shrinking only the ``trial`` axis (elastic cohort degradation).

    Non-trial axes keep their sizes — a tensor-parallel layout cannot shrink
    without resharding parameters — so the trial axis becomes
    ``len(survivors) // prod(other axes)`` and any leftover survivors are
    dropped to keep the grid rectangular.  Axis order is preserved.  Returns
    ``None`` when no strictly narrower mesh exists (no mesh, no trial axis,
    or too few survivors for even one trial row) — callers then degrade to
    the single-device vmap tier (``mesh=None``).
    """
    if mesh is None or TRIAL_AXIS not in mesh.shape:
        return None
    old_t = mesh.shape[TRIAL_AXIS]
    other = math.prod(s for name, s in mesh.shape.items() if name != TRIAL_AXIS)
    new_t = len(survivors) // other
    if new_t < 1 or new_t >= old_t:
        return None
    sizes = {
        name: (new_t if name == TRIAL_AXIS else mesh.shape[name])
        for name in mesh.axis_names
    }
    return make_mesh(sizes, devices=list(survivors)[: new_t * other])


def needs_safe_conv(mesh: Mesh | None) -> bool:
    """True when grouped-convolution gradients cannot be trusted on this
    mesh: XLA's SPMD partitioner miscompiles grouped-conv filter gradients
    once the mesh carries a non-data axis of size > 1 (measured; see
    ``katib_tpu/ops/depthwise.py``).  Model builders consult this to select
    the partitioner-safe conv formulations."""
    if mesh is None:
        return False
    return any(size > 1 for name, size in mesh.shape.items() if name != DATA_AXIS)
