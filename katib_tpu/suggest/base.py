"""Suggester contract + registry.

The reference runs every algorithm as a per-experiment gRPC deployment behind
``GetSuggestions`` / ``ValidateAlgorithmSettings`` (``api.proto:34-40``, composer
``composer.go:72``).  Here a suggester is an in-process object owned by the
orchestrator — same contract, no pod, no network:

- ``validate(spec)``        <-> ``ValidateAlgorithmSettings``
- ``get_suggestions(...)``  <-> ``GetSuggestions`` with ``current_request_number``

Statefulness contract (parity with the reference's semantics, §3.2 of
SURVEY.md): suggesters may keep in-memory state for the lifetime of an
experiment (hyperopt Trials store / ENAS session / PBT queue analogs) but must
either (a) derive state from the trial history passed in (random/grid/TPE/
GP/Sobol are fully stateless here), or (b) persist durable state in
``experiment.algorithm_settings`` (Hyperband, mirroring the reference's
state-in-CR round trip ``suggestionclient.go:194-196``) so an orchestrator
restart can resume.
"""

from __future__ import annotations

import abc
import hashlib
from typing import Callable, Type

import numpy as np

from katib_tpu.core.types import (
    Experiment,
    ExperimentSpec,
    Trial,
    TrialAssignmentSet,
)



def parse_eta(settings) -> int:
    """The successive-halving reduction factor: an integer > 1 (default 3).
    One parser for hyperband and asha."""
    raw = settings.get("eta")
    if raw is None:
        return 3
    try:
        eta_f = float(raw)
    except (TypeError, ValueError):
        raise SuggesterError("eta must be an integer > 1") from None
    eta = int(eta_f)
    if eta != eta_f or eta <= 1:
        raise SuggesterError("eta must be an integer > 1")
    return eta

class SuggesterError(ValueError):
    """Invalid algorithm settings (gRPC INVALID_ARGUMENT analog)."""


class SuggestionsNotReady(RuntimeError):
    """The algorithm needs currently-running trials to finish before it can
    propose more (e.g. a Hyperband rung or CMA-ES generation barrier).  The
    orchestrator waits for a trial completion and retries — the analog of the
    reference's controller retry on suggestion-service errors
    (``suggestionclient.go:57-60``)."""


class SearchExhausted(RuntimeError):
    """The algorithm has nothing more to propose (grid fully enumerated,
    Hyperband brackets finished).  The orchestrator completes the experiment —
    the analog of Hyperband's empty reply when ``current_s < 0``
    (``hyperband/service.py:47-49``)."""


#: Exceptions that are suggester *control flow*, not faults — the
#: orchestrator's circuit breaker must never count them as failures.
CONTROL_FLOW_EXCEPTIONS = (SearchExhausted, SuggestionsNotReady)


def call_suggester(
    suggester: "Suggester",
    experiment: Experiment,
    count: int,
    breaker=None,
    injector=None,
    deadline: float | None = None,
    events: tuple = (),
) -> tuple[list[TrialAssignmentSet], str]:
    """One fault-isolated ``get_suggestions`` call — the single seam through
    which the orchestrator talks to an algorithm.

    Returns ``(proposals, outcome)`` with outcome one of ``"ok"``,
    ``"exhausted"``, ``"not_ready"``, ``"error"``.  Control-flow signals
    (:data:`CONTROL_FLOW_EXCEPTIONS`) close the ``breaker`` — they prove the
    suggester is healthy — while any other exception is recorded as a failure
    with its traceback (the reference retries suggestion-service RPC errors
    at the controller, ``suggestionclient.go:57-60``; here the breaker bounds
    those retries).  The caller checks ``breaker.tripped`` for the terminal
    verdict and ``breaker.allow()`` before calling again.  ``injector`` is
    the ``faults.FaultInjector`` chaos seam.

    With ``deadline`` set the call runs on a daemon worker thread and a call
    still blocked after ``deadline`` seconds is abandoned: the breaker
    records the failure (bounded retries, then the experiment fails with a
    diagnosis) instead of the caller blocking forever behind a wedged
    algorithm.  The abandoned call's eventual result, if any, is discarded —
    a proposal set that missed its deadline was never journaled.  ``events``
    are stop/halt events a deadline wait also honors.
    """
    import traceback as _traceback

    if deadline is not None:
        return _call_suggester_deadline(
            suggester, experiment, count, breaker, injector, deadline, events
        )

    try:
        if injector is not None:
            injector.on_suggester_call(events=events)
        proposals = suggester.get_suggestions(experiment, count)
    except SearchExhausted:
        if breaker is not None:
            breaker.record_success()
        return [], "exhausted"
    except SuggestionsNotReady:
        if breaker is not None:
            breaker.record_success()
        return [], "not_ready"
    except Exception:
        if breaker is not None:
            breaker.record_failure(_traceback.format_exc(limit=20))
        return [], "error"
    if breaker is not None:
        breaker.record_success()
    return proposals, "ok"


def _call_suggester_deadline(
    suggester, experiment, count, breaker, injector, deadline, events
) -> tuple[list[TrialAssignmentSet], str]:
    """Deadline wrapper: the call itself runs (fault-isolated, no breaker —
    the outer frame owns the verdict) on a daemon thread; a timeout is a
    breaker failure with a "deadline" diagnosis."""
    import traceback as _traceback

    box: dict = {}

    def _worker():
        try:
            if injector is not None:
                injector.on_suggester_call(events=events)
            box["result"] = (suggester.get_suggestions(experiment, count), "ok")
        except SearchExhausted:
            box["result"] = ([], "exhausted")
        except SuggestionsNotReady:
            box["result"] = ([], "not_ready")
        except Exception:
            box["traceback"] = _traceback.format_exc(limit=20)
            box["result"] = ([], "error")

    from katib_tpu.utils.clock import get_clock

    clock = get_clock()
    t = clock.spawn(_worker, name="katib-suggest-call", daemon=True)
    waited = 0.0
    poll = min(0.05, deadline)
    while waited < deadline and t.is_alive():
        if any(ev.is_set() for ev in events):
            break
        clock.join_thread(t, poll)
        waited += poll
    if "result" not in box:
        if breaker is not None:
            breaker.record_failure(
                f"get_suggestions exceeded its {deadline:.1f}s deadline "
                "(call abandoned; see loopStallDeadlineSeconds)"
            )
        return [], "error"
    proposals, outcome = box["result"]
    if breaker is not None:
        if outcome == "error":
            breaker.record_failure(
                box.get("traceback", "get_suggestions raised")
            )
        else:
            breaker.record_success()
    return proposals, outcome


class Suggester(abc.ABC):
    """One suggestion algorithm bound to one experiment."""

    #: registry key, e.g. "random"
    name: str = ""

    #: whether proposals depend on observed results.  The async suggest
    #: loop keeps a deep proposal lookahead for NON-adaptive suggesters
    #: (random/grid/sobol enumerate the same points regardless of history)
    #: but clamps it to the in-flight width for adaptive ones — racing an
    #: ASHA/BO/PBT suggester far ahead of its observations burns the trial
    #: budget on uninformed proposals (e.g. rung-0 randoms that crowd out
    #: promotions).  Conservative default: adaptive.
    adaptive: bool = True

    def __init__(self, spec: ExperimentSpec):
        self.spec = spec
        self.validate(spec)

    # -- contract ----------------------------------------------------------

    @classmethod
    def validate(cls, spec: ExperimentSpec) -> None:
        """Raise SuggesterError on invalid settings/space for this algorithm."""

    @abc.abstractmethod
    def get_suggestions(
        self, experiment: Experiment, count: int
    ) -> list[TrialAssignmentSet]:
        """Propose up to ``count`` new trials given the experiment's history."""

    # -- shared helpers ----------------------------------------------------

    def seed(self, extra: int = 0) -> int:
        """Deterministic per-experiment seed.  ``random_state`` setting wins;
        otherwise the experiment name seeds it, so reruns are reproducible.

        ``extra`` selects an independent stream: it is HASH-MIXED with the
        base, never added — additive composition makes adjacent seeds
        produce overlapping generator families (seed 2's stream at index n
        equals seed 1's at n+1), which silently correlates what should be
        independent replicates (e.g. a multi-seed benchmark's random
        baseline collapsing to one sample)."""
        s = self.spec.algorithm.setting("random_state") or self.spec.algorithm.setting(
            "seed"
        )
        base = str(int(s)) if s is not None else self.spec.name
        digest = hashlib.sha256(f"{base}:{extra}".encode()).digest()
        # 4 bytes: sklearn's random_state requires [0, 2^32)
        return int.from_bytes(digest[:4], "little")

    def rng(self, extra: int = 0) -> np.random.Generator:
        return np.random.default_rng(self.seed(extra))

    @staticmethod
    def completed_trials(experiment: Experiment) -> list[Trial]:
        """Trials usable as observations, in start order."""
        done = [
            t
            for t in experiment.trials.values()
            if t.condition.is_completed_ok() and t.observation is not None
        ]
        return sorted(done, key=lambda t: t.start_time)

    def top_trials(self, trials: list[Trial], k: int) -> list[Trial]:
        """The k best trials by the experiment objective (missing
        observations dropped).  Shared ranking rule for the
        successive-halving family (hyperband, asha)."""
        obj = self.spec.objective
        scored = [(t.objective_value(obj), t) for t in trials]
        scored = [(v, t) for v, t in scored if v is not None]
        scored.sort(key=lambda p: p[0], reverse=obj.type.value == "maximize")
        return [t for _, t in scored[:k]]

    def rung_device_labels(self, r: int) -> dict[str, str]:
        """``{DEVICES_LABEL: r}`` when the ``devices_per_rung`` setting is
        truthy — the rung's resource value also sizes the trial's sub-mesh
        lease (honored by the orchestrator's ElasticSliceAllocator), so
        promoted survivors get more chips, not just more epochs.  One copy
        of the setting parse for every rung-based suggester."""
        from katib_tpu.utils.booleans import parse_bool

        if parse_bool(self.spec.algorithm.setting("devices_per_rung")):
            from katib_tpu.core.types import DEVICES_LABEL

            return {DEVICES_LABEL: str(r)}
        return {}

    @staticmethod
    def check_resource_in_space(
        spec, resource_name: str, lo: float, hi: float, *, what: str = "resource bounds"
    ) -> None:
        """Raise unless ``[lo, hi]`` lies inside the declared feasible range
        of the resource parameter.  ``ParameterSpec.cast`` rounds but does
        not clamp, so rung resources outside the range would emit trial
        assignments outside the declared search space.  Shared by the
        successive-halving family (hyperband, asha)."""
        p = next((p for p in spec.parameters if p.name == resource_name), None)
        if p is None or p.feasible.min is None or p.feasible.max is None:
            return  # presence / type of the parameter is checked separately
        if lo < p.feasible.min or hi > p.feasible.max:
            raise SuggesterError(
                f"{what} [{lo:g}, {hi:g}] fall outside parameter "
                f"{resource_name!r}'s feasible range "
                f"[{p.feasible.min:g}, {p.feasible.max:g}]"
            )

    @staticmethod
    def observed_xy(
        experiment: Experiment,
    ) -> tuple[list[dict], np.ndarray]:
        """(params, objective values) for completed trials; values are
        sign-flipped so that LOWER IS ALWAYS BETTER internally."""
        obj = experiment.spec.objective
        sign = 1.0 if obj.type.value == "minimize" else -1.0
        xs, ys = [], []
        for t in Suggester.completed_trials(experiment):
            v = t.objective_value(obj)
            if v is None:
                continue
            xs.append(t.params())
            ys.append(sign * v)
        return xs, np.asarray(ys, dtype=np.float64)


# ---------------------------------------------------------------------------
# Registry
# ---------------------------------------------------------------------------

_REGISTRY: dict[str, Callable[[ExperimentSpec], Suggester]] = {}


def register(name: str) -> Callable[[Type[Suggester]], Type[Suggester]]:
    def deco(cls: Type[Suggester]) -> Type[Suggester]:
        cls.name = name
        _REGISTRY[name] = cls
        return cls

    return deco


def _resolve(name: str) -> Type[Suggester]:
    """Registry lookup with the lazy-import fallback — shared by construction
    and validation so the two paths can never drift on what's resolvable."""
    # import for registration side effects
    import importlib

    from katib_tpu.suggest import algorithms  # noqa: F401

    if name not in _REGISTRY and name in algorithms.LAZY_ALGORITHMS:
        importlib.import_module(algorithms.LAZY_ALGORITHMS[name])
    if name not in _REGISTRY:
        raise SuggesterError(
            f"unknown algorithm {name!r}; registered: {sorted(registered_algorithms())}"
        )
    return _REGISTRY[name]


def make_suggester(spec: ExperimentSpec) -> Suggester:
    """Instantiate the registered suggester for an experiment spec — the
    analog of the composer resolving the algorithm image from KatibConfig
    (``composer.go:72``)."""
    return _resolve(spec.algorithm.name)(spec)


def validate_spec(spec: ExperimentSpec) -> None:
    """Run the registered algorithm's ``validate`` WITHOUT instantiating it.
    Construction can have side effects (``remote``'s composer mode spawns a
    service subprocess), which a validate-only caller must never trigger —
    the analog of ``ValidateAlgorithmSettings`` being a separate RPC from
    suggestion serving."""
    _resolve(spec.algorithm.name).validate(spec)


def registered_algorithms() -> list[str]:
    from katib_tpu.suggest import algorithms  # noqa: F401

    return sorted(set(_REGISTRY) | set(algorithms.LAZY_ALGORITHMS))
