from katib_tpu.suggest.base import (  # noqa: F401
    SearchExhausted,
    Suggester,
    SuggesterError,
    SuggestionsNotReady,
    make_suggester,
    registered_algorithms,
)
from katib_tpu.suggest.space import SpaceEncoder  # noqa: F401
