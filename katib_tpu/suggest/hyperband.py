"""Hyperband — successive-halving brackets over a resource parameter.

Capability parity with the reference's ``hyperband`` service
(``pkg/suggestion/v1beta1/hyperband/service.py:36-200``), with two design
changes:

1. **Explicit persisted state.**  The reference round-trips mutated algorithm
   settings through ``Suggestion.Status.AlgorithmSettings``
   (``service.py:56`` -> ``suggestionclient.go:194-196``) to stay stateless.
   Here bracket state is a small JSON blob in
   ``experiment.algorithm_settings["_hyperband_state"]`` — same contract
   (restart-safe, no in-memory state), without scattering derived values
   across individual settings keys.
2. **Rung membership via labels.**  The reference selects "the latest N
   trials sorted by start time" (``service.py:127-134``) to find the current
   rung; trials here carry ``hyperband-s`` / ``hyperband-i`` labels, so rung
   membership is exact even with retries or out-of-order starts.

Math (matching the reference): eta (default 3), r_l = max resource,
s_max = floor(log_eta(r_l)); bracket s from s_max down to 0 runs rungs
i = 0..s with sizes n_0 = ceil((s_max+1) * eta^s / (s+1)),
n_i = ceil(n_{i-1} / eta) and resources r_i = r_l * eta^(i-s); each rung
copies the top n_i trials of the previous rung with the resource parameter
raised.
"""

from __future__ import annotations

import json
import math

from katib_tpu.core.types import (
    Experiment,
    ExperimentSpec,
    ParameterAssignment,
    Trial,
    TrialAssignmentSet,
)
from katib_tpu.suggest.base import (
    parse_eta,
    SearchExhausted,
    Suggester,
    SuggesterError,
    SuggestionsNotReady,
    register,
)
from katib_tpu.suggest.space import SpaceEncoder

STATE_KEY = "_hyperband_state"
S_LABEL = "hyperband-s"
I_LABEL = "hyperband-i"




def _s_max(r_l: float, eta: int) -> int:
    # epsilon guards float truncation: log(1000)/log(10) = 2.9999999999999996
    return int(math.floor(math.log(r_l) / math.log(eta) + 1e-9))


@register("hyperband")
class HyperbandSuggester(Suggester):
    @classmethod
    def validate(cls, spec: ExperimentSpec) -> None:
        s = spec.algorithm.settings
        if "r_l" not in s or "resource_name" not in s:
            raise SuggesterError("hyperband requires settings r_l and resource_name")
        try:
            r_l = float(s["r_l"])
        except (TypeError, ValueError):
            raise SuggesterError("r_l must be a positive number") from None
        if r_l <= 0:
            raise SuggesterError("r_l must be a positive number")
        eta = parse_eta(s)
        if not any(p.name == s["resource_name"] for p in spec.parameters):
            raise SuggesterError(
                f"resource_name {s['resource_name']!r} must be a declared parameter"
            )
        s_max = _s_max(r_l, eta)
        max_parallel = int(math.ceil(eta**s_max))
        if spec.parallel_trial_count < max_parallel:
            raise SuggesterError(
                f"parallel_trial_count must be >= {max_parallel} for r_l={r_l}, eta={eta}"
            )
        # smallest rung resource is r_l * eta^(-s_max) (deepest bracket's
        # first rung, _resource with i=0, s=s_max), floored at 1
        cls.check_resource_in_space(
            spec,
            s["resource_name"],
            cls._resource(r_l, eta, s_max, 0),
            r_l,
            what="rung resources",
        )

    # -- parameters --------------------------------------------------------

    def _cfg(self) -> tuple[float, int, int, str]:
        s = self.spec.algorithm.settings
        r_l = float(s["r_l"])
        eta = parse_eta(s)
        return r_l, eta, _s_max(r_l, eta), s["resource_name"]

    @staticmethod
    def _rung_sizes(s_max: int, s: int, eta: int) -> list[int]:
        n0 = int(math.ceil((s_max + 1) * eta**s / (s + 1)))
        sizes = [n0]
        for _ in range(s):
            sizes.append(int(math.ceil(sizes[-1] / eta)))
        return sizes

    @staticmethod
    def _resource(r_l: float, eta: int, s: int, i: int) -> int:
        return max(1, int(r_l * eta ** (i - s)))

    # -- state -------------------------------------------------------------

    def _load_state(self, experiment: Experiment) -> dict:
        raw = experiment.algorithm_settings.get(STATE_KEY)
        if raw:
            return json.loads(raw)
        _, _, s_max, _ = self._cfg()
        return {"s": s_max, "i": 0}

    def _save_state(self, experiment: Experiment, state: dict) -> None:
        experiment.algorithm_settings[STATE_KEY] = json.dumps(state)

    # -- rung helpers ------------------------------------------------------

    @staticmethod
    def _rung_trials(experiment: Experiment, s: int, i: int) -> list[Trial]:
        return [
            t
            for t in experiment.trials.values()
            if t.labels.get(S_LABEL) == str(s) and t.labels.get(I_LABEL) == str(i)
        ]

    # ranking shared with asha via Suggester.top_trials

    # -- main --------------------------------------------------------------

    def get_suggestions(
        self, experiment: Experiment, count: int
    ) -> list[TrialAssignmentSet]:
        r_l, eta, s_max, resource_name = self._cfg()
        state = self._load_state(experiment)
        space = SpaceEncoder(self.spec.parameters)

        while True:
            s, i = state["s"], state["i"]
            if s < 0:
                raise SearchExhausted("hyperband brackets finished")
            sizes = self._rung_sizes(s_max, s, eta)
            r_i = self._resource(r_l, eta, s, i)
            rung = self._rung_trials(experiment, s, i)

            # rung target: nominal size, shrunk to the survivor count when the
            # previous rung had failures (otherwise the rung could never fill
            # and the experiment would deadlock on an empty proposal list)
            if i == 0:
                survivors: list[Trial] = []
                target = sizes[0]
            else:
                prev = self._rung_trials(experiment, s, i - 1)
                if any(not t.condition.is_terminal() for t in prev):
                    raise SuggestionsNotReady(
                        f"hyperband bracket s={s} rung {i-1} still running"
                    )
                survivors = self.top_trials(
                    [t for t in prev if t.condition.is_completed_ok()], sizes[i]
                )
                if not survivors:
                    # whole previous rung failed; abandon bracket
                    state = {"s": s - 1, "i": 0}
                    self._save_state(experiment, state)
                    continue
                target = min(sizes[i], len(survivors))

            if len(rung) < target:
                missing = target - len(rung)
                if i == 0:
                    proposals = self._master_rung(
                        space, resource_name, r_i, missing, s, skip=len(rung)
                    )
                else:
                    proposals = [
                        self._promote(t, resource_name, r_i, s, i)
                        for t in survivors[len(rung) : len(rung) + missing]
                    ]
                return proposals[:count]

            # rung fully proposed: wait for completion, then advance
            if any(not t.condition.is_terminal() for t in rung):
                raise SuggestionsNotReady(
                    f"hyperband bracket s={s} rung {i} has trials in flight"
                )
            completed_ok = [t for t in rung if t.condition.is_completed_ok()]
            if i < s and completed_ok:
                state = {"s": s, "i": i + 1}
            else:
                state = {"s": s - 1, "i": 0}
            self._save_state(experiment, state)

    def _rung_labels(self, s: int, i: int, r: int) -> dict[str, str]:
        """Rung identity labels, plus the per-trial device budget when
        ``devices_per_rung`` is set: the rung's resource value ALSO sizes the
        trial's sub-mesh lease (``katib-tpu/devices``, honored by the
        orchestrator's ElasticSliceAllocator) — survivors get more chips,
        not just more epochs.  TPU-native elasticity the reference has no
        analog for (its ``r_i`` can only reach the container's argv)."""
        return {S_LABEL: str(s), I_LABEL: str(i), **self.rung_device_labels(r)}

    def _master_rung(
        self,
        space: SpaceEncoder,
        resource_name: str,
        r: int,
        n: int,
        s: int,
        skip: int = 0,
    ) -> list[TrialAssignmentSet]:
        # deterministic per-bracket stream; burn `skip` samples so partial
        # proposals (count < rung size) never repeat configurations
        rng = self.rng(extra=1000 * s)
        for _ in range(skip):
            space.sample(rng)
        out = []
        for _ in range(n):
            params = space.sample(rng)
            params[resource_name] = self.spec.parameter(resource_name).cast(r)
            out.append(
                TrialAssignmentSet(
                    assignments=space.to_assignments(params),
                    labels=self._rung_labels(s, 0, r),
                )
            )
        return out

    def _promote(
        self, trial: Trial, resource_name: str, r: int, s: int, i: int
    ) -> TrialAssignmentSet:
        assignments = [
            ParameterAssignment(
                a.name,
                self.spec.parameter(resource_name).cast(r) if a.name == resource_name else a.value,
            )
            for a in trial.spec.assignments
        ]
        labels = self._rung_labels(s, i, r)
        labels["hyperband-parent"] = trial.name
        return TrialAssignmentSet(assignments=assignments, labels=labels)

    def total_budget(self) -> int:
        """Total number of trials hyperband will run (for budget planning)."""
        r_l, eta, s_max, _ = self._cfg()
        return sum(
            sum(self._rung_sizes(s_max, s, eta)) for s in range(s_max, -1, -1)
        )
