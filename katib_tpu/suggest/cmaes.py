"""CMA-ES — native implementation of the (mu/mu_w, lambda) evolution strategy
with covariance matrix adaptation (Hansen's tutorial formulation).

Capability parity with the reference's ``cmaes`` algorithm (goptuna CMA-ES
sampler, ``pkg/suggestion/v1beta1/goptuna/converter.go:40-75``, including the
IPOP/BIPOP restart variants selected via the ``restart_strategy`` setting).

State model: CMA-ES is generation-based.  Rather than hiding state in the
process (the reference service loses its study on restart — SURVEY.md §3.2),
every proposed trial carries labels ``cmaes-generation`` and ``cmaes-index``;
the suggester replays completed trials from the experiment history to
reconstruct identical strategy state, so it is restart-safe by construction.
"""

from __future__ import annotations

import math

import numpy as np

from katib_tpu.core.types import (
    Experiment,
    ExperimentSpec,
    TrialAssignmentSet,
)
from katib_tpu.suggest.base import (
    Suggester,
    SuggesterError,
    SuggestionsNotReady,
    register,
)
from katib_tpu.suggest.space import SpaceEncoder

GEN_LABEL = "cmaes-generation"
IDX_LABEL = "cmaes-index"


class CmaState:
    """Pure CMA-ES strategy state over the unit hypercube."""

    def __init__(self, dim: int, seed: int, sigma0: float = 0.25, popsize: int | None = None):
        self.dim = dim
        self.rng = np.random.default_rng(seed)
        self.lam = popsize or (4 + int(3 * math.log(dim)))
        self.mu = self.lam // 2
        w = math.log(self.mu + 0.5) - np.log(np.arange(1, self.mu + 1))
        self.weights = w / w.sum()
        self.mueff = 1.0 / np.sum(self.weights**2)

        n = float(dim)
        self.cc = (4 + self.mueff / n) / (n + 4 + 2 * self.mueff / n)
        self.cs = (self.mueff + 2) / (n + self.mueff + 5)
        self.c1 = 2 / ((n + 1.3) ** 2 + self.mueff)
        self.cmu = min(
            1 - self.c1,
            2 * (self.mueff - 2 + 1 / self.mueff) / ((n + 2) ** 2 + self.mueff),
        )
        self.damps = 1 + 2 * max(0.0, math.sqrt((self.mueff - 1) / (n + 1)) - 1) + self.cs
        self.chiN = math.sqrt(n) * (1 - 1 / (4 * n) + 1 / (21 * n * n))

        self.mean = np.full(dim, 0.5)
        self.sigma = sigma0
        self.C = np.eye(dim)
        self.ps = np.zeros(dim)
        self.pc = np.zeros(dim)
        self.generation = 0

    def ask(self) -> np.ndarray:
        """Sample lambda candidates, clipped to the unit cube."""
        # eigendecomposition each generation (dims are tiny for HP search)
        d2, B = np.linalg.eigh(self.C)
        d2 = np.maximum(d2, 1e-20)
        A = B @ np.diag(np.sqrt(d2))
        z = self.rng.standard_normal((self.lam, self.dim))
        x = self.mean + self.sigma * z @ A.T
        return np.clip(x, 0.0, 1.0)

    def tell(self, xs: np.ndarray, ys: np.ndarray) -> None:
        """Update strategy state from a full generation (lower y better)."""
        order = np.argsort(ys, kind="stable")
        elite = xs[order[: self.mu]]
        old_mean = self.mean.copy()
        self.mean = self.weights @ elite

        d2, B = np.linalg.eigh(self.C)
        d2 = np.maximum(d2, 1e-20)
        inv_sqrt = B @ np.diag(1.0 / np.sqrt(d2)) @ B.T

        y_mean = (self.mean - old_mean) / self.sigma
        self.ps = (1 - self.cs) * self.ps + math.sqrt(
            self.cs * (2 - self.cs) * self.mueff
        ) * inv_sqrt @ y_mean
        hsig = float(
            np.linalg.norm(self.ps)
            / math.sqrt(1 - (1 - self.cs) ** (2 * (self.generation + 1)))
            / self.chiN
            < 1.4 + 2 / (self.dim + 1)
        )
        self.pc = (1 - self.cc) * self.pc + hsig * math.sqrt(
            self.cc * (2 - self.cc) * self.mueff
        ) * y_mean

        artmp = (elite - old_mean) / self.sigma
        self.C = (
            (1 - self.c1 - self.cmu) * self.C
            + self.c1
            * (np.outer(self.pc, self.pc) + (1 - hsig) * self.cc * (2 - self.cc) * self.C)
            + self.cmu * artmp.T @ np.diag(self.weights) @ artmp
        )
        self.sigma = self.sigma * math.exp(
            (self.cs / self.damps) * (np.linalg.norm(self.ps) / self.chiN - 1)
        )
        self.sigma = float(min(self.sigma, 1.0))
        self.generation += 1


@register("cmaes")
class CmaEsSuggester(Suggester):
    @classmethod
    def validate(cls, spec: ExperimentSpec) -> None:
        numeric = [p for p in spec.parameters if p.type.value in ("double", "int")]
        if len(numeric) != len(spec.parameters):
            raise SuggesterError("cmaes supports only double/int parameters")
        if len(numeric) < 2:
            raise SuggesterError("cmaes requires at least 2 parameters")
        rs = spec.algorithm.settings.get("restart_strategy", "none")
        if rs not in ("none", "ipop", "bipop"):
            raise SuggesterError("restart_strategy must be none, ipop, or bipop")
        if "sigma" in spec.algorithm.settings and float(spec.algorithm.settings["sigma"]) <= 0:
            raise SuggesterError("sigma must be positive")

    def _replay(
        self, experiment: Experiment, space: SpaceEncoder
    ) -> tuple[CmaState, int]:
        """Rebuild strategy state from the labeled trial history.

        Returns ``(state, label_gen)`` where ``label_gen`` is the history
        generation the next proposals belong to.  The label counter is
        monotonic across IPOP/BIPOP restarts (the strategy's internal
        generation resets, the labels never do — otherwise post-restart trials
        would collide with old generation-0 labels and corrupt replay).
        """
        sigma0 = float(self.spec.algorithm.settings.get("sigma", 0.25))
        popsize = self.spec.algorithm.settings.get("population_size")
        state = CmaState(
            space.n_dims,
            seed=self.seed(),
            sigma0=sigma0,
            popsize=int(popsize) if popsize else None,
        )
        restart = self.spec.algorithm.settings.get("restart_strategy", "none")

        # group completed labeled trials by generation
        by_gen: dict[int, list] = {}
        for t in experiment.trials.values():
            if GEN_LABEL not in t.labels:
                continue
            by_gen.setdefault(int(t.labels[GEN_LABEL]), []).append(t)

        obj = self.spec.objective
        sign = 1.0 if obj.type.value == "minimize" else -1.0
        gen = 0
        stagnation = 0
        best_y = math.inf
        while gen in by_gen:
            trials = by_gen[gen]
            done = [
                t
                for t in trials
                if t.condition.is_completed_ok()
                and t.observation
                and t.objective_value(obj) is not None
            ]
            if len(done) < state.lam:
                # generation still in flight — ask() below must reproduce it,
                # so do NOT advance; caller handles pending logic
                break
            done = sorted(done, key=lambda t: int(t.labels[IDX_LABEL]))[: state.lam]
            xs = np.stack([space.encode(t.params()) for t in done])
            ys = np.array([sign * t.objective_value(obj) for t in done])
            # burn one ask() so the RNG stream stays aligned with the
            # generation that produced these trials
            state.ask()
            state.tell(xs, ys)
            gen_best = float(np.min(ys))
            if gen_best < best_y - 1e-12:
                best_y, stagnation = gen_best, 0
            else:
                stagnation += 1
            # IPOP restart: double population after prolonged stagnation
            if restart in ("ipop", "bipop") and (
                stagnation >= 10 + state.dim or state.sigma < 1e-8
            ):
                state = CmaState(
                    space.n_dims,
                    seed=self.seed(extra=gen + 1),
                    sigma0=sigma0,
                    popsize=state.lam * 2 if restart == "ipop" else state.lam,
                )
                stagnation = 0
            gen += 1
        return state, gen

    def get_suggestions(
        self, experiment: Experiment, count: int
    ) -> list[TrialAssignmentSet]:
        space = SpaceEncoder(self.spec.parameters)
        state, label_gen = self._replay(experiment, space)

        # which members of the current generation are already proposed?
        current = [
            t
            for t in experiment.trials.values()
            if t.labels.get(GEN_LABEL) == str(label_gen)
        ]
        # an index counts as proposed while its trial is in flight or finished
        # with a usable objective; failed members (and succeeded ones whose
        # observation lacks the objective metric) are retried with the same
        # deterministic point (PBT-style requeue, reference
        # ``pbt/service.py:303-322`` applies the same policy)
        obj = self.spec.objective

        def _usable(t) -> bool:
            if not t.condition.is_terminal():
                return True
            return t.condition.is_completed_ok() and t.objective_value(obj) is not None

        proposed_idx = {int(t.labels[IDX_LABEL]) for t in current if _usable(t)}
        pending = [t for t in current if not t.condition.is_terminal()]
        if len(proposed_idx) >= state.lam and pending:
            raise SuggestionsNotReady(
                f"cmaes generation {label_gen} has {len(pending)} trials in flight"
            )
        xs = state.ask()
        out: list[TrialAssignmentSet] = []
        for i in range(state.lam):
            if i in proposed_idx:
                continue
            if len(out) >= count:
                break
            out.append(
                TrialAssignmentSet(
                    assignments=space.to_assignments(space.decode(xs[i])),
                    labels={GEN_LABEL: str(label_gen), IDX_LABEL: str(i)},
                )
            )
        if not out and not pending:
            raise SuggestionsNotReady(
                "cmaes: waiting for generation results to be observed"
            )
        return out
