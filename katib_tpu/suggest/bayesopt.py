"""Gaussian-process Bayesian optimization.

Capability parity with the reference's ``bayesianoptimization`` service
(skopt ``Optimizer`` with a GP base estimator,
``pkg/suggestion/v1beta1/skopt/base_service.py``).  skopt is not in this
image; the GP comes from scikit-learn (same underlying model skopt wraps) and
the acquisition loop is implemented here.

Settings (mirroring the reference's accepted skopt settings,
``skopt/base_service.py:31-40``):
- ``base_estimator``    only "GP" is supported
- ``n_initial_points``  random-sample count before modeling (default 10)
- ``acq_func``          "ei" | "pi" | "lcb" | "gp_hedge" (case-insensitive;
                        skopt spells them "EI"/"PI"/"LCB").  The reference
                        default is gp_hedge — skopt's portfolio strategy:
                        each acquisition proposes its best candidate, one is
                        picked by softmax over accumulated gains, and every
                        proposal's predicted mean is subtracted from its
                        acquisition's gain so the portfolio adapts toward
                        whichever acquisition proposes low-mean points.
- ``acq_optimizer``     accepted for YAML compat ("auto"/"sampling"/"lbfgs");
                        candidates are always optimized by sampling here
- ``random_state``      seed
"""

from __future__ import annotations

import numpy as np

from katib_tpu.core.types import Experiment, ExperimentSpec, TrialAssignmentSet
from katib_tpu.suggest.base import Suggester, SuggesterError, register
from katib_tpu.suggest.space import SpaceEncoder

_ACQ_FUNCS = ("ei", "pi", "lcb", "gp_hedge")
_ACQ_OPTIMIZERS = ("auto", "sampling", "lbfgs")
# the reference service's skopt default (``skopt/base_service.py:33``)
_DEFAULT_ACQ = "gp_hedge"


@register("bayesianoptimization")
class BayesOptSuggester(Suggester):
    @classmethod
    def validate(cls, spec: ExperimentSpec) -> None:
        import importlib.util

        # sklearn/scipy imports are deferred for startup speed; presence
        # still fails at submission, not mid-run
        for dep in ("scipy", "sklearn"):
            if importlib.util.find_spec(dep) is None:
                raise SuggesterError(
                    f"bayesianoptimization requires {dep} (the 'bayesopt' extra)"
                )
        s = spec.algorithm.settings
        if s.get("base_estimator", "GP") != "GP":
            raise SuggesterError("only base_estimator=GP is supported")
        if s.get("acq_func", _DEFAULT_ACQ).lower() not in _ACQ_FUNCS:
            raise SuggesterError(f"acq_func must be one of {_ACQ_FUNCS}")
        if s.get("acq_optimizer", "auto").lower() not in _ACQ_OPTIMIZERS:
            raise SuggesterError(f"acq_optimizer must be one of {_ACQ_OPTIMIZERS}")
        if "n_initial_points" in s and int(s["n_initial_points"]) < 1:
            raise SuggesterError("n_initial_points must be >= 1")

    def _fit_gp(self, X: np.ndarray, y: np.ndarray, seed: int):
        import warnings

        from sklearn.exceptions import ConvergenceWarning
        from sklearn.gaussian_process import GaussianProcessRegressor
        from sklearn.gaussian_process.kernels import ConstantKernel, Matern, WhiteKernel

        kernel = ConstantKernel(1.0) * Matern(
            length_scale=np.full(X.shape[1], 0.5),
            length_scale_bounds=(1e-2, 1e2),
            nu=2.5,
        ) + WhiteKernel(noise_level=1e-6, noise_level_bounds=(1e-12, 1e-1))
        gp = GaussianProcessRegressor(
            kernel=kernel, normalize_y=True, random_state=seed, n_restarts_optimizer=1
        )
        with warnings.catch_warnings():
            # noise-free synthetic objectives routinely pin the WhiteKernel at
            # its lower bound; that is expected, not a fit failure
            warnings.simplefilter("ignore", ConvergenceWarning)
            gp.fit(X, y)
        return gp

    @staticmethod
    def _scores(
        mu: np.ndarray, sigma: np.ndarray, y_best: float, acq: str, xi: float = 0.01
    ) -> np.ndarray:
        """Acquisition scores from a shared GP posterior (one ``predict``
        serves every acquisition — gp_hedge needs all three per ask)."""
        # scipy.stats costs ~2s of import time; every orchestrator start
        # imports this module via the algorithm registry, so the import
        # stays inside the only function that needs it
        from scipy.stats import norm

        sigma = np.maximum(sigma, 1e-9)
        if acq == "lcb":
            return -(mu - 1.96 * sigma)  # maximize negative lower bound
        imp = y_best - mu - xi  # minimizing internally
        z = imp / sigma
        if acq == "pi":
            return norm.cdf(z)
        return imp * norm.cdf(z) + sigma * norm.pdf(z)  # EI

    # -- gp_hedge portfolio state: call-history state, so it must ride the
    # resume hooks (base contract: everything else derives from trial
    # history; these pickles restore the adaptive portfolio on --resume)

    def state_dict(self) -> dict:
        return {"hedge_gains": list(getattr(self, "_hedge_gains", np.zeros(3)))}

    def load_state_dict(self, data: dict) -> None:
        gains = data.get("hedge_gains")
        if gains is not None and len(gains) == 3:
            self._hedge_gains = np.asarray(gains, dtype=float)

    def get_suggestions(
        self, experiment: Experiment, count: int
    ) -> list[TrialAssignmentSet]:
        space = SpaceEncoder(self.spec.parameters)
        settings = self.spec.algorithm.settings
        n_init = int(settings.get("n_initial_points", 10))
        # default matches the reference service's skopt default (gp_hedge,
        # ``skopt/base_service.py:33``) so an acq-less Katib YAML behaves
        # the same here as upstream
        acq = settings.get("acq_func", _DEFAULT_ACQ).lower()

        xs, ys = self.observed_xy(experiment)
        rng = self.rng(extra=len(experiment.trials))

        out: list[TrialAssignmentSet] = []
        if len(xs) < n_init:
            need = min(count, n_init - len(xs))
            out.extend(
                TrialAssignmentSet(assignments=space.sample_assignments(rng))
                for _ in range(need)
            )
            if len(out) == count:
                return out
        if not xs:
            # no observations to model yet: fill the rest randomly
            out.extend(
                TrialAssignmentSet(assignments=space.sample_assignments(rng))
                for _ in range(count - len(out))
            )
            return out

        X = np.stack([space.encode_onehot(x) for x in xs])
        y = ys.copy()
        seed = self.seed(extra=len(experiment.trials))
        n_cand = 1024
        hedge_gains = getattr(self, "_hedge_gains", None)
        if hedge_gains is None:
            hedge_gains = self._hedge_gains = np.zeros(3)
        hedge_funcs = ("ei", "pi", "lcb")
        while len(out) < count:
            gp = self._fit_gp(X, y, seed)
            # candidate pool: random configurations in one-hot space
            cand_params = [space.sample(rng) for _ in range(n_cand)]
            X_cand = np.stack([space.encode_onehot(p) for p in cand_params])
            # one posterior evaluation serves every acquisition below
            mu, sigma = gp.predict(X_cand, return_std=True)
            y_best = float(np.min(y))
            if acq == "gp_hedge":
                # skopt portfolio: each acquisition nominates its argmax,
                # selection is probability-matched on accumulated gains,
                # and every nominee's predicted mean decrements its gain
                picks = [
                    int(np.argmax(self._scores(mu, sigma, y_best, a)))
                    for a in hedge_funcs
                ]
                logits = hedge_gains - hedge_gains.max()
                probs = np.exp(logits) / np.exp(logits).sum()
                chosen = int(rng.choice(3, p=probs))
                hedge_gains -= mu[picks]
                best = cand_params[picks[chosen]]
            else:
                best = cand_params[
                    int(np.argmax(self._scores(mu, sigma, y_best, acq)))
                ]
            out.append(TrialAssignmentSet(assignments=space.to_assignments(best)))
            # hallucinate the GP mean at the chosen point (constant-liar) so a
            # batch of suggestions spreads out instead of stacking
            x_new = space.encode_onehot(best)[None, :]
            X = np.concatenate([X, x_new])
            y = np.append(y, float(gp.predict(x_new)[0]))
        return out
