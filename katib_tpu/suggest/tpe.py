"""Tree-structured Parzen Estimator — native implementation.

Capability parity with the reference's ``tpe`` (hyperopt,
``hyperopt/base_service.py:28``) and ``multivariate-tpe`` (optuna TPESampler
with ``multivariate=True``, ``optuna/base_service.py:42``), re-implemented
from the TPE paper (Bergstra et al., NeurIPS 2011) rather than wrapping a
library (neither hyperopt nor optuna ships in this image, and the native
version is ~1 page of numpy).

Sketch: split completed trials into the best ``gamma``-quantile ("good") and
the rest ("bad"); fit Parzen density estimators l(x) over good and g(x) over
bad; draw candidates from l and keep the one maximizing l(x)/g(x), which is
monotone in expected improvement.

- Numeric dims: mixture of truncated Gaussians on the encoded unit interval,
  one component per observation plus a uniform prior component; bandwidths
  from a spacing heuristic.
- Categorical dims: Dirichlet-smoothed category counts.
- ``multivariate-tpe``: densities are evaluated jointly (product kernel per
  mixture component) instead of per-dimension, capturing parameter
  interactions the univariate variant ignores.
"""

from __future__ import annotations

import math

import numpy as np

from katib_tpu.core.types import Experiment, ExperimentSpec, TrialAssignmentSet
from katib_tpu.suggest.base import Suggester, SuggesterError, register
from katib_tpu.suggest.space import SpaceEncoder

_SQRT2PI = math.sqrt(2.0 * math.pi)


def _truncnorm_pdf(x: np.ndarray, mu: float, sigma: float) -> np.ndarray:
    """Gaussian truncated to [0,1], evaluated at x (vectorized)."""
    from scipy.stats import norm

    z = norm.cdf((1.0 - mu) / sigma) - norm.cdf((0.0 - mu) / sigma)
    z = max(z, 1e-12)
    return np.exp(-0.5 * ((x - mu) / sigma) ** 2) / (sigma * _SQRT2PI * z)


class _ParzenNumeric:
    """1-D Parzen estimator over [0,1] with a uniform prior component.

    ``prior_weight`` scales the uniform component against the (unit-weight)
    observation kernels — the reference hyperopt setting of the same name
    (``hyperopt/service.py:71``)."""

    def __init__(self, obs: np.ndarray, prior_weight: float = 1.0):
        # observation ORDER is preserved: in multivariate mode component j must
        # be the same observation across every dimension
        self.mus = np.asarray(obs, dtype=np.float64)
        self.prior_weight = float(prior_weight)
        n = len(self.mus)
        if n == 0:
            self.sigmas = np.array([])
            return
        # bandwidth: distance to farther neighbor (hyperopt-style), clipped
        order = np.argsort(self.mus)
        sorted_mus = self.mus[order]
        padded = np.concatenate([[0.0], sorted_mus, [1.0]])
        left = sorted_mus - padded[:-2]
        right = padded[2:] - sorted_mus
        sigma_sorted = np.maximum(left, right)
        sigmas = np.empty(n)
        sigmas[order] = sigma_sorted
        self.sigmas = np.clip(sigmas, 1.0 / (min(100.0, 1.0 + n)), 1.0)

    def sample(self, rng: np.random.Generator, n: int) -> np.ndarray:
        out = np.empty(n)
        k = len(self.mus)
        w = self.prior_weight
        for i in range(n):
            # prior component gets weight w/(k+w), each kernel 1/(k+w).
            # w == 1 uses the single-draw form so default-config runs keep
            # their exact pre-prior_weight random streams (reproducibility)
            if w == 1.0:
                j = rng.integers(k + 1)
                pick_prior = j == k
            else:
                pick_prior = rng.random() < w / (k + w)
                j = rng.integers(k) if not pick_prior else k
            if pick_prior:
                out[i] = rng.random()
            else:
                v = rng.normal(self.mus[j], self.sigmas[j])
                out[i] = min(1.0, max(0.0, v))
        return out

    def pdf(self, x: np.ndarray) -> np.ndarray:
        """Mixture density at x; uniform prior always contributes."""
        x = np.asarray(x, dtype=np.float64)
        k = len(self.mus)
        w = self.prior_weight
        total = np.full_like(x, w)  # uniform prior component, pdf = 1 on [0,1]
        for mu, s in zip(self.mus, self.sigmas):
            total = total + _truncnorm_pdf(x, mu, s)
        return total / (k + w)

    def component_pdfs(self, x: np.ndarray) -> np.ndarray:
        """(k+1, len(x)) per-component densities (for multivariate joint)."""
        x = np.asarray(x, dtype=np.float64)
        rows = [np.ones_like(x)]
        for mu, s in zip(self.mus, self.sigmas):
            rows.append(_truncnorm_pdf(x, mu, s))
        return np.stack(rows)


class _ParzenCategorical:
    """Dirichlet-smoothed categorical estimator."""

    def __init__(self, indices: np.ndarray, n_choices: int, prior: float = 1.0):
        counts = np.bincount(indices.astype(int), minlength=n_choices).astype(float)
        self.weights = (counts + prior) / (counts.sum() + prior * n_choices)
        # per-observation one-hot-ish component view for multivariate mode:
        # each component is the smoothed distribution conditioned on one obs
        self.n_choices = n_choices
        self.obs = indices.astype(int)
        self.prior = prior

    def sample(self, rng: np.random.Generator, n: int) -> np.ndarray:
        return rng.choice(self.n_choices, size=n, p=self.weights)

    def pmf(self, idx: np.ndarray) -> np.ndarray:
        return self.weights[np.asarray(idx, dtype=int)]

    def component_pmfs(self, idx: np.ndarray) -> np.ndarray:
        """(k+1, len(idx)): row 0 is the uniform prior; row j+1 upweights obs j."""
        idx = np.asarray(idx, dtype=int)
        uniform = np.full(len(idx), 1.0 / self.n_choices)
        rows = [uniform]
        for o in self.obs:
            w = np.full(self.n_choices, self.prior / self.n_choices)
            w[o] += 1.0
            w /= w.sum()
            rows.append(w[idx])
        return np.stack(rows)


class _TPECore:
    def __init__(
        self,
        space: SpaceEncoder,
        gamma: float,
        n_candidates: int,
        multivariate: bool,
        prior_weight: float = 1.0,
    ):
        self.space = space
        self.gamma = gamma
        self.n_candidates = n_candidates
        self.multivariate = multivariate
        self.prior_weight = float(prior_weight)

    def split(self, ys: np.ndarray) -> int:
        """Number of 'good' observations (lower y is better)."""
        n = len(ys)
        return max(1, min(int(np.ceil(self.gamma * n)), 25))

    def suggest_one(
        self, xs_enc: np.ndarray, ys: np.ndarray, rng: np.random.Generator
    ) -> np.ndarray:
        order = np.argsort(ys, kind="stable")
        n_good = self.split(ys)
        good = xs_enc[order[:n_good]]
        bad = xs_enc[order[n_good:]]

        d = self.space.n_dims
        good_est, bad_est = [], []
        for dim in range(d):
            if self.space.is_categorical(dim):
                nc = self.space.n_choices(dim)
                scale = max(nc - 1, 1)
                good_est.append(
                    _ParzenCategorical(
                        np.round(good[:, dim] * scale), nc, prior=self.prior_weight
                    )
                )
                bad_est.append(
                    _ParzenCategorical(
                        np.round(bad[:, dim] * scale), nc, prior=self.prior_weight
                    )
                )
            else:
                good_est.append(_ParzenNumeric(good[:, dim], self.prior_weight))
                bad_est.append(_ParzenNumeric(bad[:, dim], self.prior_weight))

        # draw candidates from the good density
        cands = np.empty((self.n_candidates, d))
        for dim in range(d):
            if self.space.is_categorical(dim):
                nc = self.space.n_choices(dim)
                idx = good_est[dim].sample(rng, self.n_candidates)
                cands[:, dim] = idx / max(nc - 1, 1)
            else:
                cands[:, dim] = good_est[dim].sample(rng, self.n_candidates)

        log_l = self._log_density(good_est, cands)
        log_g = self._log_density(bad_est, cands)
        return cands[int(np.argmax(log_l - log_g))]

    def _log_density(self, ests: list, cands: np.ndarray) -> np.ndarray:
        if not self.multivariate:
            total = np.zeros(len(cands))
            for dim, est in enumerate(ests):
                if isinstance(est, _ParzenCategorical):
                    scale = max(est.n_choices - 1, 1)
                    idx = np.round(cands[:, dim] * scale)
                    total += np.log(np.maximum(est.pmf(idx), 1e-300))
                else:
                    total += np.log(np.maximum(est.pdf(cands[:, dim]), 1e-300))
            return total
        # multivariate: joint mixture — components are aligned across dims
        # (component j = observation j in the good/bad set + shared prior row 0)
        per_dim = []
        for dim, est in enumerate(ests):
            if isinstance(est, _ParzenCategorical):
                scale = max(est.n_choices - 1, 1)
                idx = np.round(cands[:, dim] * scale)
                per_dim.append(est.component_pmfs(idx))
            else:
                per_dim.append(est.component_pdfs(cands[:, dim]))
        # (k+1, n_cands): product over dims within each component; weighted
        # mean over components (row 0 = prior at prior_weight, kernels at 1)
        joint = np.ones_like(per_dim[0])
        for mat in per_dim:
            joint = joint * mat
        k = joint.shape[0] - 1
        w = np.full(joint.shape[0], 1.0 / (k + self.prior_weight))
        w[0] *= self.prior_weight
        return np.log(np.maximum((joint * w[:, None]).sum(axis=0), 1e-300))


class _BaseTPESuggester(Suggester):
    multivariate = False

    # the reference spells this key ``n_EI_candidates``
    # (``hyperopt/service.py:72``); accept both so Katib YAMLs round-trip
    @staticmethod
    def _ei_candidates_setting(s) -> str | None:
        for key in ("n_EI_candidates", "n_ei_candidates"):
            if key in s:
                return s[key]
        return None

    @classmethod
    def validate(cls, spec: ExperimentSpec) -> None:
        s = spec.algorithm.settings
        if "gamma" in s and not (0.0 < float(s["gamma"]) < 1.0):
            raise SuggesterError("gamma must be in (0, 1)")
        ei = cls._ei_candidates_setting(s)
        if ei is not None and int(ei) < 1:
            raise SuggesterError("n_EI_candidates must be >= 1")
        if "n_startup_trials" in s and int(s["n_startup_trials"]) < 0:
            raise SuggesterError("n_startup_trials must be >= 0")
        if "prior_weight" in s and not float(s["prior_weight"]) > 0:
            raise SuggesterError("prior_weight must be > 0")

    def get_suggestions(
        self, experiment: Experiment, count: int
    ) -> list[TrialAssignmentSet]:
        space = SpaceEncoder(self.spec.parameters)
        settings = self.spec.algorithm.settings
        n_startup = int(settings.get("n_startup_trials", 10))
        gamma = float(settings.get("gamma", 0.25))
        n_cand = int(self._ei_candidates_setting(settings) or 24)
        prior_weight = float(settings.get("prior_weight", 1.0))

        xs, ys = self.observed_xy(experiment)
        rng = self.rng(extra=len(experiment.trials))

        out: list[TrialAssignmentSet] = []
        if len(xs) < n_startup:
            # startup phase: random exploration (hyperopt does the same)
            while len(out) < count and len(xs) + len(out) < max(n_startup, count):
                out.append(
                    TrialAssignmentSet(assignments=space.sample_assignments(rng))
                )
            out = out[:count]
            if len(out) == count:
                return out

        core = _TPECore(space, gamma, n_cand, self.multivariate, prior_weight)
        xs_enc = np.stack([space.encode(x) for x in xs]) if xs else np.zeros((0, space.n_dims))
        while len(out) < count:
            u = core.suggest_one(xs_enc, ys, rng)
            out.append(TrialAssignmentSet(assignments=space.to_assignments(space.decode(u))))
            # pretend the new point was observed at the median so repeated
            # asks in one batch don't collapse to the same candidate
            xs_enc = np.concatenate([xs_enc, u[None, :]])
            ys = np.append(ys, np.median(ys) if len(ys) else 0.0)
        return out


@register("tpe")
class TPESuggester(_BaseTPESuggester):
    multivariate = False


@register("multivariate-tpe")
class MultivariateTPESuggester(_BaseTPESuggester):
    multivariate = True
