"""Import side-effect module: registers the built-in CPU suggesters.

The NAS suggesters (darts/enas) pull in jax/flax/optax; they are registered
lazily by ``base.make_suggester`` so that plain HP-tuning experiments (and
black-box orchestrator processes) never pay the JAX import/backend-init cost.
"""

from katib_tpu.suggest import asha  # noqa: F401
from katib_tpu.suggest import bayesopt  # noqa: F401
from katib_tpu.suggest import cmaes  # noqa: F401
from katib_tpu.suggest import grid  # noqa: F401
from katib_tpu.suggest import hyperband  # noqa: F401
from katib_tpu.suggest import pbt  # noqa: F401
from katib_tpu.suggest import random_search  # noqa: F401
from katib_tpu.suggest import service  # noqa: F401  (registers "remote")
from katib_tpu.suggest import sobol  # noqa: F401
from katib_tpu.suggest import tpe  # noqa: F401

#: registered on first use by ``base.make_suggester``
LAZY_ALGORITHMS = {
    "darts": "katib_tpu.nas.darts.service",
    "enas": "katib_tpu.nas.enas.service",
}
