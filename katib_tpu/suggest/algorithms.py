"""Import side-effect module: registers all built-in suggesters."""

from katib_tpu.suggest import bayesopt  # noqa: F401
from katib_tpu.suggest import cmaes  # noqa: F401
from katib_tpu.suggest import grid  # noqa: F401
from katib_tpu.suggest import hyperband  # noqa: F401
from katib_tpu.suggest import pbt  # noqa: F401
from katib_tpu.suggest import random_search  # noqa: F401
from katib_tpu.suggest import sobol  # noqa: F401
from katib_tpu.suggest import tpe  # noqa: F401
