"""Grid search (reference ``optuna/base_service.py:42`` GridSampler over the
combinations of the search space).

Fully stateless: the cartesian product is enumerated in a deterministic
order and the cursor is simply the number of trials already created, so a
restarted orchestrator resumes exactly where it stopped."""

from __future__ import annotations

import itertools

from katib_tpu.core.types import (
    Experiment,
    ExperimentSpec,
    ParameterAssignment,
    TrialAssignmentSet,
)
from katib_tpu.suggest.base import SearchExhausted, Suggester, SuggesterError, register


@register("grid")
class GridSuggester(Suggester):
    adaptive = False  # fixed enumeration, safe to propose far ahead

    @classmethod
    def validate(cls, spec: ExperimentSpec) -> None:
        import math

        if math.isinf(spec.search_space_size()):
            raise SuggesterError(
                "grid search requires a finite space: every double parameter needs a step"
            )

    def _grid(self) -> list[tuple]:
        axes = [p.grid_values() for p in self.spec.parameters]
        return list(itertools.product(*axes))

    def get_suggestions(
        self, experiment: Experiment, count: int
    ) -> list[TrialAssignmentSet]:
        grid = self._grid()
        cursor = len(experiment.trials)
        if cursor >= len(grid):
            raise SearchExhausted(f"grid fully enumerated ({len(grid)} points)")
        out = []
        for combo in grid[cursor : cursor + count]:
            assignments = [
                ParameterAssignment(p.name, v)
                for p, v in zip(self.spec.parameters, combo)
            ]
            out.append(TrialAssignmentSet(assignments=assignments))
        return out
