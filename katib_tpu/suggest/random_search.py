"""Random search (reference ``hyperopt/base_service.py`` with algorithm
``random``).  Stateless: the RNG streams forward by the number of trials
already proposed, so restarts don't repeat configurations."""

from __future__ import annotations

from katib_tpu.core.types import Experiment, TrialAssignmentSet
from katib_tpu.suggest.base import Suggester, register
from katib_tpu.suggest.space import SpaceEncoder


@register("random")
class RandomSuggester(Suggester):
    adaptive = False  # history offsets the stream but never shapes points

    def get_suggestions(
        self, experiment: Experiment, count: int
    ) -> list[TrialAssignmentSet]:
        space = SpaceEncoder(self.spec.parameters)
        # offset the stream by history so resumed experiments continue the
        # sequence instead of replaying it
        rng = self.rng(extra=len(experiment.trials))
        return [
            TrialAssignmentSet(assignments=space.sample_assignments(rng))
            for _ in range(count)
        ]
