"""ASHA — asynchronous successive halving (Li et al. 2018, arXiv:1810.05934).

The reference ships synchronous Hyperband only
(``pkg/suggestion/v1beta1/hyperband/service.py``), whose rungs are
barriers: every trial in a rung must finish before the next rung starts,
so one straggler idles the whole slice.  ASHA removes the barrier — each
time the orchestrator asks for work it either *promotes* a configuration
that is in the top 1/eta of its rung, or starts a fresh configuration at
the bottom rung.  No waiting, no bracket bookkeeping, and adding trial
slots never deadlocks: exactly the scheduling shape an elastic TPU slice
wants (stragglers keep their sub-mesh; new work fills the rest).

Design notes, mirroring ``hyperband.py``'s conventions:

- **State lives in trial labels, not suggester memory.**  A trial carries
  ``asha-rung`` (its rung index) and promoted children carry
  ``asha-parent``; the promotion frontier is recomputed from
  ``experiment.trials`` on every call, so the suggester is restart-safe by
  construction (no ``state_dict`` needed).
- **Promotion rule.**  From rung ``k``: among the ``n`` completed-ok
  trials at ``k``, the top ``floor(n/eta)`` by objective are promotable;
  any of them without a child at ``k+1`` is promoted (resource raised to
  ``r_min * eta^(k+1)``, capped at ``r_max``).  Higher rungs are scanned
  first so strong configs advance before new ones start.
- **devices_per_rung** behaves exactly like Hyperband's: the rung's
  resource value also sizes the trial's sub-mesh lease
  (``katib-tpu/devices``), so promoted survivors get more chips.

Settings: ``resource_name`` (required, a declared parameter),
``r_max`` (required), ``r_min`` (default 1), ``eta`` (default 3),
``devices_per_rung`` (default off), ``sampler`` (``random`` default, or
``tpe`` for BOHB-style model-based sampling: fresh rung-0 configurations
come from a TPE fitted on ALL completed trials instead of the uniform
prior — Falkner et al. 2018's combination of Bayesian optimization with
successive halving, which neither katib nor its hyperband service has).
"""

from __future__ import annotations

import math

from katib_tpu.core.types import (
    Experiment,
    ExperimentSpec,
    ParameterAssignment,
    Trial,
    TrialAssignmentSet,
)
from katib_tpu.suggest.base import (
    Suggester,
    SuggesterError,
    parse_eta,
    register,
)
from katib_tpu.suggest.space import SpaceEncoder

RUNG_LABEL = "asha-rung"
PARENT_LABEL = "asha-parent"




@register("asha")
class AshaSuggester(Suggester):
    @classmethod
    def validate(cls, spec: ExperimentSpec) -> None:
        s = spec.algorithm.settings
        if "r_max" not in s or "resource_name" not in s:
            raise SuggesterError("asha requires settings r_max and resource_name")
        try:
            r_max = float(s["r_max"])
            r_min = float(s.get("r_min", 1))
        except (TypeError, ValueError):
            raise SuggesterError("r_max/r_min must be numbers") from None
        # resources are integer trial budgets; a fractional r_min would
        # clamp adjacent rungs to the same value and promotions would
        # re-run configs at unchanged fidelity
        if r_min < 1 or r_max < r_min:
            raise SuggesterError("need 1 <= r_min <= r_max")
        parse_eta(s)
        if not any(p.name == s["resource_name"] for p in spec.parameters):
            raise SuggesterError(
                f"resource_name {s['resource_name']!r} must be a declared parameter"
            )
        cls.check_resource_in_space(
            spec, s["resource_name"], r_min, r_max, what="r_min/r_max"
        )
        sampler = s.get("sampler", "random")
        if sampler not in ("random", "tpe"):
            raise SuggesterError(
                f"sampler must be 'random' or 'tpe', got {sampler!r}"
            )
        if sampler == "tpe":
            import importlib.util

            # TPE's model phase needs scipy; presence must fail at
            # submission, not after n_startup_trials completions
            if importlib.util.find_spec("scipy") is None:
                raise SuggesterError("sampler: tpe requires scipy")

    # -- config ------------------------------------------------------------

    def _cfg(self) -> tuple[float, float, int, int, str]:
        s = self.spec.algorithm.settings
        r_max = float(s["r_max"])
        r_min = float(s.get("r_min", 1))
        eta = parse_eta(s)
        max_rung = int(math.floor(math.log(r_max / r_min) / math.log(eta) + 1e-9))
        return r_min, r_max, eta, max_rung, s["resource_name"]

    def _resource(self, k: int) -> int:
        r_min, r_max, eta, max_rung, _ = self._cfg()
        if k >= max_rung:
            # the top rung always runs at FULL fidelity, even when
            # r_min * eta^K undershoots r_max (e.g. r_max=9, eta=2 -> 8)
            return max(1, int(r_max))
        return max(1, int(min(r_min * eta**k, r_max)))

    # -- rung bookkeeping (all from labels) --------------------------------

    @staticmethod
    def _rung_trials(experiment: Experiment, k: int) -> list[Trial]:
        return [
            t
            for t in experiment.trials.values()
            if t.labels.get(RUNG_LABEL) == str(k)
        ]

    def _promotable(self, experiment: Experiment, k: int, eta: int) -> list[Trial]:
        """Top 1/eta of rung k's completed trials without a child above."""
        done = [
            t
            for t in self._rung_trials(experiment, k)
            if t.condition.is_completed_ok()
        ]
        n_top = len(done) // eta
        if n_top == 0:
            return []
        promoted_parents = {
            t.labels.get(PARENT_LABEL)
            for t in experiment.trials.values()
            if t.labels.get(PARENT_LABEL)
        }
        return [
            t
            for t in self.top_trials(done, n_top)
            if t.name not in promoted_parents
        ]

    # -- proposals ---------------------------------------------------------

    def _labels(self, k: int, r: int) -> dict[str, str]:
        return {RUNG_LABEL: str(k), **self.rung_device_labels(r)}

    def _promote(self, trial: Trial, k: int, resource_name: str) -> TrialAssignmentSet:
        r = self._resource(k)
        assignments = [
            ParameterAssignment(
                a.name,
                self.spec.parameter(resource_name).cast(r)
                if a.name == resource_name
                else a.value,
            )
            for a in trial.spec.assignments
        ]
        labels = self._labels(k, r)
        labels[PARENT_LABEL] = trial.name
        return TrialAssignmentSet(assignments=assignments, labels=labels)

    def _fresh_batch(
        self,
        experiment: Experiment,
        space: SpaceEncoder,
        resource_name: str,
        start_index: int,
        n: int,
    ) -> list[TrialAssignmentSet]:
        """``n`` new rung-0 configurations."""
        r = self._resource(0)
        if self.spec.algorithm.setting("sampler") == "tpe":
            # BOHB-style model-based sampling (Falkner et al. 2018):
            # configurations come from a TPE fitted on every completed
            # trial, low-fidelity observations included.  ONE delegate call
            # per batch — TPE's in-batch median-injection diversifies the n
            # draws, where per-slot calls would return n identical configs
            # (same rng seed, same history).  The delegate's space excludes
            # the resource parameter: its value is a rung artifact, not a
            # hyperparameter to model.  TPE is stateless-from-history, so
            # restart determinism is preserved.
            import dataclasses

            from katib_tpu.suggest.tpe import TPESuggester

            sub_spec = dataclasses.replace(
                self.spec,
                parameters=[
                    p for p in self.spec.parameters if p.name != resource_name
                ],
            )
            props = TPESuggester(sub_spec).get_suggestions(experiment, n)
            param_dicts = [{a.name: a.value for a in p.assignments} for p in props]
        else:
            # one rng stream per rung-0 index: deterministic across
            # restarts without replaying the whole history (ASHA's rung 0
            # is unbounded, so hyperband's burn-`skip`-samples pattern
            # would be O(n^2) here)
            param_dicts = [
                space.sample(self.rng(extra=start_index + i)) for i in range(n)
            ]
        out = []
        for params in param_dicts:
            params[resource_name] = self.spec.parameter(resource_name).cast(r)
            out.append(
                TrialAssignmentSet(
                    assignments=space.to_assignments(params),
                    labels=self._labels(0, r),
                )
            )
        return out

    def get_suggestions(
        self, experiment: Experiment, count: int
    ) -> list[TrialAssignmentSet]:
        _, _, eta, max_rung, resource_name = self._cfg()
        space = SpaceEncoder(self.spec.parameters)
        # one scan per call: the promotion frontier, highest rung first so
        # strong configs advance before new ones start.  Each trial appears
        # in at most one rung's candidate list, so in-batch parent dedup is
        # inherent.
        frontier = [
            (k, t)
            for k in range(max_rung - 1, -1, -1)
            for t in self._promotable(experiment, k, eta)
        ]
        n_promote = min(len(frontier), count)
        out = [
            self._promote(t, k + 1, resource_name)
            for k, t in frontier[:n_promote]
        ]
        n_fresh = count - n_promote
        if n_fresh:
            out.extend(
                self._fresh_batch(
                    experiment,
                    space,
                    resource_name,
                    start_index=len(self._rung_trials(experiment, 0)),
                    n=n_fresh,
                )
            )
        return out
