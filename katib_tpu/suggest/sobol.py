"""Sobol quasi-random search (reference goptuna ``converter.go:40-75`` builds
a Sobol-sampler study).  Uses a scrambled Sobol sequence over the encoded unit
cube; the cursor is the number of existing trials, so the low-discrepancy
stream continues correctly across restarts."""

from __future__ import annotations

from katib_tpu.core.types import Experiment, TrialAssignmentSet
from katib_tpu.suggest.base import Suggester, register
from katib_tpu.suggest.space import SpaceEncoder


@register("sobol")
class SobolSuggester(Suggester):
    adaptive = False  # low-discrepancy sequence, independent of results

    @classmethod
    def validate(cls, spec) -> None:
        # the scipy import itself is deferred to first use for startup
        # speed; presence still fails at submission, not mid-run
        import importlib.util

        if importlib.util.find_spec("scipy") is None:
            from katib_tpu.suggest.base import SuggesterError

            raise SuggesterError("sobol requires scipy (pip install scipy)")

    def get_suggestions(
        self, experiment: Experiment, count: int
    ) -> list[TrialAssignmentSet]:
        # scipy.stats costs ~2s of import; the registry imports this module
        # on every orchestrator start, so defer to first use
        from scipy.stats import qmc

        space = SpaceEncoder(self.spec.parameters)
        sampler = qmc.Sobol(d=space.n_dims, scramble=True, seed=self.seed())
        cursor = len(experiment.trials)
        if cursor:
            sampler.fast_forward(cursor)
        points = sampler.random(count)
        return [
            TrialAssignmentSet(assignments=space.to_assignments(space.decode(u)))
            for u in points
        ]
